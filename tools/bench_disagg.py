#!/usr/bin/env python
"""Disaggregation bench: prefill/decode pools vs a monolithic fleet.

The disagg plane (``skycomputing_tpu/disagg/``) splits a serving fleet
into role-specialized pools joined by the checksummed KV-handoff plane;
this bench is where that split earns its committed verdict
(``BENCH_disagg.json``).  The acceptance scenario is ``disagg_mix`` —
an ingest wave (long prompts, short answers), a mixed middle, a chat
stream (short prompts, long answers) — replayed at EQUAL chips:

- **monolithic**: ``ServingFleet`` with 4 single-device replicas on the
  fleet's one compromise operating point, every replica both prefilling
  and decoding.  Interference is the baseline's story: every decode
  tick pays the per-engine dispatch of all 4 engines, and every engine
  keeps slots parked under long-prompt admissions.
- **disagg**: ``DisaggFleet`` with 3 prefill specialists (the same
  operating point — their slots turn over at the FIRST token, when the
  request exports as a checksummed handoff) and 1 decode specialist on
  a role-tuned point (a deep slot ledger: ``num_slots=4`` -> a 16-row
  decode slab, page budget to match) that verifies digests FIRST, then
  seats KV on the engine's existing swap-in path.

Both tails improve for structural reasons, not tuning luck: TTFT
because prefill-pool slots free at the first token instead of being
held through a full decode stream, and TPOT because the whole decode
population batches onto ONE deep slab — one decode dispatch per tick
where the monolith pays four.

Method notes (what makes the verdict replayable): latency-threshold
supervision is disabled (a wall-clock health probe would inject drains
into a latency bench — the chaos bench owns that machinery), the
garbage collector is paused during measured replays, and each topology
is replayed 4x with the latency gates comparing the MINIMUM of the
per-replay p95s.  The minimum is the right estimator here: the replay
schedule is deterministic, so wall-clock differences between same-seed
replays are pure host noise, and noise on a latency is strictly
additive — the cleanest replay is the closest observation of each
topology's true deterministic cost (the classic min-of-N bench rule).
A throwaway replay of each topology first warms the process-global
stage-program cache, so the measured zero-recompile gate checks steady
state — and because the handoff import path is the swap-in path, the
disagg run compiles nothing the warmed monolith + pool operating
points did not already own.

Gates, written into the artifact:

- ``ttft_p95`` AND ``tpot_p95`` both improve at equal chips (noise
  floors: min of 4 per-replay p95s);
- zero lost or duplicated tokens in both topologies: every admitted
  request finishes, every stream is token-identical to the one-shot
  ``generate`` reference, and the disagg streams match the monolith's
  request for request;
- zero steady-state recompiles in BOTH topologies;
- every finished disagg request crossed the handoff plane exactly once
  and the ledger conserves all of them ({pending, delivered,
  failed-with-reason} partition, nothing stranded after drain);
- all runs saw the byte-identical arrival trace (digest equality), and
  the 4 same-seed disagg replays are digest-equal, token-identical,
  and ledger-identical — the split changes the schedule, never the
  math.

Usage::

    python tools/bench_disagg.py --out BENCH_disagg.json
    python tools/bench_disagg.py --rate-scale 3.0
"""

from __future__ import annotations

import argparse
import gc
import json
import os
import sys
import time
from typing import Optional

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

MONO_REPLICAS = 4
PREFILL_REPLICAS = 3
DECODE_REPLICAS = 1
REPLAYS = 4


def run_bench(out: Optional[str], seed: int, rate_scale: float,
              epilogue: int) -> int:
    if _ROOT not in sys.path:
        sys.path.insert(0, _ROOT)
    import jax
    import numpy as np

    from skycomputing_tpu.builder import build_layer_stack
    from skycomputing_tpu.disagg import DisaggFleet
    from skycomputing_tpu.fleet import FleetSupervisor, ServingFleet
    from skycomputing_tpu.models.gpt import (
        GptConfig,
        generate,
        gpt_layer_configs,
    )
    from skycomputing_tpu.serving import Request
    from skycomputing_tpu.workload import ScenarioPlayer, get_scenario

    scenario = get_scenario("disagg_mix", seed=seed,
                            rate_scale=rate_scale)
    cfg = GptConfig(vocab_size=512, hidden_size=64,
                    num_hidden_layers=2, num_attention_heads=2,
                    max_position_embeddings=160, dropout_prob=0.0,
                    dtype="float32")
    layer_cfgs = gpt_layer_configs(cfg, deterministic=True)
    stack = build_layer_stack(layer_cfgs)
    print(f"initializing {len(layer_cfgs)}-layer GPT "
          f"(hidden={cfg.hidden_size})...", flush=True)
    params = stack.init(jax.random.key(seed),
                        np.ones((1, 8), np.int32))
    fwd = jax.jit(lambda ids: stack.apply(params, ids))

    buckets = (32, 64, 96)
    worst = scenario.max_prompt_len + scenario.max_new_tokens
    if scenario.max_prompt_len > max(buckets) or worst > 128:
        raise SystemExit(
            f"scenario {scenario.name} needs prompt<={max(buckets)} "
            f"and {worst} positions but the bench engine tops out at "
            f"128"
        )
    # paged KV so handoffs are page-aligned (the layout the export
    # checksums cover stage by stage); page geometry identical in both
    # pools — the record's geometry contract — while the decode
    # specialist runs the deep slot ledger its role is tuned for
    engine_kwargs = dict(num_slots=2, max_len=128, buckets=buckets,
                         prefill_batch=1, kv_layout="paged",
                         page_size=8)
    decode_kwargs = dict(num_slots=4, num_pages=128)
    if len(jax.devices()) < MONO_REPLICAS:
        raise SystemExit(
            f"bench needs {MONO_REPLICAS} devices for the equal-chips "
            f"comparison, found {len(jax.devices())} (run under "
            f"XLA_FLAGS=--xla_force_host_platform_device_count=8)"
        )

    def supervisor():
        # heartbeat/crash supervision stays on; the latency threshold
        # is parked out of reach — a wall-clock sickness probe firing
        # mid-replay would drain a replica INTO the latency
        # measurement (the chaos bench is where supervision is the
        # subject)
        return FleetSupervisor(check_every=1, heartbeat_misses=1,
                               sick_threshold=1e9, k_checks=3)

    def make_fleet(mode):
        if mode == "monolithic":
            return ServingFleet(layer_cfgs, params,
                                replicas=MONO_REPLICAS,
                                engine_kwargs=dict(engine_kwargs),
                                supervisor=supervisor())
        return DisaggFleet(layer_cfgs, params,
                           prefill_replicas=PREFILL_REPLICAS,
                           decode_replicas=DECODE_REPLICAS,
                           engine_kwargs=dict(engine_kwargs),
                           decode_kwargs=dict(decode_kwargs),
                           supervisor=supervisor())

    def warm(fleet):
        """Bucket warmup + counter reset: measured replays start from
        a steady-state engine, and ``stats.compiles`` afterwards counts
        exactly the steady-state recompiles the gate forbids."""
        fleet.run([
            Request(prompt=np.full((b - 2,), b + 1, np.int32),
                    max_new_tokens=2)
            for b in buckets for _ in range(2)
        ])
        fleet.reset_slo_windows()
        for rep in fleet.replicas:
            if rep.engine is not None:
                rep.engine.stats.compiles = 0

    def play(fleet):
        def probe():
            return dict(tick=fleet.tick,
                        healthy=len(fleet.healthy_replicas),
                        pending=fleet.stats.pending)

        player = ScenarioPlayer(scenario, fleet, sample_fn=probe)
        gc.collect()
        gc.disable()
        t0 = time.perf_counter()
        try:
            report = player.play()
            # idle epilogue: in-flight handoffs deliver and decode
            # rows drain inside the replay, as a production loop
            # would keep ticking
            for _ in range(int(epilogue)):
                fleet.step()
                report.timeline.append(probe())
        finally:
            gc.enable()
        report.wall_s = time.perf_counter() - t0
        return report

    def compiles(fleet) -> int:
        return sum(rep.engine.stats.compiles
                   for rep in fleet.replicas if rep.engine is not None)

    def handoff_counters(fleet):
        keys = ("handoffs_out", "handoffs_in", "handoff_failures",
                "handoff_bytes")
        total = dict.fromkeys(keys, 0)
        for rep in fleet.replicas:
            if rep.engine is None:
                continue
            snap = rep.engine.stats.snapshot()
            for k in keys:
                total[k] += snap[k]
        return total

    def streams(report):
        return [v.request.output().tolist() for v in report.finished]

    # --- cache warmup: one throwaway replay per topology -----------------
    # pays every process-global stage-program compile either operating
    # point can demand, so all measured replays start cache-warm
    print("warming the stage-program cache (throwaway replays)...",
          flush=True)
    warm_compiles = 0
    for mode in ("monolithic", "disagg"):
        throwaway = make_fleet(mode)
        warm(throwaway)
        play(throwaway)
        warm_compiles += compiles(throwaway)
    print(f"  cache warm ({warm_compiles} compiles absorbed)",
          flush=True)

    # --- measured replays: 4x each topology, INTERLEAVED -----------------
    # host noise is strongly autocorrelated (load drifts over seconds),
    # so alternating topologies makes both sample the same host epochs
    # — a drift window cannot land on one topology's replays only
    runs = {}
    replays = {"monolithic": [], "disagg": []}
    for i in range(REPLAYS):
        for mode in ("monolithic", "disagg"):
            fleet = make_fleet(mode)
            warm(fleet)
            print(f"running {scenario.name} [{mode} {i + 1}/"
                  f"{REPLAYS}]...", flush=True)
            report = play(fleet)
            replays[mode].append((fleet, report))
    for mode in ("monolithic", "disagg"):
        per_run = []
        for fleet, report in replays[mode]:
            total = report.summary()["total"]
            per_run.append(dict(
                summary_total=total,
                wall_s=round(report.wall_s, 3),
                steady_state_compiles=compiles(fleet),
            ))
        fleet, report = replays[mode][0]
        # min across same-seed replays = the noise floor: the schedule
        # is deterministic, so inter-replay spread is host noise, and
        # noise only ever ADDS wall time
        doc = dict(
            replays=per_run,
            ttft_p95_s_floor=min(
                r["summary_total"]["ttft_p95_s"] for r in per_run
            ),
            tpot_p95_s_floor=min(
                r["summary_total"]["tpot_p95_s"] for r in per_run
            ),
            fleet_stats=fleet.stats.snapshot(),
            handoff_counters=handoff_counters(fleet),
        )
        if mode == "disagg":
            doc["ledger"] = fleet.ledger.audit()
        runs[mode] = doc
        t = per_run[0]["summary_total"]
        print(f"  {mode}: finished {t['finished']}/{t['arrivals']}, "
              f"ttft_p95 floor {doc['ttft_p95_s_floor']:.4f}s, "
              f"tpot_p95 floor {doc['tpot_p95_s_floor']:.4f}s",
              flush=True)

    # --- verdicts --------------------------------------------------------
    def identity_ok(report) -> bool:
        for v in report.finished:
            r = v.request
            ref = generate(fwd, r.prompt[None],
                           max_new_tokens=r.max_new_tokens,
                           context_length=160)[0]
            if not np.array_equal(r.output(), ref):
                return False
        return True

    mono_fleet, mono_rep = replays["monolithic"][0]
    dis_fleet, dis_rep = replays["disagg"][0]
    ledger = runs["disagg"]["ledger"]

    zero_lost = all(
        len(report.finished) == len(report.admitted)
        and fleet.stats.failed == 0
        for mode in ("monolithic", "disagg")
        for fleet, report in replays[mode]
    )
    # zero rejections -> both admitted lists follow the trace order, so
    # stream k in one topology is stream k in the other
    cross_identical = (
        mono_fleet.stats.rejected == 0
        and dis_fleet.stats.rejected == 0
        and streams(mono_rep) == streams(dis_rep)
    )
    dis_reports = [rep for _, rep in replays["disagg"]]
    dis_fleets = [fl for fl, _ in replays["disagg"]]
    gates = dict(
        ttft_p95_improved=bool(
            runs["disagg"]["ttft_p95_s_floor"]
            < runs["monolithic"]["ttft_p95_s_floor"]
        ),
        tpot_p95_improved=bool(
            runs["disagg"]["tpot_p95_s_floor"]
            < runs["monolithic"]["tpot_p95_s_floor"]
        ),
        zero_lost_tokens=bool(zero_lost),
        token_identical=bool(
            identity_ok(mono_rep) and identity_ok(dis_rep)
            and cross_identical
        ),
        zero_steady_state_recompiles=bool(all(
            r["steady_state_compiles"] == 0
            for mode in ("monolithic", "disagg")
            for r in runs[mode]["replays"]
        )),
        every_request_handed_off=bool(
            ledger["delivered_total"] == len(dis_rep.finished)
            and ledger["failed_total"] == 0
        ),
        ledger_conserved=bool(
            ledger["conservation_ok"] and ledger["pending"] == 0
        ),
        workload_replayable=bool(mono_rep.digest == dis_rep.digest),
        replay_deterministic=bool(
            all(r.digest == dis_rep.digest for r in dis_reports)
            and all(streams(r) == streams(dis_rep)
                    for r in dis_reports)
            and all(f.ledger.audit() == ledger for f in dis_fleets)
        ),
    )
    passed = all(gates.values())

    report_doc = dict(
        bench="disagg_vs_monolithic",
        device_kind=jax.devices()[0].device_kind,
        model=dict(cfg.to_dict()),
        fleet=dict(
            chips_per_side=MONO_REPLICAS,
            monolithic=dict(replicas=MONO_REPLICAS),
            disagg=dict(prefill_replicas=PREFILL_REPLICAS,
                        decode_replicas=DECODE_REPLICAS,
                        decode_kwargs=decode_kwargs),
            **engine_kwargs,
        ),
        scenario=scenario.to_dict(),
        rate_scale=rate_scale,
        epilogue_ticks=epilogue,
        replays_per_topology=REPLAYS,
        digest=dis_rep.digest,
        warmup_compiles_absorbed=warm_compiles,
        notes=(
            "equal chips: 4 single-device monolithic replicas vs 3 "
            "prefill + 1 deep-slab decode specialist on the same "
            "trace; latency gates compare noise floors (min of 4 "
            "per-replay p95s over INTERLEAVED gc-free replays — "
            "same-seed replays are schedule-deterministic, so spread "
            "is additive host noise, and alternating topologies makes "
            "both sample the same host epochs); throwaway replays "
            "pre-warm the process-global "
            "stage-program cache, so zero steady-state compiles is a "
            "both-topology fact and the handoff import path (the "
            "engine's swap-in path) demonstrably adds no shapes of "
            "its own"
        ),
        runs=runs,
        gates=gates,
        passed=passed,
    )
    if out:
        with open(out, "w") as fh:
            json.dump(report_doc, fh, indent=2)
        print(f"# wrote {out}")
    print(f"ledger: {ledger['delivered_total']} delivered / "
          f"{ledger['failed_total']} failed / "
          f"{ledger['pending']} pending")
    print(f"gates: {gates}")
    print(f"# {'PASS' if passed else 'FAIL'}")
    return 0 if passed else 1


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--out", default=None,
                        help="BENCH-style JSON artifact path")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--rate-scale", type=float, default=2.5,
                        help="arrival-rate multiplier on disagg_mix "
                             "(sized so the offered decode population "
                             "fits the specialist's 16-row slab)")
    parser.add_argument("--epilogue", type=int, default=60,
                        help="idle fleet ticks after the trace drains "
                             "(where in-flight handoffs deliver)")
    args = parser.parse_args(argv)
    return run_bench(args.out, args.seed, args.rate_scale,
                     args.epilogue)


if __name__ == "__main__":
    sys.exit(main())
