#!/usr/bin/env python
"""skydet CLI: determinism & digest-integrity analysis for the replay planes.

Usage::

    python -m tools.skydet skycomputing_tpu/ tests/ --strict
    python -m tools.skydet skycomputing_tpu/ --format=json
    python -m tools.skydet --changed-only            # pre-commit mode
    python -m tools.skydet tests/ --select=DET006

Six rule families over the AST, configured from the skyaudit MANIFEST's
determinism declarations (rule catalog in ``docs/static_analysis.md``):

- DET001/DET002: clock & seed discipline — wall-clock reads in declared
  deterministic modules, global-state RNG, one-rng-per-plan;
- DET003/DET004: digest integrity — excluded fields and unsorted
  iteration on digest paths, ``id()``/``hash()`` in content identities;
- DET005: program-cache key completeness (the serving/mesh hole);
- DET006: the test-flakiness gate (wall-clock asserts, raw sleeps).

The run also proves every MANIFEST ``pure_stdlib`` module still loads
by file path on a bare runner (failures report as DET000) — the
contract the smoke gates and this very CLI depend on.

Exit codes: 0 clean, 1 findings, 2 bad invocation — same contract as
skylint/skyaudit.  ``--changed-only`` checks only files git says
changed (every skydet rule is per-file, so no whole-graph re-scan is
needed; the load check still runs, it is milliseconds).

Suppression: ``# skydet: disable=DET001`` on the finding's line; the
shipped gate runs with ZERO suppressions — exemptions live in the
MANIFEST with a rationale (``id_key_pins``,
``wallclock_test_sanctions``, ``rng_global_sanctions``).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)

from tools._loader import load_by_path  # noqa: E402 - pure stdlib helper

_engine = load_by_path("skydet_engine", "skycomputing_tpu", "analysis",
                       "determinism.py")
DetConfig = _engine.DetConfig
RULES = _engine.RULES
check_paths = _engine.check_paths
check_pure_stdlib_loads = _engine.check_pure_stdlib_loads

#: default scan scope when no paths are given (the CI gate's scope)
DEFAULT_PATHS = ("skycomputing_tpu", "tests")


def _parse_rule_set(spec: str, strict: bool) -> set:
    ids = {s.strip().upper() for s in spec.split(",") if s.strip()}
    unknown = ids - set(RULES) - {"DET000"}
    if unknown:
        msg = f"unknown rule id(s): {', '.join(sorted(unknown))}"
        if strict:
            print(f"skydet: error: {msg}", file=sys.stderr)
            raise SystemExit(2)
        print(f"skydet: warning: {msg}", file=sys.stderr)
    return ids


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="skydet", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("paths", nargs="*",
                    help="files and/or directories to check "
                         f"(default: {' '.join(DEFAULT_PATHS)})")
    ap.add_argument("--strict", action="store_true",
                    help="fail on unknown rule ids; intended for CI")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--select", default=None,
                    help="comma-separated rule ids to run (default: all)")
    ap.add_argument("--ignore", default="",
                    help="comma-separated rule ids to skip")
    ap.add_argument("--show-suppressed", action="store_true",
                    help="also report suppressed findings (marked)")
    ap.add_argument("--changed-only", action="store_true",
                    help="check only files git says changed (all skydet "
                         "rules are per-file); explicit FILE args "
                         "override git")
    ap.add_argument("--no-load-check", action="store_true",
                    help="skip the pure_stdlib file-path load "
                         "verification")
    args = ap.parse_args(argv)

    paths = args.paths or [
        p for p in (os.path.join(_ROOT, d) for d in DEFAULT_PATHS)
        if os.path.exists(p)
    ]
    for p in paths:
        if not os.path.exists(p):
            print(f"skydet: error: no such path: {p}", file=sys.stderr)
            return 2

    if args.changed_only:
        _changed = load_by_path("skydet_changed", "tools", "changed.py")
        changed = _changed.changed_python_files(paths, cwd=_ROOT)
        if changed is None:
            print("skydet: --changed-only: git unavailable, checking "
                  "everything", file=sys.stderr)
        elif not changed:
            print("skydet: --changed-only: no python changes, clean",
                  file=sys.stderr)
            if args.format == "json":
                print(json.dumps({"findings": [], "counts": {},
                                  "ok": True}, indent=2))
            return 0
        else:
            paths = changed

    config = DetConfig(
        select=_parse_rule_set(args.select, args.strict)
        if args.select else None,
        ignore=_parse_rule_set(args.ignore, args.strict)
        if args.ignore else set(),
        include_suppressed=args.show_suppressed,
    )
    findings = check_paths(paths, config)
    if not args.no_load_check:
        findings = check_pure_stdlib_loads() + findings
    active = [f for f in findings if not f.suppressed]

    if args.format == "json":
        counts: dict = {}
        for f in active:
            counts[f.rule] = counts.get(f.rule, 0) + 1
        print(json.dumps({
            "findings": [f.to_dict() for f in findings],
            "counts": counts,
            "ok": not active,
        }, indent=2))
    else:
        for f in findings:
            tag = " (suppressed)" if f.suppressed else ""
            print(f.format() + tag)
        if active:
            print(f"skydet: {len(active)} finding(s) in "
                  f"{len({f.path for f in active})} file(s)",
                  file=sys.stderr)
        else:
            print("skydet: clean", file=sys.stderr)

    return 1 if active else 0


if __name__ == "__main__":
    sys.exit(main())
