#!/usr/bin/env python
"""CI smoke for the chunked-prefill budget policy (pure stdlib).

Loads ``serving/paging.py`` by file path (the skylint idiom, so the
lint job exercises it on a bare runner, no jax/numpy installed) and
drives :class:`ChunkBudgetPolicy` through its decision table: the
decode-protecting bound, the idle opening, the starvation guarantee,
and the constructor validation.  This is the pure-scheduling half of
chunked prefill — the engine's chunk waves obey exactly what this
policy decides, so drift here is a latency regression waiting to ship.

Usage::

    python tools/chunk_smoke.py
"""

from __future__ import annotations

import importlib.util
import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_by_path(name: str, *parts: str):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_ROOT, *parts)
    )
    module = importlib.util.module_from_spec(spec)
    sys.modules[name] = module
    spec.loader.exec_module(module)
    return module


try:
    from skycomputing_tpu.serving import paging as _paging
except Exception:  # pragma: no cover - exercised on bare CI runners
    _paging = _load_by_path(
        "_skytpu_chunk_smoke", "skycomputing_tpu", "serving", "paging.py"
    )


def check(cond, message):
    if not cond:
        print(f"FAIL: {message}")
        raise SystemExit(1)
    print(f"  ok: {message}")


def main() -> int:
    Policy = _paging.ChunkBudgetPolicy

    print("decode-protecting budget:")
    policy = Policy(32, max_chunk_rows=2, idle_chunk_rows=8)
    check(policy.rows_for_tick(pending=0, decoding=4) == 0,
          "no pending chunk work -> zero rows")
    check(policy.rows_for_tick(pending=10, decoding=4) == 2,
          "live decoders cap the tick at max_chunk_rows")
    check(policy.rows_for_tick(pending=1, decoding=4) == 1,
          "budget never exceeds pending work")
    check(policy.starvation_bound_tokens() == 64,
          "starvation bound = max_chunk_rows x prefill_chunk")

    print("idle opening:")
    check(policy.rows_for_tick(pending=10, decoding=0) == 8,
          "nothing decoding -> the idle budget applies")
    check(policy.rows_for_tick(pending=3, decoding=0) == 3,
          "idle budget still never exceeds pending")
    default = Policy(16)
    check(default.max_chunk_rows == 1
          and default.idle_chunk_rows >= default.max_chunk_rows,
          "defaults: one row per busy tick, idle never tighter")

    print("validation:")
    for bad in (lambda: Policy(0),
                lambda: Policy(16, max_chunk_rows=0),
                lambda: Policy(16, max_chunk_rows=4, idle_chunk_rows=2)):
        try:
            bad()
        except ValueError:
            pass
        else:
            check(False, "invalid policy construction must raise")
    check(True, "zero/negative knobs and idle < busy all rejected")

    print("chunk-policy smoke: all checks passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
