#!/usr/bin/env python
"""Validate the bench's schedule model against measured end-to-end steps.

``bench.py`` scores allocations with the GPipe fill-drain model

    t_step = sum_k tau_k / M  +  (M-1)/M * max_k tau_k

built from per-stage times measured in isolation.  This tool checks the
model's two load-bearing claims against *actually measured* end-to-end
steps, in whichever regime the available hardware can falsify:

1. **Composition** (any device count): the isolated per-stage taus must add
   up to the measured end-to-end pipelined train_step.  On serial devices
   (one chip, or XLA's fake CPU devices — which execute one at a time, see
   probe below) the schedule collapses to sum(tau); on parallel devices it
   is the full model.  A mismatch would mean the per-stage measurements
   don't compose (dispatch gaps, queueing pollution) and the bench's taus
   are fiction.
2. **Fill-drain structure**: the compiled SPMD pipeline's wall time across
   microbatch counts M must follow (M + S - 1) ticks of size B/M — i.e.
   wall(M) ~ (M + S - 1) / M after normalizing per-microbatch work.  This
   validates the bubble term the model charges, independent of device
   parallelism (serial devices scale every tick by S, which divides out in
   the ratio).

Run under the CPU-8 test env:
    env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
        XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python tools/validate_schedule_model.py
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
import optax


def probe_device_concurrency(devices) -> float:
    """Ratio all-N-async / single (1.0 = perfect overlap, N = serial)."""
    f = jax.jit(lambda a: jnp.tanh(a @ a).sum())
    xs = [jax.device_put(jnp.ones((1200, 1200)), d) for d in devices]
    for x in xs:
        f(x).block_until_ready()
    t0 = time.perf_counter()
    f(xs[0]).block_until_ready()
    t_one = time.perf_counter() - t0
    t0 = time.perf_counter()
    rs = [f(x) for x in xs]
    jax.block_until_ready(rs)
    t_all = time.perf_counter() - t0
    return t_all / t_one


def schedule_step_time(taus, M: int) -> float:
    taus = np.asarray(taus, dtype=np.float64)
    return float(taus.sum() / M + (M - 1) / M * taus.max())


def validate_composition(devices, serial: bool, preset: str = "base") -> float:
    """Measured end-to-end MPMD train_step vs the tau-built model.

    ``preset`` scales the model: the artifact run uses "base"; the CI
    smoke (tests/test_schedule_model.py) uses "tiny" for wall time.
    """
    from skycomputing_tpu.dynamics import (
        Allocator,
        ParameterServer,
        WorkerManager,
    )
    from skycomputing_tpu.models import bert_config, bert_layer_configs
    from skycomputing_tpu.ops import cross_entropy_loss
    from skycomputing_tpu.parallel import PipelineModel

    n_stages = min(4, len(devices))
    cfg = bert_config(
        preset, dtype="float32", hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0,
    )
    model_cfg = bert_layer_configs(cfg, num_encoder_units=n_stages * 2,
                                   num_classes=3, deterministic=True)
    wm = WorkerManager()
    # in the serial regime, pin every stage to ONE device: fake CPU devices
    # share a thread pool and overlap partially (small ops of one stage
    # backfill cores another stage's matmul leaves idle), which is neither
    # the serial nor the parallel model; a single device queue serializes
    # for real, so measured == sum(tau) is a clean falsifiable claim
    wm.load_worker_pool_from_config(
        [dict(name=f"n{i}",
              device_config=dict(device_index=0 if serial else i),
              extra_config={}) for i in range(n_stages)]
    )
    Allocator(model_cfg, wm, None, None).even_allocate()

    rng = np.random.default_rng(0)
    B, L, M = 16, 128, 4
    ids = rng.integers(5, cfg.vocab_size, (B, L)).astype(np.int32)
    data = (ids, np.zeros_like(ids), np.ones_like(ids))
    labels = rng.integers(0, 3, (B,)).astype(np.int32)

    ps = ParameterServer(model_cfg, example_inputs=data,
                         rng=jax.random.key(0))
    model = PipelineModel(wm, ps, optax.sgd(1e-3), cross_entropy_loss,
                          devices=devices, num_microbatches=M)

    model.train_step(data, labels, rng=jax.random.key(0))  # warm compile
    # measure taus at MICROBATCH size — the schedule executes B/M slices,
    # and CPU throughput is not linear in batch at these sizes, so
    # full-batch taus would confound the composition check with a
    # batch-scaling error that has nothing to do with the schedule
    mb = tuple(x[: B // M] for x in data)
    taus_mb = model.measure_stage_times(mb, repeats=5, inner_iters=2)
    taus = [t * M for t in taus_mb]  # full-batch-equivalent stage times

    samples = []
    for i in range(5):
        model.train_step(data, labels, rng=jax.random.key(i))
        s = model.stats
        samples.append(s.forward_s + s.backward_s)
    measured = float(np.median(samples))

    # the schedule model charges fwd+bwd compute only; the real step also
    # pays (M-1) gradient-tree accumulations per stage and M loss/dlogits
    # evaluations.  On TPU these are bandwidth-trivial next to the matmuls;
    # on CPU at this scale they are not — measure and charge them so the
    # comparison isolates the *schedule*, not the platform's add cost.
    t_acc = 0.0
    for stage in model.stages:
        g = jax.tree_util.tree_map(jnp.zeros_like, stage.params)
        add = jax.jit(
            lambda a, b: jax.tree_util.tree_map(jnp.add, a, b)
        )
        jax.block_until_ready(add(g, g))
        t0 = time.perf_counter()
        for _ in range(3):
            out = add(g, g)
        jax.block_until_ready(out)
        t_acc += (time.perf_counter() - t0) / 3 * (M - 1)

    predicted_sched = (
        float(np.sum(taus)) if serial else schedule_step_time(taus, M)
    )
    predicted = predicted_sched + t_acc
    delta = abs(measured - predicted) / measured
    mode = "sum(tau) [serial devices]" if serial else f"GPipe model M={M}"
    print(
        f"composition: measured={measured:.3f}s predicted={predicted:.3f}s"
        f" (schedule {predicted_sched:.3f}s + accumulation {t_acc:.3f}s)"
        f" ({mode}) delta={delta * 100:.1f}%"
        f"  taus={[round(t, 3) for t in taus]}",
        flush=True,
    )
    return delta


def validate_fill_drain(devices) -> float:
    """Compiled pipeline wall(M) must track (M + S - 1)/M per-mb ticks."""
    from skycomputing_tpu.models import bert_config
    from skycomputing_tpu.parallel import make_pipeline_mesh
    from skycomputing_tpu.parallel.spmd import CompiledBertPipeline

    S = min(4, len(devices))
    mesh = make_pipeline_mesh(S, devices)
    cfg = bert_config(
        "base", dtype="float32", hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0,
    )
    rng = np.random.default_rng(0)
    B, L = 16, 128
    ids = rng.integers(5, cfg.vocab_size, (B, L)).astype(np.int32)
    types, mask = np.zeros_like(ids), np.ones_like(ids)

    walls, models = {}, {}
    for M in (2, 4, 8):
        pipe = CompiledBertPipeline(cfg, mesh, units_per_stage=2,
                                    num_microbatches=M)
        params = pipe.init(jax.random.key(0), ids, types, mask)
        logits_fn = jax.jit(
            lambda p, a, b, c, pipe=pipe: pipe._logits(p, a, b, c)
        )
        jax.block_until_ready(logits_fn(params, ids, types, mask))
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(2):
                out = logits_fn(params, ids, types, mask)
            jax.block_until_ready(out)
            best = min(best, (time.perf_counter() - t0) / 2)
        walls[M] = best
        # per-microbatch tick work is B/M -> normalize: model says
        # wall(M) proportional to (M + S - 1) * (B / M)
        models[M] = (M + S - 1) / M
        print(f"fill-drain: M={M} wall={best * 1e3:.1f}ms "
              f"model-shape={(M + S - 1) / M:.3f}", flush=True)

    # compare measured wall ratios against model-shape ratios, M=2 as base
    worst = 0.0
    for M in (4, 8):
        measured_ratio = walls[M] / walls[2]
        model_ratio = models[M] / models[2]
        delta = abs(measured_ratio - model_ratio) / model_ratio
        worst = max(worst, delta)
        print(
            f"fill-drain ratio M={M}/M=2: measured={measured_ratio:.3f} "
            f"model={model_ratio:.3f} delta={delta * 100:.1f}%",
            flush=True,
        )
    return worst


def main() -> int:
    devices = jax.devices()
    ratio = probe_device_concurrency(devices[: min(4, len(devices))])
    serial = ratio > 0.6 * min(4, len(devices))
    print(
        f"device concurrency probe: ratio={ratio:.2f} -> "
        f"{'serial' if serial else 'parallel'} execution", flush=True,
    )
    d1 = validate_composition(devices, serial)
    d2 = validate_fill_drain(devices)
    ok = d1 < 0.15 and d2 < 0.15
    print(f"schedule model validation: "
          f"composition delta {d1 * 100:.1f}%, "
          f"fill-drain worst delta {d2 * 100:.1f}% -> "
          f"{'OK (<15%)' if ok else 'FAIL (>=15%)'}", flush=True)
    out_path = os.environ.get("SKYTPU_SCHEDVAL_JSON")
    if out_path:
        import json
        import datetime

        with open(out_path, "w") as fh:
            json.dump(
                {
                    "composition_delta_pct": round(d1 * 100, 2),
                    "fill_drain_worst_delta_pct": round(d2 * 100, 2),
                    "serial_devices": bool(serial),
                    "concurrency_ratio": round(ratio, 3),
                    "platform": devices[0].platform,
                    "device_kind": devices[0].device_kind,
                    "n_devices": len(devices),
                    "threshold_pct": 15.0,
                    "ok": bool(ok),
                    "ts": datetime.datetime.now().isoformat(
                        timespec="seconds"
                    ),
                },
                fh, indent=1,
            )
            fh.write("\n")
        print(f"schedule validation artifact -> {out_path}", flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
