#!/usr/bin/env python
"""Run the experiment config ladder and summarize phase timings.

    python tools/run_ladder.py                   # all five configs
    python tools/run_ladder.py --only even_4 optimal_8
    SKYTPU_PRESET=tiny python tools/run_ladder.py --max-iters 3   # smoke

The single-process analog of the reference's Slurm experiment matrix: each
config runs through the full profile -> allocate -> train path in a fresh
subprocess (configs mutate env), and the table reports steady-state phase
means from the runner's logs.
"""

from __future__ import annotations

import argparse
import os
import re
import subprocess
import sys

HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CONFIGS = [
    "even_4",
    "optimal_8",
    "dynamic_8_stim",
    "optimal_32_96layer",
    "optimal_64_160layer",
]


def run_one(name: str, max_iters: int, log_root: str,
            timeout: float = 3600) -> dict:
    import shutil

    # fresh logs per invocation: the runner's Logger appends, and stale
    # lines from a previous ladder run would corrupt the phase means
    shutil.rmtree(log_root, ignore_errors=True)

    env = dict(os.environ)
    env["SKYTPU_MAX_ITERS"] = str(max_iters)
    env["SKYTPU_LOG_ROOT"] = log_root
    try:
        proc = subprocess.run(
            [sys.executable, os.path.join(HERE, "experiment", "launch.py"),
             "-c", os.path.join(HERE, "experiment", "configs", f"{name}.py")],
            env=env, capture_output=True, text=True, timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        print(f"  {name}: timed out after {timeout:.0f}s")
        return {"config": name, "exit": "timeout"}
    if proc.returncode != 0 and proc.stderr:
        tail = "\n".join(proc.stderr.splitlines()[-5:])
        print(f"  {name} failed (exit {proc.returncode}); stderr tail:\n"
              f"{tail}")
    result = {"config": name, "exit": proc.returncode}

    # find this run's allocation.log (layout encodes the matrix cell)
    # loss matches \S+ (a diverged rung prints 'loss: nan' — its timings
    # must still be recorded); non-finite losses are kept in the record so
    # the divergence is visible
    phase = re.compile(
        r"loss: (\S+) \| forward time: ([\d.]+) \| "
        r"backward time: ([\d.]+) \| step time: ([\d.]+)"
    )
    alloc = re.compile(r"worker rank (\d+): layers \((\d+), (\d+)\)")
    loss, fwd, bwd, step = [], [], [], []
    layers_by_rank = {}
    for root, _, files in os.walk(log_root):
        for f in files:
            if f != "allocation.log":
                continue
            for line in open(os.path.join(root, f)):
                m = phase.search(line)
                if m:
                    try:
                        loss.append(float(m.group(1)))
                    except ValueError:
                        loss.append(None)
                    fwd.append(float(m.group(2)))
                    bwd.append(float(m.group(3)))
                    step.append(float(m.group(4)))
                m = alloc.search(line)
                if m:
                    layers_by_rank[int(m.group(1))] = (
                        int(m.group(3)) - int(m.group(2))
                    )
    result["losses"] = loss
    if layers_by_rank:
        result["allocation"] = [
            layers_by_rank[r] for r in sorted(layers_by_rank)
        ]
    if len(fwd) > 1:  # drop the compile-heavy first iteration
        fwd, bwd, step = fwd[1:], bwd[1:], step[1:]
    if fwd:
        result.update(
            fwd_s=sum(fwd) / len(fwd),
            bwd_s=sum(bwd) / len(bwd),
            step_s=sum(step) / len(step),
            iters=len(fwd),
        )
    return result


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--only", nargs="*", default=None,
                        help="subset of config names (without .py)")
    parser.add_argument("--max-iters", type=int, default=5)
    parser.add_argument("--log-root", default="/tmp/skytpu_ladder")
    parser.add_argument("--timeout", type=float, default=3600,
                        help="per-rung wall budget (s)")
    parser.add_argument("--json", default=None,
                        help="write the per-rung records to this JSON file")
    args = parser.parse_args()

    names = args.only or CONFIGS
    unknown = [n for n in names if n not in CONFIGS]
    if unknown:
        print(f"unknown configs: {unknown}; known: {CONFIGS}")
        return 2

    rows = []
    for i, name in enumerate(names):
        log_root = os.path.join(args.log_root, name)
        print(f"[{i + 1}/{len(names)}] {name} ...", flush=True)
        rows.append(run_one(name, args.max_iters, log_root,
                            timeout=args.timeout))

    if args.json:
        import json

        with open(args.json, "w") as fh:
            json.dump(
                dict(
                    max_iters=args.max_iters,
                    preset=os.getenv("SKYTPU_PRESET", "(config default)"),
                    platform=os.getenv("JAX_PLATFORMS", "(default)"),
                    rungs=rows,
                ),
                fh, indent=2,
            )
        print(f"wrote {args.json}")

    print(f"\n{'config':24s} {'exit':>7s} {'fwd_s':>9s} {'bwd_s':>9s} "
          f"{'step_s':>9s}")
    for r in rows:
        if "fwd_s" in r:
            print(f"{r['config']:24s} {r['exit']!s:>7s} {r['fwd_s']:9.4f} "
                  f"{r['bwd_s']:9.4f} {r['step_s']:9.4f}")
        else:
            print(f"{r['config']:24s} {r['exit']!s:>7s} {'-':>9s} {'-':>9s} "
                  f"{'-':>9s}")
    return 0 if all(r["exit"] == 0 for r in rows) else 1


if __name__ == "__main__":
    sys.exit(main())
