#!/usr/bin/env python
"""CI smoke for the chaos plane's fault-plan core (pure stdlib).

Loads ``chaos/plan.py`` by file path (the skylint idiom, so the lint
job exercises it on a bare runner, no jax/numpy installed) and drives
the replayability contract end to end: build-time validation of kinds,
targets and params, the seeded jitter lowering, byte-identical resolved
schedules at equal seed, divergent digests at different seeds, and
every named catalog plan's structural promises (a paired workload
scenario, a sane recovery budget, kind/target consistency).  Drift in
any of these silently changes every committed chaos campaign — this
smoke is what makes "same seed, same fault schedule, forever" a CI
fact instead of a docstring.

Usage::

    python tools/chaos_smoke.py
"""

from __future__ import annotations

import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)

from tools._loader import load_module  # noqa: E402 - pure stdlib helper

_cp = load_module("skycomputing_tpu.chaos.plan",
                  fallback_name="_skytpu_chaos_smoke")
# the workload pairing must resolve against the scenario catalog, and
# that catalog is itself pure stdlib — load it the same way
_wl = load_module("skycomputing_tpu.workload.scenario",
                  fallback_name="_skytpu_chaos_smoke_wl")


def check(cond, message):
    if not cond:
        print(f"FAIL: {message}")
        raise SystemExit(1)
    print(f"  ok: {message}")


def main() -> int:
    FaultEvent, FaultPlan = _cp.FaultEvent, _cp.FaultPlan

    print("event validation:")
    for bad in (
        lambda: FaultEvent(tick=-1, kind=_cp.REPLICA_CRASH),
        lambda: FaultEvent(tick=0, kind="meteor_strike"),
        lambda: FaultEvent(tick=0, kind=_cp.REPLICA_CRASH,
                           target="fleet"),
        lambda: FaultEvent(tick=0, kind=_cp.ADMISSION_BLIP,
                           target="index:0"),
        lambda: FaultEvent(tick=0, kind=_cp.REPLICA_CRASH,
                           target="index:nope"),
        lambda: FaultEvent(tick=0, kind=_cp.STAGE_SLOWDOWN,
                           params=(("seconds", -1.0),)),
        lambda: FaultEvent(tick=0, kind=_cp.REFORM_FAILURE,
                           params=(("builds", 0),)),
        lambda: FaultEvent(tick=0, kind=_cp.REPLICA_CRASH,
                           params=(("seconds", 1.0),)),
    ):
        try:
            bad()
        except ValueError:
            pass
        else:
            check(False, "malformed events must raise at build time")
    check(True, "malformed kinds/targets/params rejected at build "
                "time")

    print("jitter lowering:")
    plan = FaultPlan(
        name="smoke", seed=3, scenario="tenant_mix",
        recovery_budget_ticks=10,
        events=(
            FaultEvent(tick=5, kind=_cp.REPLICA_CRASH,
                       jitter_ticks=3),
            FaultEvent(tick=9, kind=_cp.REPLICA_CRASH,
                       target="index:1"),
        ),
    )
    r1, r2 = plan.resolved_events(), plan.resolved_events()
    check([e.key() for e in r1] == [e.key() for e in r2],
          "same plan -> byte-identical resolved schedule")
    check(2 <= r1[0].tick <= 8 and r1[0].jitter_ticks == 0,
          "jitter stays within +/- jitter_ticks and lowers to 0")
    check(r1[1].tick == 9,
          "events without jitter keep their declared tick")
    check(plan.digest() == plan.digest(), "digest is stable")
    check(plan.digest() != plan.with_seed(4).digest(),
          "a different seed is a different campaign")
    check(plan.last_declared_tick == 9,
          "last_declared_tick bounds the pre-jitter schedule")

    print("catalog:")
    names = _cp.fault_plan_names()
    check(names == ["replica_crash_storm", "rolling_stragglers",
                    "mid_drain_kill", "swap_corruption",
                    "reform_flap", "overload_then_crash",
                    "prefill_kill_mid_handoff"],
          f"the seven named plans are registered ({names})")
    scenario_names = set(_wl.scenario_names())
    for name in names:
        p = _cp.get_fault_plan(name)
        check(p.name == name and p.events,
              f"{name}: builds with events")
        check(p.scenario in scenario_names,
              f"{name}: pairs with catalog scenario {p.scenario!r}")
        check(p.recovery_budget_ticks >= 1 and p.replicas >= 1,
              f"{name}: recovery budget and fleet shape are sane")
        check(p.digest() == _cp.get_fault_plan(name).digest(),
              f"{name}: schedule replays byte-identically")
        check(p.digest() != _cp.get_fault_plan(name, seed=1).digest(),
              f"{name}: seed participates in the digest")
        sc = _wl.get_scenario(p.scenario, seed=p.scenario_seed,
                              rate_scale=p.rate_scale,
                              ticks_scale=p.ticks_scale)
        budget_end = p.last_declared_tick + p.recovery_budget_ticks
        check(sc.total_ticks <= budget_end + 200,
              f"{name}: paired trace ends near the campaign "
              f"({sc.total_ticks} ticks vs last fault "
              f"{p.last_declared_tick})")
    try:
        _cp.get_fault_plan("no_such_campaign")
    except ValueError as exc:
        check("catalog" in str(exc), "unknown name lists the catalog")
    else:
        check(False, "unknown plan name must raise")

    print("chaos smoke: all checks passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
