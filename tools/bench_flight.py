#!/usr/bin/env python
"""Flight-recorder bench: black-box overhead + incident-plane gates.

The flight recorder (``telemetry/flight.py``) promises to be cheap
enough to leave on and deterministic enough to trust in a postmortem;
the incident plane (``telemetry/incidents.py``) promises to open
incidents on real degradation and stay silent otherwise.  This bench
turns all four promises into a committed verdict
(``BENCH_flight.json``):

- **overhead**: the ``diurnal_ramp`` scenario replayed with the
  recorder+incident plane attached vs detached (time-series enabled in
  BOTH modes so the comparison isolates the black box), min wall over
  repeats per mode — the attached run must cost <= 2% more;
- **detection**: the ``replica_crash_storm`` and
  ``prefill_kill_mid_handoff`` chaos campaigns must each open at least
  one incident whose postmortem bundle cause-chains correctly (the
  chain anchors at a ``fault`` stage and shows ``impact``);
- **silence**: the same campaigns' fault-free reference replays must
  open ZERO incidents — a detector that cries wolf on a healthy fleet
  is worse than no detector;
- **determinism**: two same-seed faulted replays must produce
  byte-identical deterministic flight logs and equal bundle digests.

Usage::

    python tools/bench_flight.py --out BENCH_flight.json
    python tools/bench_flight.py --skip-overhead   # campaign gates only
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Optional

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: campaigns whose incident stories this bench gates on
_CAMPAIGNS = ("replica_crash_storm", "prefill_kill_mid_handoff")

#: overhead budget: flight-attached step wall vs detached, min-over-repeats
_OVERHEAD_LIMIT = 0.02

_OVERHEAD_SCENARIO = "diurnal_ramp"
_OVERHEAD_TICKS_SCALE = 0.5
_OVERHEAD_REPEATS = 4


def run_bench(out: Optional[str], seed: int,
              skip_overhead: bool) -> int:
    if _ROOT not in sys.path:
        sys.path.insert(0, _ROOT)
    import time

    import jax
    import numpy as np

    from skycomputing_tpu.builder import build_layer_stack
    from skycomputing_tpu.chaos import FaultInjector, get_fault_plan
    from skycomputing_tpu.disagg import DisaggFleet
    from skycomputing_tpu.fleet import FleetSupervisor, ServingFleet
    from skycomputing_tpu.models.gpt import GptConfig, gpt_layer_configs
    from skycomputing_tpu.serving import Request
    from skycomputing_tpu.telemetry.incidents import (
        cause_chain,
        chain_stages,
    )
    from skycomputing_tpu.workload import ScenarioPlayer, get_scenario

    cfg = GptConfig(vocab_size=512, hidden_size=64,
                    num_hidden_layers=2, num_attention_heads=2,
                    max_position_embeddings=160, dropout_prob=0.0,
                    dtype="float32")
    layer_cfgs = gpt_layer_configs(cfg, deterministic=True)
    stack = build_layer_stack(layer_cfgs)
    print(f"initializing {len(layer_cfgs)}-layer GPT "
          f"(hidden={cfg.hidden_size})...", flush=True)
    params = stack.init(jax.random.key(seed),
                        np.ones((1, 8), np.int32))

    buckets = (32, 64, 96)
    engine_kwargs = dict(num_slots=2, max_len=128, buckets=buckets,
                         prefill_batch=1, kv_layout="paged",
                         page_size=8)

    def make_fleet(*, replicas=2, disagg=False, flight=False):
        # sick_threshold is effectively off: EWMA-of-wall-latency
        # detection is wall-driven by design, so a GC pause in ONE of
        # the two same-seed replays would inject detect/drain events
        # into one flight log and fail the byte-identity gate on
        # machine noise; dead/slot-leak detection (what the campaigns
        # exercise) is tick-deterministic and stays on
        supervisor = FleetSupervisor(check_every=1,
                                     heartbeat_misses=1,
                                     sick_threshold=1e9, k_checks=3)
        if disagg:
            fleet = DisaggFleet(
                layer_cfgs, params,
                prefill_replicas=1, decode_replicas=replicas - 1,
                engine_kwargs=dict(engine_kwargs),
                supervisor=supervisor,
            )
        else:
            fleet = ServingFleet(
                layer_cfgs, params, replicas=replicas,
                engine_kwargs=dict(engine_kwargs),
                supervisor=supervisor,
            )
        # the overhead comparison must isolate the black box, so the
        # time-series runs in BOTH modes (attach_flight enables it)
        fleet.enable_timeseries()
        if flight:
            fleet.attach_flight()
        return fleet

    # compile warmup once: every fleet shares the stage-program cache
    warm_fleet = make_fleet()
    warm_fleet.run([
        Request(prompt=np.full((b - 2,), b + 1, np.int32),
                max_new_tokens=2) for b in buckets
    ])

    gates, doc = {}, {}

    # --- overhead: diurnal_ramp, flight on vs off ---------------------------
    if not skip_overhead:
        def timed_replay(flight: bool) -> float:
            fleet = make_fleet(flight=flight)
            scenario = get_scenario(_OVERHEAD_SCENARIO, seed=seed,
                                    ticks_scale=_OVERHEAD_TICKS_SCALE)
            player = ScenarioPlayer(scenario, fleet)
            t0 = time.perf_counter()
            player.play()
            return time.perf_counter() - t0

        walls = {"off": [], "on": []}
        for rep in range(_OVERHEAD_REPEATS):
            # interleaved so machine drift hits both modes equally
            walls["off"].append(timed_replay(False))
            walls["on"].append(timed_replay(True))
            print(f"  overhead repeat {rep}: "
                  f"off={walls['off'][-1]:.3f}s "
                  f"on={walls['on'][-1]:.3f}s", flush=True)
        base, attached = min(walls["off"]), min(walls["on"])
        overhead = attached / base - 1.0
        gates["recorder_overhead"] = bool(overhead <= _OVERHEAD_LIMIT)
        doc["overhead"] = dict(
            scenario=_OVERHEAD_SCENARIO,
            ticks_scale=_OVERHEAD_TICKS_SCALE,
            repeats=_OVERHEAD_REPEATS,
            wall_s_off=[round(w, 4) for w in walls["off"]],
            wall_s_on=[round(w, 4) for w in walls["on"]],
            min_wall_s_off=round(base, 4),
            min_wall_s_on=round(attached, 4),
            overhead_frac=round(overhead, 5),
            limit_frac=_OVERHEAD_LIMIT,
        )
        print(f"  overhead: {overhead * 100:+.2f}% "
              f"(limit {_OVERHEAD_LIMIT * 100:.0f}%)", flush=True)

    # --- campaigns: detection, silence, determinism -------------------------
    def replay(plan, injector):
        fleet = make_fleet(replicas=plan.replicas, disagg=plan.disagg,
                           flight=True)
        if injector is not None:
            fleet.fault_injector = injector
        scenario = get_scenario(plan.scenario, seed=plan.scenario_seed,
                                rate_scale=plan.rate_scale,
                                ticks_scale=plan.ticks_scale)
        ScenarioPlayer(scenario, fleet).play()
        for _ in range(plan.recovery_budget_ticks + 10):
            fleet.step()
        return fleet

    campaigns = {}
    for name in _CAMPAIGNS:
        plan = get_fault_plan(name, seed=seed)
        t0 = __import__("time").perf_counter()
        print(f"running {name} (scenario {plan.scenario}, "
              f"{plan.replicas} replicas"
              f"{', disagg' if plan.disagg else ''})...", flush=True)

        # discarded warm replay: the faulted path (re-formed engines
        # included) compiles its stage programs into the process-global
        # cache HERE, so the gated runs below see identical cache state
        # — without this, run A records the recompiles run B then finds
        # cached, and the byte-identical-log gate measures jit-cache
        # temperature instead of the recorder
        replay(plan, FaultInjector(plan))
        ref = replay(plan, None)
        fleet_a = replay(plan, FaultInjector(plan))
        fleet_b = replay(plan, FaultInjector(plan))  # same seed again

        bundles = fleet_a.bundles
        chains = [chain_stages(cause_chain(b["flight_log"]))
                  for b in bundles]
        cause_chained = [stages for stages in chains
                         if stages[:1] == ["fault"]
                         and "impact" in stages]
        log_a = json.dumps(fleet_a.flight.deterministic_log(),
                           sort_keys=True)
        log_b = json.dumps(fleet_b.flight.deterministic_log(),
                           sort_keys=True)
        digests_a = [b["digest"] for b in fleet_a.bundles]
        digests_b = [b["digest"] for b in fleet_b.bundles]

        cgates = dict(
            incident_opened=bool(
                fleet_a.incidents.opened_total >= 1),
            incident_cause_chained=bool(cause_chained),
            reference_zero_incidents=bool(
                ref.incidents.opened_total == 0),
            deterministic_flight_log=bool(log_a == log_b),
            deterministic_bundle_digests=bool(
                digests_a == digests_b and digests_a),
        )
        gates.update({f"{name}.{g}": ok for g, ok in cgates.items()})
        wall_s = __import__("time").perf_counter() - t0
        campaigns[name] = dict(
            plan_digest=plan.digest(),
            incidents=fleet_a.incidents.incidents_json(),
            flight=fleet_a.flight.snapshot(),
            flight_digest=fleet_a.flight.digest(),
            bundle_digests=digests_a,
            bundle_rules=[b["incident"]["rule"] for b in bundles],
            cause_chains=chains,
            reference_incidents_opened=ref.incidents.opened_total,
            gates=cgates,
            wall_s=round(wall_s, 3),
        )
        failed = [g for g, ok in cgates.items() if not ok]
        print(f"  {name}: "
              f"{'PASS' if not failed else 'FAIL'} "
              f"({fleet_a.incidents.opened_total} incidents, "
              f"rules {sorted(set(campaigns[name]['bundle_rules']))}, "
              f"{wall_s:.1f}s"
              f"{'' if not failed else ', failed: ' + ', '.join(failed)})",
              flush=True)

    all_passed = all(gates.values())
    report_doc = dict(
        bench="flight_recorder",
        device_kind=jax.devices()[0].device_kind,
        model=dict(cfg.to_dict()),
        fleet=dict(engine_kwargs),
        seed=seed,
        notes=(
            "overhead compares min wall over interleaved repeats with "
            "the time-series enabled in both modes; campaign gates "
            "require >=1 correctly cause-chained incident on faulted "
            "runs, zero incidents on fault-free references, and "
            "byte-identical flight logs + equal bundle digests across "
            "same-seed replays"
        ),
        campaigns=campaigns,
        gates=gates,
        passed=all_passed,
        **doc,
    )
    if out:
        with open(out, "w") as f:
            json.dump(report_doc, f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"wrote {out}")
    print(f"flight bench: {'PASS' if all_passed else 'FAIL'}")
    return 0 if all_passed else 1


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--out", default=None,
                        help="write the JSON artifact here")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--skip-overhead", action="store_true",
                        help="campaign gates only (faster iteration)")
    args = parser.parse_args()
    return run_bench(args.out, args.seed, args.skip_overhead)


if __name__ == "__main__":
    sys.exit(main())
