"""Compatibility alias: the reference framework's package name.

The reference ships as ``scaelum`` (``/root/reference/setup.py:21-22``); this
module lets reference users keep their imports while getting the TPU-native
implementation.  ``import scaelum`` re-exports the full
:mod:`skycomputing_tpu` API surface under the familiar names, including the
``scaelum.dynamics`` / ``scaelum.runner`` / ... submodule paths.
"""

import sys as _sys

import skycomputing_tpu as _impl
from skycomputing_tpu import *  # noqa: F401,F403
from skycomputing_tpu import (
    builder,
    config,
    dataset,
    dynamics,
    models,
    ops,
    parallel,
    registry,
    runner,
    stimulator,
    utils,
)

# familiar submodule paths: scaelum.dynamics, scaelum.runner, ...
for _name in (
    "builder",
    "config",
    "dataset",
    "dynamics",
    "models",
    "ops",
    "parallel",
    "registry",
    "runner",
    "stimulator",
    "utils",
):
    _sys.modules[f"scaelum.{_name}"] = getattr(_impl, _name)

# the reference exposed the model zoo as ``scaelum.model``, and timer/
# logger as their own submodules (scaelum/timer/, scaelum/logger/)
_sys.modules["scaelum.model"] = models
model = models

from skycomputing_tpu.utils import logger as _logger_mod
from skycomputing_tpu.utils import timer as _timer_mod

_sys.modules["scaelum.timer"] = _timer_mod
_sys.modules["scaelum.logger"] = _logger_mod
timer = _timer_mod
logger = _logger_mod

__version__ = _impl.__version__
__all__ = list(getattr(_impl, "__all__", [])) + ["model"]
