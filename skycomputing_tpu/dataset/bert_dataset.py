"""GLUE fine-tuning dataset.

Parity with the reference ``GlueDataset`` (``scaelum/dataset/bert_dataset.py:
17-46``): tokenize a GLUE task's TSVs into ``InputFeatures`` with a pickle
cache, ``__getitem__`` returning ``((input_ids, input_mask, segment_ids),
label)``.  Additions for the zero-egress TPU environment: if ``data_dir`` or
``vocab_file`` is missing, the dataset degrades to a deterministic synthetic
corpus with the same row shapes instead of failing.
"""

from __future__ import annotations

import os
import pickle
from typing import Optional

import numpy as np

from ..registry import DATASET
from ..utils import Logger
from .glue import (
    PROCESSORS,
    BertTokenizer,
    build_synthetic_vocab,
    convert_examples_to_features,
)


@DATASET.register_module
class GlueDataset:
    def __init__(
        self,
        data_dir: str,
        vocab_file: Optional[str] = None,
        max_seq_length: int = 128,
        do_lower_case: bool = False,
        processor: str = "mnli",
        split: str = "train",
        bert_model: str = "large-uncased",  # accepted for config parity
        cache_dir: Optional[str] = None,
        synthetic_num_samples: int = 512,
    ):
        self.max_seq_length = max_seq_length
        proc_cls = PROCESSORS[processor.lower()]
        self.processor = proc_cls()
        self.label_list = self.processor.get_labels()
        logger = Logger()

        have_data = bool(data_dir) and os.path.isdir(data_dir)
        have_vocab = bool(vocab_file) and os.path.isfile(vocab_file)

        if have_data and have_vocab:
            cache_dir = cache_dir or data_dir
            vocab_tag = os.path.basename(vocab_file)
            cache_file = os.path.join(
                cache_dir,
                f"{processor}_{split}_{max_seq_length}_{do_lower_case}_"
                f"{vocab_tag}.cache.pkl",
            )
            if os.path.isfile(cache_file):
                with open(cache_file, "rb") as fh:
                    features = pickle.load(fh)
            else:
                tokenizer = BertTokenizer(
                    vocab_file=vocab_file, do_lower_case=do_lower_case
                )
                if split == "train":
                    examples = self.processor.get_train_examples(data_dir)
                else:
                    examples = self.processor.get_dev_examples(data_dir)
                features, _ = convert_examples_to_features(
                    examples, self.label_list, max_seq_length, tokenizer
                )
                try:
                    with open(cache_file, "wb") as fh:
                        pickle.dump(features, fh)
                except OSError:  # read-only data dir: skip caching
                    pass
            self.input_ids = np.asarray(
                [f.input_ids for f in features], dtype=np.int32
            )
            self.input_mask = np.asarray(
                [f.input_mask for f in features], dtype=np.int32
            )
            self.segment_ids = np.asarray(
                [f.segment_ids for f in features], dtype=np.int32
            )
            self.labels = np.asarray([f.label_id for f in features], dtype=np.int32)
            self.synthetic = False
        else:
            logger.info(
                f"GlueDataset: data_dir={data_dir!r} or vocab_file={vocab_file!r} "
                "unavailable — using deterministic synthetic corpus"
            )
            vocab = build_synthetic_vocab()
            # distinct corpora per split so eval never scores training rows
            rng = np.random.default_rng(11 + sum(ord(c) for c in split))
            n = synthetic_num_samples
            # LEARNABLE corpus, not label noise: labels drawn first, then
            # each row's tokens drawn from a class-conditional band of the
            # vocabulary (bands overlap ~30% so the task is non-trivial
            # but separable).  With uniform random labels a classifier can
            # never beat ln(num_classes), so ladder/runner loss curves on
            # the synthetic fallback could only prove *execution* — flat
            # at ~1.10 for 3 classes (VERDICT r04 weak #7).  Class signal
            # makes "loss falls" a real statement about training.
            num_classes = len(self.label_list)
            self.labels = rng.integers(
                0, num_classes, size=(n,)
            ).astype(np.int32)
            usable = len(vocab) - 5
            band = int(usable / (0.7 * num_classes + 0.3))
            starts = 5 + (
                np.arange(num_classes) * int(0.7 * band)
            ).astype(np.int64)
            lo = starts[self.labels][:, None]
            self.input_ids = (
                lo + rng.integers(0, band, size=(n, max_seq_length))
            ).clip(max=len(vocab) - 1).astype(np.int32)
            lengths = rng.integers(8, max_seq_length + 1, size=(n,))
            self.input_mask = (
                np.arange(max_seq_length)[None, :] < lengths[:, None]
            ).astype(np.int32)
            self.input_ids *= self.input_mask
            seg = rng.integers(1, max_seq_length, size=(n,))
            self.segment_ids = (
                np.arange(max_seq_length)[None, :] >= seg[:, None]
            ).astype(np.int32) * self.input_mask
            self.synthetic = True

    def __len__(self):
        return len(self.input_ids)

    def __getitem__(self, idx):
        return (
            (self.input_ids[idx], self.input_mask[idx], self.segment_ids[idx]),
            int(self.labels[idx]),
        )


__all__ = ["GlueDataset"]
