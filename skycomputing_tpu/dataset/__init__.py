from ..registry import DATASET
from .bert_dataset import GlueDataset
from .data_generator import (
    BaseGenerator,
    DataloaderGenerator,
    RandomTensorGenerator,
    RandomTokenGenerator,
)
from .dataloader import DataLoader
from .datasets import (
    CIFAR10Dataset,
    RandomBertDataset,
    RandomImageDataset,
    RandomLmDataset,
    RandomMlpDataset,
)
from . import glue

__all__ = [
    "DATASET",
    "GlueDataset",
    "BaseGenerator",
    "DataloaderGenerator",
    "RandomTensorGenerator",
    "RandomTokenGenerator",
    "DataLoader",
    "CIFAR10Dataset",
    "RandomBertDataset",
    "RandomImageDataset",
    "RandomLmDataset",
    "RandomMlpDataset",
    "glue",
]
