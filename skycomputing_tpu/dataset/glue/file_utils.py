"""Resource resolution for dataset assets.

The reference vendors an S3/HTTP cached-download helper
(``scaelum/dataset/glue/file_utils.py:88-241``, boto3/requests).  This
environment is zero-egress by design, so the TPU build's ``cached_path``
resolves local filesystem paths (absolute, relative, or under
``SKYTPU_DATA_HOME``) and fails loudly — with the reason — on remote URLs
instead of attempting a download.  The API shape (path-in, usable-path-out)
is preserved so dataset code written against the reference keeps working
when pointed at local assets.
"""

from __future__ import annotations

import os
from typing import Optional
from urllib.parse import urlparse

DATA_HOME_ENV = "SKYTPU_DATA_HOME"


def url_to_filename(url: str, etag: Optional[str] = None) -> str:
    """Deterministic cache filename for a resource identifier."""
    import hashlib

    name = hashlib.sha256(url.encode()).hexdigest()
    if etag:
        name += "." + hashlib.sha256(etag.encode()).hexdigest()[:16]
    return name


def cached_path(path_or_url: str, cache_dir: Optional[str] = None) -> str:
    """Resolve a resource to a local path.

    Local paths are returned (after existence check, trying
    ``$SKYTPU_DATA_HOME`` as a base for relative paths); ``http(s)://`` and
    ``s3://`` raise with an actionable message, because this runtime has no
    network egress.
    """
    parsed = urlparse(path_or_url)
    if parsed.scheme in ("http", "https", "s3"):
        raise OSError(
            f"cannot fetch {path_or_url!r}: this runtime has no network "
            f"egress. Download the resource out-of-band and pass its local "
            f"path (or set ${DATA_HOME_ENV} and use a relative path)."
        )

    if os.path.exists(path_or_url):
        return path_or_url

    data_home = os.environ.get(DATA_HOME_ENV)
    if data_home:
        candidate = os.path.join(data_home, path_or_url)
        if os.path.exists(candidate):
            return candidate

    raise FileNotFoundError(
        f"resource {path_or_url!r} not found locally"
        + (f" (also tried under ${DATA_HOME_ENV}={data_home})" if data_home
           else f" (set ${DATA_HOME_ENV} to add a search base)")
    )


__all__ = ["cached_path", "url_to_filename", "DATA_HOME_ENV"]
