"""GLUE task processors + example->feature conversion.

Behavioral parity with the reference's vendored GLUE preprocessing
(``/root/reference/scaelum/dataset/glue/processor.py:10-310``): TSV readers
per task (MRPC/MNLI/CoLA/SST-2), ``[CLS] a [SEP] b [SEP]`` packing with
segment ids, attention-mask construction, and zero-padding to
``max_seq_length``.  Implemented fresh from the standard BERT data format.
"""

from __future__ import annotations

import csv
import os
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple


@dataclass
class InputExample:
    guid: str
    text_a: str
    text_b: Optional[str] = None
    label: Optional[str] = None


@dataclass
class InputFeatures:
    input_ids: List[int]
    input_mask: List[int]
    segment_ids: List[int]
    label_id: int


def read_tsv(path: str, quotechar: Optional[str] = None) -> List[List[str]]:
    with open(path, encoding="utf-8") as fh:
        return [
            line
            for line in csv.reader(fh, delimiter="\t", quotechar=quotechar)
        ]


class DataProcessor:
    """Base class: one GLUE task's file layout and label set."""

    def get_train_examples(self, data_dir: str) -> List[InputExample]:
        raise NotImplementedError

    def get_dev_examples(self, data_dir: str) -> List[InputExample]:
        raise NotImplementedError

    def get_labels(self) -> List[str]:
        raise NotImplementedError


class MrpcProcessor(DataProcessor):
    def get_train_examples(self, data_dir):
        return self._examples(read_tsv(os.path.join(data_dir, "train.tsv")), "train")

    def get_dev_examples(self, data_dir):
        return self._examples(read_tsv(os.path.join(data_dir, "dev.tsv")), "dev")

    def get_labels(self):
        return ["0", "1"]

    @staticmethod
    def _examples(lines, set_type):
        examples = []
        for i, line in enumerate(lines):
            if i == 0:
                continue
            examples.append(
                InputExample(
                    guid=f"{set_type}-{i}",
                    text_a=line[3],
                    text_b=line[4],
                    label=line[0],
                )
            )
        return examples


class MnliProcessor(DataProcessor):
    def get_train_examples(self, data_dir):
        return self._examples(read_tsv(os.path.join(data_dir, "train.tsv")), "train")

    def get_dev_examples(self, data_dir):
        return self._examples(
            read_tsv(os.path.join(data_dir, "dev_matched.tsv")), "dev_matched"
        )

    def get_labels(self):
        return ["contradiction", "entailment", "neutral"]

    @staticmethod
    def _examples(lines, set_type):
        examples = []
        for i, line in enumerate(lines):
            if i == 0:
                continue
            examples.append(
                InputExample(
                    guid=f"{set_type}-{line[0]}",
                    text_a=line[8],
                    text_b=line[9],
                    label=line[-1],
                )
            )
        return examples


class ColaProcessor(DataProcessor):
    def get_train_examples(self, data_dir):
        return self._examples(read_tsv(os.path.join(data_dir, "train.tsv")), "train")

    def get_dev_examples(self, data_dir):
        return self._examples(read_tsv(os.path.join(data_dir, "dev.tsv")), "dev")

    def get_labels(self):
        return ["0", "1"]

    @staticmethod
    def _examples(lines, set_type):
        return [
            InputExample(guid=f"{set_type}-{i}", text_a=line[3], label=line[1])
            for i, line in enumerate(lines)
        ]


class Sst2Processor(DataProcessor):
    def get_train_examples(self, data_dir):
        return self._examples(read_tsv(os.path.join(data_dir, "train.tsv")), "train")

    def get_dev_examples(self, data_dir):
        return self._examples(read_tsv(os.path.join(data_dir, "dev.tsv")), "dev")

    def get_labels(self):
        return ["0", "1"]

    @staticmethod
    def _examples(lines, set_type):
        examples = []
        for i, line in enumerate(lines):
            if i == 0:
                continue
            examples.append(
                InputExample(guid=f"{set_type}-{i}", text_a=line[0], label=line[1])
            )
        return examples


PROCESSORS: Dict[str, type] = {
    "mrpc": MrpcProcessor,
    "mnli": MnliProcessor,
    "cola": ColaProcessor,
    "sst-2": Sst2Processor,
}


def truncate_seq_pair(tokens_a: List[str], tokens_b: List[str], max_length: int):
    """Trim the longer of the pair until the combined length fits."""
    while len(tokens_a) + len(tokens_b) > max_length:
        if len(tokens_a) > len(tokens_b):
            tokens_a.pop()
        else:
            tokens_b.pop()


def convert_examples_to_features(
    examples: Sequence[InputExample],
    label_list: Sequence[str],
    max_seq_length: int,
    tokenizer,
) -> Tuple[List[InputFeatures], Dict[str, int]]:
    """Tokenize/pack/pad examples into fixed-length feature rows."""
    label_map = {label: i for i, label in enumerate(label_list)}
    features = []
    for example in examples:
        tokens_a = tokenizer.tokenize(example.text_a)
        tokens_b = tokenizer.tokenize(example.text_b) if example.text_b else None

        if tokens_b is not None:
            truncate_seq_pair(tokens_a, tokens_b, max_seq_length - 3)
        else:
            tokens_a = tokens_a[: max_seq_length - 2]

        tokens = ["[CLS]"] + tokens_a + ["[SEP]"]
        segment_ids = [0] * len(tokens)
        if tokens_b is not None:
            tokens += tokens_b + ["[SEP]"]
            segment_ids += [1] * (len(tokens_b) + 1)

        input_ids = tokenizer.convert_tokens_to_ids(tokens)
        input_mask = [1] * len(input_ids)

        pad = [0] * (max_seq_length - len(input_ids))
        input_ids += pad
        input_mask += pad
        segment_ids += pad

        features.append(
            InputFeatures(
                input_ids=input_ids,
                input_mask=input_mask,
                segment_ids=segment_ids,
                label_id=label_map[example.label],
            )
        )
    return features, label_map


__all__ = [
    "InputExample",
    "InputFeatures",
    "DataProcessor",
    "MrpcProcessor",
    "MnliProcessor",
    "ColaProcessor",
    "Sst2Processor",
    "PROCESSORS",
    "convert_examples_to_features",
    "truncate_seq_pair",
    "read_tsv",
]
