"""Clean-room WordPiece tokenization for BERT-style preprocessing.

Behavioral parity target: the reference's vendored tokenizer
(``/root/reference/scaelum/dataset/glue/tokenization.py:84,191,311`` —
``BertTokenizer`` = basic tokenization + greedy longest-match-first WordPiece
over a ``vocab.txt``).  This is an independent implementation of the public
WordPiece algorithm, not a copy: whitespace/punctuation/CJK splitting,
optional lower-casing with accent stripping, and greedy sub-word matching
with ``##`` continuation prefixes.
"""

from __future__ import annotations

import collections
import unicodedata
from typing import Dict, List, Optional


def load_vocab(vocab_file: str) -> Dict[str, int]:
    """vocab.txt (one token per line) -> token->id map.

    Ids are assigned by line number unconditionally so they match the row
    indices of a pretrained checkpoint's embedding table even when the file
    contains blank or duplicate lines (duplicates keep their last id, as in
    the canonical BERT loader).
    """
    vocab = collections.OrderedDict()
    with open(vocab_file, encoding="utf-8") as fh:
        for index, line in enumerate(fh):
            token = line.rstrip("\n")
            if token:
                vocab[token] = index
    return vocab


def whitespace_tokenize(text: str) -> List[str]:
    text = text.strip()
    return text.split() if text else []


def _is_whitespace(ch: str) -> bool:
    return ch in (" ", "\t", "\n", "\r") or unicodedata.category(ch) == "Zs"


def _is_control(ch: str) -> bool:
    if ch in ("\t", "\n", "\r"):
        return False
    return unicodedata.category(ch).startswith("C")


def _is_punctuation(ch: str) -> bool:
    cp = ord(ch)
    if (33 <= cp <= 47) or (58 <= cp <= 64) or (91 <= cp <= 96) or (123 <= cp <= 126):
        return True
    return unicodedata.category(ch).startswith("P")


def _is_cjk(cp: int) -> bool:
    return (
        0x4E00 <= cp <= 0x9FFF
        or 0x3400 <= cp <= 0x4DBF
        or 0x20000 <= cp <= 0x2A6DF
        or 0x2A700 <= cp <= 0x2B73F
        or 0x2B740 <= cp <= 0x2B81F
        or 0x2B820 <= cp <= 0x2CEAF
        or 0xF900 <= cp <= 0xFAFF
        or 0x2F800 <= cp <= 0x2FA1F
    )


class BasicTokenizer:
    """Whitespace/punctuation/CJK splitting with optional lower-casing."""

    def __init__(self, do_lower_case: bool = True):
        self.do_lower_case = do_lower_case

    def tokenize(self, text: str) -> List[str]:
        text = self._clean(text)
        text = self._pad_cjk(text)
        tokens = whitespace_tokenize(text)
        out: List[str] = []
        for token in tokens:
            if self.do_lower_case:
                token = token.lower()
                token = self._strip_accents(token)
            out.extend(self._split_punct(token))
        return whitespace_tokenize(" ".join(out))

    @staticmethod
    def _clean(text: str) -> str:
        chars = []
        for ch in text:
            cp = ord(ch)
            if cp == 0 or cp == 0xFFFD or _is_control(ch):
                continue
            chars.append(" " if _is_whitespace(ch) else ch)
        return "".join(chars)

    @staticmethod
    def _pad_cjk(text: str) -> str:
        chars = []
        for ch in text:
            if _is_cjk(ord(ch)):
                chars.append(f" {ch} ")
            else:
                chars.append(ch)
        return "".join(chars)

    @staticmethod
    def _strip_accents(text: str) -> str:
        text = unicodedata.normalize("NFD", text)
        return "".join(ch for ch in text if unicodedata.category(ch) != "Mn")

    @staticmethod
    def _split_punct(token: str) -> List[str]:
        pieces: List[List[str]] = []
        start_new = True
        for ch in token:
            if _is_punctuation(ch):
                pieces.append([ch])
                start_new = True
            else:
                if start_new:
                    pieces.append([])
                    start_new = False
                pieces[-1].append(ch)
        return ["".join(p) for p in pieces]


class WordpieceTokenizer:
    """Greedy longest-match-first sub-word tokenization."""

    def __init__(
        self,
        vocab: Dict[str, int],
        unk_token: str = "[UNK]",
        max_input_chars_per_word: int = 200,
    ):
        self.vocab = vocab
        self.unk_token = unk_token
        self.max_input_chars_per_word = max_input_chars_per_word

    def tokenize(self, text: str) -> List[str]:
        output: List[str] = []
        for token in whitespace_tokenize(text):
            chars = list(token)
            if len(chars) > self.max_input_chars_per_word:
                output.append(self.unk_token)
                continue
            start = 0
            pieces: List[str] = []
            bad = False
            while start < len(chars):
                end = len(chars)
                cur = None
                while start < end:
                    piece = "".join(chars[start:end])
                    if start > 0:
                        piece = "##" + piece
                    if piece in self.vocab:
                        cur = piece
                        break
                    end -= 1
                if cur is None:
                    bad = True
                    break
                pieces.append(cur)
                start = end
            output.extend([self.unk_token] if bad else pieces)
        return output


class BertTokenizer:
    """Full BERT tokenizer: basic split then WordPiece, with id conversion."""

    def __init__(
        self,
        vocab_file: Optional[str] = None,
        do_lower_case: bool = True,
        vocab: Optional[Dict[str, int]] = None,
        max_len: int = 512,
    ):
        if vocab is None:
            if vocab_file is None:
                raise ValueError("either vocab or vocab_file is required")
            vocab = load_vocab(vocab_file)
        self.vocab = vocab
        self.ids_to_tokens = {v: k for k, v in vocab.items()}
        self.basic_tokenizer = BasicTokenizer(do_lower_case=do_lower_case)
        self.wordpiece_tokenizer = WordpieceTokenizer(vocab=vocab)
        self.max_len = max_len

    def tokenize(self, text: str) -> List[str]:
        tokens: List[str] = []
        for token in self.basic_tokenizer.tokenize(text):
            tokens.extend(self.wordpiece_tokenizer.tokenize(token))
        return tokens

    def convert_tokens_to_ids(self, tokens: List[str]) -> List[int]:
        unk = self.vocab.get("[UNK]", 0)
        ids = [self.vocab.get(t, unk) for t in tokens]
        if len(ids) > self.max_len:
            raise ValueError(
                f"sequence of {len(ids)} tokens exceeds max_len={self.max_len}"
            )
        return ids

    def convert_ids_to_tokens(self, ids: List[int]) -> List[str]:
        return [self.ids_to_tokens[i] for i in ids]


SPECIAL_TOKENS = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"]


def train_wordpiece_vocab(
    texts,
    vocab_size: int = 8000,
    min_frequency: int = 2,
    do_lower_case: bool = True,
    num_merges_per_round: int = 200,
) -> Dict[str, int]:
    """Learn a WordPiece vocabulary from raw texts (BPE-style training).

    The reference only ships a *loader* for pretrained vocab files; this
    trainer closes the loop for from-scratch corpora.  Standard algorithm:
    words become character sequences (continuations prefixed ``##``), then
    the highest-frequency adjacent pair is merged repeatedly until the
    vocabulary budget is spent.  Greedy longest-match tokenization with the
    result reconstructs training words exactly.

    ``vocab_size`` caps the TOTAL vocabulary (special tokens + base
    characters + merged subwords).  The specials and the corpus's
    base-character inventory are always included even when they alone
    exceed the budget — dropping them would make training words
    untokenizable — so tiny budgets are overshot, and large budgets spend
    ``vocab_size - specials - characters`` entries on merges.
    """
    basic = BasicTokenizer(do_lower_case=do_lower_case)
    word_freq: Dict[str, int] = collections.Counter()
    for text in texts:
        for word in basic.tokenize(text):
            word_freq[word] += 1

    # each word as a tuple of current symbols
    words = {
        w: [w[0]] + ["##" + ch for ch in w[1:]]
        for w, f in word_freq.items()
        if f >= min_frequency
    }

    vocab = collections.OrderedDict(
        (t, i) for i, t in enumerate(SPECIAL_TOKENS)
    )

    def add(token: str) -> None:
        if token not in vocab:
            vocab[token] = len(vocab)

    for symbols in words.values():
        for s in symbols:
            add(s)

    while len(vocab) < vocab_size:
        pair_freq: Dict[tuple, int] = collections.Counter()
        for w, symbols in words.items():
            f = word_freq[w]
            for a, b in zip(symbols, symbols[1:]):
                pair_freq[(a, b)] += f
        if not pair_freq:
            break
        # merge a batch of top pairs per round, applied in ONE pass per
        # word (left-to-right, higher-frequency pair wins on overlap):
        # one-pair-per-corpus-scan training is O(vocab * corpus), and so
        # is scanning once per batched pair — batching trades exact
        # merge order for a num_merges_per_round speedup
        merges: Dict[tuple, str] = {}
        for (a, b), f in pair_freq.most_common(num_merges_per_round):
            if len(vocab) + len(merges) >= vocab_size or f < min_frequency:
                break
            merged = a + b.removeprefix("##")
            if merged in vocab or merged in merges.values():
                continue
            merges[(a, b)] = merged
        if not merges:
            break
        for merged in merges.values():
            add(merged)
        for w, symbols in words.items():
            out = []
            i = 0
            while i < len(symbols):
                if (
                    i + 1 < len(symbols)
                    and (symbols[i], symbols[i + 1]) in merges
                ):
                    out.append(merges[(symbols[i], symbols[i + 1])])
                    i += 2
                else:
                    out.append(symbols[i])
                    i += 1
            words[w] = out
    return vocab


def build_synthetic_vocab(size: int = 1024, seed: int = 0) -> Dict[str, int]:
    """Deterministic toy vocabulary for offline/zero-download operation."""
    import random

    rng = random.Random(seed)
    vocab = collections.OrderedDict((t, i) for i, t in enumerate(SPECIAL_TOKENS))
    alphabet = "abcdefghijklmnopqrstuvwxyz"
    while len(vocab) < size:
        length = rng.randint(2, 8)
        word = "".join(rng.choice(alphabet) for _ in range(length))
        if rng.random() < 0.3:
            word = "##" + word
        if word not in vocab:
            vocab[word] = len(vocab)
    return vocab


__all__ = [
    "load_vocab",
    "whitespace_tokenize",
    "BasicTokenizer",
    "WordpieceTokenizer",
    "BertTokenizer",
    "build_synthetic_vocab",
    "train_wordpiece_vocab",
    "SPECIAL_TOKENS",
]
