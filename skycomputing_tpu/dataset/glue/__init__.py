from .processor import (
    PROCESSORS,
    ColaProcessor,
    DataProcessor,
    InputExample,
    InputFeatures,
    MnliProcessor,
    MrpcProcessor,
    Sst2Processor,
    convert_examples_to_features,
)
from .tokenization import (
    BasicTokenizer,
    BertTokenizer,
    WordpieceTokenizer,
    build_synthetic_vocab,
    load_vocab,
    train_wordpiece_vocab,
)

__all__ = [
    "PROCESSORS",
    "ColaProcessor",
    "DataProcessor",
    "InputExample",
    "InputFeatures",
    "MnliProcessor",
    "MrpcProcessor",
    "Sst2Processor",
    "convert_examples_to_features",
    "BasicTokenizer",
    "BertTokenizer",
    "WordpieceTokenizer",
    "build_synthetic_vocab",
    "load_vocab",
    "train_wordpiece_vocab",
]
