"""Benchmark-input generators (reference: ``scaelum/dataset/data_generator.py``).

``DataloaderGenerator`` in the reference returns the *first batch forever*
(``data_generator.py:33-34`` — a latent bug); here it cycles properly but
also offers ``fixed=True`` to reproduce the reference's (useful for
benchmarking) behavior of a deterministic probe batch.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

from ..registry import DATA_GENERATOR


class BaseGenerator:
    def generate(self):
        raise NotImplementedError


@DATA_GENERATOR.register_module
class RandomTensorGenerator(BaseGenerator):
    """A random float tensor of a configured size (device-benchmark probe)."""

    def __init__(self, size: Sequence[int], dtype: str = "float32", seed: int = 0):
        self.size = tuple(size)
        self.dtype = dtype
        self._rng = np.random.default_rng(seed)

    def generate(self):
        return self._rng.normal(size=self.size).astype(self.dtype)


@DATA_GENERATOR.register_module
class RandomTokenGenerator(BaseGenerator):
    """BERT-shaped probe inputs: (input_ids, token_type_ids, attention_mask)."""

    def __init__(self, batch_size: int = 32, seq_length: int = 128,
                 vocab_size: int = 30522, seed: int = 0):
        self.batch_size = batch_size
        self.seq_length = seq_length
        self.vocab_size = vocab_size
        self._rng = np.random.default_rng(seed)

    def generate(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        ids = self._rng.integers(
            5, self.vocab_size, size=(self.batch_size, self.seq_length),
            dtype=np.int32,
        )
        types = np.zeros_like(ids)
        mask = np.ones_like(ids)
        return ids, types, mask


@DATA_GENERATOR.register_module
class DataloaderGenerator(BaseGenerator):
    """Draw probe batches from a real dataloader config."""

    def __init__(self, generator_cfg: dict, fixed: bool = True):
        from ..builder import build_dataloader_from_cfg

        self._dataloader = build_dataloader_from_cfg(generator_cfg)
        self._fixed = fixed
        self._iter = None
        self._first = None

    def generate(self):
        if self._fixed:
            if self._first is None:
                self._first = self._next_batch()[0]
            return self._first
        try:
            if self._iter is None:
                self._iter = iter(self._dataloader)
            batch = next(self._iter)
        except StopIteration:
            self._iter = None
            batch = self._next_batch()
        return batch[0]

    def _next_batch(self):
        try:
            return next(iter(self._dataloader))
        except StopIteration:
            raise ValueError(
                "DataloaderGenerator: underlying dataloader yields no batches "
                "(dataset smaller than batch_size with drop_last=True?)"
            ) from None


__all__ = [
    "BaseGenerator",
    "RandomTensorGenerator",
    "RandomTokenGenerator",
    "DataloaderGenerator",
]
