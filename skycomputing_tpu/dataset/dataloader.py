"""A minimal numpy DataLoader.

The reference hands torch ``DataLoader`` objects around
(``builder/builder.py:44-49``); the TPU build keeps data on host as numpy and
feeds jit-compiled steps directly — no worker processes, no torch tensors.
Datasets are map-style: ``__len__`` + ``__getitem__`` returning
``(inputs_tuple, label)`` rows.
"""

from __future__ import annotations

from typing import Iterator, Sequence, Tuple

import numpy as np


def _stack(rows):
    """Stack a list of rows with matching nesting into batched arrays."""
    first = rows[0]
    if isinstance(first, (tuple, list)):
        return tuple(_stack([r[i] for r in rows]) for i in range(len(first)))
    return np.stack([np.asarray(r) for r in rows])


class DataLoader:
    def __init__(
        self,
        dataset,
        batch_size: int = 1,
        shuffle: bool = False,
        drop_last: bool = True,
        seed: int = 0,
        num_workers: int = 0,  # accepted for config parity; unused
    ):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        self._rng = np.random.default_rng(seed)

    def __len__(self) -> int:
        n = len(self.dataset)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size

    def __iter__(self) -> Iterator[Tuple]:
        order = np.arange(len(self.dataset))
        if self.shuffle:
            self._rng.shuffle(order)
        for start in range(0, len(order), self.batch_size):
            idx = order[start : start + self.batch_size]
            if self.drop_last and len(idx) < self.batch_size:
                return
            rows = [self.dataset[int(i)] for i in idx]
            data = _stack([r[0] for r in rows])
            labels = np.asarray([r[1] for r in rows])
            yield data, labels


__all__ = ["DataLoader"]
