"""Toy datasets (reference: ``scaelum/dataset/dataset.py:15-46``)."""

from __future__ import annotations

import numpy as np

from ..registry import DATASET


@DATASET.register_module
class RandomMlpDataset:
    """Random-feature regression-style dataset for MLP smoke tests."""

    def __init__(self, num_samples: int = 256, in_features: int = 32,
                 num_classes: int = 4, seed: int = 0):
        rng = np.random.default_rng(seed)
        self.features = rng.normal(size=(num_samples, in_features)).astype(np.float32)
        self.labels = rng.integers(0, num_classes, size=(num_samples,))

    def __len__(self):
        return len(self.features)

    def __getitem__(self, idx):
        return (self.features[idx],), int(self.labels[idx])


@DATASET.register_module
class RandomImageDataset:
    """CIFAR-shaped random images (offline stand-in for CIFAR10Dataset)."""

    def __init__(self, num_samples: int = 256, shape=(3, 32, 32),
                 num_classes: int = 10, seed: int = 0):
        rng = np.random.default_rng(seed)
        self.images = rng.normal(size=(num_samples, *shape)).astype(np.float32)
        self.labels = rng.integers(0, num_classes, size=(num_samples,))

    def __len__(self):
        return len(self.images)

    def __getitem__(self, idx):
        return (self.images[idx],), int(self.labels[idx])


@DATASET.register_module
class RandomBertDataset:
    """Synthetic MNLI-shaped rows: ((input_ids, mask, segment_ids), label).

    Shape-identical to GlueDataset output (``dataset/bert_dataset.py:34-37``)
    so the whole training path runs with zero downloads.
    """

    def __init__(self, num_samples: int = 512, max_seq_length: int = 128,
                 vocab_size: int = 30522, num_classes: int = 3, seed: int = 0):
        rng = np.random.default_rng(seed)
        self.input_ids = rng.integers(
            5, vocab_size, size=(num_samples, max_seq_length), dtype=np.int32
        )
        lengths = rng.integers(8, max_seq_length + 1, size=(num_samples,))
        self.input_mask = (
            np.arange(max_seq_length)[None, :] < lengths[:, None]
        ).astype(np.int32)
        self.input_ids *= self.input_mask
        seg_split = rng.integers(1, max_seq_length, size=(num_samples,))
        self.segment_ids = (
            np.arange(max_seq_length)[None, :] >= seg_split[:, None]
        ).astype(np.int32) * self.input_mask
        self.labels = rng.integers(0, num_classes, size=(num_samples,))

    def __len__(self):
        return len(self.input_ids)

    def __getitem__(self, idx):
        return (
            (self.input_ids[idx], self.input_mask[idx], self.segment_ids[idx]),
            int(self.labels[idx]),
        )


@DATASET.register_module
class CIFAR10Dataset:
    """CIFAR-10 from the local binary distribution (reference registry name,
    ``scaelum/dataset/dataset.py:28``).

    Reads the standard ``data_batch_*.bin`` files (3073-byte records: 1 label
    byte + 3072 CHW pixel bytes) with pure numpy — no torchvision, no
    downloads.  Missing ``data_dir`` degrades to a deterministic synthetic
    set with identical row shapes, like ``GlueDataset``.
    """

    def __init__(self, data_dir: str = "", train: bool = True,
                 num_synthetic: int = 256, seed: int = 0):
        import glob
        import os

        pattern = "data_batch_*.bin" if train else "test_batch.bin"
        files = sorted(glob.glob(os.path.join(data_dir, pattern))) if data_dir else []
        if files:
            records = np.concatenate([
                np.frombuffer(open(f, "rb").read(), dtype=np.uint8).reshape(
                    -1, 3073
                )
                for f in files
            ])
            self.labels = records[:, 0].astype(np.int64)
            images = records[:, 1:].reshape(-1, 3, 32, 32)
            self.images = images.astype(np.float32) / 255.0
            self.synthetic = False
        else:
            if data_dir:
                from ..utils import Logger

                Logger().info(
                    f"CIFAR10Dataset: no {pattern} under {data_dir!r} — "
                    "using deterministic synthetic images (the binary "
                    "distribution unpacks into cifar-10-batches-bin/)"
                )
            rng = np.random.default_rng(seed)
            self.images = rng.random((num_synthetic, 3, 32, 32)).astype(
                np.float32
            )
            self.labels = rng.integers(0, 10, size=(num_synthetic,))
            self.synthetic = True

    def __len__(self):
        return len(self.images)

    def __getitem__(self, idx):
        return (self.images[idx],), int(self.labels[idx])


@DATASET.register_module
class RandomLmDataset:
    """Synthetic causal-LM rows: ((input_ids,), input_ids).

    Labels ARE the input ids (the loss shifts internally), with a repeated
    n-gram structure so a working LM visibly drives the loss toward zero.
    """

    def __init__(self, num_samples: int = 512, seq_length: int = 128,
                 vocab_size: int = 50257, ngram: int = 8, seed: int = 0):
        rng = np.random.default_rng(seed)
        reps = (seq_length + ngram - 1) // ngram
        rows = []
        for _ in range(num_samples):
            pattern = rng.integers(0, vocab_size, size=(ngram,), dtype=np.int32)
            rows.append(np.tile(pattern, reps)[:seq_length])
        self.input_ids = np.stack(rows)

    def __len__(self):
        return len(self.input_ids)

    def __getitem__(self, idx):
        row = self.input_ids[idx]
        return (row,), row


__all__ = [
    "RandomMlpDataset",
    "RandomImageDataset",
    "RandomBertDataset",
    "RandomLmDataset",
    "CIFAR10Dataset",
]
