"""Python-file-as-config system.

Parity with the reference config layer
(``/root/reference/scaelum/config/config.py:10-78``): a ``.py`` file is
executed, its non-dunder / non-module / non-class globals are harvested into an
attribute-dict ``Config``, with optional single-level ``base`` inheritance.
"""

from __future__ import annotations

import inspect
import os.path as osp
import sys
from importlib.machinery import SourceFileLoader
from typing import Any, Dict


class Config(dict):
    """Dict whose values are also reachable as attributes."""

    def __missing__(self, name):
        raise KeyError(name)

    def __getattr__(self, name: str) -> Any:
        try:
            return self[name]
        except KeyError as e:
            raise AttributeError(name) from e

    def __setattr__(self, name: str, value: Any) -> None:
        self[name] = value

    def update(self, config: Dict) -> "Config":  # type: ignore[override]
        for k, v in config.items():
            self[k] = v
        return self

    @staticmethod
    def from_dict(data: Dict) -> "Config":
        cfg = Config()
        cfg.update(data)
        return cfg


def _py_to_dict(py_path: str) -> Dict[str, Any]:
    """Execute a python file and harvest its plain-value globals."""
    if not py_path.endswith(".py"):
        raise ValueError(f"config file must be a .py file, got {py_path!r}")

    py_path = osp.abspath(py_path)
    parent_dir = osp.dirname(py_path)
    inserted = parent_dir not in sys.path
    if inserted:
        sys.path.insert(0, parent_dir)

    module_name = "_skytpu_config_" + osp.splitext(osp.basename(py_path))[0]
    try:
        loader = SourceFileLoader(fullname=module_name, path=py_path)
        module = loader.load_module()  # noqa: deprecated but dependency-free
    finally:
        if inserted:
            sys.path.remove(parent_dir)

    harvested = {
        k: v
        for k, v in vars(module).items()
        if not k.startswith("__")
        and not inspect.ismodule(v)
        and not inspect.isclass(v)
    }
    sys.modules.pop(module_name, None)
    return harvested


def load_config(file_path: str) -> Config:
    """Load a python config file, honoring a ``base = "other.py"`` field."""
    config = Config.from_dict(_py_to_dict(file_path))
    base = config.pop("base", None)
    if base:
        base_path = osp.join(osp.dirname(osp.abspath(file_path)), base)
        base_config = Config.from_dict(_py_to_dict(base_path))
        config = base_config.update(config)
    return config


__all__ = ["Config", "load_config"]
