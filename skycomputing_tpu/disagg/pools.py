"""DisaggFleet: role-specialized replica pools behind one handoff plane.

Disaggregated serving (DistServe / Splitwise, PAPERS.md) runs prefill
and decode on SEPARATE replica pools so each can specialize: prefill
replicas take big buckets and high prefill batch (throughput work,
compute-bound), decode replicas take deep slot ledgers and live-span
gathers (latency work, memory-bound).  The request's KV crosses the
pool boundary as the engine's own swap record — host page copies plus
the PR 16 checksum fold — wrapped in a :class:`~.handoff.HandoffRecord`
and conserved by a :class:`~.handoff.HandoffLedger`:

- **export** — a prefill replica that has seeded a request's first
  token detaches it through :meth:`~..serving.engine.ServingEngine.
  export_handoff` (the public preempt/swap path verbatim); the fleet
  takes custody of the (request, swap record) pair and the ledger
  enqueues the checksummed contract.
- **deliver** — each tick the fleet walks pending records in enqueue
  order: the decode pool's admission controller gates the seat, a
  ``plan_check.verify_handoff_payload`` pre-flight rejects geometry a
  decode engine cannot hold, and the router ranks decode replicas with
  the same page-aligned prefix affinity prefill placement uses.
  :meth:`~..serving.engine.ServingEngine.import_handoff` verifies the
  checksum FIRST; the resume path is the existing swap-in path, so the
  decode pool adds **no new compile shapes**.
- **conserve** — a corrupted record fails WITH a reason and the request
  recomputes from its prompt on the decode side (committed tokens
  intact: the stream is exact either way); a prefill replica that dies
  mid-handoff leaves its in-flight records fleet-held, and the pump
  re-dispatches them — nothing strands, which is exactly the invariant
  the chaos auditor gates (``chaos/invariants.py``).

The fleet loop, routing, self-heal and autoscaling are all inherited:
this class only adds role-aware dispatch (fresh work → prefill pool,
token-carrying work → decode pool, degrading to the whole fleet when a
pool is empty — both pools run the same engine type, so serving
degraded beats stranding), per-pool admission, and the handoff pump.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from ..fleet.admission import AdmissionController, AdmitDecision, BATCH
from ..fleet.fleet import ServingFleet
from ..fleet.replica import DRAINING, HEALTHY, EngineReplica
from ..serving.batcher import FAILED, FINISHED, Request
from ..serving.engine import _stage_slab_checksums
from ..serving.kv_cache import QuantizedPages
from ..telemetry import get_tracer
from .handoff import HandoffLedger, HandoffRecord, PENDING

# the two pool roles (EngineReplica.role values)
PREFILL = "prefill"
DECODE = "decode"


def _kv_dtype_name(engine) -> str:
    """The record/geometry dtype name — one normalization for BOTH
    sides of the handoff, so a None (default) paged dtype can never
    read as a mismatch against itself."""
    return str(engine.kv_dtype or "float32")


class DisaggFleet(ServingFleet):
    """Prefill + decode replica pools with a checksummed KV handoff."""

    def __init__(
        self,
        model_cfg,
        params_list,
        *,
        prefill_replicas: int = 1,
        decode_replicas: int = 1,
        prefill_kwargs: Optional[Dict[str, Any]] = None,
        decode_kwargs: Optional[Dict[str, Any]] = None,
        prefill_admission: Optional[AdmissionController] = None,
        decode_admission: Optional[AdmissionController] = None,
        devices=None,
        **kwargs,
    ):
        if prefill_replicas < 1 or decode_replicas < 1:
            raise ValueError(
                "a disaggregated fleet needs >= 1 replica in EACH "
                "pool (an empty pool cannot serve its phase)"
            )
        for banned in ("replicas", "replica_specs", "admission"):
            if banned in kwargs:
                raise ValueError(
                    f"{banned!r} is not a DisaggFleet knob: pool sizes "
                    f"are prefill_replicas/decode_replicas and each "
                    f"pool carries its own admission controller"
                )
        #: per-pool engine-kwarg overrides, kept for autoscaler ADDs:
        #: a scaled-up prefill replica re-forms with the PREFILL pool's
        #: operating point (big buckets, high prefill batch, chunked
        #: prefill), a decode add with the DECODE pool's (deep slots,
        #: live-span gather) — role_spec() is the single source
        self._pool_kwargs = {
            PREFILL: dict(prefill_kwargs or {}),
            DECODE: dict(decode_kwargs or {}),
        }
        devs = (list(devices) if devices is not None
                else list(jax.devices()))
        specs: List[Dict[str, Any]] = []
        seq = 0
        for role, count in ((PREFILL, int(prefill_replicas)),
                            (DECODE, int(decode_replicas))):
            for _ in range(count):
                spec = dict(self._pool_kwargs[role])
                spec["role"] = role
                spec["devices"] = [devs[seq % len(devs)]]
                specs.append(spec)
                seq += 1
        self._device_seq = seq
        self.prefill_admission = (prefill_admission
                                  or AdmissionController())
        self.decode_admission = (decode_admission
                                 or AdmissionController())
        # remember which baselines the CALLER left unset: the base ctor
        # stamps the front-door controller with fleet-wide capacity,
        # but each pool's bound was sized for THAT pool
        rescale = [
            ctrl for ctrl in (self.prefill_admission,
                              self.decode_admission)
            if getattr(ctrl, "baseline_capacity", None) is None
        ]
        #: the conservation ledger every handoff passes through — the
        #: chaos auditor's gate surface
        self.ledger = HandoffLedger()
        #: fleet-held swap payloads for PENDING records: (request,
        #: engine swap record).  Host-side numpy, so a dead prefill
        #: replica cannot take an in-flight handoff down with it.
        self._payloads: Dict[int, Tuple[Request, dict]] = {}
        #: token count at delivery, per delivered request — the first
        #: tick that grows past it closes the ``kv_handoff`` trace arc
        self._handoff_watermark: Dict[int, int] = {}
        super().__init__(model_cfg, params_list,
                         replica_specs=specs,
                         admission=self.prefill_admission,
                         devices=devs, **kwargs)
        for ctrl, role in ((self.prefill_admission, PREFILL),
                           (self.decode_admission, DECODE)):
            if ctrl in rescale:
                ctrl.baseline_capacity = max(
                    self._pool_capacity_slots(role), 1
                )

    # --- pool views ---------------------------------------------------------
    def pool_replicas(self, role: str) -> List[EngineReplica]:
        """Every replica carrying ``role``, any state."""
        return [r for r in self.replicas if r.role == role]

    def _pool_healthy(self, role: str) -> List[EngineReplica]:
        return [r for r in self.pool_replicas(role)
                if r.state == HEALTHY and not r.crashed
                and r.engine is not None]

    def _pool_capacity_slots(self, role: str) -> int:
        return sum(r.engine.num_slots
                   for r in self._pool_healthy(role))

    def _pool_pending_depth(self, role: str) -> int:
        depth = sum(r.engine.stats.queue_depth
                    for r in self._pool_healthy(role))
        if role == DECODE:
            # undelivered handoffs ARE decode backlog: the decode
            # pool's front door must see work that is committed but
            # not yet seated, or the bound lies under prefill pressure
            depth += len(self.ledger.pending())
        return depth

    def role_spec(self, role: str) -> Dict[str, Any]:
        """The replica spec a per-pool scale-up builds with: the
        pool's engine operating point, its role tag, and the next
        device in the fleet's round-robin placement.  This is what
        :class:`~..fleet.autoscaler.FleetAutoscaler` (per-pool mode)
        passes to ``add_replica``."""
        if role not in self._pool_kwargs:
            raise ValueError(
                f"unknown pool role {role!r} "
                f"(have {sorted(self._pool_kwargs)})"
            )
        spec = dict(self._pool_kwargs[role])
        spec["role"] = role
        spec["devices"] = [
            self._devices[self._device_seq % len(self._devices)]
        ]
        self._device_seq += 1
        return spec

    # --- per-pool admission + role-aware dispatch ---------------------------
    def _admit_decision(self, priority: str,
                        deadline_s: Optional[float]) -> AdmitDecision:
        """Both pools gate every submit: the prefill controller judges
        the pool the request enters, the decode controller judges the
        pool it must eventually seat on — admitting prefill work a full
        decode pool can never drain would just move the queue somewhere
        the Retry-After hint cannot see.  The binding rejection names
        its pool in the decision detail."""
        tpot = self._window_percentile(self._tpot_window, 50)
        for ctrl, role in ((self.prefill_admission, PREFILL),
                           (self.decode_admission, DECODE)):
            decision = ctrl.decide(
                pending=self._pool_pending_depth(role),
                capacity_slots=self._pool_capacity_slots(role),
                priority=priority,
                deadline_s=deadline_s,
                tpot_p50_s=tpot,
            )
            if not decision.admitted:
                detail = dict(decision.detail or {})
                detail["pool"] = role
                return AdmitDecision(
                    False, reason=decision.reason,
                    retry_after_s=decision.retry_after_s,
                    detail=detail,
                )
        return decision

    def _dispatch_role(self, request: Request) -> Optional[str]:
        """Fresh work prefills; work with committed tokens (a refused
        handoff recomputing, a migrated decode) belongs to the decode
        pool.  An empty pool degrades to fleet-wide dispatch — both
        pools run the same engine type, so serving degraded beats
        parking requests against a pool that may never re-form."""
        role = DECODE if request.tokens else PREFILL
        return role if self._pool_healthy(role) else None

    # --- the handoff pump ---------------------------------------------------
    def step(self) -> None:
        super().step()
        self._pump_handoffs()
        # the pump runs after the base step's flight tap; drain the
        # ledger transitions it just produced under the tick they
        # happened on (super().step() already advanced self.tick)
        self._flight_drain_ledger(self.tick - 1)

    def _pump_handoffs(self) -> None:
        """One pass of the handoff plane, after the fleet tick: deliver
        the records already in flight, THEN export this tick's finished
        prefills, then close arcs whose request took its first decode
        tick.  Deliver-before-export means every handoff spends at
        least one tick PENDING — the in-flight window where a prefill
        death or a corruption fault can actually land (export-then-
        deliver would close the window inside one pump, and the chaos
        plane could never observe a record mid-flight)."""
        self._deliver_pending()
        self._export_ready()
        self._close_arcs()

    def _export_ready(self) -> int:
        """Detach every prefill-pool request past its first token as a
        ledgered handoff; returns how many exported this pass."""
        exported = 0
        tracer = get_tracer()
        for replica in self.pool_replicas(PREFILL):
            if (replica.state not in (HEALTHY, DRAINING)
                    or replica.crashed or replica.engine is None):
                continue
            engine = replica.engine
            ready = [rid for rid, r in engine._running.items()
                     if r.tokens and not r.done]
            for rid in ready:
                # fleet-owned requests only, and at most one handoff
                # per request EVER (a degraded-dispatch decode landing
                # on a prefill replica must not re-export)
                if (rid not in self._pending
                        or self.ledger.state_of(rid) is not None):
                    continue
                try:
                    request, payload = engine.export_handoff(rid)
                except (KeyError, ValueError):
                    continue  # raced done/preempt-refusal; next tick
                record = HandoffRecord(
                    request_id=rid,
                    source=replica.name,
                    prompt_len=int(request.prompt.size),
                    prefilled_len=int(request.effective_prompt.size),
                    index=int(payload["index"]),
                    pages=int(payload["pages"]),
                    checksum=str(payload["checksum"]),
                    slab_checksums=tuple(
                        _stage_slab_checksums(payload["data"])
                    ),
                    page_size=int(engine.page_size),
                    max_pages_per_request=int(
                        engine.max_pages_per_request
                    ),
                    stages=len(engine.stages),
                    kv_dtype=_kv_dtype_name(engine),
                    tick=int(self.tick),
                )
                self.ledger.enqueue(record)
                self._payloads[rid] = (request, payload)
                # custody moves to the fleet: un-assign so a dying
                # prefill replica's dead-drain cannot collect (and
                # double-queue) a request that already left it — the
                # request stays in _pending, so has_work() holds
                self._assignment.pop(rid, None)
                exported += 1
                if tracer is not None:
                    tracer.async_begin(
                        "kv_handoff",
                        tracer.lane("fleet", "disagg"), rid,
                        {"request": rid, "source": replica.name,
                         "pages": record.pages,
                         "prefilled_len": record.prefilled_len},
                    )
        return exported

    def _decode_geometry(self) -> Optional[Dict[str, Any]]:
        """The decode pool's per-request KV shape (any healthy member
        — the pool is homogeneous by construction); None while the
        pool has no healthy replica."""
        for replica in self._pool_healthy(DECODE):
            e = replica.engine
            return dict(
                page_size=int(e.page_size),
                max_pages_per_request=int(e.max_pages_per_request),
                stages=len(e.stages),
                kv_dtype=_kv_dtype_name(e),
            )
        return None

    def _deliver_pending(self) -> int:
        """Seat pending records on the decode pool, enqueue order.

        Deferral is not failure: a full or headless decode pool leaves
        records PENDING and the next tick retries — the ledger (and the
        chaos auditor behind it) guarantees they cannot be forgotten.
        """
        pending = self.ledger.pending()
        if not pending:
            return 0
        from ..analysis.plan_check import verify_handoff_payload

        tracer = get_tracer()
        geometry = self._decode_geometry()
        tpot = self._window_percentile(self._tpot_window, 50)
        delivered = 0
        for record in pending:
            rid = record.request_id
            held = self._payloads.get(rid)
            if held is None:  # pragma: no cover - custody is internal
                self.ledger.mark_failed(rid, "handoff payload lost")
                continue
            request, payload = held
            if geometry is None:
                break  # headless decode pool: everything defers
            # the decode pool's own front door gates each seat (raw
            # engine queue depth: the pending-handoff backlog is what
            # is being drained HERE, counting it against itself would
            # wedge the pump)
            gate = self.decode_admission.decide(
                pending=sum(r.engine.stats.queue_depth
                            for r in self._pool_healthy(DECODE)),
                capacity_slots=self._pool_capacity_slots(DECODE),
                priority=BATCH,
                tpot_p50_s=tpot,
            )
            if not gate.admitted:
                break  # pool full/blipped: defer in enqueue order
            problems = verify_handoff_payload(record.to_dict(),
                                              geometry)
            if problems:
                # verify-then-apply: a record no decode engine can
                # seat dies HERE with a reason, and the request
                # recomputes from its prompt (role-aware redispatch)
                self._payloads.pop(rid, None)
                self.ledger.mark_failed(
                    rid, f"handoff geometry mismatch: {problems[0]}"
                )
                self._end_arc(rid, tracer, outcome="geometry_reject")
                self._redispatch_one(request)
                continue
            ranked = self.router.rank(self.replica_snapshots(),
                                      prompt=request.prompt,
                                      role=DECODE)
            outcome: Optional[bool] = None
            target = ""
            for name in ranked:
                rep = self._by_name[name]
                try:
                    outcome = rep.engine.import_handoff(request,
                                                        payload)
                except ValueError:
                    continue  # request already live there; next
                target = name
                break
            if outcome is None:
                continue  # nobody could take it; stays PENDING
            self._payloads.pop(rid, None)
            if outcome:
                self.ledger.mark_delivered(rid, target)
                self._assignment[rid] = target
                self._handoff_watermark[rid] = len(request.tokens)
                self.router.record_dispatch(target, request.prompt)
                delivered += 1
            else:
                # checksum refused at import: counted on the decode
                # engine (handoff_failures), reasoned in the ledger,
                # and the request is already re-queued there to
                # recompute from its prompt — or FAILED with a verdict
                # when its resume prefix fits no bucket
                self.ledger.mark_failed(
                    rid, "checksum mismatch at import; recomputing "
                         "from prompt"
                )
                if request.status == FAILED:
                    self._end_arc(rid, tracer, outcome="failed")
                    self._fail(request, request.fail_reason
                               or "handoff record corrupted")
                else:
                    self._assignment[rid] = target
                    self._end_arc(rid, tracer, outcome="recompute")
        return delivered

    def _close_arcs(self) -> None:
        """End each delivered request's ``kv_handoff`` arc at its
        first decode tick past the delivery watermark (or terminal
        state) — the TTFT-shaped span of the pool gap itself."""
        if not self._handoff_watermark:
            return
        tracer = get_tracer()
        for rid in list(self._handoff_watermark):
            request = self._pending.get(rid) or self._finished.get(rid)
            mark = self._handoff_watermark[rid]
            if request is None:
                # swept terminal between pumps; close what we can
                del self._handoff_watermark[rid]
                self._end_arc(rid, tracer, outcome="terminal")
                continue
            if (len(request.tokens) > mark or request.done
                    or request.status in (FINISHED, FAILED)):
                del self._handoff_watermark[rid]
                self._end_arc(rid, tracer,
                              outcome="first_decode_tick",
                              tokens=len(request.tokens))

    def _end_arc(self, rid: int, tracer, **args) -> None:
        if tracer is None:
            return
        tracer.async_end("kv_handoff",
                         tracer.lane("fleet", "disagg"), rid,
                         dict(args, request=rid))

    # --- chaos surface ------------------------------------------------------
    def corrupt_handoff(self, request_id: Optional[int] = None,
                        *, force: bool = False) -> Optional[int]:
        """Flip a byte in a fleet-held handoff payload (the sanctioned
        ``handoff_corruption`` chaos hook — rot on the wire between
        pools, applied through the custody surface, never by
        monkeypatching).

        Targets ``request_id``'s pending payload when given, else the
        oldest pending one.  With ``force`` and nothing in flight, an
        export pass runs first so there is something to poison.
        Returns the corrupted request id, or None when no handoff
        exists and none can be forced — the injector logs that
        honestly instead of inventing a fault that never happened."""
        def pending_ids() -> List[int]:
            return [r.request_id for r in self.ledger.pending()
                    if r.request_id in self._payloads]

        if request_id is not None:
            if (self.ledger.state_of(request_id) != PENDING
                    or request_id not in self._payloads):
                raise KeyError(
                    f"request {request_id} holds no pending handoff"
                )
            rid: Optional[int] = request_id
        else:
            ids = pending_ids()
            rid = min(ids) if ids else None
            if rid is None and force:
                self._export_ready()
                ids = pending_ids()
                rid = min(ids) if ids else None
            if rid is None:
                return None
        _request, payload = self._payloads[rid]
        pairs = payload["data"][0]
        k_host, v_host = pairs[0]
        leaf = k_host.values if isinstance(k_host, QuantizedPages) \
            else k_host
        raw = bytearray(np.ascontiguousarray(leaf).tobytes())
        raw[0] ^= 0xFF
        bad = np.frombuffer(bytes(raw), dtype=leaf.dtype).reshape(
            leaf.shape
        )
        if isinstance(k_host, QuantizedPages):
            k_host = QuantizedPages(bad, k_host.scale)
        else:
            k_host = bad
        pairs[0] = (k_host, v_host)
        return rid


__all__ = [
    "DECODE",
    "DisaggFleet",
    "PREFILL",
]
