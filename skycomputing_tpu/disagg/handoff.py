"""The KV-handoff contract: portable prefill→decode records + ledger.

Disaggregated serving (DistServe/Splitwise, PAPERS.md) splits one
request across two engines: a prefill specialist computes the KV pages,
a decode specialist consumes them.  The thing that crosses the gap is a
:class:`HandoffRecord` — request identity, the prefilled watermark, the
page-table index, and the sha256 checksum fold the PR 16 swap plane
already computes over every host slab (``_swap_record_checksum``), plus
one per-stage slab digest so a corrupted stage is attributable.  The
record deliberately carries NO tensor data: the page payload rides the
engine's own swap record (host numpy), and this module stays pure
stdlib so the CI lint job can file-path-load it on a bare runner
(``tools/disagg_smoke.py``) and prove the contract without jax or
numpy installed.

:class:`HandoffLedger` is the front door's conservation ledger.  Its
invariant — **every enqueued record sits in exactly one of
{pending, delivered, failed-with-reason}** — is what the chaos auditor
gates (``chaos/invariants.py``): a prefill replica may die mid-handoff,
a record may arrive corrupted, the decode pool may be full for a while,
but no request is ever stranded or double-consumed.  State moves are
strict (``pending → delivered``, ``pending|delivered → failed``), every
failure needs a reason, and the event log is wall-clock free so two
same-seed runs produce byte-identical ledgers.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

# ledger states — the three (and only three) places a record can be
PENDING = "PENDING"
DELIVERED = "DELIVERED"
FAILED = "FAILED"
HANDOFF_STATES = (PENDING, DELIVERED, FAILED)


def _is_hex_digest(value: Any) -> bool:
    """A sha256 hex digest: 64 lowercase hex chars."""
    return (
        isinstance(value, str)
        and len(value) == 64
        and all(c in "0123456789abcdef" for c in value)
    )


def _pos_int(value: Any) -> bool:
    return isinstance(value, int) and not isinstance(value, bool) \
        and value >= 1


def _non_neg_int(value: Any) -> bool:
    return isinstance(value, int) and not isinstance(value, bool) \
        and value >= 0


@dataclass(frozen=True)
class HandoffRecord:
    """One request's portable KV identity, prefill side → decode side.

    The geometry fields (``page_size`` / ``max_pages_per_request`` /
    ``stages`` / ``kv_dtype``) are the exporting engine's — the
    importing side refuses a record whose geometry does not match its
    own (``plan_check.verify_handoff_payload``), because a swap record
    gathered under one page shape cannot seat under another.  The pool
    COUNT may differ between pools: sentinel page tables are rebuilt
    locally at swap-in, so only the per-request shape must agree.
    """

    request_id: int
    #: exporting (prefill) replica name — dead-source re-dispatch keys
    #: off this
    source: str
    prompt_len: int
    #: the prefill watermark: prompt tokens plus every token the
    #: prefill side already committed (the decode side resumes HERE)
    prefilled_len: int
    #: page-table write index the decode side resumes at
    index: int
    pages: int
    #: the PR 16 ``_swap_record_checksum`` fold over the whole record
    checksum: str
    #: one sha256 per stage's host slabs — a mismatch names the stage
    slab_checksums: Tuple[str, ...]
    page_size: int
    max_pages_per_request: int
    stages: int
    kv_dtype: str
    #: fleet tick the export happened on (deterministic, not wall time)
    tick: int = 0

    def __post_init__(self):
        if not _non_neg_int(self.request_id):
            raise ValueError(
                f"request_id must be a non-negative int, got "
                f"{self.request_id!r}"
            )
        if not isinstance(self.source, str) or not self.source:
            raise ValueError("source must be a non-empty replica name")
        for name in ("prompt_len", "prefilled_len", "index", "pages",
                     "page_size", "max_pages_per_request", "stages"):
            if not _pos_int(getattr(self, name)):
                raise ValueError(
                    f"{name} must be a positive int, got "
                    f"{getattr(self, name)!r}"
                )
        if self.prefilled_len < self.prompt_len:
            raise ValueError(
                f"prefilled watermark {self.prefilled_len} is below the "
                f"prompt length {self.prompt_len}: the prefill side "
                f"must at least cover the prompt"
            )
        if self.pages > self.max_pages_per_request:
            raise ValueError(
                f"pages={self.pages} exceeds max_pages_per_request="
                f"{self.max_pages_per_request}"
            )
        if self.pages * self.page_size < self.index:
            raise ValueError(
                f"{self.pages} pages of {self.page_size} tokens cannot "
                f"cover page-table index {self.index}"
            )
        if not _is_hex_digest(self.checksum):
            raise ValueError(
                "checksum must be a 64-char lowercase sha256 hex digest"
            )
        if (not isinstance(self.slab_checksums, tuple)
                or len(self.slab_checksums) != self.stages
                or not all(_is_hex_digest(c)
                           for c in self.slab_checksums)):
            raise ValueError(
                f"slab_checksums must be a tuple of {self.stages} "
                f"sha256 hex digests (one per stage)"
            )
        if not isinstance(self.kv_dtype, str) or not self.kv_dtype:
            raise ValueError("kv_dtype must be a non-empty dtype name")
        if not _non_neg_int(self.tick):
            raise ValueError(
                f"tick must be a non-negative int, got {self.tick!r}"
            )

    def key(self) -> tuple:
        """Digest-stable identity (everything, no wall-clock fields)."""
        return (
            self.request_id, self.source, self.prompt_len,
            self.prefilled_len, self.index, self.pages, self.checksum,
            self.slab_checksums, self.page_size,
            self.max_pages_per_request, self.stages, self.kv_dtype,
            self.tick,
        )

    def to_dict(self) -> Dict[str, Any]:
        """The payload shape ``verify_handoff_payload`` checks."""
        return dict(
            request_id=self.request_id,
            source=self.source,
            prompt_len=self.prompt_len,
            prefilled_len=self.prefilled_len,
            index=self.index,
            pages=self.pages,
            checksum=self.checksum,
            slab_checksums=list(self.slab_checksums),
            page_size=self.page_size,
            max_pages_per_request=self.max_pages_per_request,
            stages=self.stages,
            kv_dtype=self.kv_dtype,
            tick=self.tick,
        )


@dataclass
class _Entry:
    record: HandoffRecord
    state: str = PENDING
    #: decode replica the record was delivered to (set on delivery)
    target: str = ""
    #: failure reason (set on failure; never empty for FAILED)
    reason: Optional[str] = None


class HandoffLedger:
    """Conservation ledger for in-flight prefill→decode handoffs.

    Every record :meth:`enqueue`\\ d here is tracked until it is either
    :meth:`mark_delivered` (the decode side seated the swap record) or
    :meth:`mark_failed` (with a mandatory reason — corruption that fell
    back to recompute, a source that died before export completed,
    ...).  A delivered record may still fail afterwards (the decode
    side's swap-in verifies checksums FIRST and may only then discover
    corruption), so ``delivered → failed`` is a legal move; everything
    else terminal is final.  :meth:`audit` is the conservation check
    the chaos auditor gates.
    """

    def __init__(self):
        self._entries: Dict[int, _Entry] = {}
        # monotonic totals (counter discipline: these only go up)
        self.enqueued_total = 0
        self.delivered_total = 0
        self.failed_total = 0
        #: deterministic event log (no wall-clock, no ids beyond the
        #: request's own) — same-seed runs replay this byte-identically
        self.events: List[Dict[str, Any]] = []

    # --- state moves --------------------------------------------------------
    def enqueue(self, record: HandoffRecord) -> None:
        if not isinstance(record, HandoffRecord):
            raise ValueError(
                f"ledger takes HandoffRecord, got {type(record).__name__}"
            )
        if record.request_id in self._entries:
            raise ValueError(
                f"request {record.request_id} already has a handoff "
                f"(each request hands off at most once)"
            )
        self._entries[record.request_id] = _Entry(record=record)
        self.enqueued_total += 1
        self.events.append(dict(kind="enqueue",
                                request_id=record.request_id,
                                source=record.source,
                                tick=record.tick))

    def mark_delivered(self, request_id: int, target: str = "") -> None:
        entry = self._require(request_id)
        if entry.state != PENDING:
            raise ValueError(
                f"request {request_id} is {entry.state}, only PENDING "
                f"records can be delivered"
            )
        entry.state = DELIVERED
        entry.target = str(target)
        self.delivered_total += 1
        self.events.append(dict(kind="deliver", request_id=request_id,
                                target=str(target)))

    def mark_failed(self, request_id: int, reason: str) -> None:
        if not isinstance(reason, str) or not reason:
            raise ValueError(
                "a failed handoff needs a non-empty reason (conservation "
                "means failed-WITH-reason, never silently dropped)"
            )
        entry = self._require(request_id)
        if entry.state == FAILED:
            raise ValueError(
                f"request {request_id} already failed "
                f"({entry.reason!r})"
            )
        entry.state = FAILED
        entry.reason = reason
        self.failed_total += 1
        self.events.append(dict(kind="fail", request_id=request_id,
                                reason=reason))

    def _require(self, request_id: int) -> _Entry:
        entry = self._entries.get(request_id)
        if entry is None:
            raise ValueError(
                f"request {request_id} was never enqueued"
            )
        return entry

    # --- queries ------------------------------------------------------------
    def state_of(self, request_id: int) -> Optional[str]:
        entry = self._entries.get(request_id)
        return None if entry is None else entry.state

    def record(self, request_id: int) -> HandoffRecord:
        return self._require(request_id).record

    def reason(self, request_id: int) -> Optional[str]:
        return self._require(request_id).reason

    def pending(self) -> List[HandoffRecord]:
        """PENDING records in enqueue order (dict order is insertion)."""
        return [e.record for e in self._entries.values()
                if e.state == PENDING]

    def pending_for(self, source: str) -> List[HandoffRecord]:
        """PENDING records exported by ``source`` — what a dead prefill
        replica leaves in flight; re-dispatch works off this list."""
        return [r for r in self.pending() if r.source == source]

    def counts(self) -> Dict[str, int]:
        out = {PENDING: 0, DELIVERED: 0, FAILED: 0}
        for entry in self._entries.values():
            out[entry.state] += 1
        return out

    # --- conservation -------------------------------------------------------
    def conservation_ok(self) -> bool:
        """Every enqueued record in exactly one state, every failure
        reasoned, totals consistent with the entry map."""
        counts = self.counts()
        if sum(counts.values()) != len(self._entries):
            return False  # pragma: no cover - states are an enum
        if len(self._entries) != self.enqueued_total:
            return False
        if self.failed_total != counts[FAILED]:
            return False
        # delivered_total counts deliveries (a delivered record that
        # later failed still WAS delivered), so it bounds from above
        if counts[DELIVERED] > self.delivered_total:
            return False
        return all(
            entry.reason
            for entry in self._entries.values()
            if entry.state == FAILED
        )

    def audit(self) -> Dict[str, Any]:
        """Artifact-ready conservation summary (what the chaos
        auditor's ledger check serializes)."""
        counts = self.counts()
        reasons: Dict[str, int] = {}
        for entry in self._entries.values():
            if entry.state == FAILED and entry.reason:
                reasons[entry.reason] = reasons.get(entry.reason, 0) + 1
        return dict(
            total=len(self._entries),
            pending=counts[PENDING],
            delivered=counts[DELIVERED],
            failed=counts[FAILED],
            failed_reasons=reasons,
            enqueued_total=self.enqueued_total,
            delivered_total=self.delivered_total,
            failed_total=self.failed_total,
            conservation_ok=self.conservation_ok(),
        )

    def snapshot(self) -> Dict[str, Any]:
        """Metrics-plane view: monotonic totals + the pending gauge."""
        counts = self.counts()
        return dict(
            handoffs_enqueued=self.enqueued_total,
            handoffs_delivered=self.delivered_total,
            handoffs_failed=self.failed_total,
            handoffs_pending=counts[PENDING],
        )


__all__ = [
    "DELIVERED",
    "FAILED",
    "HANDOFF_STATES",
    "HandoffLedger",
    "HandoffRecord",
    "PENDING",
]
