"""Disaggregated prefill/decode serving: role-specialized replica
pools joined by a checksummed, ledgered KV-handoff plane.

``handoff`` is pure stdlib by contract (the record/ledger contract,
file-path-loadable by ``tools/disagg_smoke.py`` on a bare CI runner);
``pools`` holds :class:`DisaggFleet`, the fleet subclass that runs the
two pools and pumps handoffs between them.
"""

from .handoff import (
    DELIVERED,
    FAILED,
    HANDOFF_STATES,
    HandoffLedger,
    HandoffRecord,
    PENDING,
)
from .pools import DECODE, DisaggFleet, PREFILL

__all__ = [
    "DECODE",
    "DELIVERED",
    "DisaggFleet",
    "FAILED",
    "HANDOFF_STATES",
    "HandoffLedger",
    "HandoffRecord",
    "PENDING",
    "PREFILL",
]
