"""Name -> constructor registries.

TPU-native analog of the reference's registry layer
(``/root/reference/scaelum/registry/registry.py:8-30``): string-keyed
registries with a ``register_module`` decorator, plus a fallback namespace so
configs can name library layers directly.  The reference falls back to
``torch.nn`` attributes; here the fallback is ``flax.linen`` so a config can
say e.g. ``Dense`` without an explicit registration.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional


class Registry:
    """A name -> class/callable registry with decorator-based registration."""

    def __init__(self, name: str, fallback_module: Any = None):
        self._name = name
        self._registry: Dict[str, Any] = {}
        self._fallback_module = fallback_module

    @property
    def name(self) -> str:
        return self._name

    @property
    def modules(self) -> Dict[str, Any]:
        return dict(self._registry)

    def register_module(self, cls: Optional[Callable] = None, *, name: Optional[str] = None):
        """Register a class/callable. Usable bare or with a ``name=`` override.

        ``@REG.register_module`` or ``@REG.register_module(name="Alias")``.
        """

        def _register(obj: Callable) -> Callable:
            key = name if name is not None else obj.__name__
            if key in self._registry and self._registry[key] is not obj:
                raise KeyError(
                    f"{key!r} is already registered in registry {self._name!r}"
                )
            self._registry[key] = obj
            return obj

        if cls is None:
            return _register
        return _register(cls)

    def register(self, name: str, obj: Any) -> None:
        """Non-decorator registration under an explicit name (aliases)."""
        self._registry[name] = obj

    def get_module(self, name: str) -> Any:
        if name in self._registry:
            return self._registry[name]
        if self._fallback_module is not None and hasattr(self._fallback_module, name):
            return getattr(self._fallback_module, name)
        raise KeyError(
            f"{name!r} is not registered in registry {self._name!r} and no "
            f"fallback provides it"
        )

    def __contains__(self, name: str) -> bool:
        try:
            self.get_module(name)
            return True
        except KeyError:
            return False

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"Registry(name={self._name!r}, keys={sorted(self._registry)})"


def _linen():
    import flax.linen as nn

    return nn


class _LazyFallback:
    """Defers the flax import so registry import stays cheap."""

    def __init__(self, loader):
        self._loader = loader
        self._mod = None

    def __getattr__(self, item):
        if self._mod is None:
            self._mod = self._loader()
        return getattr(self._mod, item)

    def __bool__(self):
        return True

    # hasattr() goes through __getattr__; ensure missing names raise AttributeError
    # (getattr on the real module does that for us).


LAYER = Registry("layer", fallback_module=_LazyFallback(_linen))
DATASET = Registry("dataset")
HOOKS = Registry("hooks")
DATA_GENERATOR = Registry("data_generator")
MODEL = Registry("model")
LOSS = Registry("loss")

__all__ = [
    "Registry",
    "LAYER",
    "DATASET",
    "HOOKS",
    "DATA_GENERATOR",
    "MODEL",
    "LOSS",
]
