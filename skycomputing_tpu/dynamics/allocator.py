"""Layer->worker allocation strategies.

Parity with ``scaelum/dynamics/allocator.py``: three strategies over joint
device + model profiles, writing each worker's layer slice into
``worker.model_config``, setting pipeline ``order``, and re-ranking so rank
equals stage order (``allocator.py:141-179``).

- ``optimal_allocate`` (reference :25-179): the MIP — minimize
  ``max_d dt[d] * sum(lf[layers of d])`` under per-device memory and
  contiguity.  Solved by the built-in exact/greedy solver
  (:mod:`.solver`) instead of shelling out to CBC; same math, no native
  solver dependency.
- ``dynamic_allocate`` (reference :181-257): even split, then memory repair,
  then iterative flops x time balancing.
- ``even_allocate`` (reference :259-293): floor division + remainder spread.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Tuple

from ..telemetry import trace_span
from ..utils import Logger
from .benchmarker import DeviceBenchmarker, ModelBenchmarker
from .solver import solve_contiguous_minmax, solve_mesh_shapes
from .worker_manager import WorkerManager


class Allocator:
    def __init__(
        self,
        model_cfg: List[Dict],
        worker_manager: WorkerManager,
        model_benchmarker: ModelBenchmarker,
        device_benchmarker: DeviceBenchmarker,
        logger: Optional[Logger] = None,
    ):
        self._model_cfg = model_cfg
        self._worker_manager = worker_manager
        self._model_benchmarker = model_benchmarker
        self._device_benchmarker = device_benchmarker
        self._logger = logger or Logger()
        self._cost_override: Optional[List[float]] = None
        # worker.id -> multiplicative device-speed correction, learned from
        # live training telemetry (calibrate_device_speeds).  Keyed by the
        # worker's stable id, not rank: allocation re-ranks the pool.
        self._speed_override: Dict[str, float] = {}

    # ------------------------------------------------------------------ util
    @property
    def model_config(self) -> List[Dict]:
        """The per-layer config list this allocator partitions — the
        exact list a plan verifier needs (``verify_plan(model_config,
        worker_manager, batch)``), exposed so closed-loop callers (the
        autotuner) don't reach into privates."""
        return self._model_cfg

    def snapshot_calibration(self) -> Dict[str, object]:
        """Everything :meth:`restore_calibration` needs to undo learned
        corrections: the per-layer cost override and the per-device
        speed override.  A rolled-back tuning proposal must revert BOTH
        the partition and the calibration that produced it — otherwise
        the next solve re-derives the same rejected plan from the
        poisoned model."""
        return {
            "cost": (
                list(self._cost_override)
                if self._cost_override is not None else None
            ),
            "speed": dict(self._speed_override),
        }

    def restore_calibration(self, snapshot: Dict[str, object]) -> None:
        cost = snapshot["cost"]
        self._cost_override = list(cost) if cost is not None else None
        self._speed_override = dict(snapshot["speed"])

    def _profiles(self):
        device_results = self._device_benchmarker.benchmark()
        layer_flops, layer_mem = self._model_benchmarker.benchmark()
        if getattr(self, "_cost_override", None) is not None:
            layer_flops = list(self._cost_override)

        worker_ranks = [
            int(name.lstrip("worker")) for name in device_results.keys()
        ]
        perf = list(device_results.values())
        device_time = [p["time"] for p in perf]
        device_mem = [p["avai_mem"] for p in perf]
        if getattr(self, "_speed_override", None):
            device_time = [
                t * self._speed_override.get(
                    self._worker_manager.get_by_rank(r).id, 1.0
                )
                for r, t in zip(worker_ranks, device_time)
            ]
        return worker_ranks, device_time, device_mem, layer_flops, layer_mem

    def _apply_partition(
        self,
        worker_ranks: List[int],
        ranges: List[Optional[Tuple[int, int]]],
        orders: List[int],
    ) -> WorkerManager:
        """Write layer slices + pipeline order onto workers, then re-rank."""
        for rank, rng, order in zip(worker_ranks, ranges, orders):
            worker = self._worker_manager.get_by_rank(rank)
            if rng is None:
                worker.model_config = []
            else:
                worker.model_config = self._model_cfg[rng[0] : rng[1]]
            worker.order = order
            self._logger.info(
                f"worker rank {rank}: layers {rng}, pipeline order {order}"
            )
        self._worker_manager.reset_rank_by_order()
        return self._worker_manager

    # --------------------------------------------------------------- optimal
    def optimal_allocate(
        self, max_time: float = 300, threads: int = 24
    ) -> WorkerManager:
        """MIP-equivalent bottleneck-optimal allocation.

        ``max_time`` bounds the solver's anneal wall clock, matching the
        reference's MIP time limit semantics
        (``scaelum/dynamics/allocator.py:109-132`` gives CBC 300 s); on a
        slow host the binary-search + local-search solution is returned
        once the budget is spent, with whatever certified gap it reached.
        ``threads`` is accepted for reference-signature parity only — the
        built-in solver is single-threaded.
        """
        with trace_span("allocator.profiles", "dynamics", "allocator"):
            (worker_ranks, device_time, device_mem, layer_flops,
             layer_mem) = self._profiles()
        self._logger.info(
            f"optimal_allocate: {len(layer_flops)} layers over "
            f"{len(worker_ranks)} workers; device_time={device_time}"
        )

        with trace_span(
            "allocator.solve", "dynamics", "allocator",
            {"layers": len(layer_flops), "workers": len(worker_ranks)},
        ):
            result = solve_contiguous_minmax(
                layer_cost=layer_flops,
                layer_mem=layer_mem,
                device_time=device_time,
                device_mem=device_mem,
                anneal_seconds=max_time,
            )
        # exposed for callers that report provenance (bench.py stamps the
        # certified optimality gap into its JSON artifact)
        self.last_result = result
        self._logger.info(
            f"optimal bottleneck: {result.bottleneck:.4g} "
            f"(certified lower bound {result.lower_bound:.4g}, gap "
            f"{result.optimality_gap:.4f}, device order "
            f"{result.device_order})"
        )

        ranges = result.as_ranges(len(worker_ranks))
        # Pipeline order: devices in slice order first, empty devices after.
        orders = [0] * len(worker_ranks)
        pos = 1
        for d in result.device_order:
            orders[d] = pos
            pos += 1
        for d in range(len(worker_ranks)):
            if ranges[d] is None:
                orders[d] = pos
                pos += 1
        return self._apply_partition(worker_ranks, ranges, orders)

    # --------------------------------------------------------------- serving
    def serving_allocate(
        self, decode_benchmarker, max_time: float = 300
    ) -> WorkerManager:
        """Bottleneck-optimal partition for DECODE-step serving load.

        Same solver, different physics: the contiguous min-max machinery
        behind :meth:`optimal_allocate` (exact subset/class DP, anneal
        fallback) is profile-agnostic, so serving balance is obtained by
        swapping the per-layer profile — ``decode_benchmarker`` (a
        :class:`~..serving.profile.DecodeModelBenchmarker`) supplies one
        decode iteration's FLOPs as cost and params + preallocated
        KV-slab MB as memory, instead of the training fwd+bwd numbers.
        A training partition balances matmul-heavy FFN slices; a decode
        partition must also balance the attention units' O(max_len)
        cache reads and FIT each stage's slabs under ``mem_limit`` —
        reusing training costs mis-loads both.

        Any training-calibrated cost override
        (:meth:`calibrate_costs` and friends) is stashed for the solve:
        those corrections were learned at training granularity and
        would silently distort the decode profile.  The device-speed
        override stays — node degradation is workload-independent.
        """
        saved_bench = self._model_benchmarker
        saved_override = self._cost_override
        self._model_benchmarker = decode_benchmarker
        self._cost_override = None
        try:
            return self.optimal_allocate(max_time=max_time)
        finally:
            self._model_benchmarker = saved_bench
            self._cost_override = saved_override

    # ----------------------------------------------------- closed-loop refine
    def calibrate_costs(
        self, stage_layer_counts, measured_stage_times,
        damping: float = 1.0,
    ) -> None:
        """Rescale the per-layer cost model from ANY allocation's measured
        stage times — without re-solving.

        ``stage_layer_counts``/``measured_stage_times``: pipeline-order
        slice lengths and raw per-stage seconds of the allocation that was
        measured (need not be this allocator's current one).  The classic
        use is seeding the *first* optimal solve from the even baseline's
        measurement, which the headline bench takes anyway: isolated
        per-unit profiles miss slice-level fusion/cache effects, while the
        even pass measures every layer at deployment granularity for free.
        ``refine_allocation`` is this plus a re-solve, with the counts
        read from the allocator's own current allocation.
        """
        base_costs, _ = self._model_benchmarker.benchmark()
        costs = list(
            self._cost_override
            if getattr(self, "_cost_override", None) is not None
            else base_costs
        )
        if len(stage_layer_counts) != len(measured_stage_times):
            raise ValueError(
                f"{len(measured_stage_times)} measured times for "
                f"{len(stage_layer_counts)} stages"
            )
        pos = 0
        for n, t in zip(stage_layer_counts, measured_stage_times):
            pred = sum(costs[pos:pos + n])
            if pred > 0 and t > 0:
                scale = (float(t) / pred) ** float(damping)
                costs[pos:pos + n] = [c * scale for c in costs[pos:pos + n]]
            pos += n
        if pos != len(costs):
            raise ValueError(
                f"stage slices cover {pos} layers, model has {len(costs)}"
            )
        self._cost_override = costs

    def calibrate_costs_affine(
        self, stage_layer_counts, measured_stage_times
    ) -> Tuple[float, float]:
        """Fit a slice-size-aware cost model from measured stage times.

        The per-slice uniform rescale of :meth:`calibrate_costs` learns
        scales *at the measured allocation's granularity* — scales taken
        from an even split (3-4 units/stage) transfer poorly to the
        solver's output (1-10 units/stage), so the first optimal solve
        lands far from the measurement-refined answer (r04 headline:
        83.1 s first solve vs 29.0 s after three refine rounds).

        This fits the two-parameter model

            t_stage  ≈  a * sum(unit_costs in slice)  +  b * |slice|

        by least squares over the measured stages: ``a`` scales the
        profiled per-unit compute, ``b`` absorbs the per-unit overhead
        (dispatch, layer-boundary materialization, cache effects) that an
        isolated per-unit profile cannot see.  Both terms are additive per
        layer, so the calibrated instance stays inside the contiguous
        min-max solver's cost model: ``cost'_i = a * cost_i + b``.
        Negative fits are clamped to the best one-parameter model.

        Returns ``(a, b)`` for provenance.
        """
        base_costs, _ = self._model_benchmarker.benchmark()
        costs = list(base_costs)
        if len(stage_layer_counts) != len(measured_stage_times):
            raise ValueError(
                f"{len(measured_stage_times)} measured times for "
                f"{len(stage_layer_counts)} stages"
            )
        if sum(stage_layer_counts) != len(costs):
            raise ValueError(
                f"stage slices cover {sum(stage_layer_counts)} layers, "
                f"model has {len(costs)}"
            )
        import numpy as np

        sums, ns = [], []
        pos = 0
        for n in stage_layer_counts:
            sums.append(sum(costs[pos:pos + n]))
            ns.append(float(n))
            pos += n
        X = np.stack([np.asarray(sums), np.asarray(ns)], axis=1)
        y = np.asarray(measured_stage_times, dtype=np.float64)
        a = b = -1.0
        if len(y) >= 2:
            sol, *_ = np.linalg.lstsq(X, y, rcond=None)
            a, b = float(sol[0]), float(sol[1])
        if a < 0.0 or b < 0.0 or len(y) < 2:
            # degenerate (collinear features / tiny sample): fall back to
            # whichever single-term model explains the data better
            s, n = X[:, 0], X[:, 1]
            a_only = float(np.dot(y, s) / max(np.dot(s, s), 1e-30))
            b_only = float(np.dot(y, n) / max(np.dot(n, n), 1e-30))
            if (np.sum((y - a_only * s) ** 2)
                    <= np.sum((y - b_only * n) ** 2)):
                a, b = max(a_only, 0.0), 0.0
            else:
                a, b = 0.0, max(b_only, 0.0)
        self._cost_override = [a * c + b for c in costs]
        return a, b

    def calibrate_costs_by_type(
        self, stage_layer_counts, measured_stage_times
    ):
        """Fit one cost per distinct UNIT TYPE from measured stage times.

        The affine fit (:meth:`calibrate_costs_affine`) keeps the noisy
        single-draw timed per-unit profile in its feature (``sum of unit
        costs``), so its parameters — especially the per-unit overhead
        term — swing run to run and the solver's allocation swings with
        them.  Deep stacked models have only a handful of distinct unit
        configs (the program cache dedups on exactly this), so the
        measured stages give a small well-posed regression

            t_stage  ≈  sum_type  count(stage, type) * c_type

        whose ONLY stochastic input is the stage-time medians — the
        per-unit profile drops out of the solve entirely.  Negative
        solutions are clamped to zero and the remainder refit
        (active-set) so the override stays a valid additive cost model.

        Returns ``{type_json: cost}`` for provenance.
        """
        import json as _json

        import numpy as np

        if len(stage_layer_counts) != len(measured_stage_times):
            raise ValueError(
                f"{len(measured_stage_times)} measured times for "
                f"{len(stage_layer_counts)} stages"
            )
        if sum(stage_layer_counts) != len(self._model_cfg):
            raise ValueError(
                f"stage slices cover {sum(stage_layer_counts)} layers, "
                f"model has {len(self._model_cfg)}"
            )
        type_of = [
            _json.dumps(cfg, sort_keys=True, default=str)
            for cfg in self._model_cfg
        ]
        types = sorted(set(type_of))
        tindex = {t: i for i, t in enumerate(types)}
        A = np.zeros((len(stage_layer_counts), len(types)))
        pos = 0
        for j, n in enumerate(stage_layer_counts):
            for i in range(pos, pos + n):
                A[j, tindex[type_of[i]]] += 1.0
            pos += n
        y = np.asarray(measured_stage_times, dtype=np.float64)
        active = list(range(len(types)))
        c = np.zeros(len(types))
        for _ in range(len(types) + 1):
            if not active:
                break
            sol, *_ = np.linalg.lstsq(A[:, active], y, rcond=None)
            neg = [k for k, v in zip(active, sol) if v < 0.0]
            for k, v in zip(active, sol):
                c[k] = max(v, 0.0)
            if not neg:
                break
            active = [k for k in active if k not in neg]
        # a zero-cost type would be "free" to the solver (degenerate
        # packing); floor clamped types at 5% of the median fitted cost
        positive = [v for v in c if v > 0.0]
        if positive:
            floor = 0.05 * float(np.median(positive))
            c = np.maximum(c, floor)
        self._cost_override = [float(c[tindex[t]]) for t in type_of]
        return {t: float(c[tindex[t]]) for t in types}

    # ------------------------------------------- device-speed calibration
    def _ordered_stage_workers(self, measured_stage_times) -> List:
        """Non-empty workers in pipeline order, validated against the
        measurement list length."""
        workers = sorted(
            (w for w in self._worker_manager.worker_pool if w.model_config),
            key=lambda w: w.order,
        )
        if len(workers) != len(measured_stage_times):
            raise ValueError(
                f"{len(measured_stage_times)} measured times for "
                f"{len(workers)} non-empty stages"
            )
        return workers

    def stage_divergence(self, measured_stage_times) -> Dict[int, float]:
        """Per-worker measured/modeled stage-time ratio, median-normalized.

        For each non-empty stage (pipeline order), the cost model predicts
        ``device_time[worker] * sum(layer costs in slice)``; the ratio of
        the MEASURED stage time to that prediction, divided by the median
        ratio across stages (which absorbs the model's arbitrary global
        units), isolates per-DEVICE anomalies: a healthy calibrated world
        reads ~1.0 everywhere, a 3x-degraded node reads ~3.0.  Keyed by
        the worker's stable ``stim_index`` so the figure survives
        re-ranking and process restarts (worker uuids don't).
        """
        workers = self._ordered_stage_workers(measured_stage_times)
        worker_ranks, device_time, _, layer_flops, _ = self._profiles()
        dt = dict(zip(worker_ranks, device_time))
        raw: Dict[int, float] = {}
        pos = 0
        for w, t in zip(workers, measured_stage_times):
            n = len(w.model_config)
            pred = dt[w.rank] * sum(layer_flops[pos:pos + n])
            raw[w.stim_index] = float(t) / pred if pred > 0 and t > 0 else 1.0
            pos += n
        if pos != len(layer_flops):
            raise ValueError(
                f"stage slices cover {pos} layers, model has "
                f"{len(layer_flops)}"
            )
        ratios = sorted(raw.values())
        mid = len(ratios) // 2
        median = (
            ratios[mid]
            if len(ratios) % 2
            else 0.5 * (ratios[mid - 1] + ratios[mid])
        )
        if median <= 0:
            return {k: 1.0 for k in raw}
        return {k: v / median for k, v in raw.items()}

    def calibrate_device_speeds(
        self, measured_stage_times, damping: float = 1.0
    ) -> Dict[int, float]:
        """Fold measured per-stage divergence into the DEVICE model.

        ``calibrate_costs`` attributes measured/predicted gaps to the
        LAYERS of each slice — right for slice-size effects (fusion,
        cache), wrong for a degraded node: rescaled layers stay expensive
        wherever the re-solve moves them, so the solver never routes work
        AWAY from the slow device.  This pass attributes the gap to the
        DEVICE instead (multiplying its modeled time by the normalized
        divergence), which is exactly the straggler model.  Multiplicative
        and keyed by stable worker id, so repeated calibrations converge:
        once the override matches reality the divergence reads 1.0.

        Returns the stim_index-keyed divergence ratios for provenance.
        """
        ratios = self.stage_divergence(measured_stage_times)
        for w in self._worker_manager.worker_pool:
            if w.stim_index in ratios:
                scale = ratios[w.stim_index] ** float(damping)
                self._speed_override[w.id] = (
                    self._speed_override.get(w.id, 1.0) * scale
                )
        return ratios

    def device_scales(self) -> Dict[int, float]:
        """The CUMULATIVE device-speed override, keyed by stable
        ``stim_index`` — the serializable form of everything this
        allocator has learned about node degradation.  This (not a single
        round's divergence) is what must cross a process boundary: a
        relaunched trainer starts with a fresh override, so staging only
        the latest measurement would silently drop every earlier
        correction."""
        return {
            w.stim_index: self._speed_override[w.id]
            for w in self._worker_manager.worker_pool
            if w.id in self._speed_override
        }

    def apply_device_scales(self, scales: Dict) -> None:
        """Seed the device-speed override from a serialized map
        (``{stim_index: scale}``, int or str keys — JSON round-trips
        stringify them).  This is how a re-formed elastic world carries a
        self-heal measurement across the process boundary: the exiting
        trainer stages the scales through the rendezvous payload and the
        relaunched trainer applies them before its first allocation."""
        by_index = {int(k): float(v) for k, v in scales.items()}
        for w in self._worker_manager.worker_pool:
            if w.stim_index in by_index:
                self._speed_override[w.id] = (
                    self._speed_override.get(w.id, 1.0)
                    * by_index[w.stim_index]
                )

    def refine_allocation(
        self, measured_stage_times, damping: float = 0.5,
        max_time: float = 300, attribute: str = "layers",
    ) -> WorkerManager:
        """Re-allocate with per-layer costs calibrated to MEASURED stage
        times — closed-loop allocation.

        Per-layer profiles (static FLOPs or isolated timed units) cannot
        see slice-level effects: cache pressure makes a 10-unit stage cost
        more than 10 x one unit, so the solver underestimates big slices
        and overloads fast devices.  This pass rescales every layer's cost
        by its own stage's measured/predicted ratio (the reference's
        ``dynamic_allocate`` rebalanced iteratively on flops x time for
        the same reason, ``scaelum/dynamics/allocator.py:181-257``; here
        the feedback is real wall time) and re-solves.  Call after
        ``optimal_allocate`` + a measurement pass
        (``PipelineModel.measure_stage_times``); iterate to converge —
        each round's slices change the slice-size effects being modeled.

        ``measured_stage_times`` are raw per-stage seconds, pipeline
        order, one per worker with a non-empty slice.  ``damping``
        exponentiates the per-stage correction (``scale**damping``):
        a full-strength update (1.0) can oscillate between two
        allocations — slice-level scales are applied uniformly to a
        slice's layers, so re-solved boundaries re-mix them — while a
        damped update contracts toward a fixed point.

        ``attribute`` picks where the measured/modeled gap lands:
        ``"layers"`` (default, the historical behavior) rescales the
        slice's layer costs — right for slice-size effects; ``"devices"``
        rescales the owning device's modeled speed
        (:meth:`calibrate_device_speeds`) — right for a degraded node,
        which is the self-healing runtime's case.
        """
        if attribute == "devices":
            # validates the measurement list itself (stage_divergence)
            with trace_span("allocator.calibrate", "dynamics", "allocator",
                            {"attribute": attribute}):
                self.calibrate_device_speeds(
                    measured_stage_times, damping=damping
                )
        elif attribute == "layers":
            workers = self._ordered_stage_workers(measured_stage_times)
            with trace_span("allocator.calibrate", "dynamics", "allocator",
                            {"attribute": attribute}):
                self.calibrate_costs(
                    [len(w.model_config) for w in workers],
                    measured_stage_times,
                    damping=damping,
                )
        else:
            raise ValueError(
                f"unknown attribute {attribute!r}; use 'layers' or 'devices'"
            )
        return self.optimal_allocate(max_time=max_time)

    # ------------------------------------------------------------------ mesh
    def mesh_allocate(
        self,
        num_devices: Optional[int] = None,
        max_stages: Optional[int] = None,
        max_chips_per_stage: Optional[int] = None,
        stage_overhead: float = 0.0,
    ) -> WorkerManager:
        """Mesh-native allocation: stages over contiguous sub-mesh slices.

        The mesh-shape search (:func:`~.solver.solve_mesh_shapes`)
        chooses BOTH the contiguous layer partition and chips-per-stage
        so per-stage time/chip balances, charging ``stage_overhead``
        (seconds of host dispatch per stage per tick) against longer
        issue loops.  The result lands on the worker pool the same way
        every allocator does — the first S workers carry the slices
        (pipeline order), plus ``extra_config['mesh_chips']`` naming
        each stage's sub-mesh width; the rest go empty.  A sub-mesh
        program runs its chips in lockstep, so the search treats chips
        as same-speed — per-device heterogeneity stays the MPMD
        engine's domain, while slice-level effects feed back through
        :meth:`refine_mesh_allocation`'s calibrated LAYER costs.
        """
        with trace_span("allocator.profiles", "dynamics", "allocator"):
            (worker_ranks, _device_time, device_mem, layer_flops,
             layer_mem) = self._profiles()
        D = int(num_devices) if num_devices else len(worker_ranks)
        with trace_span(
            "allocator.mesh_solve", "dynamics", "allocator",
            {"layers": len(layer_flops), "devices": D},
        ):
            result = solve_mesh_shapes(
                layer_flops, D,
                layer_mem=layer_mem,
                mem_per_chip=min(device_mem) if device_mem else None,
                max_stages=max_stages,
                max_chips_per_stage=max_chips_per_stage,
                stage_overhead=stage_overhead,
            )
        self.last_mesh = result
        # remember the operating point so a closed-loop refine re-solves
        # under the same constraints the operator chose
        self._mesh_opts = dict(
            num_devices=D, max_stages=max_stages,
            max_chips_per_stage=max_chips_per_stage,
            stage_overhead=stage_overhead,
        )
        self._logger.info(
            f"mesh_allocate: {len(layer_flops)} layers -> "
            f"{result.num_stages} stages x chips {result.chips} over "
            f"{D} devices (bottleneck {result.bottleneck:.4g})"
        )
        ranks_sorted = sorted(worker_ranks)
        slice_of = {
            ranks_sorted[i]: result.slices[i]
            for i in range(result.num_stages)
        }
        ranges = [slice_of.get(r) for r in worker_ranks]
        orders = [0] * len(worker_ranks)
        pos = 1
        for r in ranks_sorted[: result.num_stages]:
            orders[worker_ranks.index(r)] = pos
            pos += 1
        for i, r in enumerate(worker_ranks):
            if ranges[i] is None:
                orders[i] = pos
                pos += 1
        wm = self._apply_partition(worker_ranks, ranges, orders)
        staged = sorted(
            (w for w in wm.worker_pool if w.model_config),
            key=lambda w: w.order,
        )
        for w, k in zip(staged, result.chips):
            w.extra_config["mesh_chips"] = int(k)
        for w in wm.worker_pool:
            if not w.model_config:
                w.extra_config.pop("mesh_chips", None)
        return wm

    def refine_mesh_allocation(
        self, measured_stage_times, damping: float = 0.5,
        chips: Optional[List[int]] = None,
        **mesh_kwargs,
    ) -> WorkerManager:
        """PipeDream's profiler->partitioner loop for the mesh engine.

        Measured per-stage seconds reflect ``slice cost / chips`` —
        multiply back by each stage's sub-mesh width to recover the
        slice's effective cost, fold that into the LAYER cost model
        (:meth:`calibrate_costs`; device attribution is meaningless on
        homogeneous sub-meshes), and re-run the mesh-shape search under
        the operating point :meth:`mesh_allocate` recorded (overridable
        via ``mesh_kwargs``).

        ``chips``: the live engine's chips-per-stage, pipeline order.
        Pass it when the model was built with an explicit
        ``chips_per_stage`` argument instead of through
        :meth:`mesh_allocate` — the worker pool then carries no
        ``mesh_chips`` and the default-1 fallback would de-scale wide
        stages wrong (a 2-chip stage would read at half its real cost).
        When no operating point was recorded, the re-solve caps
        ``max_chips_per_stage`` at the widest LIVE stage — never wider
        than what the operator already runs.
        """
        workers = self._ordered_stage_workers(measured_stage_times)
        if chips is None:
            chips = [
                int(w.extra_config.get("mesh_chips", 1)) for w in workers
            ]
        elif len(chips) != len(workers):
            raise ValueError(
                f"{len(chips)} chips for {len(workers)} staged workers"
            )
        else:
            chips = [int(k) for k in chips]
        effective = [
            float(t) * k for t, k in zip(measured_stage_times, chips)
        ]
        with trace_span("allocator.calibrate", "dynamics", "allocator",
                        {"attribute": "mesh"}):
            self.calibrate_costs(
                [len(w.model_config) for w in workers],
                effective,
                damping=damping,
            )
        opts = dict(getattr(
            self, "_mesh_opts",
            {"max_chips_per_stage": max(chips)},
        ))
        opts.update(mesh_kwargs)
        return self.mesh_allocate(**opts)

    # --------------------------------------------------------------- dynamic
    def dynamic_allocate(self, break_iter: int = 1000) -> WorkerManager:
        """Greedy: even split -> memory repair -> flops x time balancing."""
        (worker_ranks, device_time, device_mem, layer_flops, layer_mem) = (
            self._profiles()
        )

        if min(device_mem) <= min(layer_mem):
            raise RuntimeError(
                "The smallest worker has insufficient memory for the "
                "smallest layer"
            )

        num_layer = len(layer_flops)
        num_worker = len(worker_ranks)
        avg = math.floor(num_layer / num_worker)
        remainder = num_layer - avg * num_worker
        counts = [avg + (1 if i < remainder else 0) for i in range(num_worker)]
        partition_idx = [0]
        for c in counts:
            partition_idx.append(partition_idx[-1] + c)

        partition_idx = self._allocate_by_mem(
            partition_idx, device_mem, layer_mem
        )
        partition_idx = self._allocate_by_flops_time(
            partition_idx, device_time, layer_flops, device_mem, layer_mem,
            break_iter,
        )

        ranges: List[Optional[Tuple[int, int]]] = [
            (partition_idx[i], partition_idx[i + 1]) for i in range(num_worker)
        ]
        orders = list(range(1, num_worker + 1))
        return self._apply_partition(worker_ranks, ranges, orders)

    # ------------------------------------------------------------------ even
    def even_allocate(self) -> WorkerManager:
        """Pure arithmetic split, no profiling (reference :259-293)."""
        pool = self._worker_manager.worker_pool
        num_worker = len(pool)
        num_layer = len(self._model_cfg)
        avg = math.floor(num_layer / num_worker)
        remainder = num_layer - avg * num_worker

        cursor = 0
        for idx, worker in enumerate(pool):
            take = avg + (1 if idx < remainder else 0)
            worker.model_config = self._model_cfg[cursor : cursor + take]
            worker.order = idx + 1
            cursor += take
        return self._worker_manager

    # -------------------------------------------------- greedy repair passes
    @staticmethod
    def _mem_allocated(layer_mem, partition_idx):
        return [
            sum(layer_mem[partition_idx[j] : partition_idx[j + 1]])
            for j in range(len(partition_idx) - 1)
        ]

    def _allocate_by_mem(self, partition_idx, device_mem, layer_mem):
        """Shift slice boundaries until every device fits its slice.

        Reference ``_allocate_by_mem`` (:370-439): walk adjacent pairs,
        move boundary left when over capacity, right when there's headroom.
        """
        num_worker = len(device_mem)
        for _ in range(10 * num_worker * max(len(layer_mem), 1)):
            allocated = self._mem_allocated(layer_mem, partition_idx)
            if all(a <= m for a, m in zip(allocated, device_mem)):
                return partition_idx
            old = list(partition_idx)
            for j in range(num_worker - 1):
                # shrink overfull worker j from the right
                while (
                    self._mem_allocated(layer_mem, partition_idx)[j]
                    > device_mem[j]
                    and partition_idx[j + 1] - partition_idx[j] > 1
                ):
                    partition_idx[j + 1] -= 1
                # grow underfull worker j if the next can spare layers
                while (
                    partition_idx[j + 2] - partition_idx[j + 1] > 1
                    and sum(
                        layer_mem[partition_idx[j] : partition_idx[j + 1] + 1]
                    )
                    < device_mem[j]
                    and self._mem_allocated(layer_mem, partition_idx)[j + 1]
                    > device_mem[j + 1]
                ):
                    partition_idx[j + 1] += 1
            if old == partition_idx:
                break
        allocated = self._mem_allocated(layer_mem, partition_idx)
        if all(a <= m for a, m in zip(allocated, device_mem)):
            return partition_idx
        raise RuntimeError(f"memory allocation failed: {partition_idx}")

    def _allocate_by_flops_time(
        self, partition_idx, device_time, layer_flops, device_mem, layer_mem,
        break_iter,
    ):
        """Iteratively move boundaries toward equal flops x time per worker.

        Reference ``_allocate_by_flops_time`` (:295-368): compare each
        worker's load to the average target; grow cheap workers by one layer
        (memory permitting), shrink expensive ones.
        """
        norm = min(device_time)
        rel_time = [t / norm for t in device_time]
        num_worker = len(device_time)

        def load(j, idx):
            return sum(layer_flops[idx[j] : idx[j + 1]]) * rel_time[j]

        for _ in range(break_iter):
            target = sum(load(j, partition_idx) for j in range(num_worker)) / (
                num_worker
            )
            old = list(partition_idx)
            for j in range(num_worker - 1):
                current = load(j, partition_idx)
                if (
                    current < target
                    and partition_idx[j + 2] - partition_idx[j + 1] > 1
                ):
                    expected_mem = sum(
                        layer_mem[partition_idx[j] : partition_idx[j + 1] + 1]
                    )
                    if expected_mem < device_mem[j]:
                        partition_idx[j + 1] += 1
                else:
                    last_layer_cost = (
                        layer_flops[partition_idx[j + 1] - 1] * rel_time[j]
                    )
                    next_load = load(j + 1, partition_idx)
                    if (
                        next_load < target
                        and current > target + last_layer_cost
                        and partition_idx[j + 1] - partition_idx[j] > 1
                    ):
                        next_expected_mem = sum(
                            layer_mem[
                                partition_idx[j + 1] - 1 : partition_idx[j + 2]
                            ]
                        )
                        if next_expected_mem < device_mem[j + 1]:
                            partition_idx[j + 1] -= 1
            if old == partition_idx:
                break
        return partition_idx


__all__ = ["Allocator"]
