from .allocator import Allocator
from .benchmarker import (
    BaseBenchmarker,
    DeviceBenchmarker,
    ModelBenchmarker,
    device_available_memory_mb,
)
from .estimator import Estimator
from .parameter_server import ParameterServer
from .solver import PartitionResult, solve_contiguous_minmax
from .worker import Worker
from .worker_manager import WorkerManager

# imported last: faults.py reaches into ..runner for the Hook base, and
# runner.runner imports the names above from this (then partially
# initialized) module
from .faults import (  # noqa: E402
    FaultInjectionHook,
    FaultPlan,
    FleetFaultInjector,
)

__all__ = [
    "Allocator",
    "FaultInjectionHook",
    "FaultPlan",
    "FleetFaultInjector",
    "BaseBenchmarker",
    "DeviceBenchmarker",
    "ModelBenchmarker",
    "device_available_memory_mb",
    "Estimator",
    "ParameterServer",
    "PartitionResult",
    "solve_contiguous_minmax",
    "Worker",
    "WorkerManager",
]
