from .allocator import Allocator
from .benchmarker import (
    BaseBenchmarker,
    DeviceBenchmarker,
    ModelBenchmarker,
    device_available_memory_mb,
)
from .estimator import Estimator
from .parameter_server import ParameterServer
from .solver import PartitionResult, solve_contiguous_minmax
from .worker import Worker
from .worker_manager import WorkerManager

__all__ = [
    "Allocator",
    "BaseBenchmarker",
    "DeviceBenchmarker",
    "ModelBenchmarker",
    "device_available_memory_mb",
    "Estimator",
    "ParameterServer",
    "PartitionResult",
    "solve_contiguous_minmax",
    "Worker",
    "WorkerManager",
]
