from .allocator import Allocator
from .benchmarker import (
    BaseBenchmarker,
    DeviceBenchmarker,
    ModelBenchmarker,
    device_available_memory_mb,
)
from .estimator import Estimator
from .parameter_server import ParameterServer
from .solver import (
    MeshShapeResult,
    PartitionResult,
    solve_contiguous_minmax,
    solve_mesh_shapes,
)
from .worker import Worker
from .worker_manager import WorkerManager

# imported last: faults.py reaches into ..runner for the Hook base, and
# runner.runner imports the names above from this (then partially
# initialized) module
from .faults import (  # noqa: E402
    FaultInjectionHook,
    FaultPlan,
    FleetFaultInjector,
)

__all__ = [
    "Allocator",
    "FaultInjectionHook",
    "FaultPlan",
    "FleetFaultInjector",
    "BaseBenchmarker",
    "DeviceBenchmarker",
    "ModelBenchmarker",
    "device_available_memory_mb",
    "Estimator",
    "ParameterServer",
    "MeshShapeResult",
    "PartitionResult",
    "solve_contiguous_minmax",
    "solve_mesh_shapes",
    "Worker",
    "WorkerManager",
]
