"""Host-resident full-model parameter store.

Parity with ``scaelum/dynamics/parameter_server.py:14-39``: rank 0 keeps a
complete copy of the model, loads/saves a single-file whole-model checkpoint,
and exchanges per-layer state with pipeline stages.  Because the store is
**layer-indexed** (a list of per-layer param pytrees), a checkpoint survives
re-allocation: stages slice it by their current layer ranges
(``checkpoint_hook.py:31-40`` behavior).

Serialization uses flax msgpack (``flax.serialization``) — the ``.pth``
analog, no torch involved.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Sequence

import jax
import numpy as np
from flax import serialization

from ..builder import build_layer_stack


class ParameterServer:
    def __init__(
        self,
        model_config: List[Dict],
        example_inputs: Optional[Sequence[Any]] = None,
        rng: Optional[jax.Array] = None,
        init: bool = True,
    ):
        self._model_config = list(model_config)
        self.stack = build_layer_stack(self._model_config)
        self.params: List[Any] = []
        self._checkpointer = None  # persistent orbax handle (async saves)
        if init:
            if example_inputs is None:
                raise ValueError(
                    "example_inputs required to initialize the parameter server"
                )
            if rng is None:
                rng = jax.random.key(0)
            # keep the master copy on host memory, off the accelerators
            with jax.default_device(jax.devices("cpu")[0]):
                params = self.stack.init(rng, *example_inputs)
            # true numpy copies: stage runtimes donate their device buffers
            # on every update, and device_put to a same-device destination
            # aliases rather than copies — the master copy must never share
            # storage with anything donatable
            self.params = jax.tree_util.tree_map(np.array, params)

    @property
    def num_layers(self) -> int:
        return len(self._model_config)

    # --- whole-model checkpoint io -----------------------------------------
    def state_bytes(self) -> bytes:
        host_params = jax.tree_util.tree_map(np.asarray, self.params)
        return serialization.msgpack_serialize({"layers": host_params})

    def save_weights_to_file(self, checkpoint: str) -> None:
        with open(checkpoint, "wb") as fh:
            fh.write(self.state_bytes())

    def load_weights_from_file(self, checkpoint: str) -> None:
        with open(checkpoint, "rb") as fh:
            restored = serialization.msgpack_restore(fh.read())
        layers = restored["layers"]
        if isinstance(layers, dict):  # msgpack may round-trip lists as dicts
            layers = [layers[k] for k in sorted(layers, key=int)]
        if self.params:
            layers = [
                serialization.from_state_dict(ref, serialization.to_state_dict(new))
                for ref, new in zip(self.params, layers)
            ]
        self.params = list(layers)

    # --- orbax checkpoint io (directory-based, async-capable) ---------------
    def save_orbax(self, ckpt_dir: str, block: bool = True) -> None:
        """Save via orbax (the TPU ecosystem's checkpoint layer).

        Same layer-indexed layout as the msgpack path, so both formats are
        partition-independent; orbax adds async writes and per-array files
        that scale to sharded multi-host checkpoints.

        ``block=False`` returns as soon as the save is enqueued: orbax's
        background thread owns durability and training overlaps the write.
        Safe because the master copy is never mutated in place —
        ``update_weights`` swaps in fresh arrays, so the captured tree
        stays frozen.  Call :meth:`wait_for_saves` (or the next ``save``)
        to join.
        """
        import orbax.checkpoint as ocp

        if self._checkpointer is None:
            self._checkpointer = ocp.StandardCheckpointer()
        host_params = jax.tree_util.tree_map(np.asarray, self.params)
        self._checkpointer.save(
            os.path.abspath(ckpt_dir), {"layers": host_params}, force=True
        )
        if block:
            self._checkpointer.wait_until_finished()

    def wait_for_saves(self) -> None:
        """Join any in-flight async orbax save (durability barrier)."""
        if self._checkpointer is not None:
            self._checkpointer.wait_until_finished()

    def load_orbax(self, ckpt_dir: str) -> None:
        import orbax.checkpoint as ocp

        ckptr = ocp.StandardCheckpointer()
        target = None
        if self.params:
            # abstract template: structure + dtypes only, no data copy
            target = {
                "layers": jax.tree_util.tree_map(
                    lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype),
                    self.params,
                )
            }
        restored = ckptr.restore(os.path.abspath(ckpt_dir), target)
        self.params = list(restored["layers"])

    # --- per-layer exchange with stages ------------------------------------
    def update_weights(self, state: Any, idx: int) -> None:
        # np.array (not asarray): same-device views would alias donatable
        # stage buffers
        self.params[idx] = jax.tree_util.tree_map(np.array, state)

    def get_state_dict(self, idx: int) -> Any:
        return self.params[idx]

    def get_layer_slice(self, start: int, stop: int) -> List[Any]:
        return self.params[start:stop]


__all__ = ["ParameterServer"]
