"""Host-resident full-model parameter store.

Parity with ``scaelum/dynamics/parameter_server.py:14-39``: rank 0 keeps a
complete copy of the model, loads/saves a single-file whole-model checkpoint,
and exchanges per-layer state with pipeline stages.  Because the store is
**layer-indexed** (a list of per-layer param pytrees), a checkpoint survives
re-allocation: stages slice it by their current layer ranges
(``checkpoint_hook.py:31-40`` behavior).

Serialization uses flax msgpack (``flax.serialization``) — the ``.pth``
analog, no torch involved.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Sequence

import jax
import numpy as np
from flax import serialization

from ..builder import build_layer_stack
from ..utils.fileio import atomic_write
from ..utils.retry import retry_call


class ParameterServer:
    def __init__(
        self,
        model_config: List[Dict],
        example_inputs: Optional[Sequence[Any]] = None,
        rng: Optional[jax.Array] = None,
        init: bool = True,
    ):
        self._model_config = list(model_config)
        self.stack = build_layer_stack(self._model_config)
        self.params: List[Any] = []
        self._checkpointer = None  # persistent orbax handle (async saves)
        if init:
            if example_inputs is None:
                raise ValueError(
                    "example_inputs required to initialize the parameter server"
                )
            if rng is None:
                rng = jax.random.key(0)
            # keep the master copy on host memory, off the accelerators
            with jax.default_device(jax.devices("cpu")[0]):
                params = self.stack.init(rng, *example_inputs)
            # true numpy copies: stage runtimes donate their device buffers
            # on every update, and device_put to a same-device destination
            # aliases rather than copies — the master copy must never share
            # storage with anything donatable
            self.params = jax.tree_util.tree_map(np.array, params)

    @property
    def num_layers(self) -> int:
        return len(self._model_config)

    # --- whole-model checkpoint io -----------------------------------------
    def state_bytes(self) -> bytes:
        host_params = jax.tree_util.tree_map(np.asarray, self.params)
        return serialization.msgpack_serialize({"layers": host_params})

    def save_weights_to_file(self, checkpoint: str) -> None:
        """Crash-safe single-file save: write ``checkpoint + ".tmp"`` then
        atomically publish with ``os.replace`` (the same pattern
        ``FileRendezvous.form_world`` uses for ``world.json``).  A crash —
        or a ``kill -9`` — at ANY point before the replace leaves the
        previous checkpoint intact as the newest complete file; a torn
        half-written file can never shadow a good one."""
        blob = self.state_bytes()
        retry_call(lambda: atomic_write(checkpoint, blob),
                   retry_on=(OSError,),
                   describe=f"checkpoint save {checkpoint}")

    def load_weights_from_file(self, checkpoint: str) -> None:
        if not os.path.exists(checkpoint):
            # a deterministically missing file fails fast: only reads of
            # an EXISTING checkpoint get the transient-fault retries
            raise FileNotFoundError(f"no checkpoint at {checkpoint!r}")

        def read():
            with open(checkpoint, "rb") as fh:
                return fh.read()

        raw = retry_call(read, retry_on=(OSError,),
                         describe=f"checkpoint read {checkpoint}")
        try:
            restored = serialization.msgpack_restore(raw)
        except Exception as exc:
            # a truncated / torn msgpack otherwise surfaces as a deep
            # unpacker traceback with no mention of which file was bad
            raise ValueError(
                f"corrupt or truncated checkpoint {checkpoint!r} "
                f"({len(raw)} bytes): {exc}"
            ) from exc
        if not isinstance(restored, dict) or "layers" not in restored:
            raise ValueError(
                f"corrupt or truncated checkpoint {checkpoint!r}: no "
                f"'layers' entry (got {type(restored).__name__})"
            )
        layers = restored["layers"]
        if isinstance(layers, dict):  # msgpack may round-trip lists as dicts
            layers = [layers[k] for k in sorted(layers, key=int)]
        if self.params:
            layers = [
                serialization.from_state_dict(ref, serialization.to_state_dict(new))
                for ref, new in zip(self.params, layers)
            ]
        self.params = list(layers)

    # --- orbax checkpoint io (directory-based, async-capable) ---------------
    def save_orbax(self, ckpt_dir: str, block: bool = True) -> None:
        """Save via orbax (the TPU ecosystem's checkpoint layer).

        Same layer-indexed layout as the msgpack path, so both formats are
        partition-independent; orbax adds async writes and per-array files
        that scale to sharded multi-host checkpoints.

        ``block=False`` returns as soon as the save is enqueued: orbax's
        background thread owns durability and training overlaps the write.
        Safe because the master copy is never mutated in place —
        ``update_weights`` swaps in fresh arrays, so the captured tree
        stays frozen.  Call :meth:`wait_for_saves` (or the next ``save``)
        to join.
        """
        import orbax.checkpoint as ocp

        if self._checkpointer is None:
            self._checkpointer = ocp.StandardCheckpointer()
        host_params = jax.tree_util.tree_map(np.asarray, self.params)
        self._checkpointer.save(
            os.path.abspath(ckpt_dir), {"layers": host_params}, force=True
        )
        if block:
            self._checkpointer.wait_until_finished()

    def wait_for_saves(self) -> None:
        """Join any in-flight async orbax save (durability barrier)."""
        if self._checkpointer is not None:
            self._checkpointer.wait_until_finished()

    def load_orbax(self, ckpt_dir: str) -> None:
        import orbax.checkpoint as ocp

        ckptr = ocp.StandardCheckpointer()
        target = None
        if self.params:
            # abstract template: structure + dtypes only, no data copy
            target = {
                "layers": jax.tree_util.tree_map(
                    lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype),
                    self.params,
                )
            }
        restored = ckptr.restore(os.path.abspath(ckpt_dir), target)
        self.params = list(restored["layers"])

    # --- per-layer exchange with stages ------------------------------------
    def update_weights(self, state: Any, idx: int) -> None:
        # np.array (not asarray): same-device views would alias donatable
        # stage buffers
        self.params[idx] = jax.tree_util.tree_map(np.array, state)

    def get_state_dict(self, idx: int) -> Any:
        return self.params[idx]

    def get_layer_slice(self, start: int, stop: int) -> List[Any]:
        return self.params[start:stop]


__all__ = ["ParameterServer"]
