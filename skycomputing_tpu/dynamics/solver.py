"""Contiguous layer->device partition solver.

The reference formulates allocation as a binary MIP
(``scaelum/dynamics/allocator.py:47-132``): assign each layer to exactly one
device, each device's layers contiguous, per-device memory capacity respected,
minimizing the max device time ``q = dt[d] * sum(flops of its layers)``, and
shells out to CBC/Gurobi via pulp.  Neither pulp nor a native MIP solver is
available here, and none is needed: with contiguity + free device ordering
the problem is "partition a sequence into <= D contiguous slices assigned to
distinct heterogeneous devices, minimizing the bottleneck".  For a fixed
bottleneck T, feasibility is decided *exactly* by a subset DP with a
max-frontier dominance (reachable frontier is monotone in start index), and
the optimal T is found by binary search.  Exact for clusters up to
``exact_limit`` devices (2^D * D per probe); beyond that a randomized
max-coverage greedy takes over, polished by local search and — when a
certified optimality gap remains — time-boxed simulated annealing over the
device order with an exact per-order evaluator.  Every result carries an
*integral lower bound* (:func:`integral_lower_bound`): the max-window
capacity relaxation that, unlike a fractional waterfilling bound, respects
layer integrality, so large-cluster solutions can be certified optimal (the
paper-scale 64-device instances solve to gap 0).  If pulp happens to be
importable it is used as a cross-check oracle in tests, never as the
primary path.
"""

from __future__ import annotations

import bisect
import heapq
import math
import random
import time
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple


@dataclass
class PartitionResult:
    """Slices per device, device order = pipeline order.

    ``slices[i] = (start, end)`` half-open layer range for the device at
    pipeline position i; ``device_order[i]`` is the index (into the input
    device arrays) of that device.  Devices left empty are omitted.

    ``lower_bound`` is a *certified* integral lower bound on the bottleneck
    (see :func:`integral_lower_bound`): no assignment — contiguous or not —
    can beat it, so ``bottleneck <= lower_bound * (1 + gap)`` certifies the
    solution within ``gap`` of optimal.  The reference's CBC MIP reported
    the same kind of bound via its 20% relative-gap setting
    (``scaelum/dynamics/allocator.py:109-132``).
    """

    device_order: List[int]
    slices: List[Tuple[int, int]]
    bottleneck: float
    lower_bound: float = 0.0

    @property
    def optimality_gap(self) -> float:
        """Relative gap vs the certified bound (0.0 = provably optimal)."""
        if self.lower_bound <= 0.0:
            return float("inf")
        return max(0.0, self.bottleneck / self.lower_bound - 1.0)

    def as_ranges(self, num_devices: int) -> List[Optional[Tuple[int, int]]]:
        out: List[Optional[Tuple[int, int]]] = [None] * num_devices
        for d, s in zip(self.device_order, self.slices):
            out[d] = s
        return out


def _prefix(values: Sequence[float]) -> List[float]:
    acc = [0.0]
    for v in values:
        acc.append(acc[-1] + float(v))
    return acc


class _CoverTable:
    """cover(l, d): furthest layer index reachable from l on device d."""

    def __init__(self, layer_cost, layer_mem, device_time, device_mem):
        self.cost_prefix = _prefix(layer_cost)
        self.mem_prefix = _prefix(layer_mem)
        self.device_time = list(device_time)
        self.device_mem = list(device_mem)
        self.num_layers = len(layer_cost)

    def cover(self, start: int, d: int, T: float) -> int:
        if start >= self.num_layers:
            return self.num_layers
        dt = self.device_time[d]
        cost_budget = T / dt if dt > 0 else float("inf")
        # furthest r with cost_prefix[r] <= cost_prefix[start] + budget
        r_cost = (
            bisect.bisect_right(
                self.cost_prefix, self.cost_prefix[start] + cost_budget + 1e-12
            )
            - 1
        )
        r_mem = (
            bisect.bisect_right(
                self.mem_prefix, self.mem_prefix[start] + self.device_mem[d] + 1e-9
            )
            - 1
        )
        return max(start, min(r_cost, r_mem))


def _max_window_cost(table: _CoverTable, d: int, T: float,
                     a: int, b: int) -> float:
    """Max cost of a contiguous window within layers ``[a, b)`` that device
    d could hold at threshold T.

    Upper-bounds the contribution of device d to *any* feasible assignment
    (its slice is one such window), and — unlike a fractional waterfilling
    bound — respects layer integrality: a device with budget 1.9
    layer-costs covers at most the best real window under 1.9, not 1.9
    fractional layers.
    """
    cp, mp = table.cost_prefix, table.mem_prefix
    dt = table.device_time[d]
    cost_budget = T / dt if dt > 0 else float("inf")
    mem_budget = table.device_mem[d]
    best = 0.0
    r = a
    for start in range(a, b):
        if r < start:
            r = start
        while (
            r < b
            and cp[r + 1] - cp[start] <= cost_budget + 1e-12
            and mp[r + 1] - mp[start] <= mem_budget + 1e-9
        ):
            r += 1
        best = max(best, cp[r] - cp[start])
        if r >= b:
            break
    return best


def integral_lower_bound(table: _CoverTable, hi: float,
                         iters: int = 48, num_separators: int = 6) -> float:
    """Largest T such that every T' < T is provably infeasible.

    Certificate: pick a layer as a *separator*.  In any feasible
    assignment exactly one device's slice contains it; every other device's
    slice is a contiguous window strictly left or right of it.  So if

        sum_d maxwin_d(avoiding sep) + max_d [maxwin_d(any) - maxwin_d(avoiding)]

    falls short of the total cost at threshold T, no assignment exists at
    T.  This is a relaxation (windows may overlap), hence a valid lower
    bound on the optimal bottleneck; the separator term closes the obvious
    over-count where every device claims the one expensive layer.

    The certificate is valid for ANY separator, so T is infeasible if any
    of the ``num_separators`` heaviest layers proves it — a strictly
    tighter (and still valid) bound than the single heaviest-layer choice,
    which matters on calibrated instances where several near-equal heavy
    layers exist (the refine loop's cost models).  Six separators (was 3)
    measurably tightens timed-profile instances — their heavy layers come
    in near-equal families (embeddings, the ffn shards) and the binding
    separator is not always among the top 3 — at a per-solve cost of
    milliseconds.
    """
    L = table.num_layers
    total = table.cost_prefix[L]
    costs = [
        table.cost_prefix[i + 1] - table.cost_prefix[i] for i in range(L)
    ]
    seps = sorted(range(L), key=lambda i: -costs[i])[: max(1, num_separators)]

    def infeasible_for(sep: int, T: float, full) -> bool:
        acc = 0.0
        best_bonus = 0.0
        for d in range(len(table.device_time)):
            avoiding = max(
                _max_window_cost(table, d, T, 0, sep),
                _max_window_cost(table, d, T, sep + 1, L),
            )
            acc += avoiding
            best_bonus = max(best_bonus, full[d] - avoiding)
            if acc + best_bonus >= total - 1e-9:
                return False
        return acc + best_bonus < total - 1e-9

    def infeasible(T: float) -> bool:
        # the full-range window cost is separator-independent: compute it
        # once per (T, device), shared by every separator certificate
        full = [
            _max_window_cost(table, d, T, 0, L)
            for d in range(len(table.device_time))
        ]
        return any(infeasible_for(sep, T, full) for sep in seps)

    lo, up = 0.0, hi
    if not infeasible(lo):
        return 0.0
    for _ in range(iters):
        mid = (lo + up) / 2.0
        if infeasible(mid):
            lo = mid
        else:
            up = mid
    return lo


def _fixed_order_walk(table: _CoverTable, order: Sequence[int], T: float):
    """Maximal-cover walk along a fixed device order; exact for that order.

    Taking the maximal cover at each position is optimal for a fixed order
    because ``cover`` is non-decreasing in its start argument (prefix sums
    are monotone), so ceding layers to a later device never helps.
    """
    pos = 0
    used: List[int] = []
    slices: List[Tuple[int, int]] = []
    for d in order:
        end = table.cover(pos, d, T)
        if end > pos:
            used.append(d)
            slices.append((pos, end))
            pos = end
            if pos >= table.num_layers:
                return used, slices
    return None


def _fixed_order_opt(table: _CoverTable, order: Sequence[int], lo: float,
                     hi: float, iters: int = 45):
    """Minimal bottleneck achievable with devices tried in ``order``."""
    sol = _fixed_order_walk(table, order, hi)
    if sol is None:
        return float("inf"), None
    best_T = hi
    for _ in range(iters):
        mid = (lo + hi) / 2.0
        if hi - lo <= 1e-12 * max(hi, 1.0):
            break
        cand = _fixed_order_walk(table, order, mid)
        if cand is not None:
            sol, best_T, hi = cand, mid, mid
        else:
            lo = mid
    return best_T, sol


def _anneal_orders(table: _CoverTable, order, lower_bound: float,
                   rng: random.Random, init_bottleneck: float,
                   max_evals: int = 4000):
    """Simulated annealing over the *device order*, each order scored by its
    exact optimal slicing (binary search + maximal-cover walk).

    The greedy/local-search pipeline can misassign devices in ways single
    boundary shifts and pairwise swaps cannot repair (VERDICT r02 weak #3);
    searching order-space with an exact per-order evaluator is the
    bound-guided repair: it stops as soon as the certified lower bound is
    reached.  The budget is purely an *evaluation count* so one pass is
    deterministic for a given seed regardless of machine speed (ADVICE
    r03: a wall-clock box made same-seed runs diverge across machines);
    the caller enforces any wall cap BETWEEN passes, never inside one.

    Moves: random position swap, random move-insert, and a
    bottleneck-targeted swap that relocates the device currently pinning
    the exact evaluation — targeted repair converges far faster than blind
    permutation moves at 64-device scale.
    """
    D = len(table.device_time)
    used = list(order)
    rest = [d for d in range(D) if d not in set(used)]
    current = used + rest
    cur_val, cur_sol = _fixed_order_opt(
        table, current, lower_bound, init_bottleneck * (1 + 1e-9)
    )
    if cur_sol is None:
        return None
    best_val, best_sol = cur_val, cur_sol
    temp0 = max(cur_val - lower_bound, 1e-9)

    def bottleneck_position(sol) -> Optional[int]:
        """Index *in the current full order* of the device pinning sol."""
        s_order, s_slices = sol
        worst_d, worst_t = None, -1.0
        for d, (s, e) in zip(s_order, s_slices):
            t = table.device_time[d] * (
                table.cost_prefix[e] - table.cost_prefix[s]
            )
            if t > worst_t:
                worst_d, worst_t = d, t
        if worst_d is None:
            return None
        try:
            return current.index(worst_d)
        except ValueError:  # pragma: no cover - sol devices come from order
            return None

    for evals in range(max_evals):
        if best_val <= lower_bound * (1 + 1e-9):
            break
        frac = 1.0 - evals / max(max_evals, 1)
        temp = temp0 * 0.3 * frac + 1e-12
        cand = list(current)
        u = rng.random()
        if u < 0.4:
            i, j = rng.randrange(D), rng.randrange(D)
            cand[i], cand[j] = cand[j], cand[i]
        elif u < 0.7:
            i, j = rng.randrange(D), rng.randrange(D)
            cand.insert(j, cand.pop(i))
        else:
            i = bottleneck_position(cur_sol)
            if i is None:
                i = rng.randrange(D)
            j = rng.randrange(D)
            cand[i], cand[j] = cand[j], cand[i]
        val, sol = _fixed_order_opt(
            table, cand, lower_bound,
            max(best_val * (1 + 1e-9), cur_val * 1.25),
        )
        if sol is None:
            continue
        if val < cur_val or rng.random() < math.exp(-(val - cur_val) / temp):
            current, cur_val, cur_sol = cand, val, sol
            if val < best_val:
                best_val, best_sol = val, sol
    return best_sol


def _feasible_exact(table: _CoverTable, T: float):
    """Subset DP: frontier[mask] = furthest layer reachable using mask.

    Dominance is valid because cover(l, d) is non-decreasing in l (prefix
    sums are monotone), so only the max frontier per subset matters.
    Returns the assignment (device order + slices) or None.
    """
    D = len(table.device_time)
    L = table.num_layers
    size = 1 << D
    frontier = [0] * size
    choice = [-1] * size

    full_found = None
    for mask in range(1, size):
        best, best_d = 0, -1
        m = mask
        while m:
            low = m & (-m)
            d = low.bit_length() - 1
            m ^= low
            prev = frontier[mask ^ low]
            reach = table.cover(prev, d, T)
            if reach > best or best_d == -1:
                best, best_d = reach, d
        frontier[mask] = best
        choice[mask] = best_d
        if best >= L:
            full_found = mask
            break

    if full_found is None:
        return None

    # Backtrack: order of devices along the pipeline (reverse of peeling).
    order_rev: List[int] = []
    mask = full_found
    while mask:
        d = choice[mask]
        order_rev.append(d)
        mask ^= 1 << d
    order = list(reversed(order_rev))

    slices: List[Tuple[int, int]] = []
    pos = 0
    used_order: List[int] = []
    for d in order:
        end = table.cover(pos, d, T)
        if end > pos:
            slices.append((pos, end))
            used_order.append(d)
        pos = end
    if pos < L:  # pragma: no cover - backtrack must reproduce the DP
        return None
    return used_order, slices


def _feasible_greedy(table: _CoverTable, T: float, rng: random.Random,
                     attempts: int = 20):
    """Randomized max-coverage greedy for large device counts."""
    D = len(table.device_time)
    L = table.num_layers

    for attempt in range(attempts):
        remaining = set(range(D))
        pos = 0
        order: List[int] = []
        slices: List[Tuple[int, int]] = []
        while pos < L and remaining:
            covers = [(table.cover(pos, d, T), d) for d in remaining]
            best = max(c for c, _ in covers)
            if best <= pos:
                break
            if attempt == 0:
                _, d = max(covers)
            else:
                good = [d for c, d in covers if c >= pos + 0.9 * (best - pos)]
                d = rng.choice(good)
            end = table.cover(pos, d, T)
            order.append(d)
            slices.append((pos, end))
            remaining.discard(d)
            pos = end
        if pos >= L:
            return order, slices
    return None


def _solve_by_classes(
    layer_cost, layer_mem, device_time, device_mem, tolerance: float,
    max_classes: int = 8, max_states: int = 8_000_000,
):
    """Exact class-collapse solve (see native ``skytpu_solve_classes``).

    Devices sharing a slowdown form a class (exact equality — profiled
    per-device times collapse only when they really repeat, as the
    headline instances' integer slowdown draws do).  Two DP solves:

    - per-class MAX member memory: a relaxation of the real instance, so
      its exact optimum is a certified LOWER bound;
    - per-class MIN member memory: every produced slice fits every class
      member, so the partition maps to real devices — a feasible
      solution (an upper bound).

    With slack memory the two coincide: provably optimal, gap 0 — where
    the order-anneal left 2-6% certified gaps on noisy timed profiles.
    Returns ``(solution | None, bound | None)`` with ``solution`` a
    ``PartitionResult``-shaped tuple ``(device_order, slices,
    bottleneck)``; both None when the instance doesn't collapse (many
    distinct speeds) or the native core is unavailable.
    """
    groups: dict = {}
    for d, t in enumerate(device_time):
        groups.setdefault(float(t), []).append(d)
    D = len(device_time)
    if len(groups) > max_classes or len(groups) >= D:
        return None, None
    # fast classes first: the DP's early exit takes the lexicographically
    # smallest covering count-vector, which then spends slow devices last
    # — among equal-bottleneck optima, prefer the one that drops slow
    # workers (the allocation the schedule actually wants)
    class_dt = sorted(groups)
    members = [groups[t] for t in class_dt]
    counts = [len(m) for m in members]
    n_states = 1
    for c in counts:
        n_states *= c + 1
        if n_states > max_states:
            return None, None
    from . import native

    mem_max = [max(device_mem[d] for d in m) for m in members]
    mem_min = [min(device_mem[d] for d in m) for m in members]
    try:
        relaxed = native.solve_classes_native(
            layer_cost, layer_mem, counts, class_dt, mem_max,
            tolerance=min(tolerance, 1e-9), max_states=max_states,
        )
    except RuntimeError:
        # even with every class at max memory the model does not fit —
        # the real instance is infeasible too; let the main path raise
        # its canonical error
        return None, None
    if relaxed is None:
        return None, None
    bound = relaxed[2]
    try:
        tight = native.solve_classes_native(
            layer_cost, layer_mem, counts, class_dt, mem_min,
            tolerance=min(tolerance, 1e-9), max_states=max_states,
        )
    except RuntimeError:
        # memory-fragmented inside a class: the conservative solve has no
        # cover, but the bound above still stands for the anneal path
        return None, bound
    if tight is None:
        return None, bound
    classes, slices, bottleneck = tight
    # map class slices onto concrete devices: larger-memory members take
    # the larger slices (any assignment fits; this ordering keeps slack)
    mem_prefix = _prefix(layer_mem)
    by_class: dict = {
        k: sorted(m, key=lambda d: -device_mem[d])
        for k, m in enumerate(members)
    }
    slice_order = sorted(
        range(len(slices)),
        key=lambda i: -(mem_prefix[slices[i][1]] - mem_prefix[slices[i][0]]),
    )
    assigned = [None] * len(slices)
    taken: dict = {k: 0 for k in by_class}
    for i in slice_order:
        k = classes[i]
        assigned[i] = by_class[k][taken[k]]
        taken[k] += 1
    return (assigned, [tuple(s) for s in slices], bottleneck), bound


def solve_contiguous_minmax(
    layer_cost: Sequence[float],
    layer_mem: Sequence[float],
    device_time: Sequence[float],
    device_mem: Sequence[float],
    exact_limit: int = 12,
    tolerance: float = 1e-3,
    greedy_attempts: int = 20,
    seed: int = 0,
    use_native: bool = True,
    native_exact_limit: int = 18,
    anneal_seconds: float = 300.0,
    anneal_evals: int = 3000,
    anneal_rounds: int = 6,
    gap_target: float = 0.01,
    clock=time.monotonic,
) -> PartitionResult:
    """Minimize max_d device_time[d] * sum(layer_cost[slice_d]).

    Subject to: slices contiguous and disjoint, covering all layers; device
    order free; sum(layer_mem[slice_d]) <= device_mem[d]; empty devices
    allowed (reference MIP allows them too — constraint 4 with sum(x)=0).

    The exact subset-DP runs in the native C++ core when available
    (``dynamics/native`` — the CBC analog), extending the exact regime from
    ``exact_limit`` (pure Python) to ``native_exact_limit`` devices; the
    randomized greedy covers larger clusters either way.  The DP is
    exponential in D (~0.06s at D=14, ~1s at D=18, roughly x4.5 per +2
    devices on current hardware); raise ``native_exact_limit`` toward 22
    only if tens of seconds per allocation is acceptable — the reference
    gave its MIP solver a 300s budget, so that can be a fair trade.
    """
    D = len(device_time)
    L = len(layer_cost)
    if L == 0:
        return PartitionResult([], [], 0.0)
    if D == 0:
        raise ValueError("no devices")

    table = _CoverTable(layer_cost, layer_mem, device_time, device_mem)
    total_cost = sum(layer_cost)
    hi = total_cost * max(device_time)  # everything on the slowest device
    lower_bound = integral_lower_bound(table, hi)

    # Class-collapse exact path: few distinct device speeds (the headline
    # instances' integer slowdown draws) turn the 2^D subset DP into a
    # count-vector DP — exact in seconds where the anneal certified
    # 2-6% gaps, and its relaxed solve tightens the bound either way.
    class_solution = None
    if use_native and D > native_exact_limit:
        class_solution, class_bound = _solve_by_classes(
            layer_cost, layer_mem, device_time, device_mem, tolerance
        )
        if class_bound is not None:
            lower_bound = max(lower_bound, class_bound)
        if class_solution is not None:
            c_order, c_slices, c_bottleneck = class_solution
            if (
                lower_bound > 0
                and c_bottleneck / lower_bound - 1.0
                <= max(gap_target, tolerance)
            ):
                return PartitionResult(
                    c_order, [list(s) for s in c_slices], c_bottleneck,
                    lower_bound=lower_bound,
                )

    if use_native and D <= native_exact_limit:
        from . import native

        solved = native.solve_minmax_native(
            layer_cost, layer_mem, device_time, device_mem,
            tolerance=tolerance,
        )
        if solved is not None:
            order, slices, bottleneck = solved
            return PartitionResult(order, slices, bottleneck,
                                   lower_bound=lower_bound)

    if use_native and D > native_exact_limit:
        # Native anneal: same order-search as the Python fallback below at
        # ~10^4 x the evaluation rate, so the anneal budget that certifies
        # gap ~0.05 in Python typically reaches the gap target here.
        from . import native

        # anneal_seconds<=0 / anneal_evals<=0 means "no annealing" on the
        # Python path too — the native call then runs only the initial
        # sorted-order score + boundary polish (milliseconds)
        anneal_on = anneal_seconds > 0 and anneal_evals > 0
        try:
            solved = native.solve_large_native(
                layer_cost, layer_mem, device_time, device_mem,
                seed=seed,
                rounds=max(anneal_rounds, 1) if anneal_on else 0,
                evals0=max(anneal_evals * 20, 20000),
                wall_cap_s=anneal_seconds if anneal_on else 0.0,
                lower_bound=lower_bound,
                gap_target=gap_target,
                tolerance=tolerance,
            )
        except RuntimeError:
            # the native feasibility probe (sorted order + random
            # restarts, greedy walk) is weaker than the Python
            # _feasible_greedy's max-coverage device selection on
            # fragmented-memory instances — fall through rather than
            # declare a solvable instance infeasible; the Python path
            # raises its own error if it truly cannot cover the model
            solved = None
        if solved is not None:
            order, slices, bottleneck = solved
            # The native core's in-anneal polish is single-layer adjacent
            # shifts only; the Python local search adds 2/4-layer block
            # moves and bottleneck-device position swaps — complementary
            # neighborhoods that cost milliseconds and routinely shave
            # the last fraction of a percent off the certified gap.
            order, slices = _local_search(
                table, order, [tuple(s) for s in slices]
            )
            achieved = _bottleneck(table, order, slices)
            if (
                class_solution is not None
                and class_solution[2] < achieved
            ):
                order, slices, achieved = class_solution
            return PartitionResult(order, [list(s) for s in slices],
                                   achieved, lower_bound=lower_bound)

    rng = random.Random(seed)

    def feasible(T: float):
        if D <= exact_limit:
            return _feasible_exact(table, T)
        return _feasible_greedy(table, T, rng, attempts=greedy_attempts)

    best = feasible(hi)
    if best is None:
        raise RuntimeError(
            "allocation infeasible: memory capacities cannot hold the model "
            f"(layers={L}, devices={D})"
        )

    # Binary search down to relative tolerance, floored at the certified
    # bound — nothing below it is feasible, integrally or otherwise.
    lo = lower_bound
    for _ in range(60):
        if hi - lo <= tolerance * max(hi, 1e-30):
            break
        mid = (lo + hi) / 2.0
        sol = feasible(mid)
        if sol is not None:
            best, hi = sol, mid
        else:
            lo = mid

    order, slices = best
    if D > exact_limit:
        # greedy solutions deserve a polish: boundary moves + device swaps
        order, slices = _local_search(table, order, slices)
        achieved = _bottleneck(table, order, slices)
        # Escalating anneal: rounds of DOUBLING evaluation budgets while the
        # certified gap stays above ``gap_target``.  Each round's budget is
        # pure eval-count, so a round is deterministic per seed regardless
        # of machine speed (ADVICE r03); ``anneal_seconds`` — a generous
        # wall cap in the spirit of the reference's 300 s MIP limit
        # (``scaelum/dynamics/allocator.py:109-132``) — is checked only at
        # round BOUNDARIES, so it can skip later rounds on a slow machine
        # but never truncates a round mid-flight.
        if anneal_seconds > 0 and anneal_evals > 0:
            # `clock` is injectable (skydet DET001): the wall cap is the
            # ONLY wall-clock read in this module, and tests pin it to a
            # fake to exercise the round-boundary skip deterministically
            deadline = clock() + anneal_seconds
            evals = anneal_evals
            for _ in range(anneal_rounds):
                if lower_bound > 0:
                    gap = achieved / lower_bound - 1.0
                else:
                    gap = float("inf")
                if gap <= max(gap_target, tolerance):
                    break
                if clock() > deadline:
                    break
                annealed = _anneal_orders(
                    table, order, lower_bound, rng, achieved,
                    max_evals=evals,
                )
                if annealed is not None:
                    a_order, a_slices = annealed
                    a_order, a_slices = _local_search(
                        table, a_order, a_slices
                    )
                    if _bottleneck(table, a_order, a_slices) < achieved:
                        order, slices = a_order, list(a_slices)
                        achieved = _bottleneck(table, order, slices)
                evals *= 2
    achieved = _bottleneck(table, order, slices)
    if class_solution is not None and class_solution[2] < achieved:
        order, slices, achieved = class_solution
        slices = list(slices)
    return PartitionResult(order, slices, achieved, lower_bound=lower_bound)


def _bottleneck(table: _CoverTable, order, slices) -> float:
    worst = 0.0
    for d, (s, e) in zip(order, slices):
        worst = max(
            worst,
            table.device_time[d]
            * (table.cost_prefix[e] - table.cost_prefix[s]),
        )
    return worst


def _local_search(table: _CoverTable, order, slices, max_rounds: int = 200):
    """Hill-climb on the greedy assignment: shift slice boundaries by one
    layer and swap device positions while the bottleneck improves.

    The exact DP path doesn't need this; the randomized greedy for large
    clusters leaves a few percent on the table that these two moves — the
    classic neighborhood for contiguous-partition scheduling — recover.
    """
    order = list(order)
    slices = [list(s) for s in slices]

    def stage_time(i) -> float:
        d = order[i]
        s, e = slices[i]
        return table.device_time[d] * (
            table.cost_prefix[e] - table.cost_prefix[s]
        )

    def mem_ok(i) -> bool:
        s, e = slices[i]
        return (
            table.mem_prefix[e] - table.mem_prefix[s]
            <= table.device_mem[order[i]] + 1e-9
        )

    n = len(order)
    for _ in range(max_rounds):
        times = [stage_time(i) for i in range(n)]
        worst = max(range(n), key=lambda i: times[i])
        current = times[worst]
        improved = False

        # move a block of 1..4 boundary layers off the bottleneck stage to
        # a neighbor (single-layer shifts stall on profiles where one unit
        # is much cheaper than the imbalance — VERDICT r03 weak #2)
        for nb, take_from in ((worst - 1, "left"), (worst + 1, "right")):
            if not (0 <= nb < n) or improved:
                continue
            for k in (4, 2, 1):
                s, e = slices[worst]
                if e - s <= k:
                    continue
                old_worst, old_nb = list(slices[worst]), list(slices[nb])
                if take_from == "left" and nb == worst - 1:
                    slices[worst][0] += k
                    slices[nb][1] += k
                elif take_from == "right" and nb == worst + 1:
                    slices[worst][1] -= k
                    slices[nb][0] -= k
                else:  # pragma: no cover
                    continue
                if (
                    mem_ok(worst)
                    and mem_ok(nb)
                    and max(stage_time(worst), stage_time(nb))
                    < current - 1e-15
                ):
                    improved = True
                    break
                slices[worst], slices[nb] = old_worst, old_nb

        if improved:
            continue

        # swap the bottleneck device with any other position
        for j in range(n):
            if j == worst:
                continue
            order[worst], order[j] = order[j], order[worst]
            if (
                mem_ok(worst)
                and mem_ok(j)
                and max(stage_time(worst), stage_time(j)) < current - 1e-15
            ):
                improved = True
                break
            order[worst], order[j] = order[j], order[worst]

        if not improved:
            break

    return order, [tuple(s) for s in slices]


# --------------------------------------------------------------------------
# mesh-shape search (the mesh-native engine's allocator)
# --------------------------------------------------------------------------


@dataclass
class MeshShapeResult:
    """A mesh operating point: contiguous layer slices + chips per stage
    over ONE homogeneous device order.

    ``slices[i] = (start, end)`` half-open layer range of pipeline stage
    i; ``chips[i]`` how many contiguous devices its sub-mesh owns
    (``sum(chips) <= num_devices`` — the search may leave chips unused
    when ``max_chips_per_stage`` caps useful parallelism).
    ``bottleneck`` is the scored objective ``max_i stage_costs[i] /
    chips[i] + stage_overhead * num_stages``.
    """

    slices: List[Tuple[int, int]]
    chips: List[int]
    stage_costs: List[float]
    bottleneck: float
    num_devices: int
    stage_overhead: float = 0.0

    @property
    def num_stages(self) -> int:
        return len(self.slices)


def _balanced_contiguous(
    layer_cost: Sequence[float], max_slices: int
) -> List[Tuple[int, int]]:
    """Min-max contiguous partition of ``layer_cost`` into at most
    ``max_slices`` slices over UNIT-speed slots: binary search on the
    bottleneck T with a greedy maximal cover (optimal for a fixed order
    of identical devices, same argument as ``_fixed_order_walk``)."""
    prefix = _prefix(layer_cost)
    L = len(layer_cost)

    def cover(T: float) -> Optional[List[Tuple[int, int]]]:
        slices: List[Tuple[int, int]] = []
        pos = 0
        while pos < L and len(slices) < max_slices:
            end = bisect.bisect_right(prefix, prefix[pos] + T + 1e-12) - 1
            if end <= pos:
                return None  # one layer alone exceeds T
            slices.append((pos, end))
            pos = end
        return slices if pos >= L else None

    lo = max(layer_cost) if layer_cost else 0.0
    hi = prefix[L]
    best = cover(hi)
    if best is None:  # pragma: no cover - hi always covers
        raise RuntimeError("balanced partition failed at the total cost")
    for _ in range(60):
        if hi - lo <= 1e-12 * max(hi, 1.0):
            break
        mid = (lo + hi) / 2.0
        cand = cover(mid)
        if cand is not None:
            best, hi = cand, mid
        else:
            lo = mid
    return best


def _greedy_chips(
    stage_costs: Sequence[float], num_devices: int,
    max_chips_per_stage: Optional[int] = None,
) -> List[int]:
    """Integer chips minimizing ``max_i cost_i / chips_i`` with
    ``sum(chips) <= num_devices`` and 1 <= chips_i <= cap.

    Start at one chip per stage and repeatedly give the next chip to the
    current bottleneck stage — optimal because cost/k is convex
    decreasing in k (the classic discrete resource-allocation exchange
    argument).  Chips beyond every stage's cap stay unspent.
    """
    S = len(stage_costs)
    if num_devices < S:
        raise ValueError(
            f"{S} stages need at least {S} devices, have {num_devices}"
        )
    cap = max_chips_per_stage if max_chips_per_stage else num_devices
    chips = [1] * S
    heap = [(-float(c), i) for i, c in enumerate(stage_costs)]
    heapq.heapify(heap)
    spare = num_devices - S
    while spare > 0 and heap:
        _, i = heapq.heappop(heap)
        if chips[i] >= cap:
            continue  # capped stage leaves the pool
        chips[i] += 1
        spare -= 1
        heapq.heappush(heap, (-float(stage_costs[i]) / chips[i], i))
    return chips


def solve_mesh_shapes(
    layer_cost: Sequence[float],
    num_devices: int,
    layer_mem: Optional[Sequence[float]] = None,
    mem_per_chip: Optional[float] = None,
    max_stages: Optional[int] = None,
    max_chips_per_stage: Optional[int] = None,
    stage_overhead: float = 0.0,
) -> MeshShapeResult:
    """Mesh-shape search: extend the contiguous min-max solve to choose
    BOTH the stage partition and chips-per-stage.

    For each candidate stage count S the layers get the balanced
    contiguous partition (sub-mesh chips are same-speed by construction,
    so unit devices), then ``num_devices`` chips spread greedily so
    per-stage time/chip balances (PipeDream's partitioner loop with the
    profiler's costs).  The score charges ``stage_overhead`` — the
    per-stage host dispatch cost per microbatch tick, the quantity
    ``BENCH_mesh_pipeline.json`` measures — so the search trades
    intra-stage data parallelism against issue-loop length; at overhead
    0 ties break toward FEWER stages (ascending S, strict improvement).

    Constraints: ``mem_per_chip`` bounds each stage's slice memory
    (parameters replicate over the stage's sub-mesh, so every chip holds
    its stage's full slice); ``max_chips_per_stage`` bounds useful
    intra-stage parallelism (dp cannot exceed the microbatch rows).
    """
    L = len(layer_cost)
    if L == 0:
        return MeshShapeResult([], [], [], 0.0, int(num_devices),
                               float(stage_overhead))
    if num_devices < 1:
        raise ValueError("no devices")
    if layer_mem is not None and len(layer_mem) != L:
        raise ValueError(
            f"{len(layer_mem)} layer_mem entries for {L} layers"
        )
    prefix = _prefix(layer_cost)
    mem_prefix = _prefix(layer_mem) if layer_mem is not None else None
    S_hi = min(int(num_devices), L, max_stages or int(num_devices))
    best: Optional[MeshShapeResult] = None
    for S in range(1, S_hi + 1):
        slices = _balanced_contiguous(layer_cost, S)
        if mem_prefix is not None and mem_per_chip is not None:
            if any(
                mem_prefix[e] - mem_prefix[s] > mem_per_chip + 1e-9
                for s, e in slices
            ):
                continue  # a slice no single chip can hold
        costs = [prefix[e] - prefix[s] for s, e in slices]
        chips = _greedy_chips(
            costs, int(num_devices), max_chips_per_stage
        )
        score = max(
            c / k for c, k in zip(costs, chips)
        ) + float(stage_overhead) * len(slices)
        if best is None or score < best.bottleneck - 1e-15:
            best = MeshShapeResult(
                slices=[tuple(s) for s in slices],
                chips=chips,
                stage_costs=costs,
                bottleneck=score,
                num_devices=int(num_devices),
                stage_overhead=float(stage_overhead),
            )
    if best is None:
        raise RuntimeError(
            "mesh-shape search infeasible: no stage count fits every "
            f"slice under mem_per_chip={mem_per_chip} (layers={L}, "
            f"devices={num_devices}) — parameters replicate over a "
            "stage's sub-mesh, so a slice must fit one chip"
        )
    return best


__all__ = [
    "solve_contiguous_minmax",
    "PartitionResult",
    "MeshShapeResult",
    "solve_mesh_shapes",
    "integral_lower_bound",
]
