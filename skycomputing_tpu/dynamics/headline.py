"""Shared construction of the headline-benchmark instance.

``bench.py`` (the shipped benchmark) and ``tests/test_headline_metric.py``
(the CI guard) both build their world through these helpers, so the guard
always exercises the exact instance the benchmark defaults to — a guard
testing a different instance than the bench runs manufactures false
confidence (VERDICT r02, weak #2).

Memory regime — why the default is loose
----------------------------------------
The reference's headline experiment configures ``mem_limit=-1`` for every
worker (``/root/reference/experiment/config.py:86``), which means "probe
the real free device memory" (``nvidia-smi`` minus a 500 MB guard —
``/root/reference/scaelum/builder/module_wrapper.py:187-224``).  On the
experiment's 16 GB-class GPU nodes the per-worker share of even the
160-layer stacked BERT-large is tens-to-hundreds of MB, so memory exists
as a feasibility constraint but does not bind the headline allocation:
heterogeneity enters through compute slowdowns (plus the Stimulator's
memory skew when ``STIMULATE`` is set).  ``regime="reference"`` reproduces
exactly that: a flat 16 GiB raw budget per worker, divided per-worker by
the Stimulator memory skew.

Round 2 silently switched the default to "total capacity = 1.5x the model
footprint", a memory-starved world the reference experiment never ran in.
Its *certified* optimal bottleneck (see
:func:`..solver.integral_lower_bound`) caps the optimal-vs-even speedup
near 29% — no solver can do better on that instance, so the ≥55% target
was unreachable by construction.  That regime is kept, explicitly named,
as ``regime="tight"`` for stress-testing the allocator under binding
memory.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from .solver import PartitionResult, solve_contiguous_minmax

# Flat per-worker raw memory budget emulating the reference's mem_limit=-1
# free-memory probe on its 16 GB-class GPU nodes (see module docstring).
REFERENCE_WORKER_MEM_MB = 16 * 1024.0


def worker_slowdowns(n_workers: int, kind: str = "paper") -> np.ndarray:
    """Per-worker compute slowdown factors.

    ``paper``: the reference experiment's own heterogeneity generator —
    reproducible integers in [1, 7), seed 35
    (``/root/reference/experiment/config.py:67-71``).  ``stimulator``: the
    seeded Stimulator compute draw.
    """
    if kind == "paper":
        rng = np.random.default_rng(seed=35)
        return rng.integers(low=1, high=7, size=n_workers + 1).astype(
            np.float64
        )[1:]
    if kind == "stimulator":
        from ..stimulator import Stimulator

        return np.asarray(Stimulator(n_workers).c_slowdown[:n_workers])
    raise ValueError(f"unknown slowdown kind {kind!r}")


def memory_skew(n_workers: int) -> np.ndarray:
    """The Stimulator's seeded per-worker memory skew (capacity divisor)."""
    from ..stimulator import Stimulator

    return np.asarray(Stimulator(n_workers).m_slowdown[:n_workers])


def worker_mem_budget_mb(
    layer_mem: Sequence[float],
    n_workers: int,
    regime: str = "reference",
) -> float:
    """Raw per-worker memory budget in MB (before the skew divisor).

    ``reference``: flat 16 GiB — the reference's ``mem_limit=-1`` probe
    regime (loose; compute binds).  ``tight``: total capacity = 1.5x the
    model footprint (r02's memory-starved stress regime).
    """
    if regime == "reference":
        return REFERENCE_WORKER_MEM_MB
    if regime == "tight":
        skew = memory_skew(n_workers)
        return 1.5 * float(np.sum(layer_mem)) / float(np.sum(1.0 / skew))
    raise ValueError(f"unknown memory regime {regime!r}")


def schedule_step_time(
    taus: Sequence[float], num_microbatches: int, sequential: bool = False
) -> float:
    """Step time of per-stage times under the engine's schedule.

    GPipe fill-drain: ``sum(tau)/M + (M-1)/M * max(tau)``; sequential is
    the reference's non-microbatched semantics (one batch traverses the
    stages in order, ``/root/reference/scaelum/model/rpc_model.py:49-55``).
    """
    taus = np.asarray(taus, dtype=np.float64)
    if sequential:
        return float(taus.sum())
    M = num_microbatches
    return float(taus.sum() / M + (M - 1) / M * taus.max())


def even_partition(n_layers: int, n_workers: int) -> List[int]:
    """Reference even split: floor division + remainder spread
    (``/root/reference/scaelum/dynamics/allocator.py:259-293``)."""
    base, rem = divmod(n_layers, n_workers)
    counts = [base + (1 if i < rem else 0) for i in range(n_workers)]
    idx = [0]
    for c in counts:
        idx.append(idx[-1] + c)
    return idx


def evaluate_instance(
    layer_flops: Sequence[float],
    layer_mem: Sequence[float],
    slowdowns: np.ndarray,
    num_microbatches: Optional[int] = None,
    regime: str = "reference",
    mem_budget_mb: Optional[float] = None,
    sequential: bool = False,
    tolerance: float = 1e-6,
) -> Dict:
    """Allocator + schedule math for the headline instance.

    Models per-stage time as ``slowdown_d * sum(flops of the slice)`` —
    the same proportionality ``bench.py`` realises with measured wall
    times — and returns even/optimal step times, speedup, and the solver
    result with its certified lower bound.
    """
    n_workers = len(slowdowns)
    layer_flops = list(layer_flops)
    layer_mem = list(layer_mem)
    L = len(layer_flops)
    if num_microbatches is None:
        num_microbatches = 2 * n_workers
    if mem_budget_mb is None:
        mem_budget_mb = worker_mem_budget_mb(layer_mem, n_workers, regime)
    skew = memory_skew(n_workers)
    dev_mem = mem_budget_mb / skew

    result: PartitionResult = solve_contiguous_minmax(
        layer_cost=layer_flops,
        layer_mem=layer_mem,
        device_time=list(slowdowns),
        device_mem=list(dev_mem),
        tolerance=tolerance,
    )
    flops_prefix = np.concatenate([[0.0], np.cumsum(layer_flops)])
    tau_opt = [
        float(slowdowns[d]) * float(flops_prefix[e] - flops_prefix[s])
        for d, (s, e) in zip(result.device_order, result.slices)
    ]

    idx = even_partition(L, n_workers)
    tau_even = [
        float(slowdowns[i])
        * float(flops_prefix[idx[i + 1]] - flops_prefix[idx[i]])
        for i in range(n_workers)
    ]

    t_even = schedule_step_time(tau_even, num_microbatches, sequential)
    t_opt = schedule_step_time(tau_opt, num_microbatches, sequential)
    return dict(
        step_time_even=t_even,
        step_time_optimal=t_opt,
        speedup_pct=(t_even - t_opt) / t_even * 100.0,
        solver_result=result,
        tau_even=tau_even,
        tau_optimal=tau_opt,
        mem_budget_mb=float(mem_budget_mb),
    )


__all__ = [
    "REFERENCE_WORKER_MEM_MB",
    "worker_slowdowns",
    "memory_skew",
    "worker_mem_budget_mb",
    "schedule_step_time",
    "even_partition",
    "evaluate_instance",
]
