// Exact contiguous layer->device partition solver (native core).
//
// The reference obtains native solving power by shelling out to the CBC MIP
// solver through pulp (reference: scaelum/dynamics/allocator.py:109-132).
// This is the TPU build's native equivalent: the same optimization problem
// — partition a layer sequence into contiguous slices on distinct devices,
// free device order, per-device memory capacity, minimize the bottleneck
// max_d device_time[d] * sum(layer_cost[slice_d]) — solved exactly by
// binary search over the bottleneck T with a subset-DP feasibility check
// (frontier[mask] = furthest layer reachable using device set `mask`;
// dominance is valid because reachability is monotone in the start index).
//
// Complexity per feasibility probe: O(2^D * D * log L).  In native code the
// exact regime extends to ~22 devices (the pure-Python DP in solver.py caps
// at 12); beyond that the Python greedy takes over.
//
// C ABI, consumed via ctypes (no pybind11 in the image).

#include <algorithm>
#include <chrono>
#include <random>
#include <cmath>
#include <cstdint>
#include <limits>
#include <vector>

namespace {

// furthest layer index reachable from `start` on device `d` under budget T
int cover(int start, int d, double T, int L,
          const std::vector<double>& cost_prefix,
          const std::vector<double>& mem_prefix,
          const double* device_time, const double* device_mem) {
  if (start >= L) return L;
  const double dt = device_time[d];
  const double cost_budget =
      dt > 0 ? T / dt : std::numeric_limits<double>::infinity();

  // binary search: largest r with cost_prefix[r] <= cost_prefix[start]+budget
  auto search = [&](const std::vector<double>& prefix, double budget) {
    const double limit = prefix[start] + budget + 1e-12;
    int lo = start, hi = L;  // invariant: prefix[lo] <= limit
    while (lo < hi) {
      int mid = (lo + hi + 1) / 2;
      if (prefix[mid] <= limit) lo = mid;
      else hi = mid - 1;
    }
    return lo;
  };

  const int r_cost = search(cost_prefix, cost_budget);
  const int r_mem = search(mem_prefix, device_mem[d] + 1e-9);
  const int r = r_cost < r_mem ? r_cost : r_mem;
  return r > start ? r : start;
}

// subset DP; fills order/slices on success, returns used-device count or -1
int feasible(double T, int L, int D,
             const std::vector<double>& cost_prefix,
             const std::vector<double>& mem_prefix,
             const double* device_time, const double* device_mem,
             std::vector<int>& frontier, std::vector<int>& choice,
             int* out_order, int* out_starts, int* out_ends) {
  const std::size_t size = std::size_t(1) << D;
  frontier.assign(size, 0);
  choice.assign(size, -1);

  std::size_t full = 0;
  for (std::size_t mask = 1; mask < size; ++mask) {
    int best = 0, best_d = -1;
    std::size_t m = mask;
    while (m) {
      const std::size_t low = m & (~m + 1);
      const int d = __builtin_ctzll(low);
      m ^= low;
      const int prev = frontier[mask ^ low];
      const int reach =
          cover(prev, d, T, L, cost_prefix, mem_prefix, device_time,
                device_mem);
      if (best_d == -1 || reach > best) {
        best = reach;
        best_d = d;
      }
    }
    frontier[mask] = best;
    choice[mask] = best_d;
    if (best >= L) {
      full = mask;
      break;
    }
  }
  if (full == 0) return -1;

  // peel choices: device order along the pipeline is the reverse of peeling
  std::vector<int> order_rev;
  std::size_t mask = full;
  while (mask) {
    const int d = choice[mask];
    order_rev.push_back(d);
    mask ^= std::size_t(1) << d;
  }

  int used = 0, pos = 0;
  for (auto it = order_rev.rbegin(); it != order_rev.rend(); ++it) {
    const int d = *it;
    const int end = cover(pos, d, T, L, cost_prefix, mem_prefix, device_time,
                          device_mem);
    if (end > pos) {
      out_order[used] = d;
      out_starts[used] = pos;
      out_ends[used] = end;
      ++used;
    }
    pos = end;
  }
  return pos >= L ? used : -1;
}

}  // namespace

// ---------------------------------------------------------------------------
// Large-D solver: the exact subset-DP above is exponential in D, so beyond
// ~22 devices the Python side falls back to a randomized greedy plus a
// Python-loop simulated anneal over device orders — ~7 ms per order
// evaluation, which starves the anneal on a 1-core host (the r05 headline
// instance certified gaps of 0.02-0.06 at an 80 s cap).  This native
// version runs the same search — score an order by bisecting the minimum
// bottleneck its fixed-order walk can achieve, anneal over orders with
// swap/move/bottleneck-targeted proposals, polish with boundary moves —
// at roughly 50-150 us per evaluation, turning the same wall budget into
// orders of magnitude more search effort.  Determinism: fixed eval-count
// rounds from a seeded mt19937 — bit-identical per seed whenever the
// eval budget completes inside the wall cap (the regime the tests pin);
// under a binding cap an in-round check truncates with ~0.5 s overshoot.

namespace {

struct Walked {
  std::vector<int> starts, ends;  // per position in order; start==end: empty
  bool complete = false;
};

// greedy maximal walk of `order` under budget T
void walk_order_into(const std::vector<int>& order, double T, int L,
                     const std::vector<double>& cost_prefix,
                     const std::vector<double>& mem_prefix,
                     const double* device_time, const double* device_mem,
                     Walked& w) {
  const int D = int(order.size());
  w.starts.resize(D);
  w.ends.resize(D);
  int pos = 0;
  for (int i = 0; i < D; ++i) {
    const int end = cover(pos, order[i], T, L, cost_prefix, mem_prefix,
                          device_time, device_mem);
    w.starts[i] = pos;
    w.ends[i] = end;
    pos = end;
  }
  w.complete = pos >= L;
}

// minimum bottleneck achievable by `order` (bisection over T); +inf when
// even an unbounded compute budget cannot cover L (memory-capped order)
double order_opt(const std::vector<int>& order, double lo, double hi,
                 double tolerance, int iters, int L,
                 const std::vector<double>& cost_prefix,
                 const std::vector<double>& mem_prefix,
                 const double* device_time, const double* device_mem,
                 Walked* out = nullptr) {
  thread_local Walked scratch;
  walk_order_into(order, hi, L, cost_prefix, mem_prefix, device_time,
                  device_mem, scratch);
  if (!scratch.complete) return std::numeric_limits<double>::infinity();
  double best = hi;
  if (out) *out = scratch;
  for (int it = 0; it < iters; ++it) {
    if (hi - lo <= tolerance * (hi > 1e-30 ? hi : 1e-30)) break;
    const double mid = 0.5 * (lo + hi);
    walk_order_into(order, mid, L, cost_prefix, mem_prefix, device_time,
                    device_mem, scratch);
    if (scratch.complete) {
      best = mid;
      hi = mid;
      if (out) *out = scratch;
    } else {
      lo = mid;
    }
  }
  return best;
}

double realized_bottleneck(const std::vector<int>& order, const Walked& w,
                           const std::vector<double>& cost_prefix,
                           const double* device_time) {
  double worst = 0.0;
  for (std::size_t i = 0; i < order.size(); ++i)
    worst = std::max(worst, device_time[order[i]] *
                                (cost_prefix[w.ends[i]] -
                                 cost_prefix[w.starts[i]]));
  return worst;
}

// hill-climb on slice boundaries: shift one layer between adjacent
// non-empty slices while the realized bottleneck improves
void boundary_polish(const std::vector<int>& order, Walked& w, int L,
                     const std::vector<double>& cost_prefix,
                     const std::vector<double>& mem_prefix,
                     const double* device_time, const double* device_mem,
                     int max_rounds = 200) {
  const int D = int(order.size());
  auto stage_time = [&](int i) {
    return device_time[order[i]] *
           (cost_prefix[w.ends[i]] - cost_prefix[w.starts[i]]);
  };
  auto mem_of = [&](int i) {
    return mem_prefix[w.ends[i]] - mem_prefix[w.starts[i]];
  };
  for (int round = 0; round < max_rounds; ++round) {
    bool moved = false;
    for (int i = 0; i + 1 < D; ++i) {
      if (w.ends[i] <= w.starts[i]) continue;
      int j = i + 1;
      while (j < D && w.ends[j] <= w.starts[j]) ++j;  // next non-empty
      if (j >= D) break;
      const double ti = stage_time(i), tj = stage_time(j);
      // move i's last layer to j
      if (ti > tj && w.ends[i] - w.starts[i] > 1) {
        const int layer = w.ends[i] - 1;
        const double lm = mem_prefix[layer + 1] - mem_prefix[layer];
        if (mem_of(j) + lm <= device_mem[order[j]] + 1e-9) {
          const double ni =
              device_time[order[i]] *
              (cost_prefix[layer] - cost_prefix[w.starts[i]]);
          const double nj =
              device_time[order[j]] *
              (cost_prefix[w.ends[j]] - cost_prefix[layer]);
          if (std::max(ni, nj) < std::max(ti, tj) - 1e-15) {
            --w.ends[i];
            w.starts[j] = layer;
            // intermediate empty stages must track the boundary
            for (int k = i + 1; k < j; ++k) w.starts[k] = w.ends[k] = layer;
            moved = true;
          }
        }
      } else if (tj > ti && w.ends[j] - w.starts[j] > 1) {
        // move j's first layer to i
        const int layer = w.starts[j];
        const double lm = mem_prefix[layer + 1] - mem_prefix[layer];
        if (mem_of(i) + lm <= device_mem[order[i]] + 1e-9) {
          const double ni =
              device_time[order[i]] *
              (cost_prefix[layer + 1] - cost_prefix[w.starts[i]]);
          const double nj =
              device_time[order[j]] *
              (cost_prefix[w.ends[j]] - cost_prefix[layer + 1]);
          if (std::max(ni, nj) < std::max(ti, tj) - 1e-15) {
            w.ends[i] = layer + 1;
            w.starts[j] = layer + 1;
            for (int k = i + 1; k < j; ++k)
              w.starts[k] = w.ends[k] = layer + 1;
            moved = true;
          }
        }
      }
    }
    if (!moved) break;
  }
}

}  // namespace

extern "C" {

// Anneal-based large-D solve.  Returns used-device count (>0), -1 when no
// explored order covers the model (infeasible), -2 on bad sizes.
int skytpu_solve_large(int L, int D, const double* layer_cost,
                       const double* layer_mem, const double* device_time,
                       const double* device_mem, unsigned long long seed,
                       int rounds, long evals0, double wall_cap_s,
                       double lower_bound, double gap_target,
                       double tolerance, int* out_order, int* out_starts,
                       int* out_ends, double* out_bottleneck) {
  if (L <= 0 || D <= 0 || L > 1000000 || D > 100000) return -2;

  std::vector<double> cost_prefix(L + 1, 0.0), mem_prefix(L + 1, 0.0);
  double total_cost = 0.0, max_dt = 0.0;
  for (int i = 0; i < L; ++i) {
    cost_prefix[i + 1] = cost_prefix[i] + layer_cost[i];
    mem_prefix[i + 1] = mem_prefix[i] + layer_mem[i];
    total_cost += layer_cost[i];
  }
  for (int d = 0; d < D; ++d) max_dt = std::max(max_dt, device_time[d]);
  const double hi0 = total_cost * max_dt;

  // initial order: fastest devices first (they should sit where layers
  // remain), ties by index for determinism
  std::vector<int> order(D);
  for (int d = 0; d < D; ++d) order[d] = d;
  std::sort(order.begin(), order.end(), [&](int a, int b) {
    if (device_time[a] != device_time[b])
      return device_time[a] < device_time[b];
    return a < b;
  });

  const int score_iters = 22;
  auto score = [&](const std::vector<int>& o, Walked* w = nullptr) {
    return order_opt(o, std::max(lower_bound, 0.0), hi0, tolerance,
                     score_iters, L, cost_prefix, mem_prefix, device_time,
                     device_mem, w);
  };

  Walked best_w;
  double best = score(order, &best_w);
  std::vector<int> best_order = order;
  if (std::isinf(best)) {
    // try a few random restarts before declaring infeasible
    std::mt19937_64 rng(seed ^ 0x9e3779b97f4a7c15ULL);
    for (int attempt = 0; attempt < 64 && std::isinf(best); ++attempt) {
      std::shuffle(order.begin(), order.end(), rng);
      best = score(order, &best_w);
      if (!std::isinf(best)) best_order = order;
    }
    if (std::isinf(best)) return -1;
  }
  boundary_polish(best_order, best_w, L, cost_prefix, mem_prefix, device_time,
                  device_mem);
  best = realized_bottleneck(best_order, best_w, cost_prefix, device_time);

  std::mt19937_64 rng(seed);
  const auto t_start = std::chrono::steady_clock::now();
  auto elapsed_s = [&] {
    return std::chrono::duration<double>(std::chrono::steady_clock::now() -
                                         t_start)
        .count();
  };

  long evals = evals0 > 0 ? evals0 : 20000;
  std::vector<int> cur_order = best_order;
  Walked cur_w;
  double cur = score(cur_order, &cur_w);
  std::vector<int> cand;
  for (int r = 0; r < rounds; ++r) {
    const double gap =
        lower_bound > 0 ? best / lower_bound - 1.0
                        : std::numeric_limits<double>::infinity();
    if (gap <= gap_target || elapsed_s() > wall_cap_s) break;
    // geometric temperature decay across rounds; relative scale
    const double temp0 = 0.02 * best / (1 << r);
    bool out_of_time = false;
    for (long e = 0; e < evals; ++e) {
      // bounded overshoot: rounds double geometrically, so a
      // boundary-only wall check could overrun the cap by the whole
      // last round; checking every 4096 evals caps the overrun at
      // ~0.5 s.  (Truncation point then depends on machine speed —
      // per-seed determinism holds whenever the eval budget finishes
      // inside the cap, which is how the tests pin it.)
      if ((e & 4095) == 4095 && elapsed_s() > wall_cap_s) {
        out_of_time = true;
        break;
      }
      cand = cur_order;
      const int kind = int(rng() % 3);
      if (kind == 0) {
        const int i = int(rng() % D), j = int(rng() % D);
        std::swap(cand[i], cand[j]);
      } else if (kind == 1) {
        const int i = int(rng() % D), j = int(rng() % D);
        const int d = cand[i];
        cand.erase(cand.begin() + i);
        cand.insert(cand.begin() + j, d);
      } else {
        // bottleneck-targeted: swap the CACHED bottleneck position of the
        // current order with a random other position
        int bpos = 0;
        double worst = -1.0;
        for (int i = 0; i < D; ++i) {
          const double t = device_time[cur_order[i]] *
                           (cost_prefix[cur_w.ends[i]] -
                            cost_prefix[cur_w.starts[i]]);
          if (t > worst) {
            worst = t;
            bpos = i;
          }
        }
        const int j = int(rng() % D);
        std::swap(cand[bpos], cand[j]);
      }
      Walked w;
      const double s = score(cand, &w);
      if (std::isinf(s)) continue;
      const double temp = temp0 > 1e-300 ? temp0 : 1e-300;
      if (s < cur ||
          std::generate_canonical<double, 53>(rng) <
              std::exp(-(s - cur) / temp)) {
        cur_order = cand;
        cur = s;
        cur_w = w;
        if (s < best) {
          boundary_polish(cand, w, L, cost_prefix, mem_prefix, device_time,
                          device_mem);
          const double polished =
              realized_bottleneck(cand, w, cost_prefix, device_time);
          if (polished < best) {
            best = polished;
            best_order = cand;
            best_w = w;
          }
        }
      }
    }
    if (out_of_time) break;
    evals *= 2;
  }

  int used = 0;
  for (int i = 0; i < D; ++i) {
    if (best_w.ends[i] > best_w.starts[i]) {
      out_order[used] = best_order[i];
      out_starts[used] = best_w.starts[i];
      out_ends[used] = best_w.ends[i];
      ++used;
    }
  }
  *out_bottleneck = best;
  return used > 0 ? used : -1;
}

}  // extern "C"

extern "C" {

// Returns the number of used devices (>0) on success, -1 if infeasible.
// out_order/out_starts/out_ends must have room for D entries.
int skytpu_solve_minmax(int L, int D, const double* layer_cost,
                        const double* layer_mem, const double* device_time,
                        const double* device_mem, double tolerance,
                        int max_iters, int* out_order, int* out_starts,
                        int* out_ends, double* out_bottleneck) {
  if (L <= 0 || D <= 0 || D > 30) return -2;

  std::vector<double> cost_prefix(L + 1, 0.0), mem_prefix(L + 1, 0.0);
  double total_cost = 0.0, max_dt = 0.0;
  for (int i = 0; i < L; ++i) {
    cost_prefix[i + 1] = cost_prefix[i] + layer_cost[i];
    mem_prefix[i + 1] = mem_prefix[i] + layer_mem[i];
    total_cost += layer_cost[i];
  }
  for (int d = 0; d < D; ++d) max_dt = std::max(max_dt, device_time[d]);

  std::vector<int> frontier, choice;
  std::vector<int> best_order(D), best_starts(D), best_ends(D);

  double hi = total_cost * max_dt;
  double lo = 0.0;

  int best_used =
      feasible(hi, L, D, cost_prefix, mem_prefix, device_time, device_mem,
               frontier, choice, best_order.data(), best_starts.data(),
               best_ends.data());
  if (best_used < 0) return -1;

  for (int it = 0; it < max_iters; ++it) {
    if (hi - lo <= tolerance * (hi > 1e-30 ? hi : 1e-30)) break;
    const double mid = 0.5 * (lo + hi);
    std::vector<int> order(D), starts(D), ends(D);
    const int used =
        feasible(mid, L, D, cost_prefix, mem_prefix, device_time, device_mem,
                 frontier, choice, order.data(), starts.data(), ends.data());
    if (used > 0) {
      best_used = used;
      best_order = order;
      best_starts = starts;
      best_ends = ends;
      hi = mid;
    } else {
      lo = mid;
    }
  }

  double achieved = 0.0;
  for (int i = 0; i < best_used; ++i) {
    const int d = best_order[i];
    const double t =
        device_time[d] *
        (cost_prefix[best_ends[i]] - cost_prefix[best_starts[i]]);
    achieved = std::max(achieved, t);
    out_order[i] = d;
    out_starts[i] = best_starts[i];
    out_ends[i] = best_ends[i];
  }
  *out_bottleneck = achieved;
  return best_used;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Class-collapse exact solver.  The headline instances' 64 devices carry
// only ~6 distinct slowdowns (the reference experiment draws integers in
// [1, 7)), so the 2^64 subset DP collapses to a count-vector DP: a state
// is "how many devices of each class are already used", the value is the
// max-frontier layer index (the same dominance argument as the subset DP
// — cover() is monotone in its start index).  State count is
// prod_k(count_k + 1): ~2.3M for the seed-35 draw, exact in seconds where
// the order-anneal certified gaps of 0.02-0.06.
//
// Memory heterogeneity inside a class is handled by the CALLER solving
// twice: once with each class's minimum member memory (any produced slice
// fits every member -> a real, feasible partition: an upper bound) and
// once with the maximum (a relaxation -> a certified lower bound).  With
// slack memory the two coincide and the result is provably optimal.

namespace {

// per-probe cover table: reach[k][p] = furthest layer from p on class k
void fill_cover(double T, int L, int K, const std::vector<double>& cost_prefix,
                const std::vector<double>& mem_prefix, const double* class_dt,
                const double* class_mem, std::vector<int>& reach) {
  for (int k = 0; k < K; ++k) {
    const double dt = class_dt[k];
    const double cost_budget =
        dt > 0 ? T / dt : std::numeric_limits<double>::infinity();
    int* row = reach.data() + std::size_t(k) * (L + 1);
    for (int p = 0; p <= L; ++p) {
      const double climit = cost_prefix[p] + cost_budget + 1e-12;
      const double mlimit = mem_prefix[p] + class_mem[k] + 1e-9;
      int lo = p, hi = L;
      while (lo < hi) {
        const int mid = (lo + hi + 1) / 2;
        if (cost_prefix[mid] <= climit && mem_prefix[mid] <= mlimit) lo = mid;
        else hi = mid - 1;
      }
      row[p] = lo;
    }
  }
}

}  // namespace

extern "C" {

// Exact solve over device classes.  counts[k] devices of class k share
// slowdown class_dt[k] and memory class_mem[k].  On success returns the
// number of slices (>0); out_class[i] is the CLASS of pipeline slice i.
// -1: infeasible even at the trivial threshold.  -2: size guard tripped
// (caller falls back to the anneal path).
int skytpu_solve_classes(int L, int K, const double* layer_cost,
                         const double* layer_mem, const int* counts,
                         const double* class_dt, const double* class_mem,
                         double tolerance, int max_iters,
                         long long max_states, int* out_class,
                         int* out_starts, int* out_ends,
                         double* out_bottleneck) {
  if (L <= 0 || K <= 0 || K > 12 || L > 1000000) return -2;

  long long n_states = 1;
  for (int k = 0; k < K; ++k) {
    if (counts[k] <= 0) return -2;
    n_states *= counts[k] + 1;
    if (n_states > max_states) return -2;
  }

  std::vector<double> cost_prefix(L + 1, 0.0), mem_prefix(L + 1, 0.0);
  double total_cost = 0.0, max_dt = 0.0;
  for (int i = 0; i < L; ++i) {
    cost_prefix[i + 1] = cost_prefix[i] + layer_cost[i];
    mem_prefix[i + 1] = mem_prefix[i] + layer_mem[i];
    total_cost += layer_cost[i];
  }
  for (int k = 0; k < K; ++k) max_dt = std::max(max_dt, class_dt[k]);

  std::vector<long long> stride(K);
  long long acc = 1;
  for (int k = 0; k < K; ++k) {
    stride[k] = acc;
    acc *= counts[k] + 1;
  }

  std::vector<int> reach(std::size_t(K) * (L + 1));
  std::vector<int> frontier(n_states);
  std::vector<int8_t> choice(n_states);
  std::vector<int> digits(K);

  // feasibility probe: forward count-vector DP (predecessor s - stride[k]
  // always precedes s in flat order); fills choice[] for reconstruction
  // and returns the reaching state, or -1
  auto probe = [&](double T) -> long long {
    fill_cover(T, L, K, cost_prefix, mem_prefix, class_dt, class_mem, reach);
    std::fill(frontier.begin(), frontier.end(), -1);
    frontier[0] = 0;
    std::fill(digits.begin(), digits.end(), 0);
    for (long long s = 1; s < n_states; ++s) {
      // odometer increment of the mixed-radix digits
      for (int k = 0; k < K; ++k) {
        if (++digits[k] <= counts[k]) break;
        digits[k] = 0;
      }
      int best = -1;
      int8_t best_k = -1;
      for (int k = 0; k < K; ++k) {
        if (digits[k] == 0) continue;
        const int prev = frontier[s - stride[k]];
        if (prev < 0) continue;
        const int r = reach[std::size_t(k) * (L + 1) + prev];
        if (r > best) {
          best = r;
          best_k = int8_t(k);
        }
      }
      frontier[s] = best;
      choice[s] = best_k;
      if (best >= L) return s;
    }
    return -1;
  };

  double hi = total_cost * max_dt, lo = 0.0;
  long long full = probe(hi);
  if (full < 0) return -1;
  double best_T = hi;
  for (int it = 0; it < max_iters; ++it) {
    if (hi - lo <= tolerance * (hi > 1e-30 ? hi : 1e-30)) break;
    const double mid = 0.5 * (lo + hi);
    const long long got = probe(mid);
    if (got >= 0) {
      full = got;
      best_T = mid;
      hi = mid;
    } else {
      lo = mid;
    }
  }

  // re-probe at the accepted threshold so choice[] matches, then peel
  full = probe(best_T);
  if (full < 0) return -1;  // cannot happen: best_T was feasible
  std::vector<int> class_rev;
  long long s = full;
  while (s != 0) {
    const int k = choice[s];
    if (k < 0) return -1;  // unreachable state in a peeled chain
    class_rev.push_back(k);
    s -= stride[k];
  }

  int used = 0, pos = 0;
  double achieved = 0.0;
  for (auto it = class_rev.rbegin(); it != class_rev.rend(); ++it) {
    const int k = *it;
    const int end = reach[std::size_t(k) * (L + 1) + pos];
    if (end > pos) {
      out_class[used] = k;
      out_starts[used] = pos;
      out_ends[used] = end;
      achieved = std::max(achieved,
                          class_dt[k] * (cost_prefix[end] - cost_prefix[pos]));
      ++used;
    }
    pos = end;
  }
  if (pos < L) return -1;
  *out_bottleneck = achieved;
  return used;
}

}  // extern "C"
