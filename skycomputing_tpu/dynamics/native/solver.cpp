// Exact contiguous layer->device partition solver (native core).
//
// The reference obtains native solving power by shelling out to the CBC MIP
// solver through pulp (reference: scaelum/dynamics/allocator.py:109-132).
// This is the TPU build's native equivalent: the same optimization problem
// — partition a layer sequence into contiguous slices on distinct devices,
// free device order, per-device memory capacity, minimize the bottleneck
// max_d device_time[d] * sum(layer_cost[slice_d]) — solved exactly by
// binary search over the bottleneck T with a subset-DP feasibility check
// (frontier[mask] = furthest layer reachable using device set `mask`;
// dominance is valid because reachability is monotone in the start index).
//
// Complexity per feasibility probe: O(2^D * D * log L).  In native code the
// exact regime extends to ~22 devices (the pure-Python DP in solver.py caps
// at 12); beyond that the Python greedy takes over.
//
// C ABI, consumed via ctypes (no pybind11 in the image).

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <limits>
#include <vector>

namespace {

// furthest layer index reachable from `start` on device `d` under budget T
int cover(int start, int d, double T, int L,
          const std::vector<double>& cost_prefix,
          const std::vector<double>& mem_prefix,
          const double* device_time, const double* device_mem) {
  if (start >= L) return L;
  const double dt = device_time[d];
  const double cost_budget =
      dt > 0 ? T / dt : std::numeric_limits<double>::infinity();

  // binary search: largest r with cost_prefix[r] <= cost_prefix[start]+budget
  auto search = [&](const std::vector<double>& prefix, double budget) {
    const double limit = prefix[start] + budget + 1e-12;
    int lo = start, hi = L;  // invariant: prefix[lo] <= limit
    while (lo < hi) {
      int mid = (lo + hi + 1) / 2;
      if (prefix[mid] <= limit) lo = mid;
      else hi = mid - 1;
    }
    return lo;
  };

  const int r_cost = search(cost_prefix, cost_budget);
  const int r_mem = search(mem_prefix, device_mem[d] + 1e-9);
  const int r = r_cost < r_mem ? r_cost : r_mem;
  return r > start ? r : start;
}

// subset DP; fills order/slices on success, returns used-device count or -1
int feasible(double T, int L, int D,
             const std::vector<double>& cost_prefix,
             const std::vector<double>& mem_prefix,
             const double* device_time, const double* device_mem,
             std::vector<int>& frontier, std::vector<int>& choice,
             int* out_order, int* out_starts, int* out_ends) {
  const std::size_t size = std::size_t(1) << D;
  frontier.assign(size, 0);
  choice.assign(size, -1);

  std::size_t full = 0;
  for (std::size_t mask = 1; mask < size; ++mask) {
    int best = 0, best_d = -1;
    std::size_t m = mask;
    while (m) {
      const std::size_t low = m & (~m + 1);
      const int d = __builtin_ctzll(low);
      m ^= low;
      const int prev = frontier[mask ^ low];
      const int reach =
          cover(prev, d, T, L, cost_prefix, mem_prefix, device_time,
                device_mem);
      if (best_d == -1 || reach > best) {
        best = reach;
        best_d = d;
      }
    }
    frontier[mask] = best;
    choice[mask] = best_d;
    if (best >= L) {
      full = mask;
      break;
    }
  }
  if (full == 0) return -1;

  // peel choices: device order along the pipeline is the reverse of peeling
  std::vector<int> order_rev;
  std::size_t mask = full;
  while (mask) {
    const int d = choice[mask];
    order_rev.push_back(d);
    mask ^= std::size_t(1) << d;
  }

  int used = 0, pos = 0;
  for (auto it = order_rev.rbegin(); it != order_rev.rend(); ++it) {
    const int d = *it;
    const int end = cover(pos, d, T, L, cost_prefix, mem_prefix, device_time,
                          device_mem);
    if (end > pos) {
      out_order[used] = d;
      out_starts[used] = pos;
      out_ends[used] = end;
      ++used;
    }
    pos = end;
  }
  return pos >= L ? used : -1;
}

}  // namespace

extern "C" {

// Returns the number of used devices (>0) on success, -1 if infeasible.
// out_order/out_starts/out_ends must have room for D entries.
int skytpu_solve_minmax(int L, int D, const double* layer_cost,
                        const double* layer_mem, const double* device_time,
                        const double* device_mem, double tolerance,
                        int max_iters, int* out_order, int* out_starts,
                        int* out_ends, double* out_bottleneck) {
  if (L <= 0 || D <= 0 || D > 30) return -2;

  std::vector<double> cost_prefix(L + 1, 0.0), mem_prefix(L + 1, 0.0);
  double total_cost = 0.0, max_dt = 0.0;
  for (int i = 0; i < L; ++i) {
    cost_prefix[i + 1] = cost_prefix[i] + layer_cost[i];
    mem_prefix[i + 1] = mem_prefix[i] + layer_mem[i];
    total_cost += layer_cost[i];
  }
  for (int d = 0; d < D; ++d) max_dt = std::max(max_dt, device_time[d]);

  std::vector<int> frontier, choice;
  std::vector<int> best_order(D), best_starts(D), best_ends(D);

  double hi = total_cost * max_dt;
  double lo = 0.0;

  int best_used =
      feasible(hi, L, D, cost_prefix, mem_prefix, device_time, device_mem,
               frontier, choice, best_order.data(), best_starts.data(),
               best_ends.data());
  if (best_used < 0) return -1;

  for (int it = 0; it < max_iters; ++it) {
    if (hi - lo <= tolerance * (hi > 1e-30 ? hi : 1e-30)) break;
    const double mid = 0.5 * (lo + hi);
    std::vector<int> order(D), starts(D), ends(D);
    const int used =
        feasible(mid, L, D, cost_prefix, mem_prefix, device_time, device_mem,
                 frontier, choice, order.data(), starts.data(), ends.data());
    if (used > 0) {
      best_used = used;
      best_order = order;
      best_starts = starts;
      best_ends = ends;
      hi = mid;
    } else {
      lo = mid;
    }
  }

  double achieved = 0.0;
  for (int i = 0; i < best_used; ++i) {
    const int d = best_order[i];
    const double t =
        device_time[d] *
        (cost_prefix[best_ends[i]] - cost_prefix[best_starts[i]]);
    achieved = std::max(achieved, t);
    out_order[i] = d;
    out_starts[i] = best_starts[i];
    out_ends[i] = best_ends[i];
  }
  *out_bottleneck = achieved;
  return best_used;
}

}  // extern "C"
