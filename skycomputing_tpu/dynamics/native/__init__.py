"""Native solver core: build-on-first-use + ctypes binding.

pybind11 is not in the image, so the C++ core exposes a C ABI and is loaded
with ctypes.  The shared object is compiled from ``solver.cpp`` with g++ on
first use (cached next to the source); any failure — no compiler, readonly
filesystem — degrades silently to the pure-Python solver.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional, Tuple

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "solver.cpp")
_LIB = os.path.join(_HERE, "libskytpu_solver.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    if os.path.exists(_LIB) and os.path.getmtime(_LIB) >= os.path.getmtime(
        _SRC
    ):
        return True
    # build to a temp name then os.replace: concurrent first-use processes
    # must never dlopen a half-written library
    tmp = f"{_LIB}.{os.getpid()}.tmp"
    try:
        subprocess.run(
            ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", _SRC, "-o", tmp],
            check=True,
            capture_output=True,
            timeout=120,
        )
        os.replace(tmp, _LIB)
        return True
    except Exception:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def load() -> Optional[ctypes.CDLL]:
    """The solver library, or None when native support is unavailable."""
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        if not _build():
            return None
        try:
            lib = ctypes.CDLL(_LIB)
        except OSError:
            return None
        lib.skytpu_solve_minmax.restype = ctypes.c_int
        lib.skytpu_solve_minmax.argtypes = [
            ctypes.c_int,
            ctypes.c_int,
            ctypes.POINTER(ctypes.c_double),
            ctypes.POINTER(ctypes.c_double),
            ctypes.POINTER(ctypes.c_double),
            ctypes.POINTER(ctypes.c_double),
            ctypes.c_double,
            ctypes.c_int,
            ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_double),
        ]
        lib.skytpu_solve_large.restype = ctypes.c_int
        lib.skytpu_solve_large.argtypes = [
            ctypes.c_int,
            ctypes.c_int,
            ctypes.POINTER(ctypes.c_double),
            ctypes.POINTER(ctypes.c_double),
            ctypes.POINTER(ctypes.c_double),
            ctypes.POINTER(ctypes.c_double),
            ctypes.c_ulonglong,
            ctypes.c_int,
            ctypes.c_long,
            ctypes.c_double,
            ctypes.c_double,
            ctypes.c_double,
            ctypes.c_double,
            ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_double),
        ]
        lib.skytpu_solve_classes.restype = ctypes.c_int
        lib.skytpu_solve_classes.argtypes = [
            ctypes.c_int,
            ctypes.c_int,
            ctypes.POINTER(ctypes.c_double),
            ctypes.POINTER(ctypes.c_double),
            ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_double),
            ctypes.POINTER(ctypes.c_double),
            ctypes.c_double,
            ctypes.c_int,
            ctypes.c_longlong,
            ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_double),
        ]
        _lib = lib
        return _lib


def solve_minmax_native(
    layer_cost,
    layer_mem,
    device_time,
    device_mem,
    tolerance: float = 1e-3,
    max_iters: int = 60,
) -> Optional[Tuple[List[int], List[Tuple[int, int]], float]]:
    """Native exact solve; None if the library is unavailable or infeasible
    is signalled as a RuntimeError (matching the Python solver)."""
    lib = load()
    if lib is None:
        return None

    L, D = len(layer_cost), len(device_time)
    arr = lambda xs: (ctypes.c_double * len(xs))(*[float(x) for x in xs])
    out_order = (ctypes.c_int * D)()
    out_starts = (ctypes.c_int * D)()
    out_ends = (ctypes.c_int * D)()
    out_bottleneck = ctypes.c_double()

    used = lib.skytpu_solve_minmax(
        L,
        D,
        arr(layer_cost),
        arr(layer_mem),
        arr(device_time),
        arr(device_mem),
        tolerance,
        max_iters,
        out_order,
        out_starts,
        out_ends,
        ctypes.byref(out_bottleneck),
    )
    if used == -2:
        return None  # out-of-range problem size: let Python handle it
    if used < 0:
        raise RuntimeError(
            "allocation infeasible: memory capacities cannot hold the model "
            f"(layers={L}, devices={D})"
        )
    order = [out_order[i] for i in range(used)]
    slices = [(out_starts[i], out_ends[i]) for i in range(used)]
    return order, slices, float(out_bottleneck.value)


def solve_large_native(
    layer_cost,
    layer_mem,
    device_time,
    device_mem,
    seed: int = 0,
    rounds: int = 6,
    evals0: int = 20000,
    wall_cap_s: float = 45.0,
    lower_bound: float = 0.0,
    gap_target: float = 0.01,
    tolerance: float = 1e-3,
) -> Optional[Tuple[List[int], List[Tuple[int, int]], float]]:
    """Native anneal solve for device counts beyond the exact DP's reach.

    Scores a device order by bisecting the minimum bottleneck its greedy
    fixed-order walk achieves, anneals over orders (swap / move /
    bottleneck-targeted swap proposals, eval-count rounds with doubling
    budgets), and hill-climbs slice boundaries on every improvement —
    the same search the pure-Python fallback runs, at a far higher
    evaluation rate.  Deterministic per seed whenever the eval budget
    completes inside ``wall_cap_s`` (under a binding cap an in-round
    check truncates with sub-second overshoot).  None if the library is
    unavailable; RuntimeError when no explored order covers the model.
    """
    lib = load()
    if lib is None:
        return None

    L, D = len(layer_cost), len(device_time)
    arr = lambda xs: (ctypes.c_double * len(xs))(*[float(x) for x in xs])
    out_order = (ctypes.c_int * D)()
    out_starts = (ctypes.c_int * D)()
    out_ends = (ctypes.c_int * D)()
    out_bottleneck = ctypes.c_double()

    used = lib.skytpu_solve_large(
        L,
        D,
        arr(layer_cost),
        arr(layer_mem),
        arr(device_time),
        arr(device_mem),
        int(seed) & 0xFFFFFFFFFFFFFFFF,
        int(rounds),
        int(evals0),
        float(wall_cap_s),
        float(lower_bound),
        float(gap_target),
        float(tolerance),
        out_order,
        out_starts,
        out_ends,
        ctypes.byref(out_bottleneck),
    )
    if used == -2:
        return None
    if used < 0:
        raise RuntimeError(
            "allocation infeasible: memory capacities cannot hold the model "
            f"(layers={L}, devices={D})"
        )
    order = [out_order[i] for i in range(used)]
    slices = [(out_starts[i], out_ends[i]) for i in range(used)]
    return order, slices, float(out_bottleneck.value)


def solve_classes_native(
    layer_cost,
    layer_mem,
    counts,
    class_dt,
    class_mem,
    tolerance: float = 1e-9,
    max_iters: int = 60,
    max_states: int = 8_000_000,
) -> Optional[Tuple[List[int], List[Tuple[int, int]], float]]:
    """Exact count-vector-DP solve over device CLASSES (few distinct
    slowdowns).  Returns (slice classes in pipeline order, slices,
    bottleneck); None when the library is unavailable or the size guard
    trips; RuntimeError when the class instance is infeasible — the
    caller decides whether that dooms the real instance (it does not
    when ``class_mem`` held per-class minima)."""
    lib = load()
    if lib is None:
        return None

    L, K = len(layer_cost), len(class_dt)
    arr = lambda xs: (ctypes.c_double * len(xs))(*[float(x) for x in xs])
    iarr = lambda xs: (ctypes.c_int * len(xs))(*[int(x) for x in xs])
    D = sum(int(c) for c in counts)
    out_class = (ctypes.c_int * D)()
    out_starts = (ctypes.c_int * D)()
    out_ends = (ctypes.c_int * D)()
    out_bottleneck = ctypes.c_double()

    used = lib.skytpu_solve_classes(
        L,
        K,
        arr(layer_cost),
        arr(layer_mem),
        iarr(counts),
        arr(class_dt),
        arr(class_mem),
        float(tolerance),
        int(max_iters),
        int(max_states),
        out_class,
        out_starts,
        out_ends,
        ctypes.byref(out_bottleneck),
    )
    if used == -2:
        return None
    if used < 0:
        raise RuntimeError("class instance infeasible")
    classes = [out_class[i] for i in range(used)]
    slices = [(out_starts[i], out_ends[i]) for i in range(used)]
    return classes, slices, float(out_bottleneck.value)


__all__ = [
    "solve_minmax_native",
    "solve_large_native",
    "solve_classes_native",
    "load",
]
