"""Static + timed measurement helpers.

TPU-native replacement for the reference ``Estimator``
(``scaelum/dynamics/estimator.py:15-152``):

- FLOPs come from XLA's own cost model
  (``jit(f).lower(...).compile().cost_analysis()['flops']``) instead of
  pthflops' torch-JIT tracing;
- memory uses the same accounting *formula* as the reference (param_scale x
  params + 2 x outputs + inputs, 4-byte floats, MB units) so the allocator
  interface is unchanged, but sizes are exact from avals instead of hook
  guesswork;
- speed measurement respects XLA async dispatch: warm-up compile, then
  ``block_until_ready`` timing.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def _cost_analysis(compiled) -> dict:
    """Version-normalized ``compiled.cost_analysis()`` (shared shim)."""
    from ..utils.profiling import normalize_cost_analysis

    return normalize_cost_analysis(compiled.cost_analysis())


def _as_tuple(data) -> Tuple:
    return data if isinstance(data, tuple) else (data,)


def _aval_bytes(tree, bytes_per_number: float = None) -> float:
    total = 0.0
    for leaf in jax.tree_util.tree_leaves(tree):
        n = float(np.prod(leaf.shape)) if leaf.shape else 1.0
        itemsize = (
            bytes_per_number
            if bytes_per_number is not None
            else jnp.dtype(leaf.dtype).itemsize
        )
        total += n * itemsize
    return total


class Estimator:
    """Stateless measurement helpers (kept as a namespace class for parity)."""

    @staticmethod
    def benchmark_speed(
        fn: Callable,
        args: Sequence[Any],
        device=None,
        iterations: int = 30,
        warmup: int = 3,
    ) -> float:
        """Total wall-clock of ``iterations`` executions of jitted ``fn``.

        Honest timing on an async, compiled runtime requires placing inputs on
        the target device, compiling + warming up first, and blocking on the
        final output (reference analog: 30 no-grad forwards,
        ``estimator.py:15-34``).
        """
        jitted = jax.jit(fn)
        if device is not None:
            args = jax.device_put(list(args), device)
        out = None
        for _ in range(max(warmup, 1)):
            out = jitted(*args)
        jax.block_until_ready(out)

        start = time.perf_counter()
        for _ in range(iterations):
            out = jitted(*args)
        jax.block_until_ready(out)
        return time.perf_counter() - start

    @staticmethod
    def benchmark_model(
        module,
        data: Sequence[Any],
        param_scale: int = 2,
        rng: jax.Array = None,
    ):
        """(output_avals, flops, mem_MB) for one layer — fully static.

        No parameters are materialized and no FLOPs are executed: ``init`` and
        ``apply`` are shape-traced with ``jax.eval_shape`` and FLOPs come from
        compiling the apply against abstract inputs.  This is what lets the
        model benchmarker profile a 160-layer BERT without OOM — the
        reference needed a hard-coded BERT shortcut for that
        (``benchmarker.py:163-166``).
        """
        if rng is None:
            rng = jax.random.key(0)
        data = _as_tuple(data)
        avals = tuple(
            jax.ShapeDtypeStruct(np.shape(x), np.asarray(x).dtype)
            if not isinstance(x, jax.ShapeDtypeStruct)
            else x
            for x in data
        )

        k_params, k_dropout = jax.random.split(rng)
        variables_aval = jax.eval_shape(
            lambda *xs: module.init(
                {"params": k_params, "dropout": k_dropout}, *xs
            ),
            *avals,
        )
        params_aval = variables_aval["params"]

        def apply_fn(params, *xs):
            return module.apply(
                {"params": params}, *xs, rngs={"dropout": k_dropout}
            )

        out_aval = jax.eval_shape(apply_fn, params_aval, *avals)

        compiled = jax.jit(apply_fn).lower(params_aval, *avals).compile()
        flops = float(_cost_analysis(compiled).get("flops", 0.0))

        mb = 1024.0**2
        # Reference formula (estimator.py:85-152): inputs + 2x outputs (grads)
        # + param_scale x params, at 4 bytes/number.
        input_size = _aval_bytes(avals, 4.0) / mb
        output_size = 2.0 * _aval_bytes(out_aval, 4.0) / mb
        param_size = param_scale * _aval_bytes(params_aval, 4.0) / mb
        mem_usage = input_size + output_size + param_size

        return out_aval, flops, mem_usage

    @staticmethod
    def estimate_memory(module, data: Sequence[Any], param_scale: int = 2,
                        rng: jax.Array = None):
        """(output_avals, mem_MB) — the static memory half of
        :meth:`benchmark_model` without the FLOPs compile (for callers
        that already measure cost some other way, e.g. timed profiling)."""
        if rng is None:
            rng = jax.random.key(0)
        data = _as_tuple(data)
        avals = tuple(
            jax.ShapeDtypeStruct(np.shape(x), np.asarray(x).dtype)
            if not isinstance(x, jax.ShapeDtypeStruct)
            else x
            for x in data
        )
        k_params, k_dropout = jax.random.split(rng)
        variables_aval = jax.eval_shape(
            lambda *xs: module.init(
                {"params": k_params, "dropout": k_dropout}, *xs
            ),
            *avals,
        )
        params_aval = variables_aval["params"]
        out_aval = jax.eval_shape(
            lambda params, *xs: module.apply(
                {"params": params}, *xs, rngs={"dropout": k_dropout}
            ),
            params_aval, *avals,
        )
        mb = 1024.0**2
        mem_usage = (
            _aval_bytes(avals, 4.0) / mb
            + 2.0 * _aval_bytes(out_aval, 4.0) / mb
            + param_scale * _aval_bytes(params_aval, 4.0) / mb
        )
        return out_aval, mem_usage

    @staticmethod
    def measure_flops(fn: Callable, *args) -> float:
        """XLA-reported FLOPs of an arbitrary jittable function."""
        compiled = jax.jit(fn).lower(*args).compile()
        return float(_cost_analysis(compiled).get("flops", 0.0))

    @staticmethod
    def benchmark_decode_step(
        module,
        data: Sequence[Any],
        cache_avals: Optional[Sequence[Any]] = None,
        index: Any = None,
        param_scale: int = 2,
        rng: jax.Array = None,
    ):
        """(out_avals, flops, mem_MB) for ONE decode iteration — static.

        The serving counterpart of :meth:`benchmark_model`: training
        costs (full-sequence fwd+bwd) mis-rank layers for a *decode*
        partition, where attention is dominated by the KV-cache read
        (``O(max_len)`` per token) and everything else by ``Lq=1``
        matmuls.  This profiles the layer's actual per-token program:

        - attention-style layers (``cache_avals`` given): the layer's
          ``decode(data..., k_cache, v_cache, index)`` method against
          the full slot slab;
        - embedding-style layers (a ``decode`` method, no caches):
          ``decode(data..., index)``;
        - everything else: plain ``apply``.

        Like :meth:`benchmark_model`, everything is abstract — shapes
        via ``eval_shape``, FLOPs from XLA's cost model — so a deep
        stack profiles without materializing parameters.  ``mem_MB``
        is the reference accounting formula (inputs + 2x outputs +
        ``param_scale`` x params, 4 bytes); the *preallocated KV-slab*
        memory is deliberately not included here — it is a pool-level
        quantity added by the serving profile
        (:func:`~..serving.kv_cache.kv_mb_per_layer`), which keeps one
        slab-size formula shared with the pre-flight plan verifier.
        """
        if rng is None:
            rng = jax.random.key(0)
        data = _as_tuple(data)
        avals = tuple(
            jax.ShapeDtypeStruct(np.shape(x), np.asarray(x).dtype)
            if not isinstance(x, jax.ShapeDtypeStruct)
            else x
            for x in data
        )
        method = None
        args = avals
        if cache_avals is not None:
            method = type(module).decode
            args = avals + tuple(cache_avals) + (index,)
        elif hasattr(module, "decode"):
            method = type(module).decode
            args = avals + (index,)

        k_params, k_dropout = jax.random.split(rng)
        variables_aval = jax.eval_shape(
            lambda *xs: module.init(
                {"params": k_params, "dropout": k_dropout}, *xs,
                method=method,
            ),
            *args,
        )
        params_aval = variables_aval["params"]

        def step_fn(params, *xs):
            return module.apply({"params": params}, *xs, method=method)

        out_aval = jax.eval_shape(step_fn, params_aval, *args)
        compiled = jax.jit(step_fn).lower(params_aval, *args).compile()
        flops = float(_cost_analysis(compiled).get("flops", 0.0))

        # memory counts the DATA outputs only: an attention decode also
        # returns the updated caches, but those alias the preallocated
        # slab (in-place update), not fresh per-step activations
        data_out = out_aval[0] if cache_avals is not None else out_aval
        mb = 1024.0**2
        mem_usage = (
            _aval_bytes(avals, 4.0) / mb
            + 2.0 * _aval_bytes(data_out, 4.0) / mb
            + param_scale * _aval_bytes(params_aval, 4.0) / mb
        )
        return out_aval, flops, mem_usage

    @staticmethod
    def benchmark_train_time(
        module,
        data: Sequence[Any],
        rng: jax.Array = None,
        iterations: int = 8,
        warmup: int = 2,
        repeats: int = 3,
        device=None,
    ) -> Tuple[Any, float]:
        """(outputs, measured fwd+bwd seconds per iteration) for one layer.

        The *timed* counterpart of :meth:`benchmark_model`: builds real
        params, jits one forward+backward (gradients w.r.t. params and
        inputs — what a pipeline stage actually computes each tick), warms
        the executable, then takes the best of ``repeats`` timed loops of
        ``iterations`` chained executions with one final block, matching
        the discipline of ``PipelineModel.measure_stage_times`` so
        allocator inputs and realized stage times live on the same scale.
        XLA's static FLOP count is a poor proxy for wall time on
        memory-bound units (softmax/LayerNorm-heavy attention thirds vs
        matmul-heavy FFN thirds), which mis-ranks layers for the
        allocator; measuring closes that gap.
        """
        if rng is None:
            rng = jax.random.key(0)
        data = _as_tuple(data)
        if device is not None:
            data = tuple(jax.device_put(x, device) for x in data)
        k_params, k_dropout = jax.random.split(rng)
        variables = module.init(
            {"params": k_params, "dropout": k_dropout}, *data
        )
        params = variables["params"]
        if device is not None:
            params = jax.device_put(params, device)

        def apply_fn(params, *xs):
            return module.apply(
                {"params": params}, *xs, rngs={"dropout": k_dropout}
            )

        # Time what a pipeline stage computes each tick: the forward
        # OUTPUTS (handed downstream — returned so XLA cannot dead-code
        # any of the forward) plus the vjp against a full-size cotangent,
        # w.r.t. params and the FLOAT inputs (upstream cotangents; integer
        # inputs like token ids are non-differentiable pass-throughs).  A
        # ``grad(sum(out))`` objective would let XLA elide most of the
        # forward — gradients of linear ops don't need their outputs.
        is_diff = tuple(
            jnp.issubdtype(np.asarray(x).dtype, np.inexact) for x in data
        )

        def train_like(params, diff_xs, int_xs, cotangent):
            def fwd(params, diff_xs):
                xs, di, ii = [], iter(diff_xs), iter(int_xs)
                for d in is_diff:
                    xs.append(next(di) if d else next(ii))
                return apply_fn(params, *xs)

            out, vjp = jax.vjp(fwd, params, diff_xs)
            return out, vjp(cotangent)

        outputs = apply_fn(params, *data)
        diff_xs = tuple(x for x, d in zip(data, is_diff) if d)
        int_xs = tuple(x for x, d in zip(data, is_diff) if not d)

        def fwd_shapes(params, diff_xs, int_xs):
            xs, di, ii = [], iter(diff_xs), iter(int_xs)
            for d in is_diff:
                xs.append(next(di) if d else next(ii))
            return apply_fn(params, *xs)

        # cotangent dtypes must match the TRACED outputs — weak-type
        # promotion differs between closed-over constants and traced
        # arguments, so eval_shape must receive every input as an
        # argument, exactly like the jitted step below does
        cotangent = jax.tree_util.tree_map(
            lambda a: (
                jnp.ones(a.shape, a.dtype)
                if jnp.issubdtype(a.dtype, jnp.inexact)
                else np.zeros(a.shape, jax.dtypes.float0)
            ),
            jax.eval_shape(fwd_shapes, params, diff_xs, int_xs),
        )
        step = jax.jit(train_like)
        result = None
        for _ in range(max(warmup, 1)):
            result = step(params, diff_xs, int_xs, cotangent)
        jax.block_until_ready(result)

        best = float("inf")
        for _ in range(repeats):
            start = time.perf_counter()
            for _ in range(iterations):
                result = step(params, diff_xs, int_xs, cotangent)
            jax.block_until_ready(result)
            best = min(best, (time.perf_counter() - start) / iterations)
        return outputs, best


__all__ = ["Estimator"]
