"""Static + timed measurement helpers.

TPU-native replacement for the reference ``Estimator``
(``scaelum/dynamics/estimator.py:15-152``):

- FLOPs come from XLA's own cost model
  (``jit(f).lower(...).compile().cost_analysis()['flops']``) instead of
  pthflops' torch-JIT tracing;
- memory uses the same accounting *formula* as the reference (param_scale x
  params + 2 x outputs + inputs, 4-byte floats, MB units) so the allocator
  interface is unchanged, but sizes are exact from avals instead of hook
  guesswork;
- speed measurement respects XLA async dispatch: warm-up compile, then
  ``block_until_ready`` timing.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def _as_tuple(data) -> Tuple:
    return data if isinstance(data, tuple) else (data,)


def _aval_bytes(tree, bytes_per_number: float = None) -> float:
    total = 0.0
    for leaf in jax.tree_util.tree_leaves(tree):
        n = float(np.prod(leaf.shape)) if leaf.shape else 1.0
        itemsize = (
            bytes_per_number
            if bytes_per_number is not None
            else jnp.dtype(leaf.dtype).itemsize
        )
        total += n * itemsize
    return total


class Estimator:
    """Stateless measurement helpers (kept as a namespace class for parity)."""

    @staticmethod
    def benchmark_speed(
        fn: Callable,
        args: Sequence[Any],
        device=None,
        iterations: int = 30,
        warmup: int = 3,
    ) -> float:
        """Total wall-clock of ``iterations`` executions of jitted ``fn``.

        Honest timing on an async, compiled runtime requires placing inputs on
        the target device, compiling + warming up first, and blocking on the
        final output (reference analog: 30 no-grad forwards,
        ``estimator.py:15-34``).
        """
        jitted = jax.jit(fn)
        if device is not None:
            args = jax.device_put(list(args), device)
        out = None
        for _ in range(max(warmup, 1)):
            out = jitted(*args)
        jax.block_until_ready(out)

        start = time.perf_counter()
        for _ in range(iterations):
            out = jitted(*args)
        jax.block_until_ready(out)
        return time.perf_counter() - start

    @staticmethod
    def benchmark_model(
        module,
        data: Sequence[Any],
        param_scale: int = 2,
        rng: jax.Array = None,
    ):
        """(output_avals, flops, mem_MB) for one layer — fully static.

        No parameters are materialized and no FLOPs are executed: ``init`` and
        ``apply`` are shape-traced with ``jax.eval_shape`` and FLOPs come from
        compiling the apply against abstract inputs.  This is what lets the
        model benchmarker profile a 160-layer BERT without OOM — the
        reference needed a hard-coded BERT shortcut for that
        (``benchmarker.py:163-166``).
        """
        if rng is None:
            rng = jax.random.key(0)
        data = _as_tuple(data)
        avals = tuple(
            jax.ShapeDtypeStruct(np.shape(x), np.asarray(x).dtype)
            if not isinstance(x, jax.ShapeDtypeStruct)
            else x
            for x in data
        )

        variables_aval = jax.eval_shape(
            lambda *xs: module.init({"params": rng, "dropout": rng}, *xs),
            *avals,
        )
        params_aval = variables_aval["params"]

        def apply_fn(params, *xs):
            return module.apply({"params": params}, *xs, rngs={"dropout": rng})

        out_aval = jax.eval_shape(apply_fn, params_aval, *avals)

        compiled = jax.jit(apply_fn).lower(params_aval, *avals).compile()
        flops = float(compiled.cost_analysis().get("flops", 0.0))

        mb = 1024.0**2
        # Reference formula (estimator.py:85-152): inputs + 2x outputs (grads)
        # + param_scale x params, at 4 bytes/number.
        input_size = _aval_bytes(avals, 4.0) / mb
        output_size = 2.0 * _aval_bytes(out_aval, 4.0) / mb
        param_size = param_scale * _aval_bytes(params_aval, 4.0) / mb
        mem_usage = input_size + output_size + param_size

        return out_aval, flops, mem_usage

    @staticmethod
    def measure_flops(fn: Callable, *args) -> float:
        """XLA-reported FLOPs of an arbitrary jittable function."""
        compiled = jax.jit(fn).lower(*args).compile()
        return float(compiled.cost_analysis().get("flops", 0.0))


__all__ = ["Estimator"]
