"""Device + model benchmarkers.

TPU-native re-design of ``scaelum/dynamics/benchmarker.py``:

- ``DeviceBenchmarker`` (reference :30-133) measured each RPC worker's speed
  by fanning out ``rpc_async`` calls; here every device hangs off the single
  controller, so the fan-out is a loop of timed jit executions committed to
  each device, with available memory read from ``device.memory_stats()``
  (the ``nvidia-smi`` analog) or per-worker ``mem_limit`` config.
- ``ModelBenchmarker`` (reference :136-201) measured per-layer FLOPs/memory
  by *running* each layer, with a hard-coded BERT shortcut to avoid OOM;
  here profiling is fully static (XLA cost analysis over abstract shapes —
  see ``Estimator.benchmark_model``) and the shortcut generalizes to
  config-hash dedup: identical (layer-config, input-shape) pairs are
  compiled once regardless of model family.
- Stimulator distortion matches the reference hook (:126-129): compute time
  is multiplied and available memory divided by per-worker factors, enabled
  by the ``STIMULATE`` env var or an explicit ``stimulator=`` argument.
"""

from __future__ import annotations

import abc
import json
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from ..builder import build_layer, build_layer_stack
from ..dataset import BaseGenerator
from ..stimulator import Stimulator
from ..telemetry import trace_span
from ..utils import generate_worker_name
from .estimator import Estimator
from .worker_manager import WorkerManager


class BaseBenchmarker(abc.ABC):
    @abc.abstractmethod
    def benchmark(self):
        ...


def _device_for(worker, devices):
    return devices[worker.device_index % len(devices)]


def device_available_memory_mb(device, fallback_fraction: float = 0.8) -> float:
    """Free device memory in MB; psutil host fallback for CPU fake devices."""
    stats = None
    try:
        stats = device.memory_stats()
    except Exception:
        stats = None
    if stats and "bytes_limit" in stats:
        free = stats["bytes_limit"] - stats.get("bytes_in_use", 0)
        return free / 1024.0**2
    try:
        import psutil

        return psutil.virtual_memory().available * fallback_fraction / 1024.0**2
    except Exception:  # pragma: no cover - psutil is in the image
        return 8 * 1024.0


class DeviceBenchmarker(BaseBenchmarker):
    def __init__(
        self,
        worker_manager: WorkerManager,
        data_generator: BaseGenerator,
        model_config: List[Dict],
        iterations: int = 30,
        dtype: Optional[str] = None,
        devices: Optional[Sequence[Any]] = None,
        stimulator: Optional[Stimulator] = None,
    ):
        self._worker_manager = worker_manager
        self._model_config = model_config
        self._data_generator = data_generator
        self._iterations = iterations
        self._dtype = dtype
        self._devices = list(devices) if devices is not None else jax.devices()
        if stimulator is None and os.getenv("STIMULATE") is not None:
            stimulator = Stimulator(worker_manager.size)
        self._stimulator = stimulator
        # raw per-worker measurements memoized by worker identity: the
        # refine_allocation closed loop re-enters benchmark() once per
        # re-solve, and re-timing unchanged devices only repeats compile +
        # execute work and injects fresh noise (keyed by worker.id, not
        # rank — allocation re-ranks the pool)
        self._measure_cache: Dict[str, Tuple[float, float]] = {}
        # raw SPEED measurements deduped by physical device: in the
        # single-controller world, workers mapped onto the same device
        # are the same hardware — re-timing the identical jitted proxy
        # per worker (64x at headline scale) repeats wall clock and, far
        # worse, injects per-worker noise that fakes heterogeneity the
        # solver then chases: exactly-equal raw times keep the profiled
        # device_time collapsed into its true slowdown classes, which is
        # what lets the class-exact solver certify the allocation.
        # Emulated heterogeneity (stimulator, slowdown config) applies
        # AFTER this cache, per worker, unchanged.
        self._device_time_cache: Dict[Any, float] = {}

    def local_benchmark(self, worker, data) -> Tuple[float, float]:
        """Time the proxy model on one worker's device; probe free memory."""
        device = _device_for(worker, self._devices)
        if device in self._device_time_cache:
            elapsed = self._device_time_cache[device]
        else:
            with trace_span("bench.device", "dynamics", "benchmark",
                            {"device": str(device)}):
                elapsed = self._measure_device(device, data)
            self._device_time_cache[device] = elapsed

        mem_limit = worker.extra_config.get("mem_limit", -1)
        if mem_limit and mem_limit > 0:
            avai_mem = float(mem_limit)
        else:
            avai_mem = device_available_memory_mb(device)
        return elapsed, avai_mem

    def _measure_device(self, device, data) -> float:
        """One timed proxy-model run on ``device`` (the cache-miss path)."""
        stack = build_layer_stack(self._model_config)
        data = data if isinstance(data, tuple) else (data,)
        if self._dtype is not None:
            data = tuple(np.asarray(d).astype(self._dtype) for d in data)

        params = stack.init(jax.random.key(0), *data)
        params = jax.device_put(params, device)

        def fwd(p, *xs):
            return stack.apply(p, *xs)

        return Estimator.benchmark_speed(
            fwd,
            [params, *data],
            device=device,
            iterations=self._iterations,
        )

    def benchmark(self) -> Dict[str, Dict[str, float]]:
        results: Dict[str, Dict[str, float]] = {}
        data = None

        for worker in self._worker_manager.worker_pool:
            worker_name = generate_worker_name(worker.rank)
            if worker.id not in self._measure_cache:
                if data is None:
                    data = self._data_generator.generate()
                self._measure_cache[worker.id] = self.local_benchmark(
                    worker, data
                )
            elapsed, avai_mem = self._measure_cache[worker.id]

            if self._stimulator is not None:
                # keyed by the worker's STABLE index, not current rank:
                # allocation re-ranks the pool, and a post-allocation
                # re-benchmark (the refine_allocation closed loop) must
                # see the same per-worker heterogeneity as the first pass
                elapsed *= self._stimulator.compute_slowdown(worker.stim_index)
                avai_mem /= self._stimulator.memory_slowdown(worker.stim_index)

            results[worker_name] = dict(time=elapsed, avai_mem=avai_mem)
        return results


def _layer_key(layer_cfg: Dict, input_avals) -> str:
    shapes = [(tuple(a.shape), str(a.dtype)) for a in input_avals]
    return json.dumps([layer_cfg, shapes], sort_keys=True, default=str)


class ModelBenchmarker(BaseBenchmarker):
    """Per-layer cost + memory profile over the full model config.

    Two profiling modes:

    - static (default): XLA cost-analysis FLOPs over abstract shapes —
      no params materialized, no FLOPs executed (how a 160-layer model
      profiles without OOM; generalizes the reference's hard-coded BERT
      shortcut, ``scaelum/dynamics/benchmarker.py:163-166``);
    - ``timed=True``: per-layer *measured* forward+backward seconds
      (real params, jitted, warmed, chained iterations), threading each
      layer's real outputs into the next layer's inputs exactly like the
      reference's running profiler (``benchmarker.py:156-201``).  Static
      FLOPs mis-rank memory-bound layers (attention thirds) against
      matmul-bound ones (FFN thirds), which costs the allocator real
      bottleneck quality — the headline bench profiles timed.

    Both modes dedup by (layer-config, input-shape) hash, so deep stacked
    models compile/measure each distinct unit once.
    """

    def __init__(
        self,
        model_config: List[Dict],
        data_generator: BaseGenerator,
        dtype: Optional[str] = None,
        param_scale: int = 2,
        device: Optional[str] = None,  # accepted for config parity; unused
        timed: bool = False,
        timed_iterations: int = 8,
    ):
        self._model_config = model_config
        self._data_generator = data_generator
        self._dtype = dtype
        self._param_scale = param_scale
        self._timed = bool(timed)
        self._timed_iterations = int(timed_iterations)
        self._result: Optional[Tuple[List[float], List[float]]] = None

    @property
    def model_config(self) -> List[Dict]:
        return self._model_config

    def benchmark(self) -> Tuple[List[float], List[float]]:
        """Per-layer (cost, mem_MB) lists over the full model config.

        ``cost`` is XLA FLOPs in static mode, measured fwd+bwd seconds in
        timed mode — the allocator only consumes relative magnitudes, so
        the two are drop-in interchangeable.  The result is memoized: the
        profile is deterministic given (config, generator), and in timed
        mode re-measuring on every allocator call would repeat real
        compile+execute work.
        """
        if self._result is not None:
            return self._result
        with trace_span(
            "bench.model", "dynamics", "benchmark",
            {"layers": len(self._model_config), "timed": self._timed},
        ):
            self._result = self._benchmark()
        return self._result

    def _benchmark(self) -> Tuple[List[float], List[float]]:
        data = self._data_generator.generate()
        data = data if isinstance(data, tuple) else (data,)

        cost_list: List[float] = []
        mem_list: List[float] = []
        cache: Dict[str, Tuple[Any, float, float]] = {}

        if self._timed:
            current = data
            for layer_cfg in self._model_config:
                avals = tuple(
                    jax.ShapeDtypeStruct(np.shape(x), np.asarray(x).dtype)
                    for x in jax.tree_util.tree_leaves(current)
                )
                key = _layer_key(layer_cfg, avals)
                if key in cache:
                    outputs, seconds, mem = cache[key]
                else:
                    cfg = dict(layer_cfg)
                    layer_type = cfg.pop("layer_type")
                    module = build_layer(layer_type, **cfg)
                    outputs, seconds = Estimator.benchmark_train_time(
                        module, current, iterations=self._timed_iterations
                    )
                    # memory stays the static formula so the allocator's
                    # capacity model is identical across modes (no FLOPs
                    # compile — the cost here is the measured seconds)
                    _, mem = Estimator.estimate_memory(
                        module, avals, param_scale=self._param_scale
                    )
                    cache[key] = (outputs, seconds, mem)
                cost_list.append(seconds)
                mem_list.append(mem)
                out = outputs if isinstance(outputs, tuple) else (outputs,)
                current = tuple(jax.tree_util.tree_leaves(out))
            return cost_list, mem_list

        avals = tuple(
            jax.ShapeDtypeStruct(np.shape(x), np.asarray(x).dtype) for x in data
        )
        for layer_cfg in self._model_config:
            key = _layer_key(layer_cfg, avals)
            if key in cache:
                out_aval, flops, mem = cache[key]
            else:
                cfg = dict(layer_cfg)
                layer_type = cfg.pop("layer_type")
                module = build_layer(layer_type, **cfg)
                out_aval, flops, mem = Estimator.benchmark_model(
                    module, avals, param_scale=self._param_scale
                )
                cache[key] = (out_aval, flops, mem)
            cost_list.append(flops)
            mem_list.append(mem)
            out = out_aval if isinstance(out_aval, tuple) else (out_aval,)
            avals = tuple(
                jax.ShapeDtypeStruct(a.shape, a.dtype)
                for a in jax.tree_util.tree_leaves(out)
            )

        return cost_list, mem_list


__all__ = [
    "BaseBenchmarker",
    "DeviceBenchmarker",
    "ModelBenchmarker",
    "device_available_memory_mb",
]
