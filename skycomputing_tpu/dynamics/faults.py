"""Deterministic, seeded fault-injection harness.

The :class:`~..stimulator.Stimulator` injects *static* heterogeneity: one
seeded slowdown draw per worker, fixed for the whole run.  Real
geo-distributed nodes degrade *mid-run* — the scenario the paper's
load-balanced allocation is most exposed to — so chaos tests need faults
scheduled on the training timeline: "node 2 becomes 3x slower at iter
50", byte-for-byte reproducible.  :class:`FaultPlan` is that script; the
:class:`FaultInjectionHook` applies it from inside the normal hook
lifecycle so no trainer code changes for a chaos run.

Event kinds (each a plain dict, so plans serialize as JSON):

``slowdown``   persistent compute degradation of one worker's stage
               (``worker`` = stable ``stim_index``, ``factor``; optional
               ``duration`` iters after which it clears).  Written to both
               the live :class:`StageRuntime` and the worker's
               ``extra_config`` so it survives a self-heal repartition —
               a degraded NODE stays degraded whatever layers it holds.
``stall``      one-shot transient wedge: the iteration sleeps ``seconds``.
``nan``        poison one worker's stage params with NaN (what a bad
               DIMM / bit-flip looks like by the time the loss sees it).
``drop_beat``  suppress this iteration's heartbeat collective
               (``HeartbeatHook`` consults the flag) — a process missing
               its beat window.
``corrupt_checkpoint``  truncate the newest checkpoint under ``path`` to
               a seeded fraction of its bytes — a torn write / partial
               upload as the newest artifact.

Serving-fleet event kinds (applied by :class:`FleetFaultInjector` at
fleet-TICK granularity — ``iter`` indexes ``ServingFleet.tick`` — so
one vocabulary scripts chaos for trainer and fleet alike):

``replica_crash``  the replica's engine stops responding: every
               subsequent tick raises, heartbeats stop, and the fleet
               supervisor must detect, migrate, and re-form.
``latency_spike``  per-tick stall of ``seconds`` on one replica
               (optional ``duration`` ticks; an unpinned ``seconds``
               draws seeded) — a degraded-but-alive node, the
               sick-replica detection target.
``slot_leak``  leak ``count`` KV slots from the replica's pool (slots
               allocated with no owning request) — partial capacity
               loss, what a wedged worker or an accounting bug looks
               like from the scheduler's seat.

All randomness (unspecified factors, truncation points, spike lengths)
comes from one ``numpy`` generator seeded at construction, so a plan
replays exactly.  Each applier validates its vocabulary at
construction: a trainer-only kind in a fleet plan (or vice versa) fails
at build time, not 50 iterations into a chaos run.
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..registry import HOOKS
from ..runner.hooks import Hook
from ..utils import Logger

#: trainer-timeline kinds, applied by :class:`FaultInjectionHook`
_TRAINER_KINDS = (
    "slowdown", "stall", "nan", "drop_beat", "corrupt_checkpoint",
)
#: serving-fleet kinds, applied by :class:`FleetFaultInjector`
_FLEET_KINDS = ("replica_crash", "latency_spike", "slot_leak")
_KINDS = _TRAINER_KINDS + _FLEET_KINDS

#: per-kind required event fields, validated at plan construction so a
#: malformed plan fails at build time, not 50 iterations into a chaos run
_REQUIRED_FIELDS = {
    "slowdown": ("worker", "factor"),
    "stall": ("seconds",),
    "nan": (),
    "drop_beat": (),
    "corrupt_checkpoint": ("path",),
    "replica_crash": ("replica",),
    "latency_spike": ("replica",),
    "slot_leak": ("replica",),
}


class FaultPlan:
    """An iteration-indexed script of fault events.

    ``events``: sequence of dicts with at least ``iter`` (0-based training
    iteration, matched against ``runner.iter`` at the START of that
    iteration) and ``kind`` (one of ``_KINDS``).  Events fire once, in
    listed order within an iteration.
    """

    def __init__(self, events: Sequence[Dict[str, Any]], seed: int = 0):
        self._rng = np.random.default_rng(seed)
        self.seed = seed
        self.events: List[Dict[str, Any]] = []
        for ev in events:
            ev = dict(ev)
            if "iter" not in ev:
                raise ValueError(f"fault event missing 'iter': {ev}")
            kind = ev.get("kind")
            if kind not in _KINDS:
                raise ValueError(
                    f"unknown fault kind {kind!r}; known: {_KINDS}"
                )
            missing = [f for f in _REQUIRED_FIELDS[kind] if f not in ev]
            if missing:
                raise ValueError(
                    f"fault event {ev} missing required field(s) {missing} "
                    f"for kind {kind!r}"
                )
            ev["iter"] = int(ev["iter"])
            self.events.append(ev)
        self.events.sort(key=lambda e: e["iter"])

    @classmethod
    def from_stimulator(
        cls,
        worker_num: int,
        at_iter: int = 0,
        compute_range=(1.0, 4.0),
        compute_seed: int = 42,
        seed: int = 0,
    ) -> "FaultPlan":
        """Stimulator-compatible static heterogeneity as a plan: every
        worker gets its seeded slowdown at ``at_iter`` — the same draw the
        :class:`~..stimulator.Stimulator` would produce, but applied to
        live stages on the training timeline instead of distorting the
        startup benchmark."""
        from ..stimulator import Stimulator

        stim = Stimulator(
            worker_num, compute_range=compute_range, compute_seed=compute_seed
        )
        events = [
            dict(iter=at_iter, kind="slowdown", worker=i,
                 factor=stim.compute_slowdown(i))
            for i in range(worker_num)
        ]
        return cls(events, seed=seed)

    def events_at(self, iteration: int) -> List[Dict[str, Any]]:
        return [e for e in self.events if e["iter"] == iteration]

    def draw_fraction(self, lo: float = 0.1, hi: float = 0.9) -> float:
        """One seeded draw in [lo, hi) — the truncation point for
        checkpoint corruption when the event doesn't pin one."""
        return float(lo + (hi - lo) * self._rng.random())

    def draw_spike_seconds(self, lo: float = 0.02,
                           hi: float = 0.2) -> float:
        """One seeded draw for an unpinned ``latency_spike`` stall —
        same generator as every other draw, so a plan that leaves
        ``seconds`` open still replays byte-for-byte."""
        return self.draw_fraction(lo, hi)

    def corrupt_checkpoint(
        self, path: str, keep_fraction: Optional[float] = None
    ) -> str:
        """Truncate the newest ``*.msgpack`` under ``path`` (or ``path``
        itself if it's a file) to ``keep_fraction`` of its bytes.
        Returns the corrupted file's path."""
        if os.path.isdir(path):
            candidates = [
                os.path.join(path, n)
                for n in os.listdir(path)
                # exclude training-state sidecars: "newest checkpoint"
                # means the params file, and the sidecar is written last
                # so max-mtime would otherwise always pick it
                if n.endswith(".msgpack")
                and not n.endswith(".train_state.msgpack")
            ]
            if not candidates:
                raise FileNotFoundError(f"no *.msgpack checkpoints in {path}")
            target = max(candidates, key=os.path.getmtime)
        else:
            target = path
        size = os.path.getsize(target)
        frac = (
            float(keep_fraction)
            if keep_fraction is not None
            else self.draw_fraction()
        )
        keep = max(1, int(size * frac))
        with open(target, "rb+") as fh:
            fh.truncate(keep)
        return target


@HOOKS.register_module
class FaultInjectionHook(Hook):
    """Apply a :class:`FaultPlan` from the runner's hook lifecycle.

    Register it BEFORE detection/heal hooks so an iteration's faults are
    in place when those hooks observe it.  ``applied`` records every fired
    event (with the iteration it fired at) for test assertions.
    """

    def __init__(self, plan: FaultPlan, logger: Optional[Logger] = None):
        foreign = [e for e in plan.events if e["kind"] in _FLEET_KINDS]
        if foreign:
            raise ValueError(
                f"FaultInjectionHook applies trainer-timeline faults "
                f"only; fleet kinds {sorted({e['kind'] for e in foreign})}"
                f" belong in a FleetFaultInjector plan"
            )
        self._plan = plan
        self._logger = logger or Logger()
        # worker stim_index -> (clear_at_iter, previous_factor)
        self._pending_clear: Dict[int, Any] = {}
        # stall seconds armed in before_iter, slept in after_iter: this
        # hook registers BEFORE the detection hooks, so a before_iter
        # sleep would finish before their timers start and the wedge
        # would be invisible to exactly the detectors under test
        self._pending_stall_s = 0.0
        self.applied: List[Dict[str, Any]] = []

    # --- worker/stage resolution -------------------------------------------
    @staticmethod
    def _worker_by_stim_index(runner, stim_index: int):
        for w in runner.worker_manager.worker_pool:
            if w.stim_index == stim_index:
                return w
        raise LookupError(f"no worker with stim_index {stim_index}")

    @staticmethod
    def _stage_for_worker(runner, worker):
        """The live StageRuntime holding ``worker``'s slice, or None when
        the worker currently holds no layers."""
        occupied = [
            w
            for w in sorted(
                runner.worker_manager.worker_pool, key=lambda w: w.rank
            )
            if w.model_config
        ]
        for stage_idx, w in enumerate(occupied):
            if w is worker:
                return runner.model.stages[stage_idx]
        return None

    def _set_worker_slowdown(self, runner, stim_index: int,
                             factor: float) -> None:
        worker = self._worker_by_stim_index(runner, stim_index)
        # extra_config is the durable home: PipelineModel._build_stages
        # reads it on every (re)build, so the degradation survives a
        # self-heal repartition
        worker.extra_config["slowdown"] = float(factor)
        stage = self._stage_for_worker(runner, worker)
        if stage is not None:
            stage.slowdown = float(factor)

    # --- lifecycle ----------------------------------------------------------
    def before_iter(self, runner):
        # drop_beat is one-shot per iteration: clear the PREVIOUS
        # iteration's flag here (not in after_iter — this hook registers
        # before the detection hooks, so its after_iter would clear the
        # flag before HeartbeatHook ever saw it).  A consuming
        # HeartbeatHook resets the flag itself; finding it still set
        # means no beat was scheduled that iteration (interval mismatch)
        # — record that honestly instead of letting a chaos test believe
        # a beat was suppressed.
        if getattr(runner, "fault_drop_beat", False):
            for rec in reversed(self.applied):
                if rec["kind"] == "drop_beat":
                    rec["consumed"] = False
                    break
            self._logger.info(
                "FAULT: armed drop_beat was never consumed (no heartbeat "
                "scheduled that iteration)"
            )
        runner.fault_drop_beat = False

        # clear expired slowdowns first so a back-to-back re-injection at
        # the same iteration wins
        for stim_index, (clear_at, prev) in list(self._pending_clear.items()):
            if runner.iter >= clear_at:
                self._set_worker_slowdown(runner, stim_index, prev)
                del self._pending_clear[stim_index]

        for ev in self._plan.events_at(runner.iter):
            kind = ev["kind"]
            if kind == "slowdown":
                stim_index = int(ev["worker"])
                factor = float(ev["factor"])
                if ev.get("duration"):
                    worker = self._worker_by_stim_index(runner, stim_index)
                    prev = float(worker.extra_config.get("slowdown", 1.0))
                    self._pending_clear[stim_index] = (
                        runner.iter + int(ev["duration"]), prev
                    )
                self._set_worker_slowdown(runner, stim_index, factor)
                self._logger.info(
                    f"FAULT iter {runner.iter}: worker {stim_index} "
                    f"compute slowdown x{factor}"
                )
            elif kind == "stall":
                self._pending_stall_s += float(ev["seconds"])
                self._logger.info(
                    f"FAULT iter {runner.iter}: transient stall "
                    f"{float(ev['seconds']):.3f}s armed"
                )
            elif kind == "nan":
                import jax

                worker = self._worker_by_stim_index(
                    runner, int(ev.get("worker", 0))
                )
                stage = self._stage_for_worker(runner, worker)
                if stage is None:
                    # don't lie in the log or the applied record: a chaos
                    # test asserting the NaN path ran must see the skip
                    self._logger.info(
                        f"FAULT iter {runner.iter}: worker "
                        f"{ev.get('worker', 0)} holds no layers; nan "
                        f"fault skipped"
                    )
                    ev = dict(ev, skipped=True)
                else:
                    stage.params = jax.tree_util.tree_map(
                        lambda x: x * float("nan"), stage.params
                    )
                    self._logger.info(
                        f"FAULT iter {runner.iter}: NaN-poisoned worker "
                        f"{ev.get('worker', 0)} params"
                    )
            elif kind == "drop_beat":
                runner.fault_drop_beat = True
                self._logger.info(
                    f"FAULT iter {runner.iter}: heartbeat drop armed"
                )
            elif kind == "corrupt_checkpoint":
                target = self._plan.corrupt_checkpoint(
                    ev["path"], ev.get("keep_fraction")
                )
                self._logger.info(
                    f"FAULT iter {runner.iter}: truncated checkpoint "
                    f"{target}"
                )
            self.applied.append(dict(ev, fired_at=runner.iter))

    def after_iter(self, runner):
        if self._pending_stall_s > 0.0:
            # inside the detection hooks' timing window (they registered
            # after this hook, so their after_iter runs after this sleep)
            time.sleep(self._pending_stall_s)
            self._pending_stall_s = 0.0


class FleetFaultInjector:
    """Apply a :class:`FaultPlan`'s fleet vocabulary to a serving fleet.

    The fleet twin of :class:`FaultInjectionHook`: the fleet calls
    :meth:`on_tick` at the START of every :meth:`ServingFleet.step`
    (before any replica runs and before the supervisor observes), so an
    event at tick N is in place when tick N's detection looks.  The
    target is duck-typed — anything with ``tick`` and
    ``replica_by_index(i)`` returning objects exposing ``crash()`` /
    ``inject_stall(seconds, duration_ticks)`` / ``leak_slots(count)``
    (:class:`~..fleet.replica.EngineReplica`'s fault surface) — which
    keeps dynamics -> fleet import-free.

    ``applied`` records every fired event with the tick it fired at and
    any seeded draw it consumed, for test assertions.
    """

    def __init__(self, plan: FaultPlan, logger: Optional[Logger] = None):
        foreign = [e for e in plan.events
                   if e["kind"] not in _FLEET_KINDS]
        if foreign:
            raise ValueError(
                f"FleetFaultInjector applies fleet faults only; trainer "
                f"kinds {sorted({e['kind'] for e in foreign})} belong in "
                f"a FaultInjectionHook plan"
            )
        self._plan = plan
        self._logger = logger or Logger()
        self.applied: List[Dict[str, Any]] = []
        self._validated = False

    def on_tick(self, fleet) -> None:
        if not self._validated:
            # the fleet is first available HERE, so replica indices are
            # range-checked on the first tick — before anything fires —
            # keeping the fails-at-arm-time contract the kind/field
            # validation makes at construction
            self._validated = True
            n = len(fleet.replicas)
            bad = sorted({
                int(e["replica"]) for e in self._plan.events
                if not 0 <= int(e["replica"]) < n
            })
            if bad:
                raise ValueError(
                    f"fault plan names replica indices {bad} but the "
                    f"fleet has {n} replicas"
                )
        for ev in self._plan.events_at(fleet.tick):
            kind = ev["kind"]
            replica = fleet.replica_by_index(int(ev["replica"]))
            if kind == "replica_crash":
                replica.crash()
                self._logger.info(
                    f"FAULT tick {fleet.tick}: replica {replica.name} "
                    f"crashed"
                )
            elif kind == "latency_spike":
                seconds = ev.get("seconds")
                if seconds is None:
                    seconds = self._plan.draw_spike_seconds()
                    ev = dict(ev, seconds=float(seconds))
                duration = ev.get("duration")
                replica.inject_stall(
                    float(seconds),
                    None if duration is None
                    else fleet.tick + int(duration),
                )
                self._logger.info(
                    f"FAULT tick {fleet.tick}: replica {replica.name} "
                    f"latency spike {float(seconds):.3f}s/tick"
                    + (f" for {duration} ticks" if duration else "")
                )
            elif kind == "slot_leak":
                want = int(ev.get("count", 1))
                leaked = replica.leak_slots(want)
                if leaked < want:
                    # an exhausted pool leaks fewer — record the truth
                    ev = dict(ev, leaked=leaked)
                self._logger.info(
                    f"FAULT tick {fleet.tick}: replica {replica.name} "
                    f"leaked {leaked} slot(s)"
                )
            self.applied.append(dict(ev, fired_at=fleet.tick))


__all__ = ["FaultPlan", "FaultInjectionHook", "FleetFaultInjector"]
