"""Cluster-membership registry.

Parity with ``scaelum/dynamics/worker_manager.py:7-79``.  Differences born of
the single-controller TPU design: rank 0 is *not* reserved for a host process
by default — the controller owns all devices, so every worker can hold layers.
Set ``reserve_host_rank=True`` to reproduce the reference's 1-host + N-worker
numbering.  The reference's ``assign_model_to_worker`` bug (calling a property)
is fixed.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from .worker import Worker


class WorkerManager:
    def __init__(self, reserve_host_rank: bool = False):
        self._worker_pool: List[Worker] = []
        self._first_rank = 1 if reserve_host_rank else 0

    @property
    def size(self) -> int:
        return len(self._worker_pool)

    @property
    def worker_pool(self) -> List[Worker]:
        return self._worker_pool

    def get_by_id(self, id_str: str, allow_not_found: bool = False) -> Optional[Worker]:
        for worker in self._worker_pool:
            if worker.id == id_str:
                return worker
        if allow_not_found:
            return None
        raise LookupError(f"Worker with id {id_str} is not found in the worker pool")

    def get_by_rank(self, rank: int) -> Worker:
        for worker in self._worker_pool:
            if worker.rank == rank:
                return worker
        raise LookupError(f"Worker with rank {rank} is not found in the worker pool")

    def load_worker_pool_from_config(self, config: List[Dict]) -> None:
        for i, worker_config in enumerate(config):
            worker = Worker(rank=self._first_rank + i, **worker_config)
            self._worker_pool.append(worker)

    def assign_model_to_worker(self, rank: int, model_config: List[Dict]) -> None:
        self.get_by_rank(rank).model_config = model_config

    def add_worker(self, worker_id: str, worker_config: Dict) -> None:
        rank = self._first_rank + len(self._worker_pool)
        self._worker_pool.append(
            Worker(rank=rank, worker_id=worker_id, **worker_config)
        )

    def remove_worker_by_id(self, id_str: str) -> None:
        worker = self.get_by_id(id_str)
        if worker.is_running:
            # a real error, not an assert: under ``python -O`` asserts
            # vanish and a running worker would be silently dropped from
            # the pool while its stage still executes
            raise RuntimeError(
                f"Worker {id_str} is still running; stop it before "
                f"removing it from the pool"
            )
        self._worker_pool.remove(worker)
        self._allocate_rank()

    def _allocate_rank(self) -> None:
        for i, worker in enumerate(self._worker_pool):
            worker.rank = self._first_rank + i

    def reset_rank_by_order(self) -> None:
        """Re-sort the pool by pipeline order and re-rank so rank == stage."""
        self._worker_pool.sort(key=lambda w: w.order)
        self._allocate_rank()

    def serialize(self) -> List[Dict]:
        return [w.serialize() for w in self._worker_pool]

    @staticmethod
    def deserialize(data: List[Dict]) -> "WorkerManager":
        manager = WorkerManager()
        for worker_data in data:
            manager.worker_pool.append(Worker.deserialize(worker_data))
        return manager


__all__ = ["WorkerManager"]
