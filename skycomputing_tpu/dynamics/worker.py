"""Worker value object.

Parity with the reference ``Worker`` (``scaelum/dynamics/worker.py:8-97``):
one cluster-node record with rank, name, uuid, pipeline order, running flag,
the assigned layer-config slice, and runtime knobs.  In the TPU build a
"worker" is a logical pipeline stage bound to a device index in the
controller's device list (``server_config.host/port`` become
``device_config.device_index``); ``extra_config`` carries the stage-runtime
knobs (slowdown, mem_limit, microbatch behavior).

Reference bugs intentionally fixed (SURVEY §"do NOT cargo-cult"):
``env_config`` no longer reads a never-set attribute.
"""

from __future__ import annotations

import uuid as _uuid
from typing import Any, Dict, List, Optional


class Worker:
    def __init__(
        self,
        rank: int,
        name: str,
        device_config: Optional[Dict[str, Any]] = None,
        server_config: Optional[Dict[str, Any]] = None,  # legacy-name alias
        worker_id: Optional[str] = None,
        order: Optional[int] = None,
        model_config: Optional[List[Dict]] = None,
        extra_config: Optional[Dict[str, Any]] = None,
        is_running: bool = False,
        stim_index: Optional[int] = None,
    ) -> None:
        self._rank = rank
        self._name = name
        # stable heterogeneity-profile index: allocation re-ranks workers
        # (``reset_rank_by_order``), so anything keyed by *current* rank —
        # the Stimulator's per-worker slowdown draw — mis-attributes after
        # the first allocate.  Freeze the identity at construction.
        self._stim_index = stim_index if stim_index is not None else rank
        self._is_running = is_running
        self._order = order
        self._worker_id = worker_id if worker_id is not None else str(_uuid.uuid4())
        self._device_config = device_config if device_config is not None else (
            server_config or {}
        )
        self._model_config = model_config
        self._extra_config = extra_config or {}

    # --- identity -----------------------------------------------------------
    @property
    def rank(self) -> int:
        return self._rank

    @rank.setter
    def rank(self, rank: int) -> None:
        self._rank = rank

    @property
    def id(self) -> str:
        return self._worker_id

    @property
    def name(self) -> str:
        return self._name

    @property
    def stim_index(self) -> int:
        """Rank at construction — the stable key for heterogeneity draws."""
        return self._stim_index

    # --- configs ------------------------------------------------------------
    @property
    def device_config(self) -> Dict[str, Any]:
        return self._device_config

    # legacy-name alias kept for reference-config compatibility
    server_config = device_config

    @property
    def device_index(self) -> int:
        return int(self._device_config.get("device_index", 0))

    @property
    def model_config(self) -> Optional[List[Dict]]:
        return self._model_config

    @model_config.setter
    def model_config(self, config: List[Dict]) -> None:
        self._model_config = config

    @property
    def extra_config(self) -> Dict[str, Any]:
        return self._extra_config

    # --- scheduling state ---------------------------------------------------
    @property
    def order(self) -> Optional[int]:
        return self._order

    @order.setter
    def order(self, order: int) -> None:
        self._order = order

    @property
    def is_running(self) -> bool:
        return self._is_running

    @is_running.setter
    def is_running(self, status: bool) -> None:
        self._is_running = status

    # --- transport ----------------------------------------------------------
    def serialize(self) -> Dict[str, Any]:
        return dict(self.__dict__)

    @staticmethod
    def deserialize(data: Dict[str, Any]) -> "Worker":
        kwargs = {k.lstrip("_"): v for k, v in data.items()}
        return Worker(**kwargs)

    def __repr__(self) -> str:  # pragma: no cover
        n_layers = len(self._model_config) if self._model_config else 0
        return (
            f"Worker(rank={self._rank}, name={self._name!r}, "
            f"device={self.device_index}, order={self._order}, "
            f"layers={n_layers})"
        )


__all__ = ["Worker"]
