"""Shape-bucketing admission layer for the serving engine.

Serving traffic has arbitrary prompt lengths; XLA programs have fixed
shapes.  The bridge is a small set of **prompt-length buckets**: every
prompt is right-padded to the smallest bucket that holds it, so the
prefill program compiles once per bucket and the steady-state decode
program (always ``[slots, 1]``) compiles exactly once — the SKY002
recompile discipline applied to serving.  Bucket choice trades padding
waste (few, large buckets) against warmup compiles (many buckets);
padding positions are attention-masked so they never change a token.

Admission is FIFO with same-bucket packing: the head of the queue picks
the bucket, and up to ``prefill_batch`` queued requests of that same
bucket join it (skipping over other buckets WITHOUT starving them — the
head request itself is always served first).
"""

from __future__ import annotations

import itertools
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

# request lifecycle states
QUEUED = "queued"
RUNNING = "running"
FINISHED = "finished"
# terminal non-success states (set by admission control / the fleet):
# REJECTED = refused or shed by a bounded queue / load-shedding policy,
# FAILED = lost to an unrecoverable replica failure (a request whose
# resume prefix outgrew every bucket on a dead engine) — both always
# counted, never silent
REJECTED = "rejected"
FAILED = "failed"

_REQUEST_IDS = itertools.count()


class QueueFullError(RuntimeError):
    """A bounded :class:`AdmissionQueue` refused a new submission.

    Carries ``queue_depth`` (the bound it hit) so callers can build an
    honest backpressure hint (Retry-After-style) instead of guessing.
    """

    def __init__(self, message: str, queue_depth: int = 0):
        super().__init__(message)
        self.queue_depth = int(queue_depth)


@dataclass
class Request:
    """One generation request and its runtime state.

    ``prompt`` is the token ids; ``tokens`` accumulates generated ids as
    the engine produces them (the per-request output stream).  After a
    preemption the request re-enters the queue and its *effective*
    prompt is ``prompt + tokens`` — decoding resumes by recomputing the
    KV prefix (vLLM-style recomputation preemption), so the token
    stream is preserved exactly.
    """

    prompt: np.ndarray
    max_new_tokens: int
    temperature: float = 0.0
    seed: int = 0
    request_id: int = field(default_factory=lambda: next(_REQUEST_IDS))

    # runtime state (owned by the engine)
    status: str = QUEUED
    tokens: List[int] = field(default_factory=list)
    slot: Optional[int] = None
    index: int = 0               # current sequence length in the cache
    bucket: Optional[int] = None
    preemptions: int = 0
    # chunked-prefill watermark (paged engine, prefill_chunk set):
    # prompt positions whose KV is already resident.  A request admitted
    # under chunking holds its page grant and a decode row while
    # prefilled_len < len(effective_prompt); each engine tick advances
    # the watermark by at most one chunk, interleaved with decode ticks.
    # 0 means "not mid-prefill" (the one-shot wave path never sets it,
    # and preemption resets it — recomputation replays the whole tail).
    prefilled_len: int = 0

    # the reasoned verdict for a FAILED terminal state (set by whoever
    # fails the request — fleet migration, swap-corruption fallback):
    # "every request terminal with a reason" is an auditable invariant
    # only if the reason rides on the request itself
    fail_reason: Optional[str] = None

    # SLO stamps (perf_counter seconds; None until reached)
    submitted_s: Optional[float] = None
    first_token_s: Optional[float] = None
    finished_s: Optional[float] = None

    # request-scoped tracing scratch (tracer-relative µs marks for the
    # segment currently open on this request's trace lane).  Lives ON
    # the request because the request object is the one thing that
    # survives preemption and cross-replica migration — whoever closes
    # a segment (engine finish/preempt, fleet dead-drain) finds the
    # open mark here.  Empty dict and never touched while tracing is
    # disabled.
    trace_marks: Dict[str, float] = field(default_factory=dict)

    def __post_init__(self):
        self.prompt = np.asarray(self.prompt, np.int32).reshape(-1)
        if self.prompt.size == 0:
            raise ValueError("empty prompt")
        if self.max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {self.max_new_tokens}"
            )

    @property
    def effective_prompt(self) -> np.ndarray:
        """Prompt plus already-generated tokens (the resume prefix)."""
        if not self.tokens:
            return self.prompt
        return np.concatenate(
            [self.prompt, np.asarray(self.tokens, np.int32)]
        )

    @property
    def remaining(self) -> int:
        return self.max_new_tokens - len(self.tokens)

    @property
    def done(self) -> bool:
        return len(self.tokens) >= self.max_new_tokens

    def output(self) -> np.ndarray:
        """prompt + generated tokens (the ``generate`` output layout)."""
        return np.concatenate(
            [self.prompt, np.asarray(self.tokens, np.int32)]
        )

    def ttft_s(self) -> Optional[float]:
        if self.submitted_s is None or self.first_token_s is None:
            return None
        return self.first_token_s - self.submitted_s

    def tpot_s(self) -> Optional[float]:
        """Mean per-output-token latency after the first token, or None
        when undefined (unfinished, or a single-token generation — a
        0.0 here would drag the fleet TPOT percentiles toward zero)."""
        if self.first_token_s is None or self.finished_s is None:
            return None
        n = len(self.tokens)
        if n <= 1:
            return None
        return (self.finished_s - self.first_token_s) / (n - 1)


class ShapeBucketer:
    """Prompt lengths -> the fixed bucket set the programs compile for."""

    def __init__(self, buckets: Sequence[int]):
        cleaned = sorted(set(int(b) for b in buckets))
        if not cleaned or cleaned[0] < 1:
            raise ValueError(f"invalid bucket set {list(buckets)!r}")
        self.buckets: Tuple[int, ...] = tuple(cleaned)

    @property
    def max_bucket(self) -> int:
        return self.buckets[-1]

    def bucket_for(self, length: int) -> int:
        """Smallest bucket >= length (the pad target for a prompt)."""
        for b in self.buckets:
            if length <= b:
                return b
        raise ValueError(
            f"prompt length {length} exceeds the largest bucket "
            f"{self.buckets[-1]}; add a bucket or truncate"
        )

    def pad_batch(
        self, prompts: Sequence[np.ndarray], bucket: int, rows: int,
        pad_id: int = 0,
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Right-pad ``prompts`` to [rows, bucket] + true-length vector.

        Rows beyond ``len(prompts)`` are all-pad dummies (the admission
        batch itself is a fixed shape, so a half-full admission wave
        reuses the compiled prefill program).  Dummy lengths read 1 so a
        gather at ``length - 1`` stays in range.
        """
        ids = np.full((rows, bucket), pad_id, np.int32)
        lengths = np.ones((rows,), np.int32)
        for i, p in enumerate(prompts):
            p = np.asarray(p, np.int32).reshape(-1)
            if p.size > bucket:
                raise ValueError(
                    f"prompt of length {p.size} does not fit bucket "
                    f"{bucket}"
                )
            ids[i, : p.size] = p
            lengths[i] = p.size
        return ids, lengths


class AdmissionQueue:
    """FIFO queue with same-bucket packing for prefill waves.

    ``max_queue`` bounds the depth: a full queue REJECTS new submissions
    with :class:`QueueFullError` instead of growing without bound (an
    unbounded admission queue under a traffic spike is an OOM with extra
    steps).  The bound applies to NEW admissions only — re-queues that
    preserve an already-admitted request's token stream (preemption,
    reconfiguration, fleet migration) pass ``force=True`` and always
    land, because dropping one of those silently loses committed tokens.
    """

    def __init__(self, bucketer: ShapeBucketer, prefill_batch: int = 1,
                 max_queue: Optional[int] = None):
        if prefill_batch < 1:
            raise ValueError(
                f"prefill_batch must be >= 1, got {prefill_batch}"
            )
        if max_queue is not None and max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        self.bucketer = bucketer
        self.prefill_batch = int(prefill_batch)
        self.max_queue = None if max_queue is None else int(max_queue)
        self._queue: List[Request] = []

    def __len__(self) -> int:
        return len(self._queue)

    @property
    def depth(self) -> int:
        return len(self._queue)

    def submit(self, request: Request, force: bool = False,
               require_bucket: bool = True) -> None:
        # bucket validation FIRST (its ValueError is the older contract
        # and callers match on it), capacity second, state mutation last
        # — a rejected request keeps its pre-submit state.
        # ``require_bucket=False`` is the paged engine's swap re-queue:
        # a swapped request resumes from host page copies with NO
        # prefill, so it needs no bucket — exactly how swap serves
        # resume prefixes that have outgrown every bucket.
        bucket = (
            self.bucketer.bucket_for(int(request.effective_prompt.size))
            if require_bucket else None
        )
        if (not force and self.max_queue is not None
                and len(self._queue) >= self.max_queue):
            raise QueueFullError(
                f"admission queue full ({len(self._queue)}/"
                f"{self.max_queue}); request {request.request_id} "
                f"rejected", queue_depth=len(self._queue),
            )
        if request.submitted_s is None:
            request.submitted_s = time.perf_counter()
        request.status = QUEUED
        request.bucket = bucket
        self._queue.append(request)

    def remove(self, request: Request) -> None:
        """Remove a specific queued request (the paged engine's wave
        selection dequeues its own members — tail buckets are computed
        against the live prefix cache, not the submit-time prompt).
        Identity-based: ``Request`` is a dataclass over numpy arrays,
        so ``==`` would compare prompt contents elementwise."""
        for i, r in enumerate(self._queue):
            if r is request:
                del self._queue[i]
                return
        raise ValueError(
            f"request {request.request_id} is not queued"
        )

    def shed_oldest(self) -> Optional[Request]:
        """Remove and return the oldest SHEDDABLE queued request (the
        shed policy's victim: under overload the head of the queue has
        waited longest and is the most likely to have already blown its
        deadline), or None when nothing can be shed.  A request with
        committed tokens (a preempted/migrated resume, force-queued) is
        never a victim — shedding it would lose its generated stream,
        the exact outcome ``force`` exists to prevent — and neither is
        a preempted/migrated request still waiting for its first token
        (``preemptions > 0``): its admission promise was already made
        once and must not be revoked to seat a newcomer.  The caller
        owns marking the victim ``REJECTED`` and counting the shed."""
        for i, r in enumerate(self._queue):
            if not r.tokens and r.preemptions == 0:
                return self._queue.pop(i)
        return None

    @property
    def requests(self) -> Tuple[Request, ...]:
        """Queued requests, FIFO order (read-only view)."""
        return tuple(self._queue)

    def drain(self) -> List[Request]:
        """Remove and return every queued request, FIFO order.

        Used by ``ServingEngine.reconfigure``: an operating-point change
        rebuilds the queue around a new bucketer, so the old queue's
        contents re-submit (re-bucket) into the new one.
        """
        drained, self._queue = self._queue, []
        return drained

    def next_wave(self, free_slots: int) -> Optional[List[Request]]:
        """Dequeue the next same-bucket prefill wave, or None.

        The queue head fixes the bucket (FIFO — no starvation); later
        same-bucket requests pack into the wave up to
        ``min(prefill_batch, free_slots)``.
        """
        if not self._queue or free_slots < 1:
            return None
        head_bucket = self._queue[0].bucket
        cap = min(self.prefill_batch, free_slots)
        wave: List[Request] = []
        rest: List[Request] = []
        for r in self._queue:
            if len(wave) < cap and r.bucket == head_bucket:
                wave.append(r)
            else:
                rest.append(r)
        self._queue = rest
        return wave


__all__ = [
    "AdmissionQueue",
    "FAILED",
    "FINISHED",
    "QUEUED",
    "QueueFullError",
    "REJECTED",
    "RUNNING",
    "Request",
    "ShapeBucketer",
]
