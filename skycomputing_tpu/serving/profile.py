"""Decode-step cost/memory profiling for serving-balanced allocation.

A partition balanced on TRAINING costs is wrong for serving: training
cost is full-sequence forward+backward (matmul-dominated, so FFN units
outweigh attention units), while a decode step is one token against a
``max_len``-deep KV cache (the attention units' cache read/attend work
grows with ``max_len`` while the FFN units shrink to ``Lq=1`` matmuls).
The memory picture flips too — activations vanish, but every attention
layer pins a preallocated ``[slots, max_len, heads, head_dim]`` (k, v)
slab pair for the life of the engine.

:class:`DecodeModelBenchmarker` speaks the exact ``ModelBenchmarker``
interface (``benchmark() -> (per-layer costs, per-layer mem_MB)``), so
``Allocator.serving_allocate`` drops it into the same contiguous
min-max solver (``optimal_allocate`` / ``skytpu_solve_classes``) that
balances training partitions — the solver is profile-agnostic; only
the profile changes.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import jax
import numpy as np

from ..builder import build_layer
from ..dynamics.benchmarker import BaseBenchmarker, _layer_key
from ..dynamics.estimator import Estimator
from .kv_cache import kv_mb_per_layer, kv_spec_from_config


class DecodeModelBenchmarker(BaseBenchmarker):
    """Per-layer DECODE-step cost + serving memory over a model config.

    ``cost[i]`` is the XLA-reported FLOPs of one decode iteration of
    layer ``i`` at the engine's operating point (``slots`` concurrent
    sequences, ``max_len``-deep caches) — everything the engine runs
    per token, via ``Estimator.benchmark_decode_step``.  ``mem[i]`` is
    the reference accounting formula for the decode activations/params
    PLUS the layer's preallocated KV-slab MB
    (:func:`~.kv_cache.kv_mb_per_layer` — the same formula the
    pre-flight verifier charges, so "the allocator accepted it" and
    "the verifier accepted it" can never disagree on slab size).

    Fully static (``eval_shape`` + cost analysis — no params, no FLOPs
    executed) and deduped by (layer-config, input-signature) like the
    training profiler, so deep stacks profile each distinct unit once.
    """

    def __init__(
        self,
        model_config: List[Dict],
        *,
        slots: int,
        max_len: int,
        param_scale: int = 2,
        attn_layer_type: str = "GptBlock_Attn",
        num_pages: Optional[int] = None,
        page_size: Optional[int] = None,
        kv_dtype: Optional[str] = None,
    ):
        if slots < 1 or max_len < 1:
            raise ValueError(
                f"need positive slots/max_len, got {slots}/{max_len}"
            )
        if (num_pages is None) != (page_size is None):
            raise ValueError(
                "pass num_pages AND page_size together (the paged "
                "operating point) or neither (slot layout)"
            )
        if num_pages is not None and (num_pages < 1 or page_size < 1):
            raise ValueError(
                f"need positive num_pages/page_size, got "
                f"{num_pages}/{page_size}"
            )
        if kv_dtype is not None and num_pages is None:
            raise ValueError(
                "kv_dtype is a paged-pool policy; pass num_pages/"
                "page_size with it"
            )
        self._model_config = model_config
        # paged engines: `slots` is the decode-row count
        # (max_concurrency) and `max_len` the per-request virtual span
        # (max_pages_per_request x page_size) — together they fix the
        # decode-step compute exactly like the slot layout's operating
        # point does; only the MEMORY charge changes, to the page pool
        self._slots = int(slots)
        self._max_len = int(max_len)
        self._num_pages = None if num_pages is None else int(num_pages)
        self._page_size = None if page_size is None else int(page_size)
        self._kv_dtype = None if kv_dtype is None else str(kv_dtype)
        self._param_scale = int(param_scale)
        self._attn_layer_type = attn_layer_type
        self._result: Optional[Tuple[List[float], List[float]]] = None

    @property
    def model_config(self) -> List[Dict]:
        return self._model_config

    @property
    def operating_point(self) -> Dict[str, int]:
        """The (slots, max_len) — plus (num_pages, page_size) under the
        paged layout — the profile was taken at, stamped into bench
        provenance so a partition is never reused at a different
        serving configuration without re-solving."""
        point = dict(slots=self._slots, max_len=self._max_len)
        if self._num_pages is not None:
            point.update(num_pages=self._num_pages,
                         page_size=self._page_size)
            if self._kv_dtype is not None:
                point.update(kv_dtype=self._kv_dtype)
        return point

    def benchmark(self) -> Tuple[List[float], List[float]]:
        if self._result is not None:
            return self._result
        self._result = self._benchmark()
        return self._result

    def _benchmark(self) -> Tuple[List[float], List[float]]:
        S = self._slots
        if self._num_pages is not None:
            # the paged pool's footprint replaces the slot slabs (the
            # same formula plan_check charges, so allocator and
            # verifier can never disagree on pool size); compute cost
            # below still profiles at (rows, virtual span)
            from .kv_cache import paged_kv_mb_per_layer

            kv_mb = paged_kv_mb_per_layer(
                self._model_config, self._num_pages, self._page_size,
                attn_layer_type=self._attn_layer_type,
                kv_dtype=self._kv_dtype,
            )
        else:
            kv_mb = kv_mb_per_layer(
                self._model_config, S, self._max_len,
                attn_layer_type=self._attn_layer_type,
            )
        index = jax.ShapeDtypeStruct((S,), np.int32)
        # the decode wavefront: token ids enter the first layer, hidden
        # state threads through the rest — exactly the engine's tick
        avals: Tuple = (jax.ShapeDtypeStruct((S, 1), np.int32),)
        cost_list: List[float] = []
        mem_list: List[float] = []
        cache: Dict[str, Tuple] = {}
        for i, layer_cfg in enumerate(self._model_config):
            key = _layer_key(layer_cfg, avals)
            if key in cache:
                out_aval, flops, mem = cache[key]
            else:
                cfg = dict(layer_cfg)
                layer_type = cfg.pop("layer_type")
                module = build_layer(layer_type, **cfg)
                cache_avals = None
                if layer_type == self._attn_layer_type:
                    spec = kv_spec_from_config(
                        layer_cfg.get("config", {}), self._max_len
                    )
                    shape = spec.slab_shape(S)
                    dtype = jax.numpy.dtype(spec.dtype)
                    cache_avals = (
                        jax.ShapeDtypeStruct(shape, dtype),
                        jax.ShapeDtypeStruct(shape, dtype),
                    )
                out_aval, flops, mem = Estimator.benchmark_decode_step(
                    module, avals, cache_avals=cache_avals, index=index,
                    param_scale=self._param_scale,
                )
                cache[key] = (out_aval, flops, mem)
            cost_list.append(flops)
            mem_list.append(mem + kv_mb[i])
            data_out = (
                out_aval[0] if isinstance(out_aval, tuple) else out_aval
            )
            avals = (
                jax.ShapeDtypeStruct(data_out.shape, data_out.dtype),
            )
        return cost_list, mem_list


__all__ = ["DecodeModelBenchmarker"]
