"""Slot-based KV-cache slabs: the single KV-cache implementation.

Every decoding path in the repo — ``models/gpt.py``'s
``CachedGptDecoder``/``generate_cached`` and the continuous-batching
``ServingEngine`` — stores attention keys/values in fixed-shape slabs
``[slots, max_len, heads, head_dim]`` updated in place and reads them
through the helpers here.  One implementation means one set of
invariants:

- **fixed shapes**: slabs are preallocated once; a request joining or
  leaving the batch never changes a compiled program's signature (the
  SKY002 recompile discipline applied to serving);
- **in-place, donation-friendly updates**: :func:`update_kv_cache` is a
  ``dynamic_update_slice`` (scalar index) or a vmapped per-row one
  (per-slot index vector), so a caller that donates the slab argument
  and rebinds to the output lets XLA reuse the buffer instead of
  copying ``slots x max_len`` every token;
- **masked staleness**: positions at or beyond a row's current index
  hold stale garbage by design; :func:`decode_visibility` masks them
  out of attention, so a freed slot can be handed to a new request
  without any zeroing pass.

The pool (:class:`SlotKVCachePool`) adds the host-side free-slot
allocator per pipeline stage: slots are tickets, requests borrow one
for their lifetime, and exhaustion is a queueing condition for the
admission layer — never an error.

No model imports here: ``models/gpt.py`` depends on this module (its
``decode`` methods call the update/visibility helpers), not the other
way around.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp


# --------------------------------------------------------------------------
# cache math (used inside jitted layer code)
# --------------------------------------------------------------------------


def update_kv_cache(k_cache, v_cache, k_new, v_new, index):
    """Write ``k_new``/``v_new`` into the caches at per-row positions.

    ``k_cache``/``v_cache``: [B, max_len, heads, head_dim] slabs;
    ``k_new``/``v_new``: [B, Lq, heads, head_dim]; ``index``: either a
    scalar (all rows write at the same offset — the single-request
    decode path) or a [B] vector (each row writes at its own offset —
    the continuous-batching path, where every slot sits at a different
    sequence position).  Returns the updated ``(k_cache, v_cache)``.
    Out-of-range indices clamp (``dynamic_update_slice`` semantics), so
    an inactive slot carried through a full-slab decode step can never
    write outside its own row.
    """
    k_new = k_new.astype(k_cache.dtype)
    v_new = v_new.astype(v_cache.dtype)
    if jnp.ndim(index) == 0:
        k_cache = jax.lax.dynamic_update_slice(
            k_cache, k_new, (0, index, 0, 0)
        )
        v_cache = jax.lax.dynamic_update_slice(
            v_cache, v_new, (0, index, 0, 0)
        )
        return k_cache, v_cache

    def row(cache, new, i):
        return jax.lax.dynamic_update_slice(cache, new, (i, 0, 0))

    k_cache = jax.vmap(row)(k_cache, k_new, index)
    v_cache = jax.vmap(row)(v_cache, v_new, index)
    return k_cache, v_cache


def decode_visibility(index, query_len: int, max_len: int):
    """Causal visibility mask for incremental decode: [B|1, Lq, max_len].

    Query position ``q`` of row ``b`` sits at absolute position
    ``index[b] + q`` and may attend to cache positions ``<=`` it.
    Stale garbage beyond a row's current length is strictly in the
    future, so this one mask both enforces causality and hides freed
    slots' leftovers.  ``index`` scalar -> leading axis 1 (broadcasts
    over the batch); ``index`` [B] -> per-row masks.
    """
    q_pos = jnp.reshape(index, (-1, 1)) + jnp.arange(
        query_len, dtype=jnp.int32
    )
    k_pos = jnp.arange(max_len, dtype=jnp.int32)
    return k_pos[None, None, :] <= q_pos[:, :, None]


def decode_positions(index, query_len: int):
    """Absolute positions [B|1, Lq] of the query tokens (for wpe)."""
    return jnp.reshape(index, (-1, 1)) + jnp.arange(
        query_len, dtype=jnp.int32
    )


# --------------------------------------------------------------------------
# paged cache math (used inside jitted layer code; host bookkeeping —
# the allocator, refcounts, radix prefix index — lives in serving/paging.py)
# --------------------------------------------------------------------------


class QuantizedPages(NamedTuple):
    """An int8 page slab with its per-page-per-head dequant scales.

    ``values``: [num_pages, page_size, heads, head_dim] int8;
    ``scale``: [num_pages, heads] float32 — the parallel *scale slab*.
    One symmetric amax scale covers a (page, head) tile: dequantized
    value = ``values * scale``.  A NamedTuple so it rides jit/pytree
    plumbing (donation, device_put, scatter/gather helpers) exactly
    like a plain slab array; every paged-math entry point here
    dispatches on this type, so ``kv_dtype="int8"`` changes no caller
    signatures.
    """

    values: jax.Array
    scale: jax.Array


def quantize_pages(values, scale_hint=None):
    """Symmetric per-page-per-head int8 quantization of a page-shaped
    fp array [..., page_size, heads, head_dim] -> (int8, scale[...,
    heads]).  ``scale_hint`` (same shape as the returned scale) floors
    the scale: pages re-quantized on append keep a monotone scale so
    already-stored tokens never lose range."""
    amax = jnp.max(jnp.abs(values.astype(jnp.float32)), axis=(-3, -1))
    scale = amax / 127.0
    if scale_hint is not None:
        scale = jnp.maximum(scale, scale_hint)
    safe = jnp.maximum(scale, 1e-12)
    q = jnp.clip(
        jnp.round(values.astype(jnp.float32)
                  / safe[..., None, :, None]),
        -127, 127,
    ).astype(jnp.int8)
    return q, jnp.where(scale > 0, scale, 1.0).astype(jnp.float32)


def _paged_update_kv_int8(
    k_slab: QuantizedPages, v_slab: QuantizedPages,
    k_new, v_new, page_table, index, valid_len,
):
    """int8 twin of the fp scatter: quantize AT WRITE TIME.

    Writes land page-at-a-time: for each page a row's new tokens touch,
    the old page is gathered, dequantized, merged with the new
    positions, garbage (``>= valid_len``) zeroed, and re-quantized with
    a per-page-per-head amax scale FLOORED at the page's previous scale
    (``quantize_pages`` hint) — so a page's scale is monotone over its
    tenancy and an append can only widen, never clip, what earlier
    tokens stored.  A page whose first live position is this write
    (``page_start >= index``) takes a fresh scale: whatever the
    previous tenant left in the scale slab is garbage, exactly like the
    value slab's no-zeroing story.

    Shared pages are never written (the pool's COW contract), so the
    per-row page updates are disjoint and scatter order cannot matter —
    the same argument as the fp path, at page granularity.
    """
    num_pages, page_size = k_slab.values.shape[0], k_slab.values.shape[1]
    R, Lq = k_new.shape[0], k_new.shape[1]
    max_pages = page_table.shape[1]
    index = jnp.reshape(index, (-1,))
    valid = jnp.reshape(valid_len, (-1,))
    # pages a row's span [index, index+Lq) can straddle (static bound)
    n_touch = (Lq - 1) // page_size + 2

    def update_one(slab: QuantizedPages, new) -> QuantizedPages:
        vals, scales = slab.values, slab.scale
        new = new.astype(jnp.float32)
        for j in range(n_touch):
            lp = index // page_size + j  # [R] logical page
            in_span = (lp <= (index + Lq - 1) // page_size) & (
                lp * page_size < valid
            ) & (lp < max_pages)
            phys = jnp.take_along_axis(
                page_table, jnp.clip(lp, 0, max_pages - 1)[:, None],
                axis=1,
            )[:, 0]
            real = in_span & (phys >= 0) & (phys < num_pages)
            src = jnp.clip(phys, 0, num_pages - 1)
            old_q = vals[src]                 # [R, ps, H, D]
            old_s = scales[src]               # [R, H]
            old_f = old_q.astype(jnp.float32) * old_s[:, None, :, None]
            gpos = lp[:, None] * page_size + jnp.arange(
                page_size, dtype=jnp.int32
            )  # [R, ps] global positions of this page
            offset = gpos - index[:, None]
            write_here = (
                (offset >= 0) & (offset < Lq)
                & (gpos < valid[:, None])
            )
            picked = jnp.take_along_axis(
                new,
                jnp.broadcast_to(
                    jnp.clip(offset, 0, Lq - 1)[:, :, None, None],
                    (R, page_size) + new.shape[2:],
                ),
                axis=1,
            )
            merged = jnp.where(write_here[..., None, None], picked,
                               old_f)
            live = gpos < valid[:, None]
            merged = jnp.where(live[..., None, None], merged, 0.0)
            # a page whose live data starts at this write takes a fresh
            # scale (the previous tenant's slab entry is stale garbage)
            has_old = (lp * page_size < index)[:, None]
            hint = jnp.where(has_old, old_s, 0.0)
            q, s = quantize_pages(merged, scale_hint=hint)
            dest = jnp.where(real, phys, num_pages)
            vals = vals.at[dest].set(q, mode="drop")
            scales = scales.at[dest].set(s, mode="drop")
        return QuantizedPages(vals, scales)

    return update_one(k_slab, k_new), update_one(v_slab, v_new)


def paged_update_kv(
    k_slab, v_slab, k_new, v_new, page_table, index, valid_len
):
    """Scatter ``k_new``/``v_new`` into paged slabs through page tables.

    ``k_slab``/``v_slab``: [num_pages, page_size, heads, head_dim]
    physical page pools; ``k_new``/``v_new``: [R, Lq, heads, head_dim];
    ``page_table``: [R, max_pages] int32, logical page -> physical page,
    padded with an out-of-range sentinel (>= num_pages);
    ``index``: [R] start position of each row's new tokens;
    ``valid_len``: [R] true end position — writes at or beyond it (the
    pad tail of a bucketed prefill) are DROPPED, so pad positions never
    touch a page and a row never writes outside the pages it holds.
    Returns the updated ``(k_slab, v_slab)``.

    Rows never write a page mapped by another holder: the pool's grant
    contract (serving/paging.py) keeps shared pages read-only — a
    partial shared page is copied-on-write into a private page before
    the owner's first append — so scatter destinations are disjoint
    across rows by construction and scatter order cannot matter.

    ``k_slab``/``v_slab`` may be :class:`QuantizedPages` (the
    ``kv_dtype="int8"`` pool): writes then quantize at write time with
    per-page-per-head scales kept in the parallel scale slab — see
    :func:`_paged_update_kv_int8`.
    """
    if isinstance(k_slab, QuantizedPages):
        return _paged_update_kv_int8(
            k_slab, v_slab, k_new, v_new, page_table, index, valid_len
        )
    num_pages, page_size = k_slab.shape[0], k_slab.shape[1]
    R, Lq = k_new.shape[0], k_new.shape[1]
    max_pages = page_table.shape[1]
    pos = jnp.reshape(index, (-1, 1)) + jnp.arange(Lq, dtype=jnp.int32)
    logical = pos // page_size
    phys = jnp.take_along_axis(
        page_table, jnp.clip(logical, 0, max_pages - 1), axis=1
    )
    flat = phys * page_size + pos % page_size
    oob = num_pages * page_size  # 'drop' sentinel destination
    keep = (
        (pos < jnp.reshape(valid_len, (-1, 1)))
        & (logical < max_pages)
        & (phys >= 0) & (phys < num_pages)
    )
    flat = jnp.where(keep, flat, oob).reshape(-1)

    def scatter(slab, new):
        flat_slab = slab.reshape((num_pages * page_size,) + slab.shape[2:])
        flat_slab = flat_slab.at[flat].set(
            new.astype(slab.dtype).reshape((R * Lq,) + new.shape[2:]),
            mode="drop",
        )
        return flat_slab.reshape(slab.shape)

    return scatter(k_slab, k_new), scatter(v_slab, v_new)


def gather_kv_pages(k_slab, v_slab, page_table):
    """Per-row virtual cache views through page tables.

    Returns ``(k, v)`` of shape [R, max_pages * page_size, heads,
    head_dim]: row r's logically-contiguous sequence, assembled by
    gathering its pages.  Sentinel table entries clamp into the slab and
    read garbage — those virtual positions are at or beyond the row's
    current length by the pool's covering invariant, so
    :func:`decode_visibility` masks them exactly like the slot layout
    masks a freed row's stale tail.

    :class:`QuantizedPages` slabs dequantize during the gather (int8
    value x its page's per-head scale), returning float32 views — the
    XLA reference path's dequant site; the fused kernel
    (``ops/paged_attention.py``) dequantizes per block in VMEM instead
    and never materializes these views at all.
    """
    quantized = isinstance(k_slab, QuantizedPages)
    vals = k_slab.values if quantized else k_slab
    num_pages, page_size = vals.shape[0], vals.shape[1]
    R = page_table.shape[0]
    pos = (
        page_table[:, :, None] * page_size
        + jnp.arange(page_size, dtype=jnp.int32)[None, None, :]
    )
    pos = jnp.clip(pos.reshape(R, -1), 0, num_pages * page_size - 1)

    def gather(slab):
        if isinstance(slab, QuantizedPages):
            flat = slab.values.reshape(
                (num_pages * page_size,) + slab.values.shape[2:]
            )
            page_of = pos // page_size
            return (
                flat[pos].astype(jnp.float32)
                * slab.scale[page_of][:, :, :, None]
            )
        flat = slab.reshape((num_pages * page_size,) + slab.shape[2:])
        return flat[pos]

    return gather(k_slab), gather(v_slab)


# --------------------------------------------------------------------------
# slab specification + allocation
# --------------------------------------------------------------------------


@dataclass(frozen=True)
class KVCacheSpec:
    """Shape/dtype of one attention layer's slab (minus the slot axis)."""

    max_len: int
    num_heads: int
    head_dim: int
    dtype: str = "float32"

    def slab_shape(self, slots: int) -> Tuple[int, int, int, int]:
        return (slots, self.max_len, self.num_heads, self.head_dim)

    def slab_mb(self, slots: int) -> float:
        """Size of the (k, v) slab PAIR in MB."""
        n = float(slots * self.max_len * self.num_heads * self.head_dim)
        return 2.0 * n * jnp.dtype(self.dtype).itemsize / 1024.0**2


def kv_spec_from_config(config, max_len: int) -> KVCacheSpec:
    """Spec from a GPT-style config (dict or object with the fields)."""
    get = (
        config.get if isinstance(config, dict)
        else lambda k, d=None: getattr(config, k, d)
    )
    heads = int(get("num_attention_heads"))
    hidden = int(get("hidden_size"))
    return KVCacheSpec(
        max_len=int(max_len),
        num_heads=heads,
        head_dim=hidden // heads,
        dtype=str(get("dtype", "float32")),
    )


def init_layer_caches(
    specs: Sequence[KVCacheSpec], slots: int, device=None
) -> List[Tuple[jax.Array, jax.Array]]:
    """Zeroed (k, v) slab pairs, one per attention layer, optionally
    committed to ``device``.  This is the one allocation site both the
    single-request decoder and the serving pool build on."""
    caches = []
    for spec in specs:
        shape = spec.slab_shape(slots)
        dtype = jnp.dtype(spec.dtype)
        pair = (jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))
        if device is not None:
            pair = jax.device_put(pair, device)
        caches.append(pair)
    return caches


class SlotKVCachePool:
    """Preallocated per-stage slabs + a host-side free-slot allocator.

    One pool per pipeline stage: the slabs live on the stage's device
    (allocated once, updated in place), while slot bookkeeping is pure
    host state.  A slot id is valid across every layer of the stage —
    request r owns row ``slot`` of all ``len(specs)`` slab pairs.

    Exhaustion contract: :meth:`allocate` returns ``None`` when no slot
    is free — the admission layer queues the request; nothing raises.
    """

    def __init__(
        self, specs: Sequence[KVCacheSpec], slots: int, device=None
    ):
        if slots < 1:
            raise ValueError(f"need at least 1 slot, got {slots}")
        self.specs = list(specs)
        self.num_slots = int(slots)
        self.device = device
        self.slabs = init_layer_caches(self.specs, self.num_slots, device)
        # LIFO free list: reusing the hottest row keeps its pages warm
        self._free: List[int] = list(range(self.num_slots))[::-1]

    @property
    def free_slots(self) -> int:
        return len(self._free)

    @property
    def used_slots(self) -> int:
        return self.num_slots - len(self._free)

    @property
    def occupancy(self) -> float:
        return self.used_slots / self.num_slots

    def allocate(self) -> Optional[int]:
        """One free slot id, or None when the pool is exhausted."""
        if not self._free:
            return None
        return self._free.pop()

    def acquire(self, slot: int) -> None:
        """Claim a SPECIFIC free slot — the multi-stage engine allocates
        a slot id once and acquires the same row in every other stage's
        pool, so one id addresses a request's cache across the whole
        pipeline."""
        if slot not in self._free:
            raise ValueError(f"slot {slot} is not free")
        self._free.remove(slot)

    def release(self, slot: int) -> None:
        if not 0 <= slot < self.num_slots:
            raise ValueError(
                f"slot {slot} out of range [0, {self.num_slots})"
            )
        if slot in self._free:
            raise ValueError(f"slot {slot} double-released")
        # no zeroing: stale rows are masked by decode_visibility and
        # fully overwritten (prefix [:bucket]) on the next prefill
        self._free.append(slot)

    def total_mb(self) -> float:
        """Preallocated slab memory of this pool in MB (all layers)."""
        return float(
            sum(spec.slab_mb(self.num_slots) for spec in self.specs)
        )


def init_paged_caches(
    specs: Sequence[KVCacheSpec],
    num_pages: int,
    page_size: int,
    device=None,
    kv_dtype: Optional[str] = None,
) -> List[Tuple[jax.Array, jax.Array]]:
    """Zeroed paged (k, v) slab pairs ``[num_pages, page_size, heads,
    head_dim]``, one per attention layer.  Same total bytes as a slot
    slab whenever ``num_pages * page_size == slots * max_len`` — the
    equal-memory pivot the paged-vs-slot bench holds fixed.

    ``kv_dtype="int8"`` allocates :class:`QuantizedPages` pairs instead:
    int8 value slabs plus float32 ``[num_pages, heads]`` scale slabs
    (zero scale dequantizes to zero, so no zeroing pass is ever owed) —
    ~4x the pages per MB of a float32 pool, ~2x a bf16 one.
    """
    caches = []
    for spec in specs:
        shape = (num_pages, page_size, spec.num_heads, spec.head_dim)
        if kv_dtype == "int8":
            def one():
                return QuantizedPages(
                    jnp.zeros(shape, jnp.int8),
                    jnp.zeros((num_pages, spec.num_heads),
                              jnp.float32),
                )

            pair = (one(), one())
        elif kv_dtype is not None:
            raise ValueError(
                f"kv_dtype must be 'int8' or None (the model dtype), "
                f"got {kv_dtype!r}"
            )
        else:
            dtype = jnp.dtype(spec.dtype)
            pair = (jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))
        if device is not None:
            pair = jax.device_put(pair, device)
        caches.append(pair)
    return caches


def paged_kv_mb_per_layer(
    model_cfg: Sequence[dict],
    num_pages: int,
    page_size: int,
    attn_layer_type: str = "GptBlock_Attn",
    kv_dtype: Optional[str] = None,
) -> List[float]:
    """Per-layer paged-pool MB for a layer-config list — the paged twin
    of :func:`kv_mb_per_layer`.  ``kv_dtype=None`` keeps the model
    dtype through the permissive ``jnp.dtype`` itemsize (byte-identical
    to the slot formula at equal positions — any jnp-valid model dtype
    stays accountable, exactly as before quantization existed); an
    EXPLICIT ``kv_dtype`` charges through
    ``serving/paging.paged_pool_mb`` — the ONE quantized-width formula
    the allocator, the profiler, and the pre-flight verifier all share
    (so they can never disagree on pool size), strict about its dtype
    table because a silently mis-sized quantized pool is the drift the
    sharing exists to prevent."""
    from .paging import paged_pool_mb

    out: List[float] = []
    for cfg in model_cfg:
        if cfg.get("layer_type") == attn_layer_type:
            spec = kv_spec_from_config(cfg.get("config", {}), page_size)
            if kv_dtype is None:
                out.append(spec.slab_mb(num_pages))
            else:
                out.append(paged_pool_mb(
                    num_pages, page_size, spec.num_heads,
                    spec.head_dim, kv_dtype=kv_dtype,
                ))
        else:
            out.append(0.0)
    return out


def kv_mb_per_layer(
    model_cfg: Sequence[dict],
    slots: int,
    max_len: int,
    attn_layer_type: str = "GptBlock_Attn",
) -> List[float]:
    """Per-layer preallocated KV-slab MB for a layer-config list.

    Non-attention layers contribute 0.0; attention layers contribute
    their (k, v) slab pair at ``slots`` x ``max_len``.  This is the
    memory profile the serving-balanced allocator and the pre-flight
    plan verifier add on top of the parameter/activation formula.
    """
    out: List[float] = []
    for cfg in model_cfg:
        if cfg.get("layer_type") == attn_layer_type:
            spec = kv_spec_from_config(cfg.get("config", {}), max_len)
            out.append(spec.slab_mb(slots))
        else:
            out.append(0.0)
    return out


__all__ = [
    "KVCacheSpec",
    "QuantizedPages",
    "SlotKVCachePool",
    "decode_positions",
    "decode_visibility",
    "gather_kv_pages",
    "init_layer_caches",
    "init_paged_caches",
    "kv_mb_per_layer",
    "kv_spec_from_config",
    "paged_kv_mb_per_layer",
    "paged_update_kv",
    "quantize_pages",
    "update_kv_cache",
]
