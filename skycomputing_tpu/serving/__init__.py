"""Continuous-batching inference over the load-balanced MPMD pipeline.

The training side of this repo reproduces the paper's contribution —
profile-driven layer->device allocation for heterogeneous pipelines;
this package is the serving side the ROADMAP's north star demands:

- :mod:`.kv_cache` — the single slot-based KV-cache implementation
  (fixed ``[slots, max_len, heads, head_dim]`` slabs, free-slot
  allocator, donation-friendly in-place updates) that also backs
  ``models/gpt.py``'s single-request decoder;
- :mod:`.batcher` — shape-bucketing admission (prompt lengths padded to
  a small fixed bucket set so steady-state decode compiles once);
- :mod:`.engine` — :class:`ServingEngine`, iteration-level continuous
  batching (Orca-style: requests join/leave the running batch between
  decode steps) over pipeline stages placed by the allocator, with
  :class:`ServingStats` SLO metrics;
- :mod:`.profile` — :class:`DecodeModelBenchmarker`, the decode-step
  cost/memory profile that makes ``Allocator.serving_allocate`` produce
  serving-balanced partitions instead of reusing training costs.

(``models/gpt.py``'s decode paths import ``kv_cache`` function-locally,
so the models -> serving edge never executes at import time and the
package can import its submodules eagerly without a cycle.)
"""

from __future__ import annotations

from .batcher import (
    AdmissionQueue,
    QueueFullError,
    Request,
    ShapeBucketer,
)
from .engine import ServingEngine, ServingStats
from .kv_cache import (
    KVCacheSpec,
    SlotKVCachePool,
    init_layer_caches,
    kv_mb_per_layer,
    kv_spec_from_config,
    update_kv_cache,
)
from .profile import DecodeModelBenchmarker

__all__ = [
    "AdmissionQueue",
    "DecodeModelBenchmarker",
    "KVCacheSpec",
    "QueueFullError",
    "Request",
    "ServingEngine",
    "ServingStats",
    "ShapeBucketer",
    "SlotKVCachePool",
    "init_layer_caches",
    "kv_mb_per_layer",
    "kv_spec_from_config",
    "update_kv_cache",
]
