"""Continuous-batching inference over the load-balanced MPMD pipeline.

The training side of this repo reproduces the paper's contribution —
profile-driven layer->device allocation for heterogeneous pipelines;
this package is the serving side the ROADMAP's north star demands:

- :mod:`.kv_cache` — the KV-cache device math for both layouts: slot
  slabs (fixed ``[slots, max_len, heads, head_dim]``, also backing
  ``models/gpt.py``'s single-request decoder) and paged pools
  (``[num_pages, page_size, heads, head_dim]`` gather/scatter through
  page tables; :class:`QuantizedPages` stores them int8 with
  per-page-per-head scale slabs, quantized at write time), donation-
  friendly in-place updates throughout — the fused decode kernel that
  walks page tables in-kernel lives in ``ops/paged_attention.py`` and
  is engine-selected via ``attn_impl=``;
- :mod:`.paging` — the paged host bookkeeping (pure stdlib):
  free-list page allocator with refcounts and copy-on-write grants,
  radix prefix index for compute-once shared prompts, decode-row
  ledger, swap-vs-recompute preemption policy;
- :mod:`.batcher` — shape-bucketing admission (prompt lengths padded to
  a small fixed bucket set so steady-state decode compiles once);
- :mod:`.engine` — :class:`ServingEngine`, iteration-level continuous
  batching (Orca-style: requests join/leave the running batch between
  decode steps) over pipeline stages placed by the allocator, with
  :class:`ServingStats` SLO metrics; ``prefill_chunk=`` interleaves
  budgeted prefill chunks with decode ticks, ``spec_k=`` layers
  draft-model speculative decoding on the paged layout;
- :mod:`.speculative` — :class:`DraftModel`, the prefix-slice draft
  (shares the target's stage-0 params and page slabs) plus the greedy
  acceptance rule;
- :mod:`.profile` — :class:`DecodeModelBenchmarker`, the decode-step
  cost/memory profile that makes ``Allocator.serving_allocate`` produce
  serving-balanced partitions instead of reusing training costs.

(``models/gpt.py``'s decode paths import ``kv_cache`` function-locally,
so the models -> serving edge never executes at import time and the
package can import its submodules eagerly without a cycle.)
"""

from __future__ import annotations

from .batcher import (
    AdmissionQueue,
    QueueFullError,
    Request,
    ShapeBucketer,
)
from .engine import ServingEngine, ServingStats
from .kv_cache import (
    KVCacheSpec,
    QuantizedPages,
    SlotKVCachePool,
    gather_kv_pages,
    init_layer_caches,
    init_paged_caches,
    kv_mb_per_layer,
    kv_spec_from_config,
    paged_kv_mb_per_layer,
    paged_update_kv,
    quantize_pages,
    update_kv_cache,
)
from .paging import (
    ChunkBudgetPolicy,
    KV_DTYPE_ITEMSIZE,
    PagedKVCachePool,
    RadixPrefixIndex,
    RowAllocator,
    choose_preempt_mode,
    paged_pool_mb,
    pages_for,
    pages_per_mb,
)
from .profile import DecodeModelBenchmarker
from .speculative import DraftModel, greedy_accept_count

__all__ = [
    "AdmissionQueue",
    "ChunkBudgetPolicy",
    "DecodeModelBenchmarker",
    "DraftModel",
    "KVCacheSpec",
    "KV_DTYPE_ITEMSIZE",
    "PagedKVCachePool",
    "QuantizedPages",
    "QueueFullError",
    "RadixPrefixIndex",
    "Request",
    "RowAllocator",
    "ServingEngine",
    "ServingStats",
    "ShapeBucketer",
    "SlotKVCachePool",
    "choose_preempt_mode",
    "gather_kv_pages",
    "greedy_accept_count",
    "init_layer_caches",
    "init_paged_caches",
    "kv_mb_per_layer",
    "kv_spec_from_config",
    "paged_kv_mb_per_layer",
    "paged_pool_mb",
    "paged_update_kv",
    "pages_for",
    "pages_per_mb",
    "quantize_pages",
    "update_kv_cache",
]
