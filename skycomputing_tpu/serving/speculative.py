"""Draft-model speculative decoding for the paged serving engine.

Speculative decoding breaks the one-token-per-forward bound of
autoregressive decode: a cheap **draft** model proposes ``k`` tokens
autoregressively, then the **target** model verifies all ``k + 1``
positions in ONE batched forward (``models/gpt.apply_kv_paged`` at
``Lq = k + 1`` — the same program shape discipline as bucketed
prefill, so accept/reject is recompile-free).  On the greedy path the
committed stream is token-identical to non-speculative decoding *by
construction*: the target's own argmax at every position is what
commits; the draft only decides how many of those positions one tick
may confirm at once.

The draft here is a **prefix layer slice sharing the target's params**
(``models/gpt.draft_slice_indices``): embeddings + the first
``draft_blocks`` transformer blocks + the LM head.  Because the slice
is a prefix, the hidden states entering its layers are exactly the
target's, so the draft's KV cache for those layers IS the target's
stage-0 page slabs:

- **no draft prefill** — the target's prefill already wrote the pages
  the draft reads;
- **no extra KV memory** — the draft appends speculative KV into the
  same granted pages (within the request's reserved span, so the page
  allocator's worst-case charge already covers it: *grant-for-k* is
  free);
- **rollback is a watermark truncate** — a rejected draft token's KV
  sits beyond the request's committed ``index``, exactly like the pad
  tail of a bucketed prefill: masked by ``decode_visibility``,
  overwritten by the next committed write, refcounts untouched.  The
  verify forward itself rewrites the accepted positions' KV for the
  draft's layers (same params, same inputs), so draft-written state
  never outlives a tick.

The only resident cost is a copy of the LM-head (+ final LayerNorm)
params on the draft's device when the head lives on another stage —
``extra_param_mb`` reports it and the engine charges it in the
pre-flight (``analysis/plan_check`` ``serving.draft_mb``).
"""

from __future__ import annotations

import json
from typing import Any, Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..models.gpt import apply_kv_paged, attn_indices
from ..parallel.pipeline import _donation_enabled

# Process-level draft-program cache, the engine's _STAGE_PROGRAMS twin:
# jax's compile cache keys on function identity, so same-config drafts
# (fleet replica re-forms, test engines) must share one closure to
# restart at cache-hit speed.
_DRAFT_PROGRAMS: Dict[str, Any] = {}


def greedy_accept_count(
    draft_tokens: Sequence[int], target_tokens: Sequence[int]
) -> int:
    """Accepted draft prefix length under greedy verification: the
    longest prefix where the draft's proposal equals the target's own
    argmax at that position.  Pure host logic — the whole accept/
    commit/rollback decision, unit-testable without a model."""
    n = 0
    for d, t in zip(draft_tokens, target_tokens):
        if int(d) != int(t):
            break
        n += 1
    return n


def tree_param_mb(params) -> float:
    """Total MB of a param tree (the pre-flight charge for the draft's
    device-resident head copy)."""
    leaves = jax.tree_util.tree_leaves(params)
    return float(
        sum(np.prod(l.shape) * l.dtype.itemsize for l in leaves)
        / 1024.0 ** 2
    )


class DraftModel:
    """The drafting half of speculative decoding: a prefix slice of the
    target (embeddings + ``draft_blocks`` blocks + LM head) compiled as
    one ``Lq = 1`` paged decode program on the target's FIRST stage
    device, reading and writing the first ``draft_blocks`` pairs of
    that stage's page slabs.

    ``modules``/``params`` are the already-sliced lists (the engine
    slices the full stack with ``models/gpt.draft_slice_indices`` and
    device-puts the head's params); ``extra_param_mb`` is the resident
    memory this draft ADDS to the device (0 when the head already lives
    there — the single-stage engine).
    """

    def __init__(
        self,
        modules: Sequence[Any],
        params: Sequence[Any],
        device,
        *,
        extra_param_mb: float = 0.0,
        program_key: Optional[str] = None,
        attn_impl: str = "xla",
    ):
        self.modules = list(modules)
        self.params = list(params)
        self.device = device
        self.num_attn = len(attn_indices(self.modules))
        if self.num_attn < 1:
            raise ValueError(
                "draft slice carries no attention unit — nothing to "
                "draft with"
            )
        self.extra_param_mb = float(extra_param_mb)
        self.attn_impl = attn_impl
        cached = (
            _DRAFT_PROGRAMS.get(program_key)
            if program_key is not None else None
        )
        if cached is not None:
            self._step_donated, self._loop_donated = cached
            return
        mods = self.modules
        impl = attn_impl

        def step(params_list, tokens, slabs, tables, index, valid_len):
            # argmax FUSED into the program: drafting is greedy by
            # definition (only the target's verify logits ever commit
            # a token), so the draft never needs its logits on the
            # host — one jit call per draft step, token ids in, token
            # ids out, no per-step device->host sync
            out, new_slabs = apply_kv_paged(
                mods, params_list, tokens[:, None], slabs, tables,
                index, valid_len, attn_impl=impl,
            )
            nxt = jnp.argmax(out[:, 0], axis=-1).astype(jnp.int32)
            return nxt, new_slabs

        def loop(params_list, tokens, slabs, tables, index, reserve,
                 k):
            # the WHOLE k-step autoregressive draft as ONE compiled
            # program (k static, unrolled): per-step dispatch cost was
            # measured at ~half a full decode tick on the CPU fallback
            # — paying it k times per speculative tick ate most of the
            # speculation win.  One dispatch per tick drafts all k.
            cur = tokens
            proposals = []
            for j in range(k):
                idx = index + j
                valid = jnp.minimum(idx + 1, reserve)
                cur, slabs = step(
                    params_list, cur, slabs, tables, idx, valid
                )
                proposals.append(cur)
            return jnp.stack(proposals, axis=1), slabs

        if _donation_enabled():
            self._step_donated = jax.jit(step, donate_argnums=(2,))
            self._loop_donated = jax.jit(
                loop, static_argnums=(6,), donate_argnums=(2,)
            )
        else:
            self._step_donated = jax.jit(step)
            self._loop_donated = jax.jit(loop, static_argnums=(6,))
        if program_key is not None:
            _DRAFT_PROGRAMS[program_key] = (
                self._step_donated, self._loop_donated
            )

    @staticmethod
    def program_key(
        draft_cfgs: Sequence[Dict], max_len: int,
        attn_impl: str = "xla", kv_dtype=None,
    ) -> str:
        """Cache key: the sliced layer configs + cache depth + donation
        + the attention impl / KV storage dtype (both change traced
        code) — the engine's stage program-key recipe, draft flavored."""
        return json.dumps(
            ["draft", list(draft_cfgs), int(max_len),
             bool(_donation_enabled()), str(attn_impl), str(kv_dtype)],
            sort_keys=True, default=str,
        )

    def decode_step(self, tokens, slabs, tables, index, valid_len):
        """One draft step: ``tokens`` [rows] int32 in, next greedy
        ``tokens`` [rows] out (a DEVICE array — feed it straight back
        for the next step; the engine hosts it once after the loop).
        ``slabs`` must be exactly the first ``num_attn`` (k, v) pairs
        of the target's stage-0 slabs; the caller rebinds the stage's
        slab prefix to ``new_slabs`` (donation discipline, same as
        every stage program)."""
        if len(slabs) != self.num_attn:
            raise ValueError(
                f"draft needs {self.num_attn} slab pairs, got "
                f"{len(slabs)}"
            )
        # donation discipline: the donated handle is rebound by the
        # same statement that consumes it (the engine's slab idiom)
        nxt, slabs = self._step_donated(self.params, tokens, slabs,
                                        tables, index, valid_len)
        return nxt, slabs

    def draft_k(self, tokens, slabs, tables, index, reserve, k):
        """The whole ``k``-token autoregressive draft in ONE dispatch:
        ``tokens`` [rows] (each row's last committed token) in,
        proposals [rows, k] out, with per-step writes capped at
        ``reserve`` (the rows' page reservations).  ``k`` is a static
        shape argument — one compiled program per (rows, k), the same
        discipline as the verify forward's ``Lq = k + 1``."""
        if len(slabs) != self.num_attn:
            raise ValueError(
                f"draft needs {self.num_attn} slab pairs, got "
                f"{len(slabs)}"
            )
        proposals, slabs = self._loop_donated(
            self.params, tokens, slabs, tables, index, reserve, int(k)
        )
        return proposals, slabs


__all__ = ["DraftModel", "greedy_accept_count", "tree_param_mb"]
