"""Paged KV-cache bookkeeping: page allocator, radix prefix cache, swap.

PURE STDLIB BY CONTRACT (the skylint/router idiom): everything here is
host-side decision logic over ints and tuples — no jax, no numpy — so
``tools/paging_smoke.py`` can load this file by path on a bare CI
runner and exercise every allocator/refcount/radix decision without an
accelerator stack installed.  The device half (slab gather/scatter
math) lives in ``serving/kv_cache.py`` next to the slot-slab helpers.

Why pages.  The slot layout strands memory: one request = one fixed
``[max_len]`` cache row, so a 14-token prompt in a 192-position row
wastes ~93% of it and concurrency is hard-capped at the slot count.
PagedAttention (Kwon et al., SOSP '23) recovers that memory by slicing
the slab into fixed ``page_size``-position **pages** handed out from a
free list; a request holds ``ceil(len / page_size)`` pages instead of a
whole row, so concurrency floats with actual footprint at equal pool
MB.  SGLang-style **radix prefix caching** then makes shared prompt
prefixes compute-once: finished prompts stay indexed by token ids, a
new request that shares a prefix maps the matching pages (refcount
bump) and only prefills its tail.

The invariants, in one place:

- **refcounts own liveness**: a page is free iff its refcount is zero.
  Live request tables hold one ref per mapped page; the radix index
  holds one ref per page of every cached prefix.  Releasing a request
  can therefore leave its prompt pages alive (cache retention — the
  whole point), and evicting a cache entry can leave pages alive that
  a running request still maps.
- **only whole tokens are shared, only read-only pages are mapped**: a
  full page inside the shared prefix is mapped directly; the partial
  tail page of a prefix is **copied on write** (the engine performs the
  device copy the :class:`PageGrant` names) into a private page before
  the sharer appends — nobody ever writes a page another holder can
  read, so sharing is safe without any versioning.
- **admission charges pages**: :meth:`PagedKVCachePool.acquire`
  reserves the request's full worst-case footprint
  (``ceil((len + max_new) / page_size)`` minus the fully-shared pages)
  up front, evicting least-recently-used cache entries when the free
  list runs short.  A request that cannot be charged queues (``None``),
  never corrupts — the slot pool's exhaustion-is-queueing contract at
  page granularity, and full reservation means a running request can
  never die of page exhaustion mid-decode.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple


def pages_for(length: int, page_size: int) -> int:
    """Pages needed to hold ``length`` positions (ceil division)."""
    return -(-int(length) // int(page_size))


#: bytes per stored KV element, by pool dtype — pure stdlib on purpose
#: (no jnp.dtype here): this table is the ONE place the quantized byte
#: width is defined, shared by the allocator accounting below, the
#: engine's slab allocation (serving/kv_cache.py calls back into
#: :func:`paged_pool_mb`), and the pre-flight verifier
#: (analysis/plan_check.py) — so "the allocator accepted it" and "the
#: verifier accepted it" can never disagree on pool size.
KV_DTYPE_ITEMSIZE: Dict[str, int] = {
    "int8": 1,
    "float16": 2,
    "bfloat16": 2,
    "float32": 4,
    "float64": 8,
}

#: the scale slab's element width (float32 per (page, head) — one scale
#: per quantized tile, see serving/kv_cache.QuantizedPages)
KV_SCALE_ITEMSIZE = 4


def paged_pool_mb(
    num_pages: int,
    page_size: int,
    num_heads: int,
    head_dim: int,
    kv_dtype: str = "float32",
) -> float:
    """MB of one attention layer's paged (k, v) pool PAIR.

    ``kv_dtype="int8"`` charges 1-byte values plus the parallel
    per-page-per-head float32 scale slabs (k and v each carry one) —
    the scale overhead is ``4 / (page_size * head_dim)`` bytes per
    position per head, so int8 still lands ~4x the pages per MB of a
    float32 pool and ~2x a bf16 one (the ``pages_per_mb`` doubling the
    bench gates).  Unknown dtypes raise: silent fallback here would let
    the allocator and verifier drift apart.
    """
    try:
        itemsize = KV_DTYPE_ITEMSIZE[str(kv_dtype)]
    except KeyError:
        raise ValueError(
            f"unknown kv_dtype {kv_dtype!r}; known: "
            f"{sorted(KV_DTYPE_ITEMSIZE)}"
        ) from None
    n = float(num_pages) * page_size * num_heads * head_dim
    values = 2.0 * n * itemsize  # the (k, v) pair
    scales = (
        2.0 * float(num_pages) * num_heads * KV_SCALE_ITEMSIZE
        if str(kv_dtype) == "int8" else 0.0
    )
    return (values + scales) / 1024.0 ** 2


def pages_per_mb(
    page_size: int, num_heads: int, head_dim: int,
    kv_dtype: str = "float32",
) -> float:
    """Pages one MB of pool holds at this dtype — the capacity knob the
    int8 policy turns (scale-slab overhead included)."""
    per_page = paged_pool_mb(1, page_size, num_heads, head_dim,
                             kv_dtype=kv_dtype)
    return 1.0 / per_page


# --------------------------------------------------------------------------
# radix prefix index
# --------------------------------------------------------------------------


class _TrieNode:
    __slots__ = ("children", "entry")

    def __init__(self):
        self.children: Dict[int, _TrieNode] = {}
        # one entry whose token sequence passes through this node (most
        # recently inserted wins) — enough to answer "who holds pages
        # covering this prefix", because any sequence through the node
        # shares the node's full root path
        self.entry: Optional["_PrefixEntry"] = None


@dataclass
class _PrefixEntry:
    tokens: Tuple[int, ...]
    pages: Tuple[int, ...]
    stamp: int  # logical LRU clock, bumped on every hit


class RadixPrefixIndex:
    """Token-id trie mapping cached prompt prefixes to their pages.

    ``insert(tokens, pages)`` records a served prompt; ``lookup(query)``
    returns ``(shared, pages)`` where ``shared`` is the longest common
    prefix (in tokens) between the query and any cached prompt, and
    ``pages`` is the cached prompt's page list (its first
    ``ceil(shared / page_size)`` entries cover the match).  Entries are
    bounded (``max_entries``) and evicted least-recently-used; eviction
    returns the evicted entry so the pool can drop its page refs.

    The trie is rebuilt from the surviving entries on eviction — entry
    counts are bounded and prompts are short relative to rebuild cost,
    and a rebuild can never leave a stale ``node.entry`` pointing at
    freed pages (the failure mode incremental unlinking invites).
    """

    def __init__(self, max_entries: int = 256):
        if max_entries < 1:
            raise ValueError(
                f"max_entries must be >= 1, got {max_entries}"
            )
        self.max_entries = int(max_entries)
        self._entries: Dict[Tuple[int, ...], _PrefixEntry] = {}
        self._root = _TrieNode()
        self._clock = 0

    def __len__(self) -> int:
        return len(self._entries)

    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    def insert(self, tokens: Sequence[int],
               pages: Sequence[int]) -> bool:
        """Record ``tokens -> pages``.  Returns True when a NEW entry
        was created (the caller then owns bumping page refcounts); an
        existing identical prompt only refreshes its LRU stamp — its
        original pages stay authoritative, so no refs change hands."""
        key = tuple(int(t) for t in tokens)
        if not key:
            return False
        existing = self._entries.get(key)
        if existing is not None:
            existing.stamp = self._tick()
            return False
        entry = _PrefixEntry(key, tuple(int(p) for p in pages),
                             self._tick())
        self._entries[key] = entry
        node = self._root
        node.entry = entry
        for t in key:
            node = node.children.setdefault(t, _TrieNode())
            node.entry = entry
        return True

    def lookup(self, tokens: Sequence[int]) -> Tuple[int, Tuple[int, ...]]:
        """Longest cached prefix of ``tokens``: ``(shared, pages)``;
        ``(0, ())`` on a miss.  Refreshes the donor's LRU stamp — a
        prefix that keeps getting hit is the last one to evict."""
        depth, entry = self.lookup_entry(tokens)
        if entry is None:
            return 0, ()
        return depth, entry.pages

    def lookup_entry(
        self, tokens: Sequence[int],
    ) -> Tuple[int, Optional[_PrefixEntry]]:
        """Like :meth:`lookup` but returns the donor entry itself (the
        pool needs its token key to shield it from LRU eviction while a
        grant against it is in flight)."""
        node = self._root
        depth = 0
        best: Optional[_PrefixEntry] = None
        for t in tokens:
            child = node.children.get(int(t))
            if child is None:
                break
            node = child
            depth += 1
            if node.entry is not None:
                best = node.entry
        if best is None or depth == 0:
            return 0, None
        best.stamp = self._tick()
        return depth, best

    def evict_lru(
        self, protect: Tuple[int, ...] = (),
    ) -> Optional[_PrefixEntry]:
        """Evict the least-recently-used entry (skipping the ``protect``
        token sequence — the donor of an in-flight grant must survive
        the eviction its own admission triggers).  Returns the evicted
        entry so the caller drops its page refs, or None."""
        victims = [
            e for k, e in self._entries.items() if k != tuple(protect)
        ]
        if not victims:
            return None
        victim = min(victims, key=lambda e: e.stamp)
        del self._entries[victim.tokens]
        self._rebuild()
        return victim

    def clear(self) -> List[_PrefixEntry]:
        """Drop every entry (page-geometry reconfigure); returns them
        so the caller releases their refs."""
        dropped = list(self._entries.values())
        self._entries.clear()
        self._root = _TrieNode()
        return dropped

    def _rebuild(self) -> None:
        self._root = _TrieNode()
        for entry in self._entries.values():
            node = self._root
            node.entry = entry
            for t in entry.tokens:
                node = node.children.setdefault(t, _TrieNode())
                node.entry = entry


# --------------------------------------------------------------------------
# page pool
# --------------------------------------------------------------------------


@dataclass
class PageGrant:
    """One admission's page reservation, returned by
    :meth:`PagedKVCachePool.acquire`.

    ``page_table`` maps logical page k -> physical page id for the
    request's whole reserved span.  ``shared_tokens`` of the prefix are
    already resident (prefill only the tail from there).  When the
    shared prefix ends mid-page, ``cow_src``/``cow_dst`` name the
    device copy the engine must perform BEFORE writing: the donor's
    partial page is cloned into the request's first private page so
    the append never touches a shared page."""

    request_id: int
    page_table: List[int]
    shared_tokens: int = 0
    shared_pages: int = 0
    cow_src: Optional[int] = None
    cow_dst: Optional[int] = None
    new_pages: List[int] = field(default_factory=list)


class PagedKVCachePool:
    """Free-list page allocator + refcounts + radix prefix cache.

    Host bookkeeping only — one instance per engine governs the page id
    space across every pipeline stage (page id p addresses row p of all
    stages' slabs, the paged twin of the slot pool's cross-stage slot
    ids).  Exhaustion contract: :meth:`acquire` returns ``None`` when
    the request cannot be charged even after evicting reusable cache
    entries — a queueing condition for the admission layer, never an
    error, and never a partial mutation.
    """

    def __init__(
        self,
        num_pages: int,
        page_size: int,
        max_pages_per_request: int,
        *,
        enable_prefix_cache: bool = True,
        max_prefix_entries: int = 256,
        kv_dtype: str = "float32",
    ):
        if num_pages < 1 or page_size < 1:
            raise ValueError(
                f"need positive num_pages/page_size, got "
                f"{num_pages}/{page_size}"
            )
        if not 1 <= max_pages_per_request <= num_pages:
            raise ValueError(
                f"max_pages_per_request must be in [1, {num_pages}], "
                f"got {max_pages_per_request}"
            )
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self.max_pages_per_request = int(max_pages_per_request)
        # the allocator POLICY knob: what a page physically stores.
        # "int8" pages carry a parallel per-page-per-head scale slab —
        # the accounting here (pool_mb) and every page copy the pool
        # plans (cow_plan, the engine's swap path) must include it.
        # Any other string is carried verbatim as the MODEL dtype (the
        # engine passes it through for accounting/labels; only
        # pool_mb's byte table is strict, and only when asked).
        self.kv_dtype = str(kv_dtype)
        self.enable_prefix_cache = bool(enable_prefix_cache)
        # LIFO free list, same warm-row rationale as the slot pool
        self._free: List[int] = list(range(self.num_pages))[::-1]
        self._refs: Dict[int, int] = {}
        self._tables: Dict[int, List[int]] = {}  # request_id -> pages
        self.index = RadixPrefixIndex(max_prefix_entries)
        # counters (the engine mirrors these into ServingStats)
        self.prefix_hits = 0
        self.prefix_tokens_reused = 0
        self.prefix_evictions = 0
        self.cow_copies = 0

    # --- accounting ---------------------------------------------------------
    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def pages_in_use(self) -> int:
        return self.num_pages - len(self._free)

    @property
    def reclaimable_pages(self) -> int:
        """Free pages obtainable by evicting every cache entry: cached
        pages whose ONLY refs are cache refs.  Admission headroom is
        ``free_pages + reclaimable_pages``."""
        claims: Dict[int, int] = {}
        for entry in self.index._entries.values():
            for p in entry.pages:
                claims[p] = claims.get(p, 0) + 1
        return sum(
            1 for p, n in claims.items() if self._refs.get(p, 0) == n
        )

    @property
    def virtual_len(self) -> int:
        """Positions one request can span: the paged ``max_len``."""
        return self.max_pages_per_request * self.page_size

    def pool_mb(self, num_heads: int, head_dim: int) -> float:
        """One attention layer's (k, v) pool MB at this pool's
        ``kv_dtype`` — scale slabs included under int8 (the single
        quantized-width formula, see :func:`paged_pool_mb`)."""
        return paged_pool_mb(
            self.num_pages, self.page_size, num_heads, head_dim,
            kv_dtype=self.kv_dtype,
        )

    def cow_plan(self, grant: "PageGrant") -> List[Tuple[str, int, int]]:
        """Device copies a grant's copy-on-write clone requires:
        ``[("values", src, dst)]`` — plus ``("scales", src, dst)`` on an
        int8 pool, because a cloned page dequantized with the DONOR's
        scale but re-scaled under its new owner would silently corrupt
        the shared prefix.  The engine executes this plan across every
        stage's slabs; an empty list means no COW was granted."""
        if grant.cow_src is None:
            return []
        plan: List[Tuple[str, int, int]] = [
            ("values", grant.cow_src, grant.cow_dst)
        ]
        if self.kv_dtype == "int8":
            plan.append(("scales", grant.cow_src, grant.cow_dst))
        return plan

    def table(self, request_id: int) -> List[int]:
        return list(self._tables[request_id])

    def holds(self, request_id: int) -> bool:
        return request_id in self._tables

    # --- ref plumbing -------------------------------------------------------
    def _ref(self, page: int) -> None:
        self._refs[page] = self._refs.get(page, 0) + 1

    def _unref(self, page: int) -> bool:
        """Drop one ref; True when the page fell free."""
        n = self._refs.get(page, 0) - 1
        if n < 0:
            raise ValueError(f"page {page} unref'd below zero")
        if n == 0:
            del self._refs[page]
            self._free.append(page)
            return True
        self._refs[page] = n
        return False

    def _can_cover(self, need: int,
                   protect: Tuple[int, ...] = ()) -> bool:
        """Whether ``need`` pages are coverable by the free list plus
        full eviction of every unprotected cache entry — checked BEFORE
        evicting, so a doomed acquire returns None without spending the
        cache."""
        if len(self._free) >= need:
            return True
        claims: Dict[int, int] = {}
        for key, entry in self.index._entries.items():
            if key == tuple(protect):
                continue
            for p in entry.pages:
                claims[p] = claims.get(p, 0) + 1
        reclaimable = sum(
            1 for p, n in claims.items() if self._refs.get(p, 0) == n
        )
        return len(self._free) + reclaimable >= need

    def _evict_until(self, need: int,
                     protect: Tuple[int, ...] = ()) -> None:
        """Evict LRU cache entries until ``need`` pages are free (or no
        evictable entry remains).  ``protect`` shields the donor prompt
        of the in-flight acquire."""
        while len(self._free) < need:
            victim = self.index.evict_lru(protect)
            if victim is None:
                return
            self.prefix_evictions += 1
            for p in victim.pages:
                self._unref(p)

    # --- admission ----------------------------------------------------------
    def peek_shared(self, tokens: Sequence[int]) -> int:
        """Shared-prefix tokens a lookup WOULD reuse (no state change
        beyond an LRU refresh): capped at ``len(tokens) - 1`` so the
        last prompt position is always recomputed — its logits seed the
        first generated token."""
        if not self.enable_prefix_cache:
            return 0
        shared, _ = self.index.lookup(tokens)
        return min(shared, len(tokens) - 1)

    def acquire(
        self,
        request_id: int,
        tokens: Sequence[int],
        total_len: int,
        *,
        use_prefix: bool = True,
    ) -> Optional[PageGrant]:
        """Charge a request's full reserved span and build its table.

        ``tokens`` is the effective prompt (prefix-cache key);
        ``total_len`` the worst-case sequence length to reserve
        (``len(tokens) + max_new``).  Returns ``None`` — with NO state
        mutated — when the free list (after LRU cache eviction) cannot
        cover the non-shared pages.
        """
        if request_id in self._tables:
            raise ValueError(f"request {request_id} already holds pages")
        tokens = tuple(int(t) for t in tokens)
        total_len = max(int(total_len), len(tokens))
        total_pages = pages_for(total_len, self.page_size)
        if total_pages > self.max_pages_per_request:
            raise ValueError(
                f"request {request_id} needs {total_pages} pages; "
                f"max_pages_per_request={self.max_pages_per_request}"
            )
        shared = 0
        donor: Tuple[int, ...] = ()
        donor_tokens: Tuple[int, ...] = ()
        if use_prefix and self.enable_prefix_cache and tokens:
            matched, entry = self.index.lookup_entry(tokens)
            if entry is not None:
                donor = entry.pages
                donor_tokens = entry.tokens
            shared = min(matched, len(tokens) - 1)
        s_full = shared // self.page_size
        need = total_pages - s_full
        if not self._can_cover(need, protect=donor_tokens):
            return None  # even full cache eviction cannot cover it
        if len(self._free) < need:
            # eviction must never free the donor's pages mid-grant:
            # its exact token sequence is shielded (protection is the
            # contract, not the LRU-refresh recency luck of lookup)
            self._evict_until(need, protect=donor_tokens)
        if len(self._free) < need:
            return None
        new = [self._free.pop() for _ in range(need)]
        table = list(donor[:s_full]) + new
        for p in donor[:s_full]:
            self._ref(p)
        for p in new:
            self._refs[p] = 1
        cow_src = cow_dst = None
        if shared % self.page_size:
            # the prefix ends mid-page: clone the donor's partial page
            # into the first private page before any append touches it
            cow_src = donor[s_full]
            cow_dst = new[0]
            self.cow_copies += 1
        if shared:
            self.prefix_hits += 1
            self.prefix_tokens_reused += shared
        self._tables[request_id] = table
        return PageGrant(
            request_id=request_id,
            page_table=list(table),
            shared_tokens=shared,
            shared_pages=s_full,
            cow_src=cow_src,
            cow_dst=cow_dst,
            new_pages=new,
        )

    def acquire_pages(self, request_id: int,
                      n_pages: int) -> Optional[List[int]]:
        """Plain page reservation with no prefix semantics (the swap-in
        resume path: contents arrive from the host pool, not prefill)."""
        if request_id in self._tables:
            raise ValueError(f"request {request_id} already holds pages")
        n_pages = int(n_pages)
        if not 1 <= n_pages <= self.max_pages_per_request:
            raise ValueError(
                f"need 1..{self.max_pages_per_request} pages, "
                f"got {n_pages}"
            )
        if not self._can_cover(n_pages):
            return None
        if len(self._free) < n_pages:
            self._evict_until(n_pages)
        if len(self._free) < n_pages:
            return None
        pages = [self._free.pop() for _ in range(n_pages)]
        for p in pages:
            self._refs[p] = 1
        self._tables[request_id] = pages
        return list(pages)

    def rollback_grant(self, grant: PageGrant) -> None:
        """Undo an acquire whose wave the engine then refused (tail
        bucket disagreed after eviction): pages handed back AND the
        hit/COW counters reversed, so observability never counts reuse
        that did not happen.  Only valid before any device work used
        the grant."""
        self.release(grant.request_id)
        if grant.shared_tokens:
            self.prefix_hits -= 1
            self.prefix_tokens_reused -= grant.shared_tokens
        if grant.cow_src is not None:
            self.cow_copies -= 1

    def release(self, request_id: int) -> int:
        """Drop the request's refs; returns how many pages fell free.
        Pages the radix cache (or another request) still references
        survive — that is the cache-retention win, not a leak."""
        table = self._tables.pop(request_id, None)
        if table is None:
            raise KeyError(f"request {request_id} holds no pages")
        return sum(1 for p in table if self._unref(p))

    def register_prefix(self, request_id: int,
                        tokens: Sequence[int]) -> bool:
        """Index a served prompt so later requests can share it.  The
        entry refs the prompt-covering prefix of the request's table,
        keeping those pages warm after the request finishes."""
        if not self.enable_prefix_cache:
            return False
        tokens = tuple(int(t) for t in tokens)
        if not tokens:
            return False
        table = self._tables.get(request_id)
        if table is None:
            raise KeyError(f"request {request_id} holds no pages")
        n = pages_for(len(tokens), self.page_size)
        pages = table[:n]
        if (tuple(tokens) not in self.index._entries
                and len(self.index) >= self.index.max_entries):
            victim = self.index.evict_lru()
            if victim is not None:
                self.prefix_evictions += 1
                for p in victim.pages:
                    self._unref(p)
        if not self.index.insert(tokens, pages):
            return False
        for p in pages:
            self._ref(p)
        return True

    def drop_prefix_cache(self) -> int:
        """Evict every cache entry (reconfigure path); returns pages
        freed."""
        freed = 0
        for entry in self.index.clear():
            self.prefix_evictions += 1
            freed += sum(1 for p in entry.pages if self._unref(p))
        return freed

    def check_consistency(self) -> None:
        """Invariant audit for tests: every refcount equals the number
        of table + cache claims, and the free list is exactly the
        unreferenced pages."""
        claims: Dict[int, int] = {}
        for table in self._tables.values():
            for p in table:
                claims[p] = claims.get(p, 0) + 1
        for entry in self.index._entries.values():
            for p in entry.pages:
                claims[p] = claims.get(p, 0) + 1
        if claims != self._refs:
            raise AssertionError(
                f"refcount drift: claims={claims} refs={self._refs}"
            )
        free = set(self._free)
        if len(free) != len(self._free):
            raise AssertionError("free list holds duplicates")
        if free & set(self._refs):
            raise AssertionError("page both free and referenced")
        if free | set(self._refs) != set(range(self.num_pages)):
            raise AssertionError("page neither free nor referenced")


# --------------------------------------------------------------------------
# decode-row ledger
# --------------------------------------------------------------------------


class RowAllocator:
    """Free-list ledger for decode rows (concurrency lanes).

    The paged decode program is still a fixed shape — ``[rows, 1]``
    tokens against ``[rows, max_pages]`` page tables — so a running
    request occupies a *row*, which is pure bookkeeping (its KV lives
    in pages).  Mirrors the slot pool's host interface
    (``allocate``/``acquire``/``release``/``free_slots``/...) so fleet
    replicas' slot-accounting and chaos fault surface work unchanged on
    paged engines; ``total_mb`` is 0 — rows own no device memory.
    """

    def __init__(self, rows: int):
        if rows < 1:
            raise ValueError(f"need at least 1 row, got {rows}")
        self.num_slots = int(rows)
        self._free: List[int] = list(range(self.num_slots))[::-1]

    @property
    def free_slots(self) -> int:
        return len(self._free)

    @property
    def used_slots(self) -> int:
        return self.num_slots - len(self._free)

    @property
    def occupancy(self) -> float:
        return self.used_slots / self.num_slots

    def allocate(self) -> Optional[int]:
        if not self._free:
            return None
        return self._free.pop()

    def acquire(self, row: int) -> None:
        if row not in self._free:
            raise ValueError(f"row {row} is not free")
        self._free.remove(row)

    def release(self, row: int) -> None:
        if not 0 <= row < self.num_slots:
            raise ValueError(
                f"row {row} out of range [0, {self.num_slots})"
            )
        if row in self._free:
            raise ValueError(f"row {row} double-released")
        self._free.append(row)

    def total_mb(self) -> float:
        return 0.0


# --------------------------------------------------------------------------
# chunked-prefill budget policy
# --------------------------------------------------------------------------


class ChunkBudgetPolicy:
    """Per-tick prefill-chunk admission budget (pure scheduling).

    Chunked prefill splits a prompt's non-shared tail into fixed
    ``prefill_chunk``-token chunks that ride engine ticks alongside the
    decode slab, so decode ticks are never stalled behind a whole
    prompt's prefill.  This policy is the knob that bounds the
    interleave: each tick it grants at most ``max_chunk_rows`` chunk
    rows while any request is decoding, so **no decode tick ever waits
    behind more than ``max_chunk_rows x prefill_chunk`` prefill
    positions** — the starvation bound
    (:meth:`starvation_bound_tokens`).  When nothing is decoding there
    is nothing to starve, and the budget opens up to
    ``idle_chunk_rows`` so a cold engine's prefill does not crawl.

    Pure stdlib by contract (this module's standing rule):
    ``tools/chunk_smoke.py`` file-path-loads it in the CI lint job and
    drives the decision table on a bare runner.
    """

    def __init__(
        self,
        prefill_chunk: int,
        max_chunk_rows: int = 1,
        idle_chunk_rows: Optional[int] = None,
    ):
        if int(prefill_chunk) < 1:
            raise ValueError(
                f"prefill_chunk must be >= 1, got {prefill_chunk}"
            )
        if int(max_chunk_rows) < 1:
            raise ValueError(
                f"max_chunk_rows must be >= 1, got {max_chunk_rows}"
            )
        self.prefill_chunk = int(prefill_chunk)
        self.max_chunk_rows = int(max_chunk_rows)
        self.idle_chunk_rows = (
            int(idle_chunk_rows) if idle_chunk_rows is not None
            else max(self.max_chunk_rows, 4)
        )
        if self.idle_chunk_rows < self.max_chunk_rows:
            raise ValueError(
                f"idle_chunk_rows {self.idle_chunk_rows} must be >= "
                f"max_chunk_rows {self.max_chunk_rows} (an idle engine "
                f"never has less headroom than a busy one)"
            )

    def rows_for_tick(self, *, pending: int, decoding: int) -> int:
        """Chunk rows this tick may prefill.

        ``pending`` = requests holding pages mid-prefill; ``decoding``
        = requests in the running decode batch.  Returns 0 when there
        is nothing to chunk; otherwise the decode-protecting bound (or
        the idle bound when no decode work exists to protect).
        """
        if pending <= 0:
            return 0
        if decoding <= 0:
            return min(pending, self.idle_chunk_rows)
        return min(pending, self.max_chunk_rows)

    def starvation_bound_tokens(self) -> int:
        """Worst-case prefill positions any decode tick can wait
        behind: the chunk interleave's latency guarantee."""
        return self.max_chunk_rows * self.prefill_chunk


# --------------------------------------------------------------------------
# preemption mode policy
# --------------------------------------------------------------------------


def preempt_costs(
    resume_tokens: int,
    page_count: int,
    page_size: int,
    *,
    recompute_token_cost: float = 1.0,
    swap_position_cost: float = 0.25,
) -> Tuple[float, float]:
    """(recompute_cost, swap_cost) of resuming a preempted request.

    Recompute replays ``resume_tokens`` of prefill compute; swap moves
    ``page_count * page_size`` cache positions across the host link
    twice (out + in).  The unit costs are relative weights — on real
    hardware they calibrate to measured prefill tok/s vs host-link
    GB/s; the CPU-fallback default makes swap win once a sequence has
    meaningfully outgrown a page, matching the intuition that long
    sequences are exactly the ones recomputation punishes."""
    recompute = float(resume_tokens) * float(recompute_token_cost)
    swap = 2.0 * page_count * page_size * float(swap_position_cost)
    return recompute, swap


def choose_preempt_mode(
    resume_tokens: int,
    page_count: int,
    page_size: int,
    *,
    recompute_token_cost: float = 1.0,
    swap_position_cost: float = 0.25,
    recompute_feasible: bool = True,
) -> str:
    """``"swap"`` or ``"recompute"`` — cheapest resume wins; a resume
    prefix that no longer fits any prefill bucket forces swap (the case
    recomputation structurally cannot serve)."""
    if not recompute_feasible:
        return "swap"
    recompute, swap = preempt_costs(
        resume_tokens, page_count, page_size,
        recompute_token_cost=recompute_token_cost,
        swap_position_cost=swap_position_cost,
    )
    return "swap" if swap < recompute else "recompute"


__all__ = [
    "ChunkBudgetPolicy",
    "KV_DTYPE_ITEMSIZE",
    "PageGrant",
    "PagedKVCachePool",
    "RadixPrefixIndex",
    "RowAllocator",
    "choose_preempt_mode",
    "paged_pool_mb",
    "pages_for",
    "pages_per_mb",
    "preempt_costs",
]
