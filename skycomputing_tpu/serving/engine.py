"""ServingEngine: iteration-level continuous batching over pipeline stages.

The training engine (``parallel/pipeline.py``) amortizes host dispatch
over microbatches; serving has no microbatches — it has *requests* that
arrive whenever they arrive and finish whenever they finish.  The two
techniques that make a pipeline throughput-competitive for serving, both
implemented here:

- **continuous batching** (Orca, OSDI '22): scheduling happens at
  *decode-iteration* granularity.  Every tick the engine (1) admits
  queued requests into free KV slots via a bucketed prefill wave and
  (2) runs ONE single-token decode step over the whole slot slab.
  A finishing request frees its slot between ticks; a joining request
  occupies one between ticks; the running batch never drains to
  accommodate either — the static-batching failure mode where every
  member waits for the slowest.
- **fixed-shape KV caching** in two layouts.  ``kv_layout="slot"``
  (the compatibility default): per-stage preallocated ``[slots,
  max_len, heads, head_dim]`` slabs (``serving/kv_cache.py``) — one
  whole row per request.  ``kv_layout="paged"`` (PagedAttention,
  SOSP '23 + SGLang-style radix prefix caching): per-stage
  ``[num_pages, page_size, heads, head_dim]`` page pools addressed
  through per-request page tables (host bookkeeping — free-list
  allocator, refcounts, copy-on-write prefix sharing, radix index,
  swap-preemption — in ``serving/paging.py``), so admission charges a
  request its TRUE footprint in pages and concurrency floats with
  memory instead of a slot count (>2x sustained at equal pool MB,
  gated in ``BENCH_serving.json``).  The paged decode path picks its
  attention body per engine (``attn_impl``: the fused Pallas kernel on
  TPU, the XLA gather+softmax reference elsewhere), bounds each step's
  page-table width to the wave's live span (``gather_pages="live"`` —
  the table-capacity-proportional gather was PR 9's raw speed floor),
  and can store pages int8 with per-page-per-head scale slabs
  (``kv_dtype="int8"`` — ~4x pages per MB at fp32 model dtype, bounded
  error; see docs/serving.md).  Either way every compiled program
  keeps a fixed shape regardless of which requests are live: decode
  compiles ONCE; prefill compiles once per prompt-length bucket
  (``serving/batcher.py``); after warmup the steady state is
  zero-recompile, pinned by ``xla_compile_count()`` in
  ``tests/test_serving.py``.

Pipeline integration: stages come from the same worker-manager
allocation the MPMD trainer uses (``Allocator.serving_allocate``
balances them against *decode-step* costs — see ``serving/profile.py``),
each stage's params and slabs are committed to its device, and
inter-stage hidden-state/index hops ride ``device_put_elided`` so
same-device handoffs are free and cross-device ones batch into one put.

Inactive slots ride through the decode step computing masked garbage —
that waste is the price of a fixed shape, and ``ServingStats.
batch_occupancy`` makes it visible instead of hidden.
"""

from __future__ import annotations

import hashlib
import json
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..builder import build_layer_stack
from ..models.gpt import (
    GptEmbeddings,
    _gcfg,
    apply_kv_cached,
    apply_kv_paged,
    attn_indices,
    decode_modules,
    draft_slice_indices,
)
from ..parallel.pipeline import (
    _donation_enabled,
    device_put_elided,
    xla_compile_count,
)
from ..telemetry import LiveMetricsMixin, MetricsRegistry, get_tracer
from .batcher import (
    AdmissionQueue,
    FAILED,
    FINISHED,
    QueueFullError,
    REJECTED,
    RUNNING,
    Request,
    ShapeBucketer,
)
from .kv_cache import (
    QuantizedPages,
    SlotKVCachePool,
    init_paged_caches,
    kv_spec_from_config,
)
from .paging import (
    ChunkBudgetPolicy,
    PagedKVCachePool,
    RowAllocator,
    choose_preempt_mode,
    pages_for,
)
from .speculative import (
    DraftModel,
    greedy_accept_count,
    tree_param_mb,
)


# one compiled gather/argmax pair per (batch, vocab) shape — module-level
# jits so every engine instance shares the executables
_gather_last = jax.jit(
    lambda logits, pos: logits[jnp.arange(logits.shape[0]), pos, :]
)
_argmax_tokens = jax.jit(
    lambda logits: jnp.argmax(logits, axis=-1).astype(jnp.int32)
)

# Process-level stage-program cache: the jit'd decode/prefill closures,
# keyed by the stage's layer-config signature (+ max_len + donation).
# jax's compilation cache is keyed by FUNCTION IDENTITY, so two engines
# built from identical configs would otherwise re-trace and re-compile
# every program — which makes a fleet replica's re-form pay the full
# compile bill on the serving path.  Reusing the closure lets a
# re-formed replica (and every same-config engine in tests/benches)
# restart at cache-hit speed, the serving twin of the training side's
# persistent-compile-cache-into-relaunched-trainer idea.  Safe because
# the closures are pure functions of their arguments: modules are
# stateless config-built definitions (params always passed in), and the
# signature pins the exact config that built them.
_STAGE_PROGRAMS: Dict[str, Any] = {}


@dataclass
class ServingStats:
    """SLO accounting for a :class:`ServingEngine` (the serving
    counterpart of ``PipelineStats``).

    Counters are cumulative since engine construction; ``queue_depth``
    and ``batch_occupancy`` are gauges from the last iteration.
    ``compiles`` counts XLA backend compiles observed during engine
    calls — after bucket warmup it must stop moving (the steady-state
    zero-recompile contract).  ``queue_stalls`` counts iterations where
    admission wanted a slot and none was free (the pool-exhaustion
    queueing path); ``preemptions`` counts slot evictions
    (recomputation-style: the request re-queues and its KV prefix is
    rebuilt on re-admission).
    """

    iterations: int = 0
    prefill_waves: int = 0
    prefill_tokens: int = 0
    decode_tokens: int = 0
    generated_tokens: int = 0
    admitted: int = 0
    finished: int = 0
    preemptions: int = 0
    queue_stalls: int = 0
    # bounded-admission accounting: submissions refused (policy
    # "reject") or displaced (policy "shed") by a full queue — load
    # shedding is only acceptable when it is visible
    queue_rejections: int = 0
    compiles: int = 0
    # paged-KV accounting (kv_layout="paged"; zero on slot engines):
    # prefix_hits/prefix_tokens_reused measure the radix cache
    # (prefill compute NOT spent), cow_copies the partial-page clones
    # that keep shared pages read-only, swap_outs/swap_ins the
    # host-pool preemption path, prefix_evictions the LRU pressure
    prefix_hits: int = 0
    prefix_tokens_reused: int = 0
    cow_copies: int = 0
    swap_outs: int = 0
    swap_ins: int = 0
    # swap records whose swap-in checksum verification failed: the
    # record is dropped and the victim resumes by recompute-from-prompt
    # instead of restoring poisoned KV — corruption is only acceptable
    # when it is caught, counted, and survived
    swap_corruptions: int = 0
    prefix_evictions: int = 0
    # chunked-prefill accounting (prefill_chunk set): prefill_chunks
    # counts chunk rows computed (one request-chunk each);
    # chunk_stalls counts ticks where pending chunk work was deferred
    # by the decode-protecting budget — sustained growth means prefill
    # demand exceeds the interleave budget (raise max_chunk_rows or
    # prefill_chunk, or accept the TTFT cost)
    prefill_chunks: int = 0
    chunk_stalls: int = 0
    # int8-KV accounting (kv_dtype="int8"): quantized_pages counts
    # page-tile quantization events (every page a write wave touched
    # re-quantizes through its scale — write amplification made
    # visible); dequant_blocks counts page blocks dequantized by
    # attention reads (active rows x gathered table width per step —
    # the work the bounded gather and the fused kernel shrink)
    quantized_pages: int = 0
    dequant_blocks: int = 0
    # speculative-decoding accounting (spec_k > 0): draft_tokens =
    # USABLE draft proposals (capped at each row's remaining token
    # budget — surplus drafts a row could never commit don't deflate
    # the rate), accepted_draft_tokens committed after the target's
    # verify forward agreed, spec_rollbacks = verify outcomes that
    # truncated a row's watermark past written speculative KV
    # (accepted_draft_tokens / draft_tokens is the live accept rate
    # the speculation speedup rides on; exactly 1.0 for a perfect
    # draft)
    draft_tokens: int = 0
    accepted_draft_tokens: int = 0
    spec_rollbacks: int = 0
    # disaggregated-serving accounting (the prefill/decode handoff
    # plane, docs/disagg.md): handoffs_out counts swap records exported
    # as portable handoffs, handoffs_in records seated for swap-in
    # resume on this engine, handoff_failures records refused at the
    # import checksum gate (the request recomputes from its prompt —
    # counted, never lost), handoff_bytes the host payload moved
    handoffs_out: int = 0
    handoffs_in: int = 0
    handoff_failures: int = 0
    handoff_bytes: int = 0
    # gauges
    queue_depth: int = 0
    batch_occupancy: float = 0.0
    pages_in_use: int = 0
    free_pages: int = 0
    # blocked wall time per phase (timed across block_until_ready)
    prefill_s: float = 0.0
    decode_s: float = 0.0
    # per-request SLO samples
    ttft_s: List[float] = field(default_factory=list)
    tpot_s: List[float] = field(default_factory=list)

    #: metric classification (telemetry.MetricsRegistry contract):
    #: counters are cumulative for the ENGINE's lifetime and never
    #: reset — ``reconfigure()`` preserves this object, and a fleet
    #: replica's re-form (a new engine = a new lifetime) is bridged by
    #: ``EngineReplica.stats_snapshot`` carrying the prior generations'
    #: totals, so time-series rate derivation stays well-defined.
    #: Covers ``snapshot()`` keys, derived fields included.
    FIELD_TYPES = {
        "iterations": "counter", "prefill_waves": "counter",
        "prefill_tokens": "counter", "decode_tokens": "counter",
        "generated_tokens": "counter", "admitted": "counter",
        "finished": "counter", "preemptions": "counter",
        "queue_stalls": "counter", "queue_rejections": "counter",
        "compiles": "counter", "prefill_s": "counter",
        "decode_s": "counter",
        "prefix_hits": "counter", "prefix_tokens_reused": "counter",
        "cow_copies": "counter", "swap_outs": "counter",
        "swap_ins": "counter", "swap_corruptions": "counter",
        "prefix_evictions": "counter",
        "prefill_chunks": "counter", "chunk_stalls": "counter",
        "quantized_pages": "counter", "dequant_blocks": "counter",
        "draft_tokens": "counter",
        "accepted_draft_tokens": "counter",
        "spec_rollbacks": "counter",
        "handoffs_out": "counter", "handoffs_in": "counter",
        "handoff_failures": "counter", "handoff_bytes": "counter",
        "queue_depth": "gauge", "batch_occupancy": "gauge",
        "pages_in_use": "gauge", "free_pages": "gauge",
        "tokens_per_s": "gauge",
        "ttft_p50_s": "gauge", "ttft_p95_s": "gauge",
        "tpot_p50_s": "gauge", "tpot_p95_s": "gauge",
    }

    #: the cumulative subset a replica carries across re-forms
    COUNTER_FIELDS = tuple(
        k for k, v in FIELD_TYPES.items()
        if v == "counter"
    )

    def tokens_per_s(self) -> float:
        """Generated tokens per second of engine compute wall clock."""
        elapsed = self.prefill_s + self.decode_s
        return self.generated_tokens / elapsed if elapsed > 0 else 0.0

    def snapshot(self) -> Dict[str, Any]:
        """JSON-able summary (percentiles over the SLO samples)."""
        def pct(xs, q):
            return float(np.percentile(xs, q)) if xs else None

        return dict(
            iterations=self.iterations,
            prefill_waves=self.prefill_waves,
            prefill_tokens=self.prefill_tokens,
            decode_tokens=self.decode_tokens,
            generated_tokens=self.generated_tokens,
            admitted=self.admitted,
            finished=self.finished,
            preemptions=self.preemptions,
            queue_stalls=self.queue_stalls,
            queue_rejections=self.queue_rejections,
            compiles=self.compiles,
            prefix_hits=self.prefix_hits,
            prefix_tokens_reused=self.prefix_tokens_reused,
            cow_copies=self.cow_copies,
            swap_outs=self.swap_outs,
            swap_ins=self.swap_ins,
            swap_corruptions=self.swap_corruptions,
            prefix_evictions=self.prefix_evictions,
            prefill_chunks=self.prefill_chunks,
            chunk_stalls=self.chunk_stalls,
            quantized_pages=self.quantized_pages,
            dequant_blocks=self.dequant_blocks,
            draft_tokens=self.draft_tokens,
            accepted_draft_tokens=self.accepted_draft_tokens,
            spec_rollbacks=self.spec_rollbacks,
            handoffs_out=self.handoffs_out,
            handoffs_in=self.handoffs_in,
            handoff_failures=self.handoff_failures,
            handoff_bytes=self.handoff_bytes,
            queue_depth=self.queue_depth,
            batch_occupancy=self.batch_occupancy,
            pages_in_use=self.pages_in_use,
            free_pages=self.free_pages,
            prefill_s=self.prefill_s,
            decode_s=self.decode_s,
            tokens_per_s=self.tokens_per_s(),
            ttft_p50_s=pct(self.ttft_s, 50),
            ttft_p95_s=pct(self.ttft_s, 95),
            tpot_p50_s=pct(self.tpot_s, 50),
            tpot_p95_s=pct(self.tpot_s, 95),
        )


class _ServingStage:
    """One pipeline stage: module slice + device + slabs + programs."""

    def __init__(
        self,
        stage_index: int,
        modules: Sequence[Any],
        params: Sequence[Any],
        device,
        num_slots: int,
        max_len: int,
        program_key: Optional[str] = None,
    ):
        self.stage_index = stage_index
        self.modules = list(modules)
        self.device = device
        # trace-lane name, same convention as StageRuntime.lane_name so
        # serving and training timelines read identically in Perfetto
        self.lane_name = f"stage {stage_index} [{device}]"
        self.params: List[Any] = jax.device_put(list(params), device)
        specs = [
            kv_spec_from_config(
                _gcfg(self.modules[i].config).to_dict(), max_len
            )
            for i in attn_indices(self.modules)
        ]
        self.specs = specs
        self.pool = SlotKVCachePool(specs, num_slots, device=device)
        cached = (
            _STAGE_PROGRAMS.get(program_key)
            if program_key is not None else None
        )
        if cached is not None:
            # same config signature -> the closures (and jax's traced/
            # compiled cache behind their identity) are reusable as-is
            self._decode_donated, self._prefill_donated = cached
            return
        mods, stage_specs = self.modules, specs

        def decode(params_list, data, caches, index):
            return apply_kv_cached(mods, params_list, data, caches, index)

        def prefill(params_list, data, slabs, slot_ids):
            # scratch caches sized to the bucket: the prefix 0..L-1 is
            # exactly what must land in the slabs, so the filled scratch
            # IS the scatter payload
            rows, bucket = data.shape[0], data.shape[1]
            scratch = [
                (
                    jnp.zeros(
                        (rows, bucket, s.num_heads, s.head_dim),
                        jnp.dtype(s.dtype),
                    ),
                    jnp.zeros(
                        (rows, bucket, s.num_heads, s.head_dim),
                        jnp.dtype(s.dtype),
                    ),
                )
                for s in stage_specs
            ]
            out, scratch = apply_kv_cached(
                mods, params_list, data, scratch, 0
            )
            # rows assigned the sentinel slot id (padding rows of a
            # half-full wave) drop out of the scatter entirely
            new_slabs = [
                (
                    k_slab.at[slot_ids, :bucket].set(ks, mode="drop"),
                    v_slab.at[slot_ids, :bucket].set(vs, mode="drop"),
                )
                for (ks, vs), (k_slab, v_slab) in zip(scratch, slabs)
            ]
            return out, new_slabs

        # donated twins (convention: *_donated handles are consumed on
        # call — the engine rebinds pool.slabs to the outputs on the
        # same line).  Donation follows the backend like the training
        # engine: in-place slab reuse pays on TPU/GPU, is inert on CPU.
        if _donation_enabled():
            self._decode_donated = jax.jit(decode, donate_argnums=(2,))
            self._prefill_donated = jax.jit(prefill, donate_argnums=(2,))
        else:
            self._decode_donated = jax.jit(decode)
            self._prefill_donated = jax.jit(prefill)
        if program_key is not None:
            _STAGE_PROGRAMS[program_key] = (
                self._decode_donated, self._prefill_donated
            )

    def build_pool(self, num_slots: int) -> SlotKVCachePool:
        """A fresh (unassigned) slab pool for a new slot count.

        Engine ``reconfigure`` pre-builds every stage's new pool BEFORE
        evicting anything, so a slab-allocation failure (device OOM on
        a larger slot count) surfaces while the engine is still fully
        intact.  The decode/prefill programs re-trace once for the new
        slab shape — a deliberate, visible warmup cost, the same one
        engine construction pays."""
        return SlotKVCachePool(self.specs, num_slots, device=self.device)


# small paged-slab utilities, module-level jits so every engine shares
# the executables (shape-keyed: one compile per slab geometry).
# _copy_page is undonated, so on accelerators each COW event pays a
# slab-sized copy; COW fires at most once per prefix-hit admission, so
# this is off the per-token path — donate + rebind if it ever shows up
_copy_page = jax.jit(lambda slab, src, dst: slab.at[dst].set(slab[src]))
_gather_rows = jax.jit(
    lambda slab, table: slab[jnp.clip(table, 0, slab.shape[0] - 1)]
)
_scatter_rows = jax.jit(
    lambda slab, table, vals: slab.at[table].set(
        vals.astype(slab.dtype), mode="drop"
    )
)


class _PagedServingStage:
    """One pipeline stage under the PAGED layout: module slice + device
    + per-attention-layer page slabs ``[num_pages, page_size, heads,
    head_dim]`` + the one fused step program (prefill and decode are
    the same function at different input shapes — see
    ``models/gpt.apply_kv_paged``)."""

    def __init__(
        self,
        stage_index: int,
        modules: Sequence[Any],
        params: Sequence[Any],
        device,
        num_pages: int,
        page_size: int,
        program_key: Optional[str] = None,
        kv_dtype: Optional[str] = None,
        attn_impl: str = "xla",
    ):
        self.stage_index = stage_index
        self.modules = list(modules)
        self.device = device
        self.lane_name = f"stage {stage_index} [{device}]"
        self.params: List[Any] = jax.device_put(list(params), device)
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self.kv_dtype = kv_dtype
        self.attn_impl = attn_impl
        self.specs = [
            kv_spec_from_config(
                _gcfg(self.modules[i].config).to_dict(), page_size
            )
            for i in attn_indices(self.modules)
        ]
        self.slabs = self.build_slabs(num_pages, page_size)
        cached = (
            _STAGE_PROGRAMS.get(program_key)
            if program_key is not None else None
        )
        if cached is not None:
            self._step_donated = cached
            return
        mods = self.modules
        impl = attn_impl

        def step(params_list, data, slabs, tables, index, valid_len):
            return apply_kv_paged(
                mods, params_list, data, slabs, tables, index,
                valid_len, attn_impl=impl,
            )

        if _donation_enabled():
            self._step_donated = jax.jit(step, donate_argnums=(2,))
        else:
            self._step_donated = jax.jit(step)
        if program_key is not None:
            _STAGE_PROGRAMS[program_key] = self._step_donated

    def build_slabs(self, num_pages: int, page_size: int):
        """Fresh zeroed page slabs (construction + the reconfigure
        pre-build, so an allocation failure surfaces while the engine
        is still intact).  ``kv_dtype="int8"`` builds QuantizedPages
        pairs: int8 values + the parallel float32 scale slabs."""
        return init_paged_caches(
            self.specs, num_pages, page_size, device=self.device,
            kv_dtype=self.kv_dtype,
        )

    def apply_cow_plan(self, plan) -> None:
        """Execute the pool's copy-on-write plan
        (``PagedKVCachePool.cow_plan``) against this stage's slabs —
        the plan, not this method, is the source of truth for WHAT a
        clone copies: on an int8 pool it names the scale row alongside
        the values (a cloned page dequantized with the donor's scale
        but re-scaled under its new owner would corrupt the shared
        prefix).  A plan/slab mismatch — a scale copy planned for a
        pool whose slabs are not quantized, or vice versa — raises:
        that is kv_dtype drift between the allocator and the device
        slabs, never something to paper over."""
        copies: Dict[str, Any] = {}
        for kind, src, dst in plan:
            if kind not in ("values", "scales"):
                raise ValueError(f"unknown COW plan entry {kind!r}")
            copies[kind] = (np.int32(src), np.int32(dst))
        if not copies:
            return

        def cp(slab):
            quantized = isinstance(slab, QuantizedPages)
            if "scales" in copies and not quantized:
                raise ValueError(
                    "COW plan names a scale copy but this stage's "
                    "slabs are not quantized — pool/stage kv_dtype "
                    "drift"
                )
            if not quantized:
                s, d = copies["values"]
                return _copy_page(slab, s, d)
            values, scale = slab.values, slab.scale
            if "values" in copies:
                s, d = copies["values"]
                values = _copy_page(values, s, d)
            if "scales" in copies:
                s, d = copies["scales"]
                scale = _copy_page(scale, s, d)
            return QuantizedPages(values, scale)

        # one pass over the slab list regardless of how many entry
        # kinds the plan carries (values + scales copy together)
        self.slabs = [(cp(k), cp(v)) for k, v in self.slabs]

    def swap_out(self, table: np.ndarray) -> List[Any]:
        """Host copies of the pages in ``table`` (sentinel-padded, so
        the gathered shape is fixed at [max_pages, page_size, ...] and
        compiles once); sentinel rows carry garbage the swap-in scatter
        drops.  int8 slabs swap their scale rows alongside the values —
        a page restored without its scale would dequantize garbage."""
        t = jnp.asarray(table, jnp.int32)

        def g(slab):
            if isinstance(slab, QuantizedPages):
                return QuantizedPages(
                    np.asarray(_gather_rows(slab.values, t)),
                    np.asarray(_gather_rows(slab.scale, t)),
                )
            return np.asarray(_gather_rows(slab, t))

        return [(g(k), g(v)) for k, v in self.slabs]

    def swap_in(self, table: np.ndarray, host_pairs: List[Any]) -> None:
        """Scatter host page copies back into fresh pages (sentinel
        table rows drop)."""
        t = jnp.asarray(table, jnp.int32)

        def s(slab, host):
            if isinstance(slab, QuantizedPages):
                return QuantizedPages(
                    _scatter_rows(slab.values, t,
                                  jnp.asarray(host.values)),
                    _scatter_rows(slab.scale, t,
                                  jnp.asarray(host.scale)),
                )
            return _scatter_rows(slab, t, jnp.asarray(host))

        self.slabs = [
            (s(k, hk), s(v, hv))
            for (k, v), (hk, hv) in zip(self.slabs, host_pairs)
        ]


def _swap_record_checksum(pages: int, index: int,
                          data: List[Any]) -> str:
    """sha256 over a swap record's host payload (page count, resume
    index, and every host array byte — int8 records hash their scale
    rows alongside the values, since a page restored under the wrong
    scale dequantizes garbage just as surely as flipped value bits).
    Stamped at swap-out, verified at swap-in: the integrity half of
    the host-pool preemption path."""
    h = hashlib.sha256()
    h.update(f"{int(pages)}:{int(index)}".encode())

    def fold(host) -> None:
        # data nests: stages -> per-layer (k, v) pairs -> arrays or
        # QuantizedPages (values + scale) — recurse to the leaves
        if isinstance(host, QuantizedPages):
            fold(host.values)
            fold(host.scale)
            return
        if isinstance(host, (list, tuple)):
            for item in host:
                fold(item)
            return
        h.update(np.ascontiguousarray(host).tobytes())

    fold(data)
    return h.hexdigest()


def _swap_record_nbytes(data: List[Any]) -> int:
    """Total host bytes a swap record parks (the payload a handoff
    moves between pools — ``handoff_bytes`` accounting)."""
    total = 0

    def fold(host) -> None:
        nonlocal total
        if isinstance(host, QuantizedPages):
            fold(host.values)
            fold(host.scale)
            return
        if isinstance(host, (list, tuple)):
            for item in host:
                fold(item)
            return
        total += int(np.ascontiguousarray(host).nbytes)

    fold(data)
    return total


def _stage_slab_checksums(data: List[Any]) -> List[str]:
    """One sha256 per stage's host slabs (same leaf fold as
    ``_swap_record_checksum``) — a corrupted handoff names the stage
    instead of just failing the whole record."""
    out = []
    for stage_pairs in data:
        h = hashlib.sha256()

        def fold(host) -> None:
            if isinstance(host, QuantizedPages):
                fold(host.values)
                fold(host.scale)
                return
            if isinstance(host, (list, tuple)):
                for item in host:
                    fold(item)
                return
            h.update(np.ascontiguousarray(host).tobytes())

        fold(stage_pairs)
        out.append(h.hexdigest())
    return out


class ServingEngine(LiveMetricsMixin):
    """Continuous-batching GPT serving over allocator-placed stages.

    ``model_cfg`` is the same layer-config list every other subsystem
    speaks (``gpt_layer_configs`` output); ``params_list`` the matching
    per-layer param trees (``LayerStack.init`` result or
    ``ParameterServer.get_layer_slice(0, n)``).  Stage placement comes
    from ``worker_manager`` (an allocator-written pool, serving-balanced
    via ``Allocator.serving_allocate``) or an explicit ``partition`` of
    layer counts; default is one stage on the first device.
    """

    def __init__(
        self,
        model_cfg: Sequence[Dict],
        params_list: Sequence[Any],
        *,
        num_slots: int = 4,
        max_len: int = 128,
        buckets: Sequence[int] = (16, 32, 64),
        prefill_batch: int = 1,
        max_queue: Optional[int] = None,
        queue_policy: str = "reject",
        pad_id: int = 0,
        worker_manager=None,
        partition: Optional[Sequence[int]] = None,
        devices: Optional[Sequence[Any]] = None,
        static_batching: bool = False,
        preflight: bool = True,
        kv_layout: str = "slot",
        page_size: int = 16,
        num_pages: Optional[int] = None,
        max_pages_per_request: Optional[int] = None,
        max_concurrency: Optional[int] = None,
        enable_prefix_cache: bool = True,
        max_prefix_entries: int = 256,
        preempt_policy: str = "auto",
        prefill_chunk: Optional[int] = None,
        max_chunk_rows: Optional[int] = None,
        spec_k: int = 0,
        draft_blocks: Optional[int] = None,
        kv_dtype: Optional[str] = None,
        attn_impl: Optional[str] = None,
        gather_pages: str = "live",
    ):
        if kv_layout not in ("slot", "paged"):
            raise ValueError(
                f"kv_layout must be 'slot' or 'paged', got {kv_layout!r}"
            )
        if preempt_policy not in ("auto", "recompute", "swap"):
            raise ValueError(
                f"preempt_policy must be 'auto', 'recompute' or 'swap', "
                f"got {preempt_policy!r}"
            )
        self.kv_layout = kv_layout
        self._paged = kv_layout == "paged"
        # --- the paged kernel/quantization operating point ------------
        # kv_dtype: None keeps the model dtype; "int8" stores pages
        # quantized (per-page-per-head scale slabs, quantize-on-write)
        # — construction state like draft_blocks, NOT a reconfigure
        # knob: a dtype flip would have to re-encode every live page.
        # attn_impl: None auto-detects — the fused Pallas kernel on a
        # TPU backend, the XLA reference elsewhere (interpret-mode
        # Pallas is available everywhere but is a correctness surface,
        # ~orders slower than XLA on CPU; pass "pallas" explicitly to
        # use it off-TPU).  gather_pages: "live" bounds every step's
        # page-table width to the wave's live span (ceil to page, then
        # to the next power-of-two page count with the largest bucket
        # as floor — a log-sized compile-shape set, each warmed like a
        # prefill bucket); "full" keeps PR 9's full-table-width gather,
        # the honest A/B baseline the bench measures against.
        if kv_dtype not in (None, "int8"):
            raise ValueError(
                f"kv_dtype must be None (model dtype) or 'int8', "
                f"got {kv_dtype!r}"
            )
        if attn_impl not in (None, "xla", "pallas"):
            raise ValueError(
                f"attn_impl must be None (auto), 'xla' or 'pallas', "
                f"got {attn_impl!r}"
            )
        if gather_pages not in ("live", "full"):
            raise ValueError(
                f"gather_pages must be 'live' or 'full', "
                f"got {gather_pages!r}"
            )
        if not self._paged and (kv_dtype is not None
                                or attn_impl is not None
                                or gather_pages != "live"):
            raise ValueError(
                "kv_dtype/attn_impl/gather_pages require "
                "kv_layout='paged' (the kernel, the quantized pool, "
                "and the bounded table gather are page-table "
                "machinery)"
            )
        self.kv_dtype = kv_dtype
        self.gather_pages = gather_pages
        if self._paged:
            self.attn_impl = attn_impl or (
                "pallas" if jax.default_backend() == "tpu" else "xla"
            )
        else:
            self.attn_impl = None
        modules = decode_modules(build_layer_stack(list(model_cfg)))
        if not attn_indices(modules) or not isinstance(
            modules[0], GptEmbeddings
        ):
            raise ValueError(
                "expected a GPT stack: GptEmbeddings + GptBlock_Attn units"
            )
        max_pos = _gcfg(modules[0].config).max_position_embeddings
        if self._paged:
            # the paged operating point: max_len becomes the PER-REQUEST
            # virtual span (max_pages_per_request x page_size), and the
            # pool depth decouples from it entirely — num_pages defaults
            # to the slot layout's byte-equal footprint
            # (num_slots x pages_for(max_len)), the equal-memory pivot
            self.page_size = int(page_size)
            if self.page_size < 1:
                raise ValueError(f"page_size must be >= 1, got {page_size}")
            if max_pages_per_request is not None:
                self.max_pages_per_request = int(max_pages_per_request)
            else:
                # derived default: cover max_len, but never let the
                # page-rounded span outgrow the model's position table
                # — a (max_len, page_size) pair that works under the
                # slot layout must not be rejected by its own rounding
                derived = pages_for(max_len, self.page_size)
                if derived * self.page_size > max_pos:
                    derived = max_pos // self.page_size
                if derived < 1:
                    raise ValueError(
                        f"page_size={self.page_size} exceeds "
                        f"max_position_embeddings={max_pos}"
                    )
                self.max_pages_per_request = derived
            max_len = self.max_pages_per_request * self.page_size
            self.num_pages = (
                int(num_pages) if num_pages is not None
                else int(num_slots) * pages_for(max_len, self.page_size)
            )
            self.max_concurrency = (
                int(max_concurrency) if max_concurrency is not None
                else min(self.num_pages, int(num_slots) * 4)
            )
            if self.max_concurrency < 1:
                raise ValueError(
                    f"max_concurrency must be >= 1, "
                    f"got {self.max_concurrency}"
                )
            # decode rows are the concurrency lanes: num_slots becomes
            # the row count so the fleet's slot-accounting, router load
            # estimates, and chaos slot leaks stay meaningful unchanged
            num_slots = self.max_concurrency
        else:
            self.page_size = None
            self.num_pages = None
            self.max_pages_per_request = None
            self.max_concurrency = None
        if max_len > max_pos:
            raise ValueError(
                f"max_len={max_len} exceeds "
                f"max_position_embeddings={max_pos}"
            )
        self.bucketer = ShapeBucketer(buckets)
        if self.bucketer.max_bucket > max_len:
            raise ValueError(
                f"largest bucket {self.bucketer.max_bucket} exceeds "
                f"max_len={max_len}"
            )
        self.num_slots = int(num_slots)
        self.max_len = int(max_len)
        self.pad_id = int(pad_id)
        self.enable_prefix_cache = bool(enable_prefix_cache)
        self.preempt_policy = preempt_policy
        self._max_prefix_entries = int(max_prefix_entries)
        if queue_policy not in ("reject", "shed"):
            raise ValueError(
                f"queue_policy must be 'reject' or 'shed', "
                f"got {queue_policy!r}"
            )
        self.max_queue = None if max_queue is None else int(max_queue)
        self.queue_policy = queue_policy
        self._queue = AdmissionQueue(
            self.bucketer, prefill_batch=prefill_batch,
            max_queue=self.max_queue,
        )
        self.prefill_batch = int(prefill_batch)
        # --- chunked prefill (paged-only): pure scheduling — split the
        # non-shared prefill tail into prefill_chunk-token chunks that
        # ride ticks alongside the decode slab
        self.prefill_chunk: Optional[int] = None
        self.max_chunk_rows: Optional[int] = None
        self._chunk_policy: Optional[ChunkBudgetPolicy] = None
        if prefill_chunk:
            if not self._paged:
                raise ValueError(
                    "prefill_chunk requires kv_layout='paged' (partial "
                    "prefill state lives in page tables)"
                )
            self._set_chunking(int(prefill_chunk), max_chunk_rows)
        elif max_chunk_rows is not None:
            raise ValueError("max_chunk_rows requires prefill_chunk")
        # --- speculative decoding (paged-only): a prefix-slice draft
        # proposes spec_k tokens per tick, the target verifies all
        # spec_k+1 positions in one batched forward
        self.spec_k = int(spec_k)
        if self.spec_k < 0:
            raise ValueError(f"spec_k must be >= 0, got {spec_k}")
        self.draft_blocks = (
            int(draft_blocks) if draft_blocks is not None else None
        )
        if self.spec_k > 0:
            if not self._paged:
                raise ValueError(
                    "spec_k requires kv_layout='paged' (the draft "
                    "shares the target's stage-0 page slabs)"
                )
            if self.draft_blocks is None:
                raise ValueError(
                    "spec_k > 0 requires draft_blocks (the prefix-"
                    "slice depth of the draft model)"
                )
        self._draft: Optional[DraftModel] = None
        # static_batching is the NAIVE baseline policy, kept on the same
        # kernels so tools/bench_serving.py isolates the scheduling
        # policy: requests join only at batch boundaries (when the
        # running batch has fully drained), so every member waits for
        # the slowest — the failure mode continuous batching removes
        self.static_batching = bool(static_batching)
        self.stats = ServingStats()
        # same snapshot() contract as the training runner's registry, so
        # one poller reads either subsystem identically
        self.metrics = MetricsRegistry()
        self.metrics.register("serving", lambda: self.stats.snapshot(),
                              types=ServingStats.FIELD_TYPES)
        # trace attribution name for request-scoped spans; a fleet
        # replica overwrites this with its replica name so a migrated
        # request's waterfall says WHERE each segment ran
        self.trace_name = "engine"
        # live observability (LiveMetricsMixin: enable_timeseries /
        # start_exporter — opt-in, zero-cost until enabled; step()
        # samples the series when one is attached)
        self.timeseries = None
        self._exporter = None
        self._running: Dict[int, Request] = {}  # request_id -> Request
        # chunked-prefill ledger: requests holding a page grant and a
        # decode row whose prefilled_len watermark has not reached the
        # end of their effective prompt (insertion order = enrollment
        # FIFO, which chunk waves honor head-first)
        self._prefilling: Dict[int, Request] = {}
        self._finished: List[Request] = []
        # closed-loop tuning: when set (tuning.ServingAutotuner attaches
        # itself here), every step ends with an observe/decide callback —
        # the serving twin of the Runner's AutotuneHook
        self.autotuner = None

        self._devices = (
            list(devices) if devices is not None else jax.devices()
        )
        # retained for reconfigure's re-run of the serving pre-flight
        # (slab memory vs budgets) against a proposed operating point;
        # the preflight opt-out carries over so both checks agree
        self._model_cfg = list(model_cfg)
        self._worker_manager = worker_manager
        self._preflight = bool(preflight)
        counts, stage_devices = self._resolve_stage_plan(
            worker_manager, partition, len(modules)
        )
        # the draft's only RESIDENT cost: a copy of the LM-head params
        # on stage 0's device when the head lives on another stage —
        # computed BEFORE the pre-flight so the verifier charges it
        self._draft_mb = (
            tree_param_mb(list(params_list)[-1])
            if self.spec_k > 0 and len(counts) > 1 else 0.0
        )
        if preflight and worker_manager is not None:
            # slabs allocate eagerly below, so an over-budget serving
            # plan must die HERE — before any slab materializes or any
            # stage program compiles — with the serving context named
            from ..analysis.plan_check import verify_plan

            verify_plan(
                list(model_cfg), worker_manager,
                (np.zeros((self.num_slots, 1), np.int32),),
                memory="error", check_donation=False,
                serving=self._serving_context(),
            ).raise_if_failed()
        if len(params_list) != len(modules):
            raise ValueError(
                f"got {len(params_list)} param trees for "
                f"{len(modules)} layers"
            )
        # paged host state: ONE page pool governs the page-id space
        # across all stages (page p = row p of every stage's slabs, the
        # paged twin of cross-stage slot ids); rows are the decode
        # concurrency lanes, shared as every stage's `.pool` facade so
        # fleet slot accounting / chaos leaks work unchanged
        if self._paged:
            self._pool = PagedKVCachePool(
                self.num_pages, self.page_size,
                self.max_pages_per_request,
                enable_prefix_cache=self.enable_prefix_cache,
                max_prefix_entries=self._max_prefix_entries,
                kv_dtype=self._pool_kv_dtype(),
            )
            self._rows = RowAllocator(self.max_concurrency)
            # request_id -> host page copies + resume state (swap pool)
            self._swapped: Dict[int, Dict[str, Any]] = {}
        else:
            self._pool = None
            self._rows = None
            self._swapped = {}
        # banked totals of pools replaced by reconfigure (counter
        # monotonicity across geometry changes)
        self._pool_base = dict(
            prefix_hits=0, prefix_tokens_reused=0, cow_copies=0,
            prefix_evictions=0,
        )
        self.stages: List[Any] = []
        cursor = 0
        for k, (n, dev) in enumerate(zip(counts, stage_devices)):
            # everything the traced programs depend on: the exact layer
            # configs of this stage's slice, the layout, the cache
            # depth, and the donation mode (the input SHAPES — bucket,
            # slot/row count, page geometry — are jit cache keys
            # already, not closure identity)
            program_key = json.dumps(
                [self._model_cfg[cursor:cursor + n], self.kv_layout,
                 self.max_len, bool(_donation_enabled()),
                 self.kv_dtype, self.attn_impl],
                sort_keys=True, default=str,
            )
            if self._paged:
                stage = _PagedServingStage(
                    k,
                    modules[cursor:cursor + n],
                    list(params_list)[cursor:cursor + n],
                    dev,
                    self.num_pages,
                    self.page_size,
                    program_key=program_key,
                    kv_dtype=self.kv_dtype,
                    attn_impl=self.attn_impl,
                )
                stage.pool = self._rows  # shared row ledger facade
            else:
                stage = _ServingStage(
                    k,
                    modules[cursor:cursor + n],
                    list(params_list)[cursor:cursor + n],
                    dev,
                    self.num_slots,
                    self.max_len,
                    program_key=program_key,
                )
            self.stages.append(stage)
            cursor += n
        self._last_device = self.stages[-1].device
        if self.spec_k > 0:
            self._draft = self._build_draft()
            # one source of truth for the resident charge (the
            # pre-stage estimate above used the same head params)
            self._draft_mb = self._draft.extra_param_mb

    def _pool_kv_dtype(self) -> str:
        """The page pool's storage dtype string: the quantization knob
        when set, else the model dtype — what the allocator accounts
        and the verifier charges (one formula, paging.paged_pool_mb)."""
        if self.kv_dtype is not None:
            return self.kv_dtype
        return str(_gcfg(self._model_cfg[0]["config"]).dtype)

    def _serving_context(self) -> Dict[str, Any]:
        """The operating point the pre-flight verifier charges."""
        if self._paged:
            ctx = dict(
                num_pages=self.num_pages, page_size=self.page_size,
                max_pages_per_request=self.max_pages_per_request,
                bucket=self.bucketer.max_bucket,
            )
            if self.kv_dtype is not None:
                # the quantized byte width (+ scale slabs) is what the
                # slabs will actually allocate — the verifier must
                # charge the same formula or the two could disagree
                ctx["kv_dtype"] = self.kv_dtype
            if self._draft_mb:
                # the speculative draft's head copy is real stage-0
                # residency — the verifier must see it
                ctx["draft_mb"] = self._draft_mb
            return ctx
        return dict(
            slots=self.num_slots, max_len=self.max_len,
            bucket=self.bucketer.max_bucket,
        )

    def _set_chunking(self, prefill_chunk: int,
                      max_chunk_rows: Optional[int]) -> None:
        """Validate + install the chunked-prefill operating point.
        ``prefill_chunk`` must be one of the prefill buckets so chunk
        waves reuse the per-bucket prefill programs (the recompile pin
        holds with zero new shapes)."""
        if prefill_chunk not in self.bucketer.buckets:
            raise ValueError(
                f"prefill_chunk={prefill_chunk} must be one of the "
                f"prefill buckets {list(self.bucketer.buckets)} — chunk "
                f"waves reuse the bucket programs"
            )
        rows = (
            int(max_chunk_rows) if max_chunk_rows is not None
            else self.prefill_batch
        )
        policy = ChunkBudgetPolicy(
            prefill_chunk, max_chunk_rows=rows,
            idle_chunk_rows=max(rows, self.prefill_batch * 2),
        )
        self.prefill_chunk = int(prefill_chunk)
        self.max_chunk_rows = rows
        self._chunk_policy = policy

    def _build_draft(self) -> DraftModel:
        """Construct the prefix-slice draft on stage 0 (fallible —
        called before any state mutates, both at construction and when
        ``reconfigure`` enables speculation)."""
        full_modules = [m for st in self.stages for m in st.modules]
        idx = draft_slice_indices(full_modules, self.draft_blocks)
        cut = idx[-2] + 1  # prefix length (idx = range(cut) + [head])
        stage0, last = self.stages[0], self.stages[-1]
        if cut > len(stage0.modules):
            raise ValueError(
                f"draft_blocks={self.draft_blocks} needs the first "
                f"{cut} layers resident on stage 0, which holds only "
                f"{len(stage0.modules)} — shrink the draft or deepen "
                f"stage 0 (the draft shares stage 0's params and slabs)"
            )
        head_module = full_modules[-1]
        if len(self.stages) > 1:
            head_params = jax.device_put(last.params[-1], stage0.device)
            extra_mb = tree_param_mb(head_params)
        else:
            head_params = stage0.params[-1]
            extra_mb = 0.0
        key = DraftModel.program_key(
            [self._model_cfg[i] for i in idx], self.max_len,
            attn_impl=self.attn_impl, kv_dtype=self.kv_dtype,
        )
        return DraftModel(
            list(stage0.modules[:cut]) + [head_module],
            list(stage0.params[:cut]) + [head_params],
            stage0.device,
            extra_param_mb=extra_mb,
            program_key=key,
            attn_impl=self.attn_impl,
        )

    def _pending_draft_mb(self) -> float:
        """The draft memory a spec-enable would ADD to stage 0 (the
        LM-head copy; 0 when it already lives there) — computed without
        allocating anything, so the pre-flight can charge it BEFORE
        :meth:`_build_draft` performs the device_put."""
        if len(self.stages) <= 1:
            return 0.0
        return tree_param_mb(self.stages[-1].params[-1])

    # --- construction helpers ----------------------------------------------
    def _resolve_stage_plan(self, worker_manager, partition, n_layers):
        """(layer counts, devices) per stage, from an allocator-written
        worker pool, an explicit partition, or the 1-stage default."""
        if worker_manager is not None and partition is not None:
            raise ValueError("pass worker_manager OR partition, not both")
        if worker_manager is not None:
            # the verifier's stage ordering (plan_check._stage_workers):
            # rank-sorted non-empty workers — one definition, so the
            # engine and the pre-flight can never disagree on stages
            from ..analysis.plan_check import _stage_workers

            workers = _stage_workers(worker_manager)
            counts = [len(w.model_config) for w in workers]
            stage_devices = [
                self._devices[w.device_index % len(self._devices)]
                for w in workers
            ]
        else:
            counts = (
                [int(c) for c in partition]
                if partition is not None else [n_layers]
            )
            stage_devices = [
                self._devices[k % len(self._devices)]
                for k in range(len(counts))
            ]
        if sum(counts) != n_layers or any(c < 1 for c in counts):
            raise ValueError(
                f"partition {counts} does not cover {n_layers} layers"
            )
        return counts, stage_devices

    # --- slot ledger (slot ids are global across stages) -------------------
    @property
    def free_slots(self) -> int:
        return self.stages[0].pool.free_slots

    def _allocate_slot(self) -> Optional[int]:
        if self._paged:
            # one shared row ledger (every stage's .pool IS self._rows)
            return self._rows.allocate()
        slot = self.stages[0].pool.allocate()
        if slot is None:
            return None
        for st in self.stages[1:]:
            st.pool.acquire(slot)
        return slot

    def _release_slot(self, slot: int) -> None:
        if self._paged:
            self._rows.release(slot)
            return
        for st in self.stages:
            st.pool.release(slot)

    # --- request-scoped tracing ---------------------------------------------
    # One stable id (request_id) threads the whole waterfall: every
    # segment span lands on the request's recycled trace lane with a
    # {"request", "replica"} attribution, and the open-segment mark
    # lives on the Request object itself so whoever ends the segment —
    # this engine, another engine after a migration, or the fleet over
    # a dead replica — can close it.  All helpers are no-ops when
    # tracing is disabled (tracer is None).

    def _trace_queued(self, request: Request, tracer) -> None:
        """Open a ``queue_wait`` segment (mark + ``queued`` instant)."""
        if tracer is None:
            return
        request.trace_marks["queued"] = tracer.now()
        lane = tracer.request_lane(request.request_id)
        if lane is not None:
            tracer.instant(
                "queued", lane,
                {"request": request.request_id,
                 "replica": self.trace_name},
            )

    def _trace_close_queue(self, request: Request, tracer,
                           end_us: Optional[float] = None,
                           **extra) -> None:
        """Close the open ``queue_wait`` segment, if any."""
        if tracer is None:
            return
        mark = request.trace_marks.pop("queued", None)
        if mark is None:
            return
        lane = tracer.request_lane(request.request_id, lease=False)
        if lane is None:
            return
        end = tracer.now() if end_us is None else end_us
        args = {"request": request.request_id,
                "replica": self.trace_name}
        args.update(extra)
        tracer.complete("queue_wait", lane, mark, args,
                        dur_us=end - mark)

    def _trace_enroll(self, request: Request, grant, tracer) -> None:
        """Chunked enrollment: admission instant, queue segment closed,
        and the request-lane ``prefill`` segment OPENED (it spans
        enrollment -> final chunk, closed by ``_trace_close_prefill``)."""
        if tracer is None:
            return
        now_us = tracer.now()
        tracer.instant(
            "admit", tracer.lane("serving", "engine"),
            {"request": request.request_id, "slot": request.slot,
             "pages": len(grant.page_table),
             "shared": grant.shared_tokens, "chunked": True},
        )
        self._trace_close_queue(request, tracer, end_us=now_us)
        request.trace_marks["prefill"] = now_us

    def _trace_close_prefill(self, request: Request, tracer,
                             end_us: Optional[float] = None,
                             **extra) -> None:
        """Close the open chunked ``prefill`` segment, if any."""
        if tracer is None:
            return
        mark = request.trace_marks.pop("prefill", None)
        if mark is None:
            return
        lane = tracer.request_lane(request.request_id, lease=False)
        if lane is None:
            return
        end = tracer.now() if end_us is None else end_us
        args = {"request": request.request_id,
                "replica": self.trace_name}
        args.update(extra)
        tracer.complete("prefill", lane, mark, args,
                        dur_us=end - mark)

    def _trace_close_decode(self, request: Request, tracer,
                            **extra) -> None:
        """Close the open ``decode`` segment, if any."""
        if tracer is None:
            return
        mark = request.trace_marks.pop("decode", None)
        if mark is None:
            return
        lane = tracer.request_lane(request.request_id, lease=False)
        if lane is None:
            return
        args = {"request": request.request_id,
                "replica": self.trace_name,
                "tokens": len(request.tokens)}
        args.update(extra)
        tracer.complete("decode", lane, mark, args)

    # --- request lifecycle --------------------------------------------------
    def submit(self, request: Request, *, force: bool = False) -> Request:
        """Queue a request (admitted into a slot on a later ``step``).

        With ``max_queue`` set, a full queue applies ``queue_policy``:
        ``"reject"`` refuses the newcomer (:class:`QueueFullError`
        propagates), ``"shed"`` displaces the oldest token-less queued
        request(s) — under overload the head has waited longest and is
        the most likely to have already blown its deadline — marking
        them ``REJECTED``.  Requests with committed tokens or a
        preemption history are never shed (their stream, or the
        admission promise already made for them, would be lost); when
        nothing is sheddable, ``"shed"`` degrades to reject.  Either
        way ``stats.queue_rejections`` counts every turned-away
        request: shedding is only acceptable when visible.

        ``force=True`` bypasses the bound and the policy — for
        re-queues of ALREADY-ADMITTED requests only (the fleet's
        migration path; preempt/reconfigure force internally): an
        admission promise, once made, survives a replica failure.
        """
        length = int(request.effective_prompt.size)
        if length + request.remaining > self.max_len:
            raise ValueError(
                f"prompt ({length}) + new tokens ({request.remaining}) "
                f"exceed max_len={self.max_len}"
            )
        tracer = get_tracer()
        try:
            # raises QueueFullError on a full bounded queue (unless
            # forced) and ValueError if no bucket fits
            self._queue.submit(request, force=force)
        except QueueFullError:
            if self.queue_policy == "shed":
                # shed until the newcomer fits: force re-queues
                # (preemption/reconfigure/migration) may have pushed the
                # queue past the bound, so one victim is not always
                # enough; requests with committed tokens are never
                # victims (shed_oldest), and when nothing is sheddable
                # the policy degrades to reject — losing generated
                # tokens is worse than turning a newcomer away
                while self._queue.depth >= (self.max_queue or 0):
                    shed = self._queue.shed_oldest()
                    if shed is None:
                        break
                    shed.status = REJECTED
                    self.stats.queue_rejections += 1
                    if tracer is not None:
                        tracer.instant(
                            "queue_shed",
                            tracer.lane("serving", "engine"),
                            {"shed": shed.request_id,
                             "admitted": request.request_id},
                        )
                        self._trace_close_queue(shed, tracer,
                                                shed=True)
                        lane = tracer.request_lane(
                            shed.request_id, lease=False)
                        if lane is not None:
                            tracer.instant(
                                "shed", lane,
                                {"request": shed.request_id,
                                 "replica": self.trace_name},
                            )
                        tracer.release_request_lane(shed.request_id)
                if self._queue.depth < (self.max_queue or 0):
                    self._queue.submit(request)
                    self.stats.admitted += 1
                    self.stats.queue_depth = self._queue.depth
                    self._trace_queued(request, tracer)
                    return request
            self.stats.queue_rejections += 1
            if tracer is not None:
                tracer.instant(
                    "queue_reject", tracer.lane("serving", "engine"),
                    {"request": request.request_id,
                     "depth": self._queue.depth},
                )
            raise
        self.stats.admitted += 1
        self.stats.queue_depth = self._queue.depth
        self._trace_queued(request, tracer)
        return request

    def preempt(self, request_id: int,
                mode: Optional[str] = None) -> Request:
        """Evict a running request; it re-queues and resumes with its
        token stream intact.

        Slot layout: always recomputation-style (the KV prefix is
        rebuilt on re-admission).  Paged layout: ``mode`` (or the
        engine's ``preempt_policy``) picks between **recompute** and
        **swap** — page contents copied to a host pool and paged back
        in on re-admission, no prefill replay.  ``"auto"`` chooses by
        resume cost (``paging.choose_preempt_mode``): recompute replays
        ``len(effective_prompt)`` tokens of prefill, swap moves the
        request's pages over the host link twice; a resume prefix that
        has outgrown every bucket forces swap — the case recomputation
        structurally cannot serve.
        """
        request = self._running.get(request_id)
        prefilling = False
        if request is None:
            request = self._prefilling.get(request_id)
            prefilling = request is not None
        if request is None:
            raise KeyError(f"request {request_id} is not running")
        if mode not in (None, "auto", "recompute", "swap"):
            # validate BEFORE any state is touched: an unknown mode
            # falling through the branches below would tear the request
            # down and then fail to re-queue it
            raise ValueError(
                f"preempt mode must be 'auto', 'recompute' or 'swap', "
                f"got {mode!r}"
            )
        if prefilling and mode == "swap":
            # a partial prefill's pages hold an incomplete prompt; a
            # swap record would resume mid-watermark on an engine that
            # may no longer chunk — recomputation replays it exactly
            raise ValueError(
                "a mid-prefill request preempts by recomputation only"
            )
        resume_len = int(request.effective_prompt.size)
        if not self._paged:
            if mode not in (None, "recompute"):
                raise ValueError(
                    f"slot engines only preempt by recomputation, "
                    f"got mode={mode!r}"
                )
            # validate the resume prefix fits a bucket BEFORE touching
            # any state: a request grown past the largest bucket cannot
            # resume by recomputation, and a failed preempt must leave
            # it running
            self.bucketer.bucket_for(resume_len)
            mode = "recompute"
        elif prefilling:
            # validate the resume prefix still fits a bucket (the
            # re-queue requires one), then recompute — no tokens were
            # generated yet, so the replay is the same admission the
            # request already passed
            self.bucketer.bucket_for(resume_len)
            mode = "recompute"
        else:
            try:
                self.bucketer.bucket_for(resume_len)
                fits = True
            except ValueError:
                fits = False
            if mode is None:
                mode = self.preempt_policy
            if mode == "auto":
                mode = choose_preempt_mode(
                    resume_len, len(self._pool.table(request_id)),
                    self.page_size, recompute_feasible=fits,
                )
            if mode == "recompute" and not fits:
                # surface the same diagnostic the slot path raises
                self.bucketer.bucket_for(resume_len)
        swap_record = None
        if mode == "swap":
            # host copies BEFORE any state mutates: a sentinel-padded
            # table keeps the gathered shape fixed, and np.asarray
            # forces the device work before the pages are freed
            table = np.full(
                (self.max_pages_per_request,), self.num_pages, np.int32
            )
            held = self._pool.table(request_id)
            table[: len(held)] = held
            data = [st.swap_out(table) for st in self.stages]
            swap_record = dict(
                pages=len(held), index=request.index, data=data,
                # integrity stamp, verified at swap-in: a record
                # corrupted while parked on the host must fall back to
                # recompute, never restore poisoned KV
                checksum=_swap_record_checksum(
                    len(held), request.index, data
                ),
            )
        if prefilling:
            self._prefilling.pop(request_id)
            request.prefilled_len = 0  # recompute replays the tail
        else:
            self._running.pop(request_id)
        self._release_slot(request.slot)
        if self._paged:
            self._pool.release(request_id)
        request.slot = None
        request.preemptions += 1
        self.stats.preemptions += 1
        if swap_record is not None:
            self._swapped[request_id] = swap_record
            self.stats.swap_outs += 1
        tracer = get_tracer()
        if tracer is not None:
            tracer.instant(
                "preempt", tracer.lane("serving", "engine"),
                {"request": request_id, "mode": mode},
            )
            # the request's decode segment ends here (the engine-lane
            # preempt instant above already carries the request id, so
            # the timeline keeps its marker without a duplicate that
            # would double trace-derived preemption counts); a
            # mid-prefill victim closes its chunked prefill segment
            self._trace_close_decode(request, tracer, preempted=True)
            self._trace_close_prefill(request, tracer, preempted=True)
        # force: the queue bound gates NEW admissions only — a preempted
        # request is already admitted and dropping it loses its tokens.
        # A swapped request needs no prefill bucket (its KV returns from
        # the host pool verbatim), so the bucket check is skipped — that
        # is exactly what lets swap serve resume prefixes recomputation
        # cannot.
        self._queue.submit(request, force=True,
                           require_bucket=(mode != "swap"))
        self.stats.queue_depth = self._queue.depth
        self._trace_queued(request, tracer)
        return request

    def drain(self) -> List[Request]:
        """Evict everything and return it, token streams intact: every
        running request is preempted (recomputation-style) and the queue
        emptied, FIFO order.  The fleet's migration primitive — the
        returned requests re-submit on another engine and resume by
        recomputing their KV prefix, so streams continue exactly.

        A running request whose resume prefix has outgrown the largest
        bucket cannot resume by recomputation; it STAYS RUNNING here
        (``preempt``'s validate-before-evict contract) and is not
        returned — the caller decides whether to keep stepping this
        engine until it finishes or declare it failed.

        Paged engines drain recomputation-style too: swap records are
        host-local (another engine has no access to this one's host
        pool), so migration resumes by re-prefilling the effective
        prompt — and any swap records held for queued requests are
        dropped with the same consequence."""
        for request_id in list(self._running) + list(self._prefilling):
            try:
                # cross-engine resume is recompute by construction
                self.preempt(request_id, mode="recompute")
            except ValueError:
                continue  # documented: not resumable, stays running
        drained = self._queue.drain()
        if self._paged:
            for r in drained:
                self._swapped.pop(r.request_id, None)
        tracer = get_tracer()
        if tracer is not None:
            # each drained request's queue_wait segment ends HERE (on
            # this engine); re-submission elsewhere opens a fresh one —
            # the migration gap stays visible, never an orphaned mark
            for r in drained:
                self._trace_close_queue(r, tracer, drained=True)
        self.stats.queue_depth = 0
        return drained

    def corrupt_swap_record(self, request_id: Optional[int] = None,
                            *, force: bool = False) -> Optional[int]:
        """Flip bits in a held swap record's host payload (the
        sanctioned ``swap_corruption`` chaos hook — host-pool rot,
        a DMA gone wrong — applied through the record surface, never
        by monkeypatching).

        Targets ``request_id``'s record when given, else the oldest
        held record.  With ``force`` and nothing parked, the oldest
        running request is swapped out first through the public
        ``preempt`` path (so there is always a record to poison).
        Returns the corrupted record's request id, or None when no
        record exists and none can be forced — the injector logs that
        honestly instead of inventing a fault that never happened."""
        if not self._paged:
            raise ValueError(
                "swap records exist on paged engines only"
            )
        if request_id is not None:
            if request_id not in self._swapped:
                raise KeyError(
                    f"request {request_id} holds no swap record"
                )
            rid = request_id
        elif self._swapped:
            rid = min(self._swapped)
        else:
            rid = None
            if force:
                # oldest running request first: smallest id = the
                # record most likely to be swapped back in soon
                for cand in sorted(self._running):
                    try:
                        self.preempt(cand, mode="swap")
                    except (ValueError, KeyError):
                        continue
                    rid = cand
                    break
            if rid is None:
                return None
        record = self._swapped[rid]
        pairs = record["data"][0]
        k_host, v_host = pairs[0]
        leaf = k_host.values if isinstance(k_host, QuantizedPages) \
            else k_host
        raw = bytearray(np.ascontiguousarray(leaf).tobytes())
        raw[0] ^= 0xFF
        bad = np.frombuffer(bytes(raw), dtype=leaf.dtype).reshape(
            leaf.shape
        )
        if isinstance(k_host, QuantizedPages):
            k_host = QuantizedPages(bad, k_host.scale)
        else:
            k_host = bad
        pairs[0] = (k_host, v_host)
        return rid

    # --- the disaggregated prefill/decode handoff plane ---------------------
    def export_handoff(self, request_id: int) -> tuple:
        """Detach a decoding request as a portable handoff: the request
        (token stream intact) plus its swap record (host page copies +
        checksum), ready for another engine's :meth:`import_handoff`.

        Rides the public preempt path in ``swap`` mode verbatim — same
        host copies, same checksum stamp, same fixed gather shape — so
        a handoff export counts as a preemption + swap-out in the
        stats, and the record popped here is byte-identical to what a
        local swap-in would have restored.  Only a request PAST prefill
        can export (its first token is seeded and its KV watermark is
        page-complete); mid-prefill requests raise, exactly as
        ``preempt(mode="swap")`` does.  The caller (the disagg pool
        front door) owns delivering the pair and conserving it in a
        ledger — after this returns, this engine holds NO state for the
        request."""
        if not self._paged:
            raise ValueError(
                "handoff export needs a paged engine (swap records are "
                "the carrier)"
            )
        request = self._running.get(request_id)
        if request is None:
            raise KeyError(
                f"request {request_id} is not decoding here"
            )
        if not request.tokens:
            raise ValueError(
                "a request hands off only after prefill seeded its "
                "first token"
            )
        if request.done:
            raise ValueError(
                "a finished request has nothing left to hand off"
            )
        self.preempt(request_id, mode="swap")
        record = self._swapped.pop(request_id)
        self._queue.remove(request)
        self.stats.queue_depth = self._queue.depth
        self.stats.handoffs_out += 1
        self.stats.handoff_bytes += _swap_record_nbytes(record["data"])
        tracer = get_tracer()
        if tracer is not None:
            tracer.instant(
                "handoff_out", tracer.lane("serving", "engine"),
                {"request": request_id, "pages": record["pages"]},
            )
            # the queue segment preempt just opened ends here: the
            # request leaves this engine entirely (the importing side
            # opens its own)
            self._trace_close_queue(request, tracer, drained=True)
        return request, record

    def import_handoff(self, request: Request, record: dict) -> bool:
        """Seat an exported handoff for swap-in resume — checksum
        verified FIRST, before the record touches any engine state.

        True: the record passed its integrity gate and is parked; the
        admission loop's existing swap-in path (``_admit_paged`` →
        ``_swap_in``) restores the pages with NO prefill and decoding
        continues at the record's index — the resume path IS the
        swap-in path, no new compile shapes.  False: the checksum did
        not match (or the payload shape cannot fit this engine), the
        poisoned record is refused, ``handoff_failures`` counts it, and
        the request re-queues to recompute from its prompt — committed
        tokens intact, so the stream is exact either way.  A corrupt
        record whose resume prefix fits no bucket is FAILED with a
        reasoned verdict, mirroring ``_swap_in``'s corruption verdict.
        """
        if not self._paged:
            raise ValueError(
                "handoff import needs a paged engine (swap records are "
                "the carrier)"
            )
        rid = request.request_id
        if (rid in self._running or rid in self._prefilling
                or rid in self._swapped
                or any(r is request for r in self._queue.requests)):
            raise ValueError(
                f"request {rid} is already live on this engine"
            )
        pages = record.get("pages")
        index = record.get("index")
        data = record.get("data")
        ok = (
            isinstance(pages, int) and 1 <= pages
            and pages <= self.max_pages_per_request
            and isinstance(index, int) and index >= 1
            and isinstance(data, list) and len(data) == len(self.stages)
        )
        if ok:
            expect = record.get("checksum")
            ok = (expect is not None
                  and _swap_record_checksum(pages, index, data)
                  == expect)
        tracer = get_tracer()
        if ok:
            self._swapped[rid] = record
            # bytes were counted once at export — the exporting side
            # owns the payload accounting, so a fleet-level sum over
            # both pools counts each handoff's bytes exactly once
            self.stats.handoffs_in += 1
        else:
            self.stats.handoff_failures += 1
            if tracer is not None:
                tracer.instant(
                    "handoff_corrupt", tracer.lane("serving", "engine"),
                    {"request": rid},
                )
            try:
                self.bucketer.bucket_for(
                    int(request.effective_prompt.size)
                )
            except ValueError:
                request.status = FAILED
                request.fail_reason = (
                    "handoff record corrupted and the resume prefix "
                    "fits no bucket"
                )
                return False
        # force: the handoff was admitted on the exporting pool — the
        # promise survives the pool boundary; a verified record resumes
        # bucket-free (swap-in), a refused one re-buckets to recompute
        self._queue.submit(request, force=True, require_bucket=not ok)
        self.stats.queue_depth = self._queue.depth
        if tracer is not None:
            tracer.instant(
                "handoff_in", tracer.lane("serving", "engine"),
                {"request": rid, "verified": ok},
            )
        self._trace_queued(request, tracer)
        return ok

    @property
    def running_requests(self) -> List[Request]:
        """Requests currently holding a slot/row (read-only view).
        Includes chunked-prefill requests mid-watermark: they hold a
        decode row and a page grant, so fleet slot-accounting and
        migration must see them as live."""
        return list(self._prefilling.values()) + list(
            self._running.values()
        )

    @property
    def queued_requests(self) -> List[Request]:
        """Requests waiting for admission, FIFO order (read-only view)."""
        return list(self._queue.requests)

    def _finish(self, request: Request, now: float) -> None:
        self._release_slot(request.slot)
        if self._paged:
            # pages the radix index still references survive the
            # release — the prefix cache's retention, not a leak
            self._pool.release(request.request_id)
        request.slot = None
        request.status = FINISHED
        request.finished_s = now
        self._running.pop(request.request_id, None)
        self._finished.append(request)
        self.stats.finished += 1
        ttft = request.ttft_s()
        tpot = request.tpot_s()
        if ttft is not None:
            self.stats.ttft_s.append(ttft)
        if tpot is not None:
            self.stats.tpot_s.append(tpot)
        tracer = get_tracer()
        if tracer is not None:
            # terminal: close the decode segment, stamp the finish, and
            # recycle the request's lane for the next live request
            self._trace_close_decode(request, tracer)
            lane = tracer.request_lane(request.request_id,
                                       lease=False)
            if lane is not None:
                tracer.instant(
                    "finish", lane,
                    {"request": request.request_id,
                     "replica": self.trace_name,
                     "tokens": len(request.tokens)},
                )
            tracer.release_request_lane(request.request_id)

    # --- the continuous-batching loop ---------------------------------------
    def has_work(self) -> bool:
        return (bool(self._running) or bool(self._prefilling)
                or self._queue.depth > 0)

    def step(self) -> None:
        """One engine iteration: admit prefill waves (or, with
        ``prefill_chunk`` set, enroll admissions and advance at most a
        budgeted number of prefill chunks), then one decode tick over
        the slot slab.  Requests join and leave the running batch only
        here, between decode steps — iteration-level scheduling; the
        chunk budget bounds how much prefill any single decode tick
        can wait behind."""
        if self._queue.depth > 0 and self.free_slots == 0:
            self.stats.queue_stalls += 1
            tracer = get_tracer()
            if tracer is not None:
                tracer.instant(
                    "queue_stall", tracer.lane("serving", "engine"),
                    {"queued": self._queue.depth},
                )
        if self._paged:
            self._admit_paged()
            if self._chunk_policy is not None:
                self._chunk_tick()
            if self.spec_k > 0 and self._draft is not None:
                self._spec_tick()
            else:
                self._decode_tick_paged()
        else:
            self._admit()
            self._decode_tick()
        self.stats.iterations += 1
        self.stats.queue_depth = self._queue.depth
        self.stats.batch_occupancy = self.stages[0].pool.occupancy
        if self._paged:
            self._sync_paged_stats()
        if self.timeseries is not None:
            self.timeseries.sample()
        if self.autotuner is not None:
            self.autotuner.on_step(self)

    def _sync_paged_stats(self) -> None:
        """Mirror the page pool's counters/gauges into ``ServingStats``
        (one owner for the numbers — the pool — one surface for the
        exporter).  ``_pool_base`` banks a replaced pool's totals so a
        geometry reconfigure never makes an engine-lifetime counter go
        backwards (the discipline ``FIELD_TYPES`` promises)."""
        pool, base = self._pool, self._pool_base
        self.stats.prefix_hits = base["prefix_hits"] + pool.prefix_hits
        self.stats.prefix_tokens_reused = (
            base["prefix_tokens_reused"] + pool.prefix_tokens_reused
        )
        self.stats.cow_copies = base["cow_copies"] + pool.cow_copies
        self.stats.prefix_evictions = (
            base["prefix_evictions"] + pool.prefix_evictions
        )
        self.stats.pages_in_use = pool.pages_in_use
        self.stats.free_pages = pool.free_pages

    def reconfigure(
        self,
        *,
        buckets: Optional[Sequence[int]] = None,
        num_slots: Optional[int] = None,
        prefill_batch: Optional[int] = None,
        num_pages: Optional[int] = None,
        page_size: Optional[int] = None,
        max_pages_per_request: Optional[int] = None,
        max_concurrency: Optional[int] = None,
        prefill_chunk: Optional[int] = None,
        max_chunk_rows: Optional[int] = None,
        spec_k: Optional[int] = None,
    ) -> None:
        """Apply a new serving operating point IN PLACE, between steps.

        The act half of the serving tuning loop: bucket set, slot count,
        and prefill wave width are all shape knobs, so changing them
        means new compiled programs — but not a new engine.  The queue
        re-buckets under the new set; ONLY a slot-count change (which
        rebuilds the per-stage slabs) additionally evicts the running
        batch recomputation-style (the :meth:`preempt` machinery: token
        streams preserved exactly, KV prefixes rebuilt on re-admission)
        — bucket/wave-width changes leave running requests decoding
        untouched.

        Verify-then-apply: the knob set passes the pre-flight verifier
        (``analysis/plan_check.verify_tuning_knobs``), a slot-count
        change re-runs the constructor's serving memory pre-flight
        (budget-charged slabs, when the engine was built from a worker
        manager) AND pre-builds the new slabs, and every live request
        is proven to fit the new bucket set — all BEFORE any state is
        touched, so a rejected reconfigure (:class:`PlanError` /
        ``ValueError`` / a slab-allocation failure) leaves the engine
        exactly as it was.

        Paged engines (``kv_layout="paged"``) additionally learn
        ``num_pages``/``page_size``/``max_pages_per_request``/
        ``max_concurrency`` (``num_slots`` aliases ``max_concurrency``,
        so the autotuner's slot proposals keep working unchanged):
        bucket and wave-width changes stay eviction-free, a
        concurrency change re-seats the running batch
        recomputation-style on the SAME page pool (swap records stay
        valid), and a page-geometry change rebuilds pool + slabs —
        running requests resume by recomputation, the prefix cache
        restarts cold (its counters banked, never reset), and host
        swap records (whose page shapes died with the geometry)
        convert to recomputation resumes only after every affected
        request is proven to fit a prefill bucket.  Paged engines also
        learn the scheduler knobs ``prefill_chunk``/``max_chunk_rows``
        (chunked prefill) and ``spec_k`` (speculative decoding) — see
        :meth:`_reconfigure_paged` for their enable/disable semantics.
        """
        from ..analysis.plan_check import verify_tuning_knobs

        if self._paged:
            self._reconfigure_paged(
                buckets=buckets, num_slots=num_slots,
                prefill_batch=prefill_batch, num_pages=num_pages,
                page_size=page_size,
                max_pages_per_request=max_pages_per_request,
                max_concurrency=max_concurrency,
                prefill_chunk=prefill_chunk,
                max_chunk_rows=max_chunk_rows, spec_k=spec_k,
            )
            return
        if any(k is not None for k in
               (num_pages, page_size, max_pages_per_request,
                max_concurrency, prefill_chunk, max_chunk_rows,
                spec_k)):
            raise ValueError(
                "page knobs (num_pages/page_size/max_pages_per_request/"
                "max_concurrency/prefill_chunk/max_chunk_rows/spec_k) "
                "require kv_layout='paged'"
            )
        if buckets is not None:
            # same normalization the constructor's ShapeBucketer applies,
            # so reconfigure accepts exactly the inputs construction
            # does; a malformed entry is left raw for the knob verifier
            # to reject with a diagnostic (never a bare TypeError here)
            try:
                new_buckets = tuple(sorted(set(int(b) for b in buckets)))
            except (TypeError, ValueError):
                new_buckets = tuple(buckets)
        else:
            new_buckets = self.bucketer.buckets
        new_slots = (
            int(num_slots) if num_slots is not None else self.num_slots
        )
        new_batch = (
            int(prefill_batch)
            if prefill_batch is not None else self.prefill_batch
        )
        verify_tuning_knobs(
            buckets=new_buckets, max_len=self.max_len,
            num_slots=new_slots, prefill_batch=new_batch,
        ).raise_if_failed()
        if (self._preflight and self._worker_manager is not None
                and (new_slots != self.num_slots
                     or max(new_buckets) > self.bucketer.max_bucket)):
            # same pre-flight the constructor ran, against the PROPOSED
            # operating point: a slab or prefill activation that no
            # longer fits the budgets (more slots, OR a raised max
            # bucket) must be rejected abstractly, not discovered as an
            # allocation OOM mid-serving.  A slot change is charged at
            # old+new slots: the atomic apply below holds BOTH pools
            # resident for a moment, and that transient peak — not the
            # steady state — is what the apply must actually fit.
            from ..analysis.plan_check import verify_plan

            charged_slots = new_slots + (
                self.num_slots if new_slots != self.num_slots else 0
            )
            verify_plan(
                self._model_cfg, self._worker_manager,
                (np.zeros((new_slots, 1), np.int32),),
                memory="error", check_donation=False,
                serving=dict(slots=charged_slots, max_len=self.max_len,
                             bucket=max(new_buckets)),
            ).raise_if_failed()
        new_bucketer = ShapeBucketer(new_buckets)
        # only a slot-count change rebuilds the slabs and therefore
        # forces eviction; bucket/prefill_batch changes keep the running
        # batch decoding untouched (running requests never consult the
        # bucketer mid-decode) and only re-bucket the queue
        must_evict = new_slots != self.num_slots
        # feasibility covers the RUNNING batch even when it stays
        # resident: a running request that no longer fits any bucket
        # could never be preempted or rolled back again — a latent trap
        # the engine must refuse to set
        live = list(self._running.values()) + list(self._queue.requests)
        for r in live:
            # a request grown past the largest NEW bucket cannot resume
            # by recomputation; reject before any eviction
            try:
                new_bucketer.bucket_for(int(r.effective_prompt.size))
            except ValueError as exc:
                raise ValueError(
                    f"reconfigure rejected: request {r.request_id} "
                    f"cannot resume under buckets {list(new_buckets)}: "
                    f"{exc}"
                ) from None
        # pre-build every stage's new slabs BEFORE touching any request
        # state: an allocation failure here leaves the engine exactly as
        # it was (the atomicity the docstring promises); old slabs free
        # as soon as the swap below drops them
        new_pools = (
            [st.build_pool(new_slots) for st in self.stages]
            if must_evict else None
        )

        tracer = get_tracer()
        old = dict(buckets=list(self.bucketer.buckets),
                   slots=self.num_slots, prefill_batch=self.prefill_batch)
        evicted: List[Request] = []
        if must_evict:
            for r in list(self._running.values()):
                self._running.pop(r.request_id)
                self._release_slot(r.slot)
                r.slot = None
                r.preemptions += 1
                self.stats.preemptions += 1
                evicted.append(r)
                if tracer is not None:
                    # same instant preempt() emits, so trace-derived
                    # preemption counts agree with ServingStats
                    tracer.instant(
                        "preempt", tracer.lane("serving", "engine"),
                        {"request": r.request_id, "reconfigure": True},
                    )
                    self._trace_close_decode(r, tracer,
                                             reconfigure=True)
        queued = self._queue.drain()
        if tracer is not None:
            for r in queued:
                self._trace_close_queue(r, tracer, rebucketed=True)
        if new_pools is not None:
            self.num_slots = new_slots
            for st, pool in zip(self.stages, new_pools):
                st.pool = pool
        self.bucketer = new_bucketer
        self.prefill_batch = new_batch
        self._queue = AdmissionQueue(new_bucketer, prefill_batch=new_batch,
                                     max_queue=self.max_queue)
        # evicted requests were admitted before anything still queued:
        # they re-enter at the head so reconfiguration cannot starve
        # them; force — every one of these was already admitted, and a
        # reconfigure must never shed what it only meant to re-bucket
        for r in evicted + queued:
            self._queue.submit(r, force=True)
            self._trace_queued(r, tracer)
        self.stats.queue_depth = self._queue.depth
        if tracer is not None:
            tracer.instant(
                "reconfigure", tracer.lane("serving", "engine"),
                dict(old=old, new=dict(buckets=list(new_buckets),
                                       slots=new_slots,
                                       prefill_batch=new_batch),
                     evicted=len(evicted)),
            )

    def _reconfigure_paged(
        self,
        *,
        buckets=None,
        num_slots=None,
        prefill_batch=None,
        num_pages=None,
        page_size=None,
        max_pages_per_request=None,
        max_concurrency=None,
        prefill_chunk=None,
        max_chunk_rows=None,
        spec_k=None,
    ) -> None:
        """The paged half of :meth:`reconfigure` (same verify-then-
        apply contract; see its docstring for the knob semantics).

        ``prefill_chunk`` and ``spec_k`` are the chunked-prefill and
        speculative-decoding knobs: ``None`` keeps the current setting,
        ``0`` disables.  Both are pure scheduling — no slab rebuild —
        but disabling chunking evicts mid-prefill requests back to the
        queue (recompute-style: no one would ever finish their chunks),
        a chunk size must be a member of the (new) bucket set, and a
        ``spec_k`` change retraces the verify program at its new
        ``Lq = spec_k + 1`` shape on the next tick (a visible one-time
        warmup, the same one construction pays per bucket).  Enabling
        speculation requires the engine to have been built with
        ``draft_blocks`` (the draft's layer slice is construction
        state)."""
        from ..analysis.plan_check import verify_tuning_knobs

        if buckets is not None:
            try:
                new_buckets = tuple(sorted(set(int(b) for b in buckets)))
            except (TypeError, ValueError):
                new_buckets = tuple(buckets)
        else:
            new_buckets = self.bucketer.buckets
        if max_concurrency is not None and num_slots is not None and (
                int(max_concurrency) != int(num_slots)):
            raise ValueError(
                "num_slots aliases max_concurrency on a paged engine; "
                f"got conflicting {num_slots} and {max_concurrency}"
            )
        new_rows = int(
            max_concurrency if max_concurrency is not None
            else num_slots if num_slots is not None
            else self.max_concurrency
        )
        new_batch = (
            int(prefill_batch)
            if prefill_batch is not None else self.prefill_batch
        )
        new_pages = (
            int(num_pages) if num_pages is not None else self.num_pages
        )
        new_psize = (
            int(page_size) if page_size is not None else self.page_size
        )
        new_mpr = (
            int(max_pages_per_request)
            if max_pages_per_request is not None
            else self.max_pages_per_request
        )
        new_virtual = new_mpr * new_psize if (
            isinstance(new_mpr, int) and isinstance(new_psize, int)
            and new_mpr > 0 and new_psize > 0
        ) else self.max_len
        # chunk / speculation knobs: None keeps, 0 disables
        new_chunk = (
            self.prefill_chunk if prefill_chunk is None
            else (int(prefill_chunk) or None)
        )
        new_chunk_rows = (
            int(max_chunk_rows) if max_chunk_rows is not None
            else self.max_chunk_rows
        )
        if max_chunk_rows is not None and new_chunk is None:
            # mirror the constructor: a rows knob with chunking off
            # (or being disabled here) must fail loudly, not silently
            # drop the operator's starvation bound
            raise ValueError("max_chunk_rows requires prefill_chunk")
        new_spec = self.spec_k if spec_k is None else int(spec_k)
        verify_tuning_knobs(
            buckets=new_buckets, max_len=new_virtual,
            num_slots=new_rows, prefill_batch=new_batch,
            num_pages=new_pages, page_size=new_psize,
            max_pages_per_request=new_mpr,
            prefill_chunk=new_chunk, spec_k=new_spec,
        ).raise_if_failed()
        if new_spec > 0 and self._draft is None and (
                self.draft_blocks is None):
            raise ValueError(
                "reconfigure rejected: spec_k > 0 requires an engine "
                "built with draft_blocks (the draft's layer slice is "
                "construction state)"
            )
        max_pos = _gcfg(
            self.stages[0].modules[0].config
        ).max_position_embeddings
        if new_virtual > max_pos:
            raise ValueError(
                f"max_pages_per_request x page_size = {new_virtual} "
                f"exceeds max_position_embeddings={max_pos}"
            )
        geometry_change = (
            new_pages != self.num_pages or new_psize != self.page_size
            or new_mpr != self.max_pages_per_request
        )
        rows_change = new_rows != self.max_concurrency
        must_evict = geometry_change or rows_change
        # an enable of speculation makes the draft's LM-head copy newly
        # resident on stage 0 — that is real memory the verifier must
        # see BEFORE _build_draft's device_put allocates it
        enabling_spec = new_spec > 0 and self._draft is None
        charged_draft_mb = (
            self._pending_draft_mb() if enabling_spec else self._draft_mb
        )
        if (self._preflight and self._worker_manager is not None
                and (geometry_change
                     or max(new_buckets) > self.bucketer.max_bucket
                     or (enabling_spec and charged_draft_mb > 0))):
            # ANY geometry change pre-builds a full second slab set
            # while the old one is still resident, so the transient
            # peak is old+new pool depth even when the new pool is
            # SMALLER — charge exactly what the apply holds (the slot
            # path's transient-peak rule, at page granularity)
            from ..analysis.plan_check import verify_plan

            charged = new_pages + (
                self.num_pages if geometry_change else 0
            )
            ctx = dict(num_pages=charged, page_size=new_psize,
                       max_pages_per_request=new_mpr,
                       bucket=max(new_buckets))
            if self.kv_dtype is not None:
                ctx["kv_dtype"] = self.kv_dtype
            if charged_draft_mb > 0:
                ctx["draft_mb"] = charged_draft_mb
            verify_plan(
                self._model_cfg, self._worker_manager,
                (np.zeros((new_rows, 1), np.int32),),
                memory="error", check_donation=False,
                serving=ctx,
            ).raise_if_failed()
        # (an off-bucket prefill_chunk was already rejected by
        # verify_tuning_knobs above — the one enforcement point)
        new_bucketer = ShapeBucketer(new_buckets)
        # feasibility BEFORE any mutation.  Swap records survive only a
        # geometry-preserving change; under a geometry change every
        # swapped request must be able to resume by recomputation.
        live = (list(self._running.values())
                + list(self._prefilling.values())
                + list(self._queue.requests))
        for r in live:
            length = int(r.effective_prompt.size)
            swapped = r.request_id in self._swapped
            if length + r.remaining > new_virtual:
                raise ValueError(
                    f"reconfigure rejected: request {r.request_id} "
                    f"spans {length + r.remaining} positions; the new "
                    f"virtual span is {new_virtual}"
                )
            if swapped and not geometry_change:
                continue  # resumes from host pages, needs no bucket
            try:
                new_bucketer.bucket_for(length)
            except ValueError as exc:
                raise ValueError(
                    f"reconfigure rejected: request {r.request_id} "
                    f"cannot resume under buckets {list(new_buckets)}: "
                    f"{exc}"
                ) from None
        # pre-build everything fallible BEFORE touching request state
        new_slabs = (
            [st.build_slabs(new_pages, new_psize) for st in self.stages]
            if geometry_change else None
        )
        new_pool = (
            PagedKVCachePool(
                new_pages, new_psize, new_mpr,
                enable_prefix_cache=self.enable_prefix_cache,
                max_prefix_entries=self._max_prefix_entries,
                kv_dtype=self._pool_kv_dtype(),
            )
            if geometry_change else None
        )
        new_row_alloc = RowAllocator(new_rows) if must_evict else None
        # pre-build the fallible chunk/spec machinery before mutation
        new_policy = None
        if new_chunk is not None:
            rows = (
                new_chunk_rows if new_chunk_rows is not None
                else new_batch
            )
            new_policy = ChunkBudgetPolicy(
                new_chunk, max_chunk_rows=rows,
                idle_chunk_rows=max(rows, new_batch * 2),
            )
        new_draft = self._draft
        if new_spec > 0 and new_draft is None:
            new_draft = self._build_draft()

        tracer = get_tracer()
        old = dict(buckets=list(self.bucketer.buckets),
                   max_concurrency=self.max_concurrency,
                   prefill_batch=self.prefill_batch,
                   num_pages=self.num_pages, page_size=self.page_size,
                   max_pages_per_request=self.max_pages_per_request,
                   prefill_chunk=self.prefill_chunk,
                   spec_k=self.spec_k)
        evicted: List[Request] = []

        def evict(r: Request, prefilling: bool) -> None:
            if prefilling:
                self._prefilling.pop(r.request_id)
                r.prefilled_len = 0  # recompute replays the tail
            else:
                self._running.pop(r.request_id)
            self._release_slot(r.slot)
            self._pool.release(r.request_id)
            r.slot = None
            r.preemptions += 1
            self.stats.preemptions += 1
            evicted.append(r)
            if tracer is not None:
                tracer.instant(
                    "preempt", tracer.lane("serving", "engine"),
                    {"request": r.request_id, "reconfigure": True},
                )
                self._trace_close_decode(r, tracer, reconfigure=True)
                self._trace_close_prefill(r, tracer, reconfigure=True)

        if must_evict:
            for r in list(self._running.values()):
                evict(r, prefilling=False)
            for r in list(self._prefilling.values()):
                evict(r, prefilling=True)
        elif new_chunk is None and self._prefilling:
            # chunking turned off with requests mid-watermark: no chunk
            # tick would ever finish them — re-queue recompute-style
            for r in list(self._prefilling.values()):
                evict(r, prefilling=True)
        queued = self._queue.drain()
        if tracer is not None:
            for r in queued:
                self._trace_close_queue(r, tracer, rebucketed=True)
        if geometry_change:
            # bank the dying pool's counters (monotonic discipline),
            # then swap in the cold pool + fresh slabs; swap records'
            # page shapes died with the geometry -> recompute resumes
            self._pool_base["prefix_hits"] += self._pool.prefix_hits
            self._pool_base["prefix_tokens_reused"] += (
                self._pool.prefix_tokens_reused
            )
            self._pool_base["cow_copies"] += self._pool.cow_copies
            self._pool_base["prefix_evictions"] += (
                self._pool.prefix_evictions
            )
            self._pool = new_pool
            for st, slabs in zip(self.stages, new_slabs):
                st.num_pages = new_pages
                st.page_size = new_psize
                st.slabs = slabs
            self._swapped.clear()
            self.num_pages = new_pages
            self.page_size = new_psize
            self.max_pages_per_request = new_mpr
            self.max_len = new_virtual
        if new_row_alloc is not None:
            self._rows = new_row_alloc
            for st in self.stages:
                st.pool = self._rows
            self.max_concurrency = new_rows
            self.num_slots = new_rows
        self.bucketer = new_bucketer
        self.prefill_batch = new_batch
        self.prefill_chunk = new_chunk
        self.max_chunk_rows = (
            new_policy.max_chunk_rows if new_policy is not None else None
        )
        self._chunk_policy = new_policy
        self.spec_k = new_spec
        if new_spec > 0:
            self._draft = new_draft
            self._draft_mb = new_draft.extra_param_mb
        self._queue = AdmissionQueue(new_bucketer, prefill_batch=new_batch,
                                     max_queue=self.max_queue)
        for r in evicted + queued:
            self._queue.submit(
                r, force=True,
                require_bucket=not (
                    r.request_id in self._swapped
                ),
            )
            self._trace_queued(r, tracer)
        self.stats.queue_depth = self._queue.depth
        if tracer is not None:
            tracer.instant(
                "reconfigure", tracer.lane("serving", "engine"),
                dict(old=old,
                     new=dict(buckets=list(new_buckets),
                              max_concurrency=new_rows,
                              prefill_batch=new_batch,
                              num_pages=new_pages, page_size=new_psize,
                              max_pages_per_request=new_mpr,
                              prefill_chunk=new_chunk,
                              spec_k=new_spec),
                     evicted=len(evicted)),
            )

    def run(
        self,
        requests: Optional[Sequence[Request]] = None,
        max_iterations: int = 100_000,
    ) -> Dict[int, np.ndarray]:
        """Drive ``step`` until the queue and batch drain; returns
        ``{request_id: prompt + generated tokens}`` for everything that
        finished during the call."""
        finished0 = len(self._finished)
        for r in requests or ():
            self.submit(r)
        for _ in range(max_iterations):
            if not self.has_work():
                break
            self.step()
        else:  # pragma: no cover - scheduler liveness guard
            raise RuntimeError(
                f"serving engine made no full drain in "
                f"{max_iterations} iterations"
            )
        return {
            r.request_id: r.output()
            for r in self._finished[finished0:]
        }

    @property
    def finished_requests(self) -> List[Request]:
        return list(self._finished)

    # --- live observability (LiveMetricsMixin provides the wiring) ----------
    def _health_snapshot(self) -> Dict[str, Any]:
        snap = dict(
            status="ok",
            queue_depth=self._queue.depth,
            running=len(self._running),
            free_slots=self.free_slots,
            iterations=self.stats.iterations,
        )
        if self._paged:
            snap.update(
                kv_layout="paged",
                free_pages=self._pool.free_pages,
                pages_in_use=self._pool.pages_in_use,
                swapped=len(self._swapped),
                prefilling=len(self._prefilling),
                # the active kernel/quantization operating point, so a
                # scrape can tell WHICH decode path a replica runs
                kv_dtype=self._pool.kv_dtype,
                attn_impl=self.attn_impl,
            )
        return snap

    # --- internals ----------------------------------------------------------
    def _admit(self) -> None:
        if self.static_batching and self._running:
            return  # batch boundary only: the naive baseline policy
        while True:
            wave = self._queue.next_wave(self.free_slots)
            if not wave:
                break
            self._prefill_wave(wave)

    def _prefill_wave(self, wave: List[Request]) -> None:
        bucket = wave[0].bucket
        rows = self.prefill_batch
        ids, lengths = self.bucketer.pad_batch(
            [r.effective_prompt for r in wave], bucket, rows, self.pad_id
        )
        # sentinel = num_slots: padding rows scatter out of range -> drop
        slot_ids = np.full((rows,), self.num_slots, np.int32)
        for i, r in enumerate(wave):
            slot = self._allocate_slot()
            assert slot is not None  # next_wave capped by free_slots
            r.slot = slot
            slot_ids[i] = slot

        tracer = get_tracer()
        span0 = tracer.now() if tracer is not None else 0.0
        t0 = time.perf_counter()
        compiles0 = xla_compile_count()
        data: Any = ids
        for st in self.stages:
            data = device_put_elided(data, st.device)
            sids = device_put_elided(slot_ids, st.device)
            if tracer is None:
                data, st.pool.slabs = st._prefill_donated(
                    st.params, data, st.pool.slabs, sids
                )
            else:
                stage0 = tracer.now()
                data, st.pool.slabs = st._prefill_donated(
                    st.params, data, st.pool.slabs, sids
                )
                tracer.complete(
                    "prefill", tracer.lane(st.lane_name, "dispatch"),
                    stage0, {"bucket": bucket},
                )
        pos = device_put_elided(lengths - 1, self._last_device)
        logits = _gather_last(data, pos)  # [rows, V]
        tokens = _argmax_tokens(logits)
        jax.block_until_ready(tokens)
        now = time.perf_counter()
        self.stats.prefill_s += now - t0
        wave_tokens = int(lengths[: len(wave)].sum())
        if tracer is not None:
            end_us = tracer.now()
            # tokens (true, un-padded) ride along so trace analysis can
            # compute per-bucket padding waste — the skewed-bucket
            # signature the autotuner acts on; the member request ids
            # make the wave attributable from the engine lane too
            tracer.complete(
                "prefill", tracer.lane("serving", "engine"), span0,
                {"bucket": bucket, "wave": len(wave),
                 "tokens": wave_tokens,
                 "requests": [r.request_id for r in wave]},
                dur_us=end_us - span0,
            )
            for r in wave:
                tracer.instant(
                    "admit", tracer.lane("serving", "engine"),
                    {"request": r.request_id, "slot": r.slot},
                )
                # request-lane waterfall: the queue_wait segment ends
                # where the wave began, the prefill segment spans the
                # wave, and the decode segment opens at the wave's end
                self._trace_close_queue(r, tracer, end_us=span0)
                lane = tracer.request_lane(r.request_id, lease=False)
                if lane is not None:
                    tracer.complete(
                        "prefill", lane, span0,
                        {"request": r.request_id,
                         "replica": self.trace_name,
                         "bucket": bucket, "slot": r.slot},
                        dur_us=end_us - span0,
                    )
                r.trace_marks["decode"] = end_us
        self.stats.prefill_waves += 1
        self.stats.prefill_tokens += wave_tokens
        # per-call delta, not a process-global diff: foreign jit work in
        # the same process must not read as engine recompiles
        self.stats.compiles += xla_compile_count() - compiles0

        tokens_np = np.asarray(tokens)
        sampled = self._sampled_rows(
            logits, [(i, r) for i, r in enumerate(wave)]
        )
        for i, r in enumerate(wave):
            tok = self._pick_token(r, tokens_np[i], sampled.get(i))
            r.tokens.append(tok)
            r.index = int(lengths[i])
            r.status = RUNNING
            self._running[r.request_id] = r
            if r.first_token_s is None:
                r.first_token_s = now
            self.stats.generated_tokens += 1
            if r.done:
                self._finish(r, now)

    def _decode_tick(self) -> None:
        active = list(self._running.values())
        if not active:
            return
        tokens = np.zeros((self.num_slots,), np.int32)
        index = np.zeros((self.num_slots,), np.int32)
        for r in active:
            tokens[r.slot] = r.tokens[-1]
            index[r.slot] = r.index

        tracer = get_tracer()
        span0 = tracer.now() if tracer is not None else 0.0
        t0 = time.perf_counter()
        compiles0 = xla_compile_count()
        data: Any = tokens[:, None]  # [slots, 1]
        for st in self.stages:
            data = device_put_elided(data, st.device)
            idx = device_put_elided(index, st.device)
            if tracer is None:
                data, st.pool.slabs = st._decode_donated(
                    st.params, data, st.pool.slabs, idx
                )
            else:
                stage0 = tracer.now()
                data, st.pool.slabs = st._decode_donated(
                    st.params, data, st.pool.slabs, idx
                )
                tracer.complete(
                    "decode", tracer.lane(st.lane_name, "dispatch"), stage0
                )
        logits = data[:, 0]  # [slots, V]
        nxt = _argmax_tokens(logits)
        jax.block_until_ready(nxt)
        now = time.perf_counter()
        self.stats.decode_s += now - t0
        if tracer is not None:
            tracer.complete(
                "decode", tracer.lane("serving", "engine"), span0,
                {"active": len(active)},
            )
        self.stats.decode_tokens += len(active)
        self.stats.generated_tokens += len(active)
        self.stats.compiles += xla_compile_count() - compiles0

        nxt_np = np.asarray(nxt)
        sampled = self._sampled_rows(
            logits, [(r.slot, r) for r in active]
        )
        for r in active:
            tok = self._pick_token(r, nxt_np[r.slot],
                                   sampled.get(r.slot))
            r.tokens.append(tok)
            r.index += 1
            if r.done:
                self._finish(r, now)

    # --- the paged scheduling loop ------------------------------------------
    def _admit_paged(self) -> None:
        """Admit from the queue while rows AND pages allow — admission
        charges PAGES (the request's reserved footprint), so
        concurrency floats with actual memory use instead of a slot
        count.  FIFO: the head either admits (prefill wave or swap-in)
        or stalls the queue — a later small request never jumps a
        starved head."""
        if self.static_batching and self._running:
            return  # batch boundary only: the naive baseline policy
        while True:
            queued = self._queue.requests
            if not queued or self._rows.free_slots < 1:
                return
            head = queued[0]
            if head.request_id in self._swapped:
                if not self._swap_in(head):
                    if head.request_id in self._swapped:
                        # pages genuinely unavailable: the head stalls
                        # the queue until a release frees them
                        self._stall_on_pages()
                        return
                    # corrupt record dropped (or the victim FAILED):
                    # re-judge the head as a normal recompute admission
                    continue
                continue
            if self._chunk_policy is not None:
                # chunked admission is charge-only (no compute): the
                # head gets its page grant and decode row, then its
                # prefill rides budgeted chunk waves across later ticks
                if not self._enroll_chunked(head):
                    self._stall_on_pages()
                    return
                continue
            wave = self._select_paged_wave()
            if wave is None:
                self._stall_on_pages()
                return
            self._prefill_wave_paged(wave)

    def _enroll_chunked(self, request: Request) -> bool:
        """Admit the queue head under chunked prefill: charge its page
        grant, seat it on a decode row, perform the grant's COW copy,
        and set the ``prefilled_len`` watermark at the shared-prefix
        boundary.  No prefill compute happens here — chunk waves do
        that, budgeted per tick.  False (nothing mutated) when the
        pages cannot be charged yet."""
        tokens = self._effective_tokens(request)
        grant = self._pool.acquire(
            request.request_id, tokens, len(tokens) + request.remaining
        )
        if grant is None:
            return False
        row = self._rows.allocate()
        assert row is not None  # caller checked free rows
        request.slot = row
        # COW before any chunk write: the donor's partial page becomes
        # this request's private page (same rule as the one-shot wave);
        # the pool's plan decides what a clone copies (scale rows ride
        # along on an int8 pool)
        plan = self._pool.cow_plan(grant)
        if plan:
            for st in self.stages:
                st.apply_cow_plan(plan)
        self._queue.remove(request)
        request.prefilled_len = grant.shared_tokens
        request.status = RUNNING
        self._prefilling[request.request_id] = request
        self.stats.queue_depth = self._queue.depth
        tracer = get_tracer()
        self._trace_enroll(request, grant, tracer)
        return True

    def _chunk_tick(self) -> None:
        """Advance chunked prefill by at most the policy's budget:
        head-fixes-the-bucket chunk waves (enrollment FIFO) until the
        budget is spent, each request advancing AT MOST ONE chunk per
        tick (fairness: the head can never eat the whole budget while
        later enrollees starve).  A tick that leaves some mid-prefill
        request without a chunk counts one ``chunk_stalls`` — work was
        actually deferred, the deliberate price of protecting decode
        latency."""
        if not self._prefilling:
            return
        budget = self._chunk_policy.rows_for_tick(
            pending=len(self._prefilling), decoding=len(self._running)
        )
        advanced: set = set()
        while budget > 0:
            wave = self._select_chunk_wave(
                min(budget, self.prefill_batch), advanced
            )
            if not wave:
                break
            advanced.update(r.request_id for r in wave)
            self._chunk_wave(wave)
            budget -= len(wave)
        # requests still mid-watermark that got NO chunk this tick:
        # the budget (or a bucket mismatch past it) deferred real work
        deferred = [
            rid for rid in self._prefilling if rid not in advanced
        ]
        if deferred:
            self.stats.chunk_stalls += 1
            tracer = get_tracer()
            if tracer is not None:
                tracer.instant(
                    "chunk_stall", tracer.lane("serving", "engine"),
                    {"deferred": len(deferred)},
                )

    def _next_chunk_len(self, request: Request) -> int:
        return min(
            self.prefill_chunk,
            int(request.effective_prompt.size) - request.prefilled_len,
        )

    def _select_chunk_wave(self, cap: int,
                           exclude: set) -> List[Request]:
        """Up to ``cap`` mid-prefill requests whose NEXT chunk pads to
        the enrollment head's bucket (same-bucket packing, FIFO head
        never skipped — the wave-selection rule at chunk granularity).
        ``exclude`` holds requests already advanced this tick, so one
        tick never gives the head a second chunk while others wait."""
        pending = [
            r for r in self._prefilling.values()
            if r.request_id not in exclude
        ]
        if not pending:
            return []
        head = pending[0]
        bucket = self.bucketer.bucket_for(self._next_chunk_len(head))
        wave: List[Request] = []
        for r in pending:
            if len(wave) >= cap:
                break
            if self.bucketer.bucket_for(
                    self._next_chunk_len(r)) == bucket:
                wave.append(r)
        return wave

    def _chunk_wave(self, wave: List[Request]) -> None:
        """One prefill-chunk wave: each member's next
        ``<= prefill_chunk`` prompt positions, padded to the wave
        bucket, scattered through the members' page tables at their
        ``prefilled_len`` watermarks — the SAME compiled program shape
        as a tail-prefill wave, so chunking adds zero compiles.  A
        member whose watermark reaches its prompt end commits its
        first token and joins the decode batch."""
        rows = self.prefill_batch
        chunks = []
        for r in wave:
            eff = r.effective_prompt
            clen = self._next_chunk_len(r)
            chunks.append(eff[r.prefilled_len:r.prefilled_len + clen])
        bucket = self.bucketer.bucket_for(int(chunks[0].size))
        ids, lengths = self.bucketer.pad_batch(
            chunks, bucket, rows, self.pad_id
        )
        sentinel = self.num_pages
        tables = np.full(
            (rows, self.max_pages_per_request), sentinel, np.int32
        )
        index = np.zeros((rows,), np.int32)
        valid = np.zeros((rows,), np.int32)  # pad rows: writes drop
        for i, r in enumerate(wave):
            held = self._pool.table(r.request_id)
            tables[i, : len(held)] = held
            index[i] = r.prefilled_len
            valid[i] = r.prefilled_len + int(chunks[i].size)

        width = self._table_width(valid)
        tables = tables[:, :width]
        self._count_quant(index, valid, width, len(wave))
        tracer = get_tracer()
        span0 = tracer.now() if tracer is not None else 0.0
        t0 = time.perf_counter()
        compiles0 = xla_compile_count()
        data = self._run_paged_stages(
            ids, tables, index, valid, tracer, "prefill",
            {"bucket": bucket, "chunk": True},
        )
        pos = device_put_elided(lengths - 1, self._last_device)
        logits = _gather_last(data, pos)  # [rows, V]
        tokens = _argmax_tokens(logits)
        jax.block_until_ready(tokens)
        now = time.perf_counter()
        self.stats.prefill_s += now - t0
        # per-chunk TRUE token counts: the padding-waste histogram and
        # serving_padding_fraction() must see what this wave actually
        # prefilled, never the members' full prompt lengths
        wave_tokens = int(sum(int(c.size) for c in chunks))
        if tracer is not None:
            end_us = tracer.now()
            tracer.complete(
                "prefill", tracer.lane("serving", "engine"), span0,
                {"bucket": bucket, "wave": len(wave),
                 "tokens": wave_tokens, "chunk": True,
                 "requests": [r.request_id for r in wave]},
                dur_us=end_us - span0,
            )
        else:
            end_us = 0.0
        self.stats.prefill_waves += 1
        self.stats.prefill_tokens += wave_tokens
        self.stats.prefill_chunks += len(wave)
        self.stats.compiles += xla_compile_count() - compiles0

        finals = [
            (i, r) for i, r in enumerate(wave)
            if r.prefilled_len + int(chunks[i].size)
            >= int(r.effective_prompt.size)
        ]
        tokens_np = np.asarray(tokens)
        sampled = self._sampled_rows(logits, finals)
        for i, r in enumerate(wave):
            clen = int(chunks[i].size)
            r.prefilled_len += clen
            if r.prefilled_len < int(r.effective_prompt.size):
                continue  # watermark advanced; more chunks to come
            # final chunk: the last true position's logits seed the
            # first generated token, exactly like a one-shot wave
            self._prefilling.pop(r.request_id)
            self._pool.register_prefix(
                r.request_id, [int(t) for t in r.prompt]
            )
            tok = self._pick_token(r, tokens_np[i], sampled.get(i))
            r.tokens.append(tok)
            r.index = r.prefilled_len
            r.prefilled_len = 0
            r.status = RUNNING
            self._running[r.request_id] = r
            if r.first_token_s is None:
                r.first_token_s = now
            self.stats.generated_tokens += 1
            if tracer is not None:
                self._trace_close_prefill(r, tracer, end_us=end_us,
                                          bucket=bucket, slot=r.slot)
                r.trace_marks["decode"] = end_us
            if r.done:
                self._finish(r, now)

    @staticmethod
    def _effective_tokens(request: Request) -> tuple:
        """The request's effective prompt as a token tuple (radix-cache
        key), cached until its generated-token count changes — wave
        selection re-scans the queue every stalled tick, and rebuilding
        O(prompt) int lists per scan would put host work proportional
        to queue depth x prompt length on the scheduling path."""
        n = len(request.tokens)
        cached = getattr(request, "_token_cache", None)
        if cached is not None and cached[0] == n:
            return cached[1]
        tokens = tuple(int(t) for t in request.effective_prompt)
        request._token_cache = (n, tokens)
        return tokens

    def _stall_on_pages(self) -> None:
        """Count a page-exhaustion stall (the row-exhaustion twin is
        counted by ``step``; rows were free here, pages were not)."""
        self.stats.queue_stalls += 1
        tracer = get_tracer()
        if tracer is not None:
            tracer.instant(
                "queue_stall", tracer.lane("serving", "engine"),
                {"queued": self._queue.depth,
                 "free_pages": self._pool.free_pages},
            )

    def _select_paged_wave(self) -> Optional[List[Any]]:
        """Dequeue the next prefill wave under the paged layout, or
        None when the head cannot be charged.

        The head's TAIL bucket (prompt minus its radix-shared prefix)
        fixes the wave's compile shape; later queued requests whose
        tails land in the same bucket pack in, each charged its own
        page grant.  Buckets are pure compile-shape classes here —
        admission capacity is pages + rows, never 'a slot of the
        head's size' (the decoupling the slot layout could not offer).
        """
        queued = self._queue.requests
        head = queued[0]
        cap = min(self.prefill_batch, self._rows.free_slots)
        wave: List[Any] = []
        bucket: Optional[int] = None
        for r in queued:
            if len(wave) >= cap:
                break
            if r.request_id in self._swapped:
                continue  # swap-ins ride their own admission path
            tokens = self._effective_tokens(r)
            length = len(tokens)
            if bucket is not None:
                # cheap pre-screen before charging pages
                peek = self._pool.peek_shared(tokens)
                if self.bucketer.bucket_for(
                        max(1, length - peek)) != bucket:
                    continue
            grant = self._pool.acquire(
                r.request_id, tokens, length + r.remaining
            )
            if grant is None:
                if r is head:
                    return None  # head starves -> the queue stalls
                break  # pages ran out mid-pack; serve what we have
            tail_bucket = self.bucketer.bucket_for(
                length - grant.shared_tokens
            )
            if bucket is None:
                bucket = tail_bucket
            elif tail_bucket != bucket:
                # the peek promised this bucket but the grant (made
                # under eviction) disagreed: hand the pages back with
                # the hit counters reversed and move on
                self._pool.rollback_grant(grant)
                continue
            wave.append((r, grant))
        if not wave:
            return None
        for r, _ in wave:
            self._queue.remove(r)
        return wave

    def _prefill_wave_paged(self, wave: List[Any]) -> None:
        """Prefill a wave of (request, grant) pairs: COW-clone partial
        shared pages, compute ONLY the non-shared tails, scatter their
        K/V through the page tables, and seat each request on a decode
        row.  A full-prefix hit costs one bucket of tail compute — the
        TTFT-drops-with-prefix-length effect the bench gates."""
        rows = self.prefill_batch
        tails = [
            r.effective_prompt[g.shared_tokens:] for r, g in wave
        ]
        bucket = self.bucketer.bucket_for(int(tails[0].size))
        ids, lengths = self.bucketer.pad_batch(
            tails, bucket, rows, self.pad_id
        )
        sentinel = self.num_pages
        tables = np.full(
            (rows, self.max_pages_per_request), sentinel, np.int32
        )
        index = np.zeros((rows,), np.int32)
        valid = np.zeros((rows,), np.int32)  # pad rows: every write drops
        for i, (r, g) in enumerate(wave):
            row = self._rows.allocate()
            assert row is not None  # wave capped by free rows
            r.slot = row
            tables[i, : len(g.page_table)] = g.page_table
            index[i] = g.shared_tokens
            valid[i] = g.shared_tokens + int(tails[i].size)
        # copy-on-write BEFORE any dispatch touches the slabs: the
        # donor's partial page becomes the sharer's private page, so
        # the tail prefill's appends never write a shared page; the
        # pool's plan decides what a clone copies (scale rows ride
        # along on an int8 pool)
        for _, g in wave:
            plan = self._pool.cow_plan(g)
            if plan:
                for st in self.stages:
                    st.apply_cow_plan(plan)

        width = self._table_width(valid)
        tables = tables[:, :width]
        self._count_quant(index, valid, width, len(wave))
        tracer = get_tracer()
        span0 = tracer.now() if tracer is not None else 0.0
        t0 = time.perf_counter()
        compiles0 = xla_compile_count()
        data = self._run_paged_stages(
            ids, tables, index, valid, tracer, "prefill",
            {"bucket": bucket},
        )
        pos = device_put_elided(lengths - 1, self._last_device)
        logits = _gather_last(data, pos)  # [rows, V]
        tokens = _argmax_tokens(logits)
        jax.block_until_ready(tokens)
        now = time.perf_counter()
        self.stats.prefill_s += now - t0
        wave_tokens = int(sum(int(t.size) for t in tails))
        shared_tokens = int(sum(g.shared_tokens for _, g in wave))
        if tracer is not None:
            end_us = tracer.now()
            tracer.complete(
                "prefill", tracer.lane("serving", "engine"), span0,
                {"bucket": bucket, "wave": len(wave),
                 "tokens": wave_tokens, "shared": shared_tokens,
                 "requests": [r.request_id for r, _ in wave]},
                dur_us=end_us - span0,
            )
            for r, g in wave:
                tracer.instant(
                    "admit", tracer.lane("serving", "engine"),
                    {"request": r.request_id, "slot": r.slot,
                     "pages": len(g.page_table),
                     "shared": g.shared_tokens},
                )
                self._trace_close_queue(r, tracer, end_us=span0)
                lane = tracer.request_lane(r.request_id, lease=False)
                if lane is not None:
                    tracer.complete(
                        "prefill", lane, span0,
                        {"request": r.request_id,
                         "replica": self.trace_name,
                         "bucket": bucket, "slot": r.slot,
                         "shared": g.shared_tokens},
                        dur_us=end_us - span0,
                    )
                r.trace_marks["decode"] = end_us
        self.stats.prefill_waves += 1
        self.stats.prefill_tokens += wave_tokens
        self.stats.compiles += xla_compile_count() - compiles0

        tokens_np = np.asarray(tokens)
        sampled = self._sampled_rows(
            logits, [(i, r) for i, (r, _) in enumerate(wave)]
        )
        for i, (r, g) in enumerate(wave):
            # index the radix cache BEFORE the done-check can release
            # the pages: a request that finishes in its prefill tick
            # still leaves its prompt warm for the next sharer
            self._pool.register_prefix(
                r.request_id, [int(t) for t in r.prompt]
            )
            tok = self._pick_token(r, tokens_np[i], sampled.get(i))
            r.tokens.append(tok)
            r.index = int(valid[i])
            r.status = RUNNING
            self._running[r.request_id] = r
            if r.first_token_s is None:
                r.first_token_s = now
            self.stats.generated_tokens += 1
            if r.done:
                self._finish(r, now)

    def _table_width(self, valid) -> int:
        """Page-table columns this step actually needs (the PR 12
        honest-gather fix): the wave's max live length, ceiled to a
        page, then to the next power-of-two page count with the largest
        bucket's span as floor — so the XLA reference gathers (and the
        kernel's grid walks) O(live tokens), not O(max_pages), while
        the distinct compile-shape set stays logarithmic and warmable
        exactly like prefill buckets.  ``gather_pages="full"`` keeps
        the PR 9 behavior: the full table width every step (the
        materializing baseline the bench A/Bs against)."""
        if self.gather_pages == "full":
            return self.max_pages_per_request
        need = max(1, pages_for(int(np.max(valid)), self.page_size))
        floor = pages_for(self.bucketer.max_bucket, self.page_size)
        width = max(need, floor)
        p = 1
        while p < width:
            p <<= 1
        return min(p, self.max_pages_per_request)

    def _count_quant(self, index, valid, width: int, rows: int) -> None:
        """int8 observability: bank this step's quantize/dequant work.
        ``quantized_pages`` = pages the write wave touched (each one
        re-quantized through its scale); ``dequant_blocks`` = page
        blocks attention dequantized (active rows x gathered width) —
        both per step, across all stages' layers would just scale by a
        constant, so the per-step count is the honest unit."""
        if self.kv_dtype != "int8":
            return
        index = np.asarray(index)
        valid = np.asarray(valid)
        live = valid > index
        if np.any(live):
            touched = (
                (valid[live] - 1) // self.page_size
                - index[live] // self.page_size + 1
            )
            self.stats.quantized_pages += int(touched.sum())
        self.stats.dequant_blocks += int(rows) * int(width)

    def _run_paged_stages(self, data, tables, index, valid, tracer,
                          span_name, span_args=None):
        """Thread one paged step through every stage — the ONE
        dispatch idiom shared by tail-prefill waves, chunk waves,
        decode ticks, and the speculative verify forward: per-stage
        device puts, the donated step program with its same-statement
        slab rebind, and a per-stage dispatch span named
        ``span_name``.  Returns the last stage's output."""
        for st in self.stages:
            data = device_put_elided(data, st.device)
            tb = device_put_elided(tables, st.device)
            ix = device_put_elided(index, st.device)
            vl = device_put_elided(valid, st.device)
            if tracer is None:
                data, st.slabs = st._step_donated(
                    st.params, data, st.slabs, tb, ix, vl
                )
            else:
                stage0 = tracer.now()
                data, st.slabs = st._step_donated(
                    st.params, data, st.slabs, tb, ix, vl
                )
                tracer.complete(
                    span_name, tracer.lane(st.lane_name, "dispatch"),
                    stage0, span_args,
                )
        return data

    def _swap_in(self, request: Request) -> bool:
        """Re-seat a swapped-out request: fresh pages, host copies
        scattered back, NO prefill — decoding continues from exactly
        where the swap-out left it.  False (nothing mutated) when the
        pages cannot be charged yet.

        Integrity gate FIRST: the record's swap-out checksum is
        re-computed over the host payload before any state is touched.
        A mismatch means the parked KV is poisoned — the record is
        dropped (``swap_corruptions`` counts it) and the request falls
        back to the recompute-from-prompt path (also returning False,
        with the record gone, so the admission loop re-judges the head
        as a normal recompute re-admission).  A victim whose resume
        prefix has outgrown every bucket cannot recompute either; it
        is FAILED with a reasoned verdict instead of served garbage."""
        record = self._swapped[request.request_id]
        expect = record.get("checksum")
        if expect is not None and _swap_record_checksum(
                record["pages"], record["index"],
                record["data"]) != expect:
            del self._swapped[request.request_id]
            self.stats.swap_corruptions += 1
            tracer = get_tracer()
            if tracer is not None:
                tracer.instant(
                    "swap_corrupt", tracer.lane("serving", "engine"),
                    {"request": request.request_id,
                     "pages": record["pages"]},
                )
            resume_len = int(request.effective_prompt.size)
            try:
                self.bucketer.bucket_for(resume_len)
            except ValueError:
                # structurally unservable: swap was the ONLY way this
                # resume prefix could return, and its record is gone
                self._queue.remove(request)
                request.status = FAILED
                request.fail_reason = (
                    "swap record corrupted and the resume prefix fits "
                    "no bucket"
                )
                self.stats.queue_depth = self._queue.depth
            return False
        pages = self._pool.acquire_pages(
            request.request_id, record["pages"]
        )
        if pages is None:
            return False
        row = self._rows.allocate()
        assert row is not None  # caller checked free rows
        table = np.full(
            (self.max_pages_per_request,), self.num_pages, np.int32
        )
        table[: len(pages)] = pages
        for st, host_pairs in zip(self.stages, record["data"]):
            st.swap_in(table, host_pairs)
        del self._swapped[request.request_id]
        self._queue.remove(request)
        request.slot = row
        request.index = record["index"]
        request.status = RUNNING
        self._running[request.request_id] = request
        self.stats.swap_ins += 1
        self.stats.queue_depth = self._queue.depth
        tracer = get_tracer()
        if tracer is not None:
            now_us = tracer.now()
            tracer.instant(
                "swap_in", tracer.lane("serving", "engine"),
                {"request": request.request_id, "pages": len(pages)},
            )
            self._trace_close_queue(request, tracer, swapped_in=True)
            request.trace_marks["decode"] = now_us
        return True

    def _decode_tick_paged(self) -> None:
        active = list(self._running.values())
        if not active:
            return
        rows = self.max_concurrency
        sentinel = self.num_pages
        tokens = np.zeros((rows,), np.int32)
        index = np.zeros((rows,), np.int32)
        valid = np.zeros((rows,), np.int32)  # inactive rows never write
        tables = np.full(
            (rows, self.max_pages_per_request), sentinel, np.int32
        )
        for r in active:
            tokens[r.slot] = r.tokens[-1]
            index[r.slot] = r.index
            valid[r.slot] = r.index + 1
            held = self._pool.table(r.request_id)
            tables[r.slot, : len(held)] = held

        width = self._table_width(valid)
        tables = tables[:, :width]
        self._count_quant(index, valid, width, len(active))
        tracer = get_tracer()
        span0 = tracer.now() if tracer is not None else 0.0
        t0 = time.perf_counter()
        compiles0 = xla_compile_count()
        data = self._run_paged_stages(
            tokens[:, None], tables, index, valid, tracer, "decode"
        )
        logits = data[:, 0]  # [rows, V]
        nxt = _argmax_tokens(logits)
        jax.block_until_ready(nxt)
        now = time.perf_counter()
        self.stats.decode_s += now - t0
        if tracer is not None:
            tracer.complete(
                "decode", tracer.lane("serving", "engine"), span0,
                {"active": len(active)},
            )
        self.stats.decode_tokens += len(active)
        self.stats.generated_tokens += len(active)
        self.stats.compiles += xla_compile_count() - compiles0

        nxt_np = np.asarray(nxt)
        sampled = self._sampled_rows(
            logits, [(r.slot, r) for r in active]
        )
        for r in active:
            tok = self._pick_token(r, nxt_np[r.slot],
                                   sampled.get(r.slot))
            r.tokens.append(tok)
            r.index += 1
            if r.done:
                self._finish(r, now)

    def _spec_tick(self) -> None:
        """One speculative decode tick (replaces the plain decode tick
        while ``spec_k > 0``): the draft proposes ``spec_k`` tokens per
        row autoregressively (``Lq=1`` against stage 0's slab prefix),
        then the whole pipeline verifies all ``spec_k + 1`` positions
        in ONE forward (``Lq=spec_k+1`` — a fixed shape, compiled once)
        and greedy acceptance commits the agreed draft prefix plus the
        target's own next token.  The committed stream is the
        non-speculative greedy stream by construction: only the
        target's argmax ever commits.

        Rollback is a watermark truncate: rejected positions' KV sits
        beyond the committed ``index``, masked by ``decode_visibility``
        and rewritten by the next committed forward; page refcounts
        never move (the admission grant already reserved the request's
        worst-case span, so drafting k ahead is pre-charged).
        Temperature-sampling rows ride the same verify forward and
        commit exactly one token from its position-0 logits — the
        identical logits a plain decode tick would produce — so their
        sample streams are untouched (and contribute nothing to the
        draft/accept/rollback counters: they never consume drafts).
        A tick with NO greedy row falls back to the plain decode tick
        — drafting for rows that cannot accept would be pure waste."""
        active = list(self._running.values())
        if not active:
            return
        if all(r.temperature > 0.0 for r in active):
            self._decode_tick_paged()
            return
        k = self.spec_k
        rows = self.max_concurrency
        sentinel = self.num_pages
        tokens = np.zeros((rows,), np.int32)
        index0 = np.zeros((rows,), np.int32)
        reserve = np.zeros((rows,), np.int32)  # inactive rows: 0 -> drop
        tables = np.full(
            (rows, self.max_pages_per_request), sentinel, np.int32
        )
        for r in active:
            tokens[r.slot] = r.tokens[-1]
            index0[r.slot] = r.index
            reserve[r.slot] = int(r.prompt.size) + r.max_new_tokens
            held = self._pool.table(r.request_id)
            tables[r.slot, : len(held)] = held

        # verify writes cap at min(index+k+1, reserve); one table width
        # (covering that bound) serves BOTH the draft loop and the
        # verify forward, so the two stay on one warmed shape set
        valid = np.minimum(index0 + k + 1, reserve)
        width = self._table_width(valid)
        tables = tables[:, :width]
        self._count_quant(index0, valid, width, len(active))
        if self.kv_dtype == "int8":
            # the draft's k Lq=1 passes also quantize (one tail-page
            # re-quant per kept step per row) and dequantize (one
            # gathered width per step) — the verify-only count above
            # would hide roughly half a spec tick's quantization work
            slots = [r.slot for r in active]
            kept = np.clip(reserve[slots] - index0[slots], 0, k)
            self.stats.quantized_pages += int(kept.sum())
            self.stats.dequant_blocks += k * len(active) * width
        tracer = get_tracer()
        span0 = tracer.now() if tracer is not None else 0.0
        t0 = time.perf_counter()
        compiles0 = xla_compile_count()
        stage0 = self.stages[0]
        d = self._draft.num_attn
        # --- draft: k sequential Lq=1 steps against stage 0's slab
        # prefix (the draft's KV IS the target's first d layers' KV —
        # prefix-slice sharing, see serving/speculative.py)
        tb0 = device_put_elided(tables, stage0.device)
        # the ENTIRE k-step autoregressive draft is one compiled
        # program (DraftModel.draft_k, k static): one dispatch and one
        # device->host transfer per tick, not k of each
        drafted_dev, new_prefix = self._draft.draft_k(
            device_put_elided(tokens, stage0.device),
            stage0.slabs[:d], tb0,
            device_put_elided(index0, stage0.device),
            device_put_elided(reserve, stage0.device), k,
        )
        stage0.slabs = list(new_prefix) + stage0.slabs[d:]
        drafted = np.asarray(drafted_dev, dtype=np.int32)
        if tracer is not None:
            tracer.complete(
                "draft", tracer.lane("serving", "engine"), span0,
                {"active": len(active), "spec_k": k},
            )
        # --- verify: one Lq=k+1 forward over the whole pipeline
        verify_span0 = tracer.now() if tracer is not None else 0.0
        verify_in = np.concatenate([tokens[:, None], drafted], axis=1)
        logits3 = self._run_paged_stages(
            verify_in, tables, index0, valid, tracer, "decode"
        )  # [rows, k+1, V]
        target = _argmax_tokens(logits3)  # [rows, k+1]
        jax.block_until_ready(target)
        now = time.perf_counter()
        self.stats.decode_s += now - t0
        if tracer is not None:
            tracer.complete(
                "decode", tracer.lane("serving", "engine"), verify_span0,
                {"active": len(active), "spec_k": k},
            )
        self.stats.compiles += xla_compile_count() - compiles0

        target_np = np.asarray(target)
        sampled = self._sampled_rows(
            logits3[:, 0], [(r.slot, r) for r in active]
        )
        committed_total = 0
        for r in active:
            row = r.slot
            if r.temperature > 0.0:
                # position-0 logits == the plain decode tick's logits;
                # the drafts for this row are discarded (sampling has
                # no greedy acceptance rule) and never counted —
                # accept-rate observability describes greedy traffic
                tok = self._pick_token(
                    r, target_np[row, 0], sampled.get(row)
                )
                commit = [tok][: min(1, r.remaining)]
            else:
                remaining = r.remaining
                accepted = greedy_accept_count(
                    drafted[row], target_np[row, :k]
                )
                commit = (
                    [int(t) for t in drafted[row, :accepted]]
                    + [int(target_np[row, accepted])]
                )
                ncommit = min(len(commit), remaining)
                commit = commit[:ncommit]
                # the accept-rate denominator counts only USABLE
                # proposals: a row whose remaining budget is below k
                # could never consume the surplus drafts (the fixed
                # draft shape still computes them), and charging them
                # would deflate the rate below 1.0 for a PERFECT draft
                self.stats.draft_tokens += min(k, remaining)
                self.stats.accepted_draft_tokens += min(
                    accepted, ncommit
                )
                # the verify wrote min(k+1, remaining) positions (its
                # valid cap); a rollback happened iff the committed
                # watermark stops short of what was written
                if ncommit < min(k + 1, remaining):
                    self.stats.spec_rollbacks += 1
            for tok in commit:
                r.tokens.append(tok)
            r.index += len(commit)
            committed_total += len(commit)
            if r.done:
                self._finish(r, now)
        self.stats.decode_tokens += committed_total
        self.stats.generated_tokens += committed_total

    @staticmethod
    def _sampled_rows(logits, rows) -> Dict[int, np.ndarray]:
        """Host copies of ONLY the logits rows that temperature
        sampling needs: ``rows`` is (row index, request) pairs; greedy
        requests cost nothing — a full [slots, vocab] device->host pull
        per token would tax every tick for the life of one sampling
        request."""
        need = [i for i, r in rows if r.temperature > 0.0]
        if not need:
            return {}
        pulled = np.asarray(logits[np.asarray(need)])
        return dict(zip(need, pulled))

    def _pick_token(self, request: Request, greedy_tok, logits_row) -> int:
        """Greedy by default; per-request temperature sampling draws
        from a request-local stream (``fold_in(key(seed), position)``)
        so interleaving with other requests never perturbs it."""
        if request.temperature <= 0.0:
            return int(greedy_tok)
        sub = jax.random.fold_in(
            jax.random.key(request.seed),
            int(request.prompt.size) + len(request.tokens),
        )
        return int(
            jax.random.categorical(
                sub,
                jnp.asarray(logits_row, jnp.float32) / request.temperature,
            )
        )


__all__ = ["ServingEngine", "ServingStats"]
