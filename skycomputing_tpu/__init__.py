"""SkyComputing-TPU: load-balanced pipeline-parallel training, TPU-native.

A from-scratch JAX/XLA framework with the capabilities of
hpcaitech/SkyComputing (reference mounted at ``/root/reference``): per-device
and per-layer profiling, MIP/greedy/even layer->device allocation, and
pipeline-parallel BERT training — re-designed for TPU (single-controller JAX,
jit-compiled stages, ICI transfers, bfloat16 MXU compute) instead of
torch.distributed RPC over a GPU cluster.
"""

__version__ = "0.1.0"

from .config import Config, load_config
from .registry import DATA_GENERATOR, DATASET, HOOKS, LAYER, LOSS, MODEL, Registry
from .utils import Logger, DistributedTimer, get_time, generate_worker_name

# Root re-exports of the main subsystem classes, as the reference does
# (``scaelum/__init__.py:1-11``).  Submodule imports stay lazy-free: these
# pull in jax/flax, which is fine for a framework package.
from .builder import (
    build_dataloader_from_cfg,
    build_hook,
    build_layer,
    build_layer_stack,
    build_module_from_cfg,
    LayerStack,
)
from .dynamics import (
    Allocator,
    DeviceBenchmarker,
    Estimator,
    ModelBenchmarker,
    ParameterServer,
    Worker,
    WorkerManager,
)
from .chaos import FaultInjector, FaultPlan, get_fault_plan
from .fleet import FleetAutoscaler, FleetSupervisor, Router, ServingFleet
from .parallel import MeshPipelineModel, PipelineModel, StageRuntime
from .runner import AutotuneHook, Hook, Runner
from .workload import Scenario, ScenarioPlayer, get_scenario
from .serving import (
    ChunkBudgetPolicy,
    DraftModel,
    PagedKVCachePool,
    RadixPrefixIndex,
    Request,
    ServingEngine,
)
from .tuning import ServingAutotuner, TuningAdvisor
from .stimulator import Stimulator
from .telemetry import (
    MetricsExporter,
    MetricsRegistry,
    MetricsTimeseries,
    SloMonitor,
    SloTarget,
    Tracer,
    disable_tracing,
    enable_tracing,
    get_tracer,
)

__all__ = [
    "Config",
    "load_config",
    "Registry",
    "LAYER",
    "DATASET",
    "HOOKS",
    "DATA_GENERATOR",
    "MODEL",
    "LOSS",
    "Logger",
    "DistributedTimer",
    "get_time",
    "generate_worker_name",
    "build_dataloader_from_cfg",
    "build_hook",
    "build_layer",
    "build_layer_stack",
    "build_module_from_cfg",
    "LayerStack",
    "Allocator",
    "DeviceBenchmarker",
    "Estimator",
    "ModelBenchmarker",
    "ParameterServer",
    "Worker",
    "WorkerManager",
    "MeshPipelineModel",
    "PipelineModel",
    "StageRuntime",
    "Hook",
    "Runner",
    "AutotuneHook",
    "ChunkBudgetPolicy",
    "DraftModel",
    "PagedKVCachePool",
    "RadixPrefixIndex",
    "Request",
    "ServingEngine",
    "ServingFleet",
    "FleetAutoscaler",
    "FleetSupervisor",
    "Router",
    "Scenario",
    "ScenarioPlayer",
    "get_scenario",
    "FaultInjector",
    "FaultPlan",
    "get_fault_plan",
    "ServingAutotuner",
    "TuningAdvisor",
    "Stimulator",
    "MetricsExporter",
    "MetricsRegistry",
    "MetricsTimeseries",
    "SloMonitor",
    "SloTarget",
    "Tracer",
    "enable_tracing",
    "disable_tracing",
    "get_tracer",
    "__version__",
]
