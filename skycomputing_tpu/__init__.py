"""SkyComputing-TPU: load-balanced pipeline-parallel training, TPU-native.

A from-scratch JAX/XLA framework with the capabilities of
hpcaitech/SkyComputing (reference mounted at ``/root/reference``): per-device
and per-layer profiling, MIP/greedy/even layer->device allocation, and
pipeline-parallel BERT training — re-designed for TPU (single-controller JAX,
jit-compiled stages, ICI transfers, bfloat16 MXU compute) instead of
torch.distributed RPC over a GPU cluster.
"""

__version__ = "0.1.0"

from .config import Config, load_config
from .registry import DATA_GENERATOR, DATASET, HOOKS, LAYER, LOSS, MODEL, Registry
from .utils import Logger, DistributedTimer, get_time, generate_worker_name

__all__ = [
    "Config",
    "load_config",
    "Registry",
    "LAYER",
    "DATASET",
    "HOOKS",
    "DATA_GENERATOR",
    "MODEL",
    "LOSS",
    "Logger",
    "DistributedTimer",
    "get_time",
    "generate_worker_name",
    "__version__",
]
