"""LayerStack: the tuple-threading sequential container.

Functional analog of the reference's ``SequentialWrapper``
(``scaelum/builder/sequential_wrapper.py:8-20``): a chain of layer modules
where each layer consumes the *tuple* of outputs of the previous one (BERT
units pass ``(hidden, mask, ...)`` tuples).  Because JAX separates modules
from parameters, the stack holds linen module instances and threads a
*list of per-layer param pytrees* alongside the data tuple.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple

import jax


def as_tuple(x) -> Tuple:
    return x if isinstance(x, tuple) else (x,)


class LayerStack:
    """An ordered chain of linen modules with tuple-threading semantics."""

    def __init__(self, modules: Sequence[Any]):
        self.modules = list(modules)

    def __len__(self) -> int:
        return len(self.modules)

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return LayerStack(self.modules[idx])
        return self.modules[idx]

    def init(self, rng: jax.Array, *inputs) -> List[Any]:
        """Initialize every layer by threading example inputs through.

        Returns a list of per-layer param pytrees (each the layer's full
        variable dict ``{'params': ...}`` collapsed to its ``params`` tree).
        """
        params_list = []
        data = tuple(inputs)
        for i, module in enumerate(self.modules):
            layer_rng, dropout_rng, rng = jax.random.split(
                jax.random.fold_in(rng, i), 3
            )
            variables = module.init(
                {"params": layer_rng, "dropout": dropout_rng}, *data
            )
            params_list.append(variables["params"])
            data = as_tuple(
                module.apply(
                    {"params": variables["params"]},
                    *data,
                    rngs={"dropout": dropout_rng},
                )
            )
        return params_list

    def apply(
        self,
        params_list: Sequence[Any],
        *inputs,
        dropout_rng: Optional[jax.Array] = None,
    ):
        """Forward the tuple of inputs through every layer.

        Returns the final layer's raw output (tensor or tuple), matching the
        reference where the last stage's output lands in the loss.
        """
        if len(params_list) != len(self.modules):
            raise ValueError(
                f"got {len(params_list)} param trees for {len(self.modules)} layers"
            )
        data = tuple(inputs)
        out = data if len(data) > 1 else data[0]
        for i, (module, params) in enumerate(zip(self.modules, params_list)):
            rngs = None
            if dropout_rng is not None:
                rngs = {"dropout": jax.random.fold_in(dropout_rng, i)}
            out = module.apply({"params": params}, *data, rngs=rngs)
            data = as_tuple(out)
        return out

__all__ = ["LayerStack", "as_tuple"]
