"""Proxy layers for device benchmarking.

The reference benchmarks device speed with a stack of torch ``Conv2d`` layers
resolved through the registry's ``torch.nn`` fallback
(``experiment/config.py:134-149``, ``registry/registry.py:20-24``).  Here the
equivalents are registered flax modules:

- ``Conv2d`` accepts torch-style NCHW inputs and ctor args so reference-shaped
  proxy configs keep working;
- ``MatmulStack`` is the TPU-native proxy — a chain of MXU-sized matmuls is a
  far better predictor of TPU throughput than convs.
"""

from __future__ import annotations

from typing import Tuple, Union

import jax.numpy as jnp
import flax.linen as nn

from ..registry import LAYER


@LAYER.register_module
class Conv2d(nn.Module):
    """Torch-signature 2D conv over NCHW inputs (proxy-model compatibility)."""

    in_channels: int
    out_channels: int
    kernel_size: Union[int, Tuple[int, int]] = 3
    padding: Union[int, Tuple[int, int]] = 0
    stride: Union[int, Tuple[int, int]] = 1
    dtype: str = "float32"

    @nn.compact
    def __call__(self, x):
        ks = self.kernel_size
        ks = (ks, ks) if isinstance(ks, int) else tuple(ks)
        pad = self.padding
        pad = (pad, pad) if isinstance(pad, int) else tuple(pad)
        st = self.stride
        st = (st, st) if isinstance(st, int) else tuple(st)

        x = jnp.transpose(x, (0, 2, 3, 1))  # NCHW -> NHWC (TPU-native layout)
        x = nn.Conv(
            features=self.out_channels,
            kernel_size=ks,
            strides=st,
            padding=[(pad[0], pad[0]), (pad[1], pad[1])],
            dtype=jnp.dtype(self.dtype),
            param_dtype=jnp.float32,
        )(x)
        return jnp.transpose(x, (0, 3, 1, 2))  # back to NCHW for chaining


@LAYER.register_module
class MatmulStack(nn.Module):
    """``depth`` chained square matmuls — an MXU-saturating speed proxy."""

    features: int = 1024
    depth: int = 4
    dtype: str = "bfloat16"

    @nn.compact
    def __call__(self, x):
        x = x.astype(jnp.dtype(self.dtype))
        for i in range(self.depth):
            x = nn.Dense(
                self.features,
                dtype=jnp.dtype(self.dtype),
                param_dtype=jnp.float32,
                name=f"mm_{i}",
            )(x)
        return x


__all__ = ["Conv2d", "MatmulStack"]
