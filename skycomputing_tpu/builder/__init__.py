"""Config-driven factories (reference: ``scaelum/builder/builder.py:12-49``).

``build_module_from_cfg`` composes: layer-config list -> ``build_layer`` each
-> ``LayerStack``.  The reference additionally wraps the stack in a
``ModuleWrapper`` carrying per-worker runtime knobs; in the TPU build those
knobs (device binding, slowdown, memory limit) belong to the pipeline stage
runtime (``skycomputing_tpu.parallel.pipeline.StageRuntime``), keeping model
construction pure.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

from ..registry import DATA_GENERATOR, HOOKS, LAYER
from .layer_stack import LayerStack, as_tuple
from . import proxy_layers  # noqa: F401 - registers Conv2d / MatmulStack


def build_layer(layer_type: str, **kwargs):
    """Instantiate one registered layer module from its config kwargs."""
    cls = LAYER.get_module(layer_type)
    return cls(**kwargs)


def build_hook(cfg: Dict):
    cfg = dict(cfg)
    hook_type = cfg.pop("type")
    return HOOKS.get_module(hook_type)(**cfg)


def build_data_generator(generator_type: str, generator_cfg: Dict):
    return DATA_GENERATOR.get_module(generator_type)(**generator_cfg)


def build_layer_stack(model_cfg: Sequence[Dict]) -> LayerStack:
    """Layer-config list -> LayerStack of instantiated modules."""
    modules = []
    for layer_cfg in model_cfg:
        cfg = dict(layer_cfg)
        layer_type = cfg.pop("layer_type")
        modules.append(build_layer(layer_type, **cfg))
    return LayerStack(modules)


# Reference-name alias: build_module_from_cfg built the worker-side stage
# module (``builder/builder.py:29-41``); rank/wrapper args are accepted and
# ignored for signature compatibility.
def build_module_from_cfg(
    model_cfg: Sequence[Dict],
    rank: Optional[int] = None,
    module_wrapper_cfg: Optional[Dict] = None,
) -> LayerStack:
    return build_layer_stack(model_cfg)


def build_dataloader_from_cfg(data_cfg: Dict):
    """Dataset cfg + dataloader cfg -> DataLoader (see dataset package)."""
    from ..dataset import DATASET, DataLoader  # local import to avoid cycle

    dataset_cfg = dict(data_cfg["dataset_cfg"])
    dataloader_cfg = dict(data_cfg.get("dataloader_cfg", {}))
    ds_type = dataset_cfg.pop("type")
    dataset = DATASET.get_module(ds_type)(**dataset_cfg)
    return DataLoader(dataset, **dataloader_cfg)


__all__ = [
    "LayerStack",
    "as_tuple",
    "build_layer",
    "build_hook",
    "build_data_generator",
    "build_layer_stack",
    "build_module_from_cfg",
    "build_dataloader_from_cfg",
]
