"""Mixture-of-experts routing and dispatch, TPU-first.

The reference has no MoE (SURVEY.md §2.2); this adds the Switch/GShard
pattern as a framework capability: a learned router picks top-k experts per
token, tokens are dispatched into fixed-capacity expert buffers with pure
einsums (static shapes — no gather/scatter, no data-dependent control
flow), expert FFNs run vmapped over a stacked expert axis, and outputs are
combined with the gate weights.  Expert parallelism is nothing but a
sharding annotation on the expert axis ('ep'): under jit XLA lowers the
dispatch/combine einsums into all-to-alls across the mesh.

Shapes:  tokens [T, H]; router logits [T, E]; dispatch/combine [T, E, C]
with capacity C = ceil(T / E * capacity_factor).  Tokens over capacity are
dropped (their combine weight is zero and the residual path carries them) —
the standard static-shape trade.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def router_probs(tokens, router_kernel) -> jax.Array:
    """[T, H] x [H, E] -> float32 routing probabilities [T, E]."""
    logits = tokens.astype(jnp.float32) @ router_kernel.astype(jnp.float32)
    return jax.nn.softmax(logits, axis=-1)


def top_k_dispatch(
    probs: jax.Array, capacity: int, top_k: int = 1
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Build static-shape dispatch/combine tensors from router probs.

    Returns (dispatch [T, E, C] bool-ish float, combine [T, E, C] float32,
    aux_loss scalar).  aux_loss is the Switch load-balance loss
    (E * sum_e fraction_tokens_e * mean_prob_e), which pushes the router
    toward uniform expert utilization.
    """
    T, E = probs.shape
    gate_vals, expert_idx = jax.lax.top_k(probs, top_k)  # [T, k]

    dispatch = jnp.zeros((T, E, capacity), jnp.float32)
    combine = jnp.zeros((T, E, capacity), jnp.float32)
    # priority: tokens keep their order per expert; k-th choices queue
    # after all (k-1)-th choices so primary routes win capacity
    for k in range(top_k):
        onehot = jax.nn.one_hot(expert_idx[:, k], E, dtype=jnp.float32)
        # position of each token within its expert's buffer (dispatch
        # counts slots already granted to earlier-priority choices)
        prior = dispatch.sum(axis=(0, 2)) if k else jnp.zeros((E,))
        pos = jnp.cumsum(onehot, axis=0) - 1.0 + prior[None, :]
        keep = (pos < capacity) & (onehot > 0)
        pos_c = jnp.clip(pos, 0, capacity - 1).astype(jnp.int32)
        slot = jax.nn.one_hot(pos_c, capacity, dtype=jnp.float32)
        mask = (keep.astype(jnp.float32) * onehot)[:, :, None] * slot
        dispatch = dispatch + mask
        combine = combine + mask * gate_vals[:, k][:, None, None]

    # Switch aux loss over the PRIMARY assignment
    primary = jax.nn.one_hot(expert_idx[:, 0], E, dtype=jnp.float32)
    frac_tokens = primary.mean(axis=0)
    mean_probs = probs.mean(axis=0)
    aux_loss = E * jnp.sum(frac_tokens * mean_probs)
    return dispatch, combine, aux_loss


def moe_dispatch_combine(tokens, dispatch, combine, expert_fn):
    """tokens [T, H] -> expert buffers [E, C, H] -> combined [T, H].

    ``expert_fn`` maps [E, C, H] -> [E, C, H'] (vmapped expert compute).
    Pure einsums: on an 'ep'-sharded expert axis XLA turns these into
    all-to-all exchanges.
    """
    expert_in = jnp.einsum("tec,th->ech", dispatch.astype(tokens.dtype),
                           tokens)
    expert_out = expert_fn(expert_in)
    return jnp.einsum("tec,ech->th", combine.astype(expert_out.dtype),
                      expert_out)


__all__ = ["router_probs", "top_k_dispatch", "moe_dispatch_combine"]
