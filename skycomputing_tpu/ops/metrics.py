"""Classification metrics for GLUE-style evaluation (pure numpy).

The reference fine-tunes MNLI but ships no metric code at all; these cover
the tasks its processors parse: accuracy (MNLI/SST-2), F1 (MRPC), and
Matthews correlation (CoLA).
"""

from __future__ import annotations

from typing import Dict

import numpy as np


def accuracy(predictions, labels) -> float:
    predictions = np.asarray(predictions)
    labels = np.asarray(labels)
    return float((predictions == labels).mean()) if len(labels) else float("nan")


def f1_score(predictions, labels, positive: int = 1) -> float:
    predictions = np.asarray(predictions)
    labels = np.asarray(labels)
    tp = int(((predictions == positive) & (labels == positive)).sum())
    fp = int(((predictions == positive) & (labels != positive)).sum())
    fn = int(((predictions != positive) & (labels == positive)).sum())
    if 2 * tp + fp + fn == 0:
        return float("nan")
    return 2 * tp / (2 * tp + fp + fn)


def matthews_corrcoef(predictions, labels) -> float:
    predictions = np.asarray(predictions)
    labels = np.asarray(labels)
    tp = int(((predictions == 1) & (labels == 1)).sum())
    tn = int(((predictions == 0) & (labels == 0)).sum())
    fp = int(((predictions == 1) & (labels == 0)).sum())
    fn = int(((predictions == 0) & (labels == 1)).sum())
    denom = np.sqrt(
        float(tp + fp) * (tp + fn) * (tn + fp) * (tn + fn)
    )
    if denom == 0:
        return 0.0
    return float((tp * tn - fp * fn) / denom)


TASK_METRICS: Dict[str, Dict] = {
    "mnli": {"accuracy": accuracy},
    "sst-2": {"accuracy": accuracy},
    "mrpc": {"accuracy": accuracy, "f1": f1_score},
    "cola": {"matthews": matthews_corrcoef},
}


def compute_task_metrics(task: str, predictions, labels) -> Dict[str, float]:
    fns = TASK_METRICS.get(task.lower(), {"accuracy": accuracy})
    return {name: fn(predictions, labels) for name, fn in fns.items()}


__all__ = [
    "accuracy",
    "f1_score",
    "matthews_corrcoef",
    "TASK_METRICS",
    "compute_task_metrics",
]
