from .flash_attention import flash_attention
from .losses import build_loss, causal_lm_loss, cross_entropy_loss, mse_loss
from .paged_attention import paged_attention, paged_attention_reference
from .metrics import (
    accuracy,
    compute_task_metrics,
    f1_score,
    matthews_corrcoef,
)

__all__ = [
    "build_loss",
    "causal_lm_loss",
    "cross_entropy_loss",
    "mse_loss",
    "flash_attention",
    "paged_attention",
    "paged_attention_reference",
    "accuracy",
    "compute_task_metrics",
    "f1_score",
    "matthews_corrcoef",
]
