from .losses import build_loss, cross_entropy_loss, mse_loss

__all__ = ["build_loss", "cross_entropy_loss", "mse_loss"]
