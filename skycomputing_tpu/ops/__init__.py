from .flash_attention import flash_attention
from .losses import build_loss, cross_entropy_loss, mse_loss

__all__ = ["build_loss", "cross_entropy_loss", "mse_loss", "flash_attention"]
