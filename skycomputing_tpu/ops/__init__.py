from .flash_attention import flash_attention
from .losses import build_loss, causal_lm_loss, cross_entropy_loss, mse_loss
from .metrics import (
    accuracy,
    compute_task_metrics,
    f1_score,
    matthews_corrcoef,
)

__all__ = [
    "build_loss",
    "causal_lm_loss",
    "cross_entropy_loss",
    "mse_loss",
    "flash_attention",
    "accuracy",
    "compute_task_metrics",
    "f1_score",
    "matthews_corrcoef",
]
