"""Fused paged-attention decode kernel (PagedAttention's kernel half).

PR 9 reproduced the *memory-management* half of PagedAttention (Kwon et
al., SOSP '23): refcounted pages, per-request page tables, copy-on-write
prefix sharing.  Its device math, though, still materialized each row's
full virtual KV view in HBM every layer of every decode tick
(``serving/kv_cache.gather_kv_pages``): a ``[R, table_width * page_size,
heads, head_dim]`` gather whose cost scales with the TABLE width, not the
tokens actually live.  This module is the kernel half: the page walk
moves INSIDE a Pallas kernel, so the gathered view never exists —

- grid ``(rows, heads, table_width)``: each program owns one (row, head)
  pair's slice of one logical page; the page table rides scalar prefetch
  (``pltpu.PrefetchScalarGridSpec``) so the K/V BlockSpec index maps
  gather the right PHYSICAL page per grid step — one page-sized block
  through VMEM at a time, the ``flash_attention.py`` streaming recipe
  applied through an indirection table;
- online softmax: running max / running sum / accumulator live in VMEM
  scratch across the page dimension (initialized at page 0, emitted at
  the last page), so the ``[Lq, positions]`` score matrix never hits HBM;
- dead pages cost no math: a page wholly beyond a row's causal bound is
  skipped with ``pl.when`` (its block DMA still issues — bounding the
  TABLE width is the engine's job, see ``ServingEngine`` ``gather_pages``);
- sentinel table entries (``>= num_pages``, the pool's padding) clamp to
  a real page and are masked by the same causal rule that masks a slot
  row's stale tail — by the pool's covering invariant a sentinel only
  ever appears past the row's live span;
- int8 pages dequantize in-kernel: ``k/v_scale`` are the pool's
  per-page-per-head scale slabs (``serving/kv_cache.QuantizedPages``),
  fetched as (1, 1) blocks by the same table indirection and multiplied
  into the block after the int8 load — the quantized pool never takes an
  HBM-side dequantized copy either.

Off-TPU the kernel runs in interpret mode (the ``flash_attention.py``
convention), which is how the CPU suite pins it against the XLA
reference; ``attn_impl="pallas"`` on a CPU engine is therefore a
correctness surface, not a fast path — the compiled kernel needs a TPU.

Layer discipline: this module speaks raw arrays only (q, slabs, tables,
scales) — the serving package's pool/grant types stay out of ``ops``;
``models/gpt.decode_paged`` unpacks them before calling in.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# Guarded: the TPU-flavored Pallas namespace (scalar prefetch, VMEM
# scratch) is packaged with jax but has seen import-time breakage on
# exotic CPU-only builds; collection of this module must never die for
# it.  Callers get a precise error only when the kernel is actually
# invoked without it.
try:  # pragma: no cover - import guard
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover - exercised on broken builds only
    pltpu = None


def _require_pltpu():
    if pltpu is None:  # pragma: no cover - broken-build path
        raise RuntimeError(
            "jax.experimental.pallas.tpu failed to import on this build; "
            "the fused paged-attention kernel is unavailable — use "
            "attn_impl='xla' (the reference path)"
        )


def _paged_kernel(table_ref, idx_ref, q_ref, k_ref, v_ref, o_ref,
                  m_ref, l_ref, acc_ref, *, page_size: int,
                  softmax_scale: float):
    """fp kernel body: one (row, head, logical page) grid cell."""
    r = pl.program_id(0)
    i = pl.program_id(2)
    Lq = q_ref.shape[1]

    @pl.when(i == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    idx = idx_ref[r]

    # page i spans positions [i*ps, (i+1)*ps); the row's last query sits
    # at idx + Lq - 1, so later pages hold nothing visible — skipping
    # them also keeps a fully-masked block from feeding exp(-inf+inf)
    # NaNs into the running max
    @pl.when(i * page_size <= idx + Lq - 1)
    def _page():
        q = q_ref[0, :, 0, :].astype(jnp.float32) * softmax_scale
        k = k_ref[0, :, 0, :].astype(jnp.float32)
        v = v_ref[0, :, 0, :].astype(jnp.float32)
        _accumulate(q, k, v, idx, i, page_size, Lq,
                    m_ref, l_ref, acc_ref)

    @pl.when(i == pl.num_programs(2) - 1)
    def _emit():
        o_ref[0, :, 0, :] = (
            acc_ref[...] / jnp.maximum(l_ref[...], 1e-30)
        ).astype(o_ref.dtype)


def _paged_kernel_int8(table_ref, idx_ref, q_ref, k_ref, v_ref,
                       ks_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref, *,
                       page_size: int, softmax_scale: float):
    """int8 kernel body: dequantize the page block with its
    per-page-per-head scale right after the load."""
    r = pl.program_id(0)
    i = pl.program_id(2)
    Lq = q_ref.shape[1]

    @pl.when(i == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    idx = idx_ref[r]

    @pl.when(i * page_size <= idx + Lq - 1)
    def _page():
        q = q_ref[0, :, 0, :].astype(jnp.float32) * softmax_scale
        k = k_ref[0, :, 0, :].astype(jnp.float32) * ks_ref[0, 0]
        v = v_ref[0, :, 0, :].astype(jnp.float32) * vs_ref[0, 0]
        _accumulate(q, k, v, idx, i, page_size, Lq,
                    m_ref, l_ref, acc_ref)

    @pl.when(i == pl.num_programs(2) - 1)
    def _emit():
        o_ref[0, :, 0, :] = (
            acc_ref[...] / jnp.maximum(l_ref[...], 1e-30)
        ).astype(o_ref.dtype)


def _accumulate(q, k, v, idx, i, page_size, Lq, m_ref, l_ref, acc_ref):
    """One online-softmax block step (the flash_attention.py inner
    body, with the causal mask phrased in LOGICAL page positions)."""
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [Lq, page_size]
    pos = i * page_size + jax.lax.broadcasted_iota(
        jnp.int32, (Lq, page_size), 1
    )
    qpos = idx + jax.lax.broadcasted_iota(
        jnp.int32, (Lq, page_size), 0
    )
    s = jnp.where(pos <= qpos, s, -jnp.inf)
    m_prev = m_ref[...]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    p = jnp.exp(s - m_new)
    corr = jnp.exp(m_prev - m_new)
    l_ref[...] = l_ref[...] * corr + jnp.sum(p, axis=-1, keepdims=True)
    acc_ref[...] = acc_ref[...] * corr + jax.lax.dot_general(
        p, v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    m_ref[...] = m_new


def paged_attention(
    q,
    k_pages,
    v_pages,
    page_table,
    index,
    *,
    k_scale=None,
    v_scale=None,
    softmax_scale: Optional[float] = None,
    interpret: Optional[bool] = None,
):
    """Fused attention over paged KV, table walk inside the kernel.

    ``q``: [R, Lq, H, D] query block (``Lq = 1`` decode, ``Lq = k + 1``
    speculative verify); ``k_pages``/``v_pages``: [num_pages, page_size,
    H, D] physical page pools — fp, or int8 with ``k_scale``/``v_scale``
    [num_pages, H] per-page-per-head dequant scales; ``page_table``:
    [R, table_width] int32 logical->physical, sentinel-padded
    (``>= num_pages`` entries clamp and are causally masked);
    ``index``: [R] (or scalar) position of each row's FIRST query —
    query ``j`` sits at ``index + j`` and sees positions ``<= index + j``.

    Returns the attention context [R, Lq, H, D] in ``q``'s dtype.  The
    math is the XLA reference's (``float32`` softmax, same causal/
    staleness mask) restructured as online softmax, so fp outputs agree
    to float32 roundoff and greedy decode streams are token-identical.
    """
    _require_pltpu()
    R, Lq, H, D = q.shape
    num_pages, page_size = k_pages.shape[0], k_pages.shape[1]
    table_width = page_table.shape[1]
    if softmax_scale is None:
        softmax_scale = float(D) ** -0.5
    if interpret is None:
        # the flash_attention.py convention: same code path everywhere,
        # compiled on TPU, interpreted (slow but exact) off it
        interpret = jax.default_backend() != "tpu"
    quantized = k_scale is not None
    if quantized != (v_scale is not None):
        raise ValueError("pass k_scale AND v_scale together (int8) "
                         "or neither (fp)")

    idx = jnp.broadcast_to(
        jnp.reshape(jnp.asarray(index, jnp.int32), (-1,)), (R,)
    )
    table = jnp.asarray(page_table, jnp.int32)

    def q_map(r, h, i, table_ref, idx_ref):
        return (r, 0, h, 0)

    def kv_map(r, h, i, table_ref, idx_ref):
        # sentinel entries clamp into the pool; their positions are past
        # the row's causal bound by the pool's covering invariant, so
        # the mask (not the clamp target) is what keeps them inert
        return (jnp.minimum(table_ref[r, i], num_pages - 1), 0, h, 0)

    def scale_map(r, h, i, table_ref, idx_ref):
        return (jnp.minimum(table_ref[r, i], num_pages - 1), h)

    in_specs = [
        pl.BlockSpec((1, Lq, 1, D), q_map),
        pl.BlockSpec((1, page_size, 1, D), kv_map),
        pl.BlockSpec((1, page_size, 1, D), kv_map),
    ]
    operands = [q, k_pages, v_pages]
    if quantized:
        in_specs += [
            pl.BlockSpec((1, 1), scale_map),
            pl.BlockSpec((1, 1), scale_map),
        ]
        operands += [k_scale, v_scale]
        body = functools.partial(
            _paged_kernel_int8, page_size=page_size,
            softmax_scale=softmax_scale,
        )
    else:
        body = functools.partial(
            _paged_kernel, page_size=page_size,
            softmax_scale=softmax_scale,
        )

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(R, H, table_width),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, Lq, 1, D), q_map),
        scratch_shapes=[
            pltpu.VMEM((Lq, 1), jnp.float32),  # running max
            pltpu.VMEM((Lq, 1), jnp.float32),  # running sum
            pltpu.VMEM((Lq, D), jnp.float32),  # output accumulator
        ],
    )
    return pl.pallas_call(
        body,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((R, Lq, H, D), q.dtype),
        interpret=interpret,
    )(table, idx, *operands)


def paged_attention_reference(
    q, k_pages, v_pages, page_table, index, *,
    k_scale=None, v_scale=None, softmax_scale: Optional[float] = None,
):
    """Plain-XLA reference with the kernel's exact contract: gather the
    virtual views (materialized — the cost the kernel removes), mask,
    float32 softmax.  The correctness anchor for the kernel tests and
    the CI smoke; the serving engine's ``attn_impl="xla"`` path computes
    the same thing through ``serving/kv_cache.gather_kv_pages``."""
    R, Lq, H, D = q.shape
    num_pages, page_size = k_pages.shape[0], k_pages.shape[1]
    if softmax_scale is None:
        softmax_scale = float(D) ** -0.5
    idx = jnp.broadcast_to(
        jnp.reshape(jnp.asarray(index, jnp.int32), (-1,)), (R,)
    )
    pos = (
        jnp.asarray(page_table, jnp.int32)[:, :, None] * page_size
        + jnp.arange(page_size, dtype=jnp.int32)[None, None, :]
    )
    flat_pos = jnp.clip(pos.reshape(R, -1), 0, num_pages * page_size - 1)

    def gather(slab, scale):
        flat = slab.reshape((num_pages * page_size,) + slab.shape[2:])
        out = flat[flat_pos].astype(jnp.float32)
        if scale is not None:
            page_of = flat_pos // page_size
            out = out * scale[page_of][:, :, :, None]
        return out

    k_virt = gather(k_pages, k_scale)  # [R, W*ps, H, D]
    v_virt = gather(v_pages, v_scale)
    s = jnp.einsum(
        "blhd,bmhd->bhlm", q.astype(jnp.float32) * softmax_scale, k_virt
    )
    virt_len = k_virt.shape[1]
    qpos = idx[:, None] + jnp.arange(Lq, dtype=jnp.int32)
    kpos = jnp.arange(virt_len, dtype=jnp.int32)
    visible = kpos[None, None, :] <= qpos[:, :, None]
    s = jnp.where(visible[:, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhlm,bmhd->blhd", p, v_virt).astype(q.dtype)


__all__ = ["paged_attention", "paged_attention_reference"]
