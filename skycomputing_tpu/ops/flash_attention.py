"""Fused blockwise attention (flash attention) as a Pallas TPU kernel.

The reference computes attention as materialized [B, H, L, L] score tensors
through torch matmul + softmax (``scaelum/model/bert_layers.py:249-275``) —
HBM-bound on TPU.  This kernel streams K/V blocks through VMEM with an
online-softmax accumulator (running max / running sum in float32), so the
score matrix never hits HBM and the MXU stays fed.

Forward is the Pallas kernel; backward is a ``jax.custom_vjp`` that
recomputes attention with plain XLA ops (exact same math, float32 softmax),
trading backward-pass memory for a simple, provably-matching gradient.  On
non-TPU backends the kernel runs in interpret mode, which is how the CPU
test suite validates it bit-for-bit against the reference softmax.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _flash_kernel(q_ref, k_ref, v_ref, bias_ref, o_ref, *, block_k: int,
                  scale: float):
    # q_ref block: [1, block_q, d]; k/v blocks: [1, L, d]; bias: [1, 1, L]
    # (bias keeps a singleton row so its block shape equals its array shape,
    # which Mosaic requires when the block is not (8, 128)-aligned)
    q = q_ref[0, :, :].astype(jnp.float32) * scale
    seq_len = k_ref.shape[1]
    block_q, head_dim = q.shape
    num_kb = seq_len // block_k

    def body(i, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        b = bias_ref[0, 0, pl.ds(i * block_k, block_k)].astype(jnp.float32)

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ()))
        )  # [block_q, block_k]
        s = s + b[None, :]

        m_blk = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m, m_blk)
        p = jnp.exp(s - m_new)
        correction = jnp.exp(m - m_new)
        l_new = l * correction + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * correction + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ()))
        )
        return m_new, l_new, acc_new

    m0 = jnp.full((block_q, 1), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, head_dim), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, num_kb, body, (m0, l0, acc0))

    o_ref[0, :, :] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _flash_forward(
    q, k, v, bias, scale, block_q, block_k, interpret
):
    """q/k/v: [B, L, H, D]; bias: [B, L] additive (0 or -1e4 style)."""
    B, L, H, D = q.shape

    def pick_block(requested: int) -> int:
        # honor the request when it tiles L exactly; otherwise fall back to
        # the largest multiple-of-8 divisor of L <= requested (Mosaic wants
        # 8-aligned sublanes), and as a last resort one full-L block
        if L <= requested:
            return L
        if L % requested == 0:
            return requested
        for b in range(requested - requested % 8, 7, -8):
            if L % b == 0:
                return b
        if L > 1024:
            # a single full-L tile would blow VMEM; make the caller pad
            raise ValueError(
                f"seq len {L} has no 8-aligned divisor <= {requested}; "
                f"pad the sequence to a multiple of 128"
            )
        return L

    block_q = pick_block(block_q)
    block_k = pick_block(block_k)

    # [B, L, H, D] -> [B*H, L, D] rows so each grid cell owns one head
    def to_rows(x):
        return x.transpose(0, 2, 1, 3).reshape(B * H, L, D)

    q_r, k_r, v_r = to_rows(q), to_rows(k), to_rows(v)
    bias_r = jnp.repeat(bias, H, axis=0)[:, None, :]  # [B*H, 1, L]

    grid = (B * H, L // block_q)
    out = pl.pallas_call(
        functools.partial(_flash_kernel, block_k=block_k, scale=scale),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda bh, iq: (bh, iq, 0)),
            pl.BlockSpec((1, L, D), lambda bh, iq: (bh, 0, 0)),
            pl.BlockSpec((1, L, D), lambda bh, iq: (bh, 0, 0)),
            pl.BlockSpec((1, 1, L), lambda bh, iq: (bh, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda bh, iq: (bh, iq, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, L, D), q.dtype),
        interpret=interpret,
    )(q_r, k_r, v_r, bias_r)

    return out.reshape(B, H, L, D).transpose(0, 2, 1, 3)


def _reference_attention(q, k, v, bias, scale):
    """Plain-XLA attention, float32 softmax — used for the backward pass."""
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q.astype(jnp.float32) * scale,
        k.astype(jnp.float32),
    )
    s = s + bias[:, None, None, :].astype(jnp.float32)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32)).astype(
        q.dtype
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def flash_attention(
    q,
    k,
    v,
    bias,
    scale: Optional[float] = None,
    block_q: int = 256,
    block_k: int = 512,
    interpret: Optional[bool] = None,
):
    """Fused attention.  q/k/v: [B, L, H, D]; bias: [B, L] additive mask.

    ``interpret=None`` auto-selects interpret mode off-TPU so the same code
    path runs (slowly but exactly) on the CPU test mesh.  Default block
    sizes were tuned on a v5e chip (L=4096: 2.2x over the einsum path at
    bq=256/bk=512; the 128/128 blocks actually lost to XLA's fused einsum);
    they clamp to L for shorter sequences.
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _flash_forward(q, k, v, bias, scale, block_q, block_k, interpret)


def _fwd(q, k, v, bias, scale, block_q, block_k, interpret):
    out = flash_attention(q, k, v, bias, scale, block_q, block_k, interpret)
    return out, (q, k, v, bias)


def _bwd(scale, block_q, block_k, interpret, residuals, g):
    q, k, v, bias = residuals
    if scale is None:
        scale = q.shape[-1] ** -0.5

    def f(q, k, v, bias):
        return _reference_attention(q, k, v, bias, scale)

    _, vjp_fn = jax.vjp(f, q, k, v, bias)
    return vjp_fn(g)


flash_attention.defvjp(_fwd, _bwd)

__all__ = ["flash_attention"]
