"""Loss functions, registry-named after their reference torch counterparts
(``runner/runner.py:50-52`` resolves ``loss_cfg['type']`` from ``torch.nn``)."""

from __future__ import annotations

import jax.numpy as jnp
import optax

from ..registry import LOSS


@LOSS.register_module(name="CrossEntropyLoss")
def cross_entropy_loss(logits, labels):
    return optax.softmax_cross_entropy_with_integer_labels(
        logits.astype(jnp.float32), labels
    ).mean()


@LOSS.register_module(name="MSELoss")
def mse_loss(predictions, targets):
    return jnp.mean((predictions.astype(jnp.float32) - targets) ** 2)


@LOSS.register_module(name="CausalLmLoss")
def causal_lm_loss(logits, labels, mask=None, pad_id=None):
    """Next-token cross entropy; labels are the (unshifted) input ids.

    Padding must not be trained on: pass ``mask`` (1 = real token, aligned
    with ``labels``) and/or ``pad_id`` (targets equal to it are ignored)
    to get a masked mean over real target positions only.  With neither,
    every position counts — correct only for unpadded batches.
    """
    per_token = optax.softmax_cross_entropy_with_integer_labels(
        logits[:, :-1].astype(jnp.float32), labels[:, 1:]
    )
    target_mask = None
    if mask is not None:
        target_mask = mask[:, 1:].astype(jnp.float32)
    if pad_id is not None:
        pad_mask = (labels[:, 1:] != pad_id).astype(jnp.float32)
        target_mask = (
            pad_mask if target_mask is None else target_mask * pad_mask
        )
    if target_mask is None:
        return per_token.mean()
    return (per_token * target_mask).sum() / jnp.maximum(
        target_mask.sum(), 1.0
    )


def build_loss(loss_cfg: dict):
    """Resolve ``{'type': <registry name>, **options}``; leftover options
    are partial-applied (e.g. ``{'type': 'CausalLmLoss', 'pad_id': 0}``)."""
    import functools
    import inspect

    cfg = dict(loss_cfg)
    name = cfg.pop("type")
    fn = LOSS.get_module(name)
    if cfg:
        known = list(inspect.signature(fn).parameters)
        unknown = [k for k in cfg if k not in known]
        if unknown:
            raise ValueError(f"loss {name} got unknown options {unknown}")
        # the first two parameters (predictions, targets) are supplied at
        # call time; binding them here would only surface as a confusing
        # TypeError inside the first jitted train step
        shadowed = [k for k in cfg if k in known[:2]]
        if shadowed:
            raise ValueError(
                f"loss {name} options {shadowed} shadow call-time arguments"
            )
        fn = functools.partial(fn, **cfg)
    return fn


__all__ = ["cross_entropy_loss", "mse_loss", "causal_lm_loss", "build_loss"]
