"""Loss functions, registry-named after their reference torch counterparts
(``runner/runner.py:50-52`` resolves ``loss_cfg['type']`` from ``torch.nn``)."""

from __future__ import annotations

import jax.numpy as jnp
import optax

from ..registry import LOSS


@LOSS.register_module(name="CrossEntropyLoss")
def cross_entropy_loss(logits, labels):
    return optax.softmax_cross_entropy_with_integer_labels(
        logits.astype(jnp.float32), labels
    ).mean()


@LOSS.register_module(name="MSELoss")
def mse_loss(predictions, targets):
    return jnp.mean((predictions.astype(jnp.float32) - targets) ** 2)


@LOSS.register_module(name="CausalLmLoss")
def causal_lm_loss(logits, labels):
    """Next-token cross entropy; labels are the (unshifted) input ids."""
    return optax.softmax_cross_entropy_with_integer_labels(
        logits[:, :-1].astype(jnp.float32), labels[:, 1:]
    ).mean()


def build_loss(loss_cfg: dict):
    cfg = dict(loss_cfg)
    name = cfg.pop("type")
    fn = LOSS.get_module(name)
    if cfg:
        raise ValueError(f"loss {name} takes no extra config, got {cfg}")
    return fn


__all__ = ["cross_entropy_loss", "mse_loss", "causal_lm_loss", "build_loss"]
