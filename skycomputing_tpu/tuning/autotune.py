"""The act half of the closed loop: apply, verify, and roll back.

``TuningAdvisor`` (pure, jax-free) decides; this module acts.  Shared
contract for both actuation surfaces (the Runner's ``AutotuneHook`` and
:class:`ServingAutotuner` here):

1. **verify-then-apply** — every proposal passes a pre-flight verifier
   BEFORE it takes effect: knob proposals through
   ``analysis/plan_check.verify_tuning_knobs``, allocation proposals
   through the full ``verify_plan`` (zero-FLOP ``eval_shape``) against
   the re-solved partition.  A rejected proposal leaves the system
   untouched and its signature blocked.
2. **measure-then-commit** — an applied proposal is provisional: the
   NEXT analysis window must show its promised metric improving by at
   least ``min_improvement``, or the change is rolled back (partition
   AND calibration for allocation proposals) and the signature blocked.
3. **everything visible** — each attempt is an async ``autotune`` arc
   on the trace (opened at apply, closed with the outcome), with
   ``autotune.analyze`` / ``autotune.apply`` / ``autotune.rollback``
   spans inside, so a Perfetto timeline shows the control loop acting
   on the same timeline it read.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from ..telemetry import get_tracer
from ..telemetry.analysis import TraceError, analyze
from ..utils import Logger
from .advisor import Proposal, TuningAdvisor

# outcome strings recorded in events lists and trace args (stable ids)
APPLIED = "applied"
COMMITTED = "committed"
NO_OP = "no_op"
REJECTED = "rejected"
ROLLED_BACK = "rolled_back"


def window_events(tracer, t0_us: float) -> List[Dict[str, Any]]:
    """Chrome events recorded at/after ``t0_us`` (lane metadata always
    included — analysis needs the process-name map regardless of when a
    lane registered).  The filter happens inside the export so a full
    ring buffer is never materialized just to be discarded."""
    return tracer.to_chrome(since_us=t0_us)["traceEvents"]


def snapshot_partition(worker_manager) -> List[tuple]:
    """Per-worker (id, layer slice, order, mesh chips) — everything
    :func:`restore_partition` needs to undo a re-allocation, including a
    mesh reshape (``mesh_chips`` is the sub-mesh width
    ``Allocator.mesh_allocate`` wrote, None for MPMD partitions)."""
    return [
        (w.id, list(w.model_config or []), w.order,
         w.extra_config.get("mesh_chips"))
        for w in worker_manager.worker_pool
    ]


def restore_partition(worker_manager, snapshot: List[tuple]) -> None:
    for worker_id, model_config, order, mesh_chips in snapshot:
        worker = worker_manager.get_by_id(worker_id)
        worker.model_config = model_config
        worker.order = order
        if mesh_chips is None:
            worker.extra_config.pop("mesh_chips", None)
        else:
            worker.extra_config["mesh_chips"] = mesh_chips
    worker_manager.reset_rank_by_order()


def improved(base: float, new: float, min_improvement: float) -> bool:
    """Did the metric move down by at least ``min_improvement``
    (relative, with a small absolute floor so near-zero baselines don't
    demand sub-noise deltas)?"""
    return new <= base - max(abs(base) * min_improvement, 1e-9)


class ServingAutotuner:
    """Closed-loop tuner for a live :class:`~..serving.ServingEngine`.

    Attaches itself as ``engine.autotuner``: every ``engine.step()``
    ends with :meth:`on_step`, and every ``tune_every`` steps the tuner
    analyzes the trace window since its last decision, asks the advisor
    for a proposal over the serving knobs (bucket set, slot count), and
    applies it through ``engine.reconfigure`` — which runs the
    pre-flight knob verifier and the live-request feasibility check
    before touching anything.  The next window then has to prove the
    change (padding waste down for a bucket change, stall share down
    for a slot change) or it is rolled back by reconfiguring straight
    back.

    Requires tracing to be enabled (the trace IS the sensor); steps
    taken while tracing is off are counted but never analyzed.
    """

    def __init__(
        self,
        engine,
        advisor: Optional[TuningAdvisor] = None,
        tune_every: int = 32,
        max_tunes: int = 3,
        min_improvement: float = 0.05,
        settle_windows: int = 2,
        logger: Optional[Logger] = None,
    ):
        if tune_every < 1:
            raise ValueError(f"tune_every must be >= 1, got {tune_every}")
        self.engine = engine
        self.advisor = advisor or TuningAdvisor()
        self.tune_every = int(tune_every)
        self.max_tunes = int(max_tunes)
        self.min_improvement = float(min_improvement)
        self.settle_windows = int(settle_windows)
        self.tunes = 0
        self.events: List[Dict[str, Any]] = []
        self.blocked: set = set()
        self._logger = logger or Logger()
        self._steps = 0
        self._window_t0: Optional[float] = None
        self._pending: Optional[Dict[str, Any]] = None
        self._arc_id = 0
        # window-scoped SLO sampling: TPOT samples appended since the
        # window opened (the decode-tail signature needs per-request
        # percentiles, which the trace alone does not carry)
        self._tpot_mark = 0
        engine.autotuner = self

    # --- trace plumbing ----------------------------------------------------
    def _lane(self, tracer):
        return tracer.lane("autotune", "serving")

    def _record(self, outcome: str, **extra) -> None:
        self.events.append(dict(outcome=outcome, step=self._steps, **extra))

    # --- the loop ----------------------------------------------------------
    def on_step(self, engine) -> None:
        self._steps += 1
        tracer = get_tracer()
        if tracer is None:
            return
        if self._window_t0 is None:
            self._window_t0 = tracer.now()
            self._window_start_step = self._steps
            self._tpot_mark = len(engine.stats.tpot_s)
            return
        if self._steps - self._window_start_step < self.tune_every:
            return
        t0 = tracer.now()
        with tracer.span("autotune.analyze", self._lane(tracer),
                         {"window_ms": (t0 - self._window_t0) / 1e3}):
            try:
                report = analyze(window_events(tracer, self._window_t0))
            except TraceError:
                report = None
        self._merge_window_slo(report, engine)
        self._window_t0 = tracer.now()
        self._window_start_step = self._steps
        self._tpot_mark = len(engine.stats.tpot_s)
        if report is None:
            return
        if self._pending is not None:
            self._settle(tracer, report)
            return
        if self.tunes >= self.max_tunes:
            return
        blocked = set(self.blocked)
        if not getattr(engine, "_paged", False):
            # prefill_chunk is a paged-only knob: a slot engine would
            # reject the proposal and burn the signature forever —
            # mask it instead of spending a blocked slot on it
            from .advisor import DECODE_TAIL

            blocked.add(DECODE_TAIL)
        proposal = self.advisor.propose_serving(
            report,
            buckets=engine.bucketer.buckets,
            num_slots=engine.num_slots,
            max_len=engine.max_len,
            prefill_chunk=getattr(engine, "prefill_chunk", None),
            blocked=blocked,
        )
        if proposal is None:
            self._record(NO_OP)
            return
        self._apply(tracer, report, proposal)

    def _merge_window_slo(self, report: Optional[Dict[str, Any]],
                          engine) -> None:
        """Fold the WINDOW's per-request TPOT percentiles into the
        report's serving section (the decode-tail signature's input —
        one merge site, so decide and judge read the same numbers).
        Windows with too few finished requests carry no percentiles:
        two samples cannot distinguish a tail from noise."""
        if report is None or not report.get("serving"):
            return
        samples = engine.stats.tpot_s
        window = [s for s in samples[self._tpot_mark:] if s is not None]
        if len(window) < 4:
            return
        ordered = sorted(window)

        def pct(q):
            i = min(len(ordered) - 1,
                    max(0, int(round(q / 100.0 * (len(ordered) - 1)))))
            return float(ordered[i])

        report["serving"]["tpot_p50_s"] = pct(50)
        report["serving"]["tpot_p95_s"] = pct(95)

    def _metric(self, report: Dict[str, Any], name: str) -> Optional[float]:
        serving = report.get("serving") or {}
        if name == "padding_fraction":
            # the field analyze() computed — same number the advisor
            # thresholded on when it proposed the change
            return serving.get("padding_fraction")
        if name == "stall_fraction":
            ticks = serving.get("prefill_waves", 0) + serving.get(
                "decode_ticks", 0
            )
            if ticks <= 0:
                return None
            return serving.get("queue_stalls", 0) / ticks
        if name == "tpot_tail_ratio":
            p50 = serving.get("tpot_p50_s")
            p95 = serving.get("tpot_p95_s")
            if not p50 or not p95 or p50 <= 0:
                return None
            return float(p95) / float(p50)
        return None

    def _apply(self, tracer, report: Dict[str, Any],
               proposal: Proposal) -> None:
        base = self._metric(report, proposal.metric)
        if base is None:
            self._record(NO_OP, note=f"metric {proposal.metric} "
                                     f"unavailable in window")
            return
        engine = self.engine
        revert = dict(buckets=list(engine.bucketer.buckets),
                      num_slots=engine.num_slots,
                      prefill_batch=engine.prefill_batch)
        if getattr(engine, "_paged", False):
            # 0 = "chunking off" in reconfigure's knob language; slot
            # engines never see the key (they would reject it)
            revert["prefill_chunk"] = engine.prefill_chunk or 0
        self._arc_id += 1
        tracer.async_begin("autotune", self._lane(tracer), self._arc_id,
                           proposal.describe())
        try:
            with tracer.span("autotune.apply", self._lane(tracer),
                             proposal.describe()):
                if proposal.knob == "buckets":
                    engine.reconfigure(buckets=proposal.value)
                elif proposal.knob == "slots":
                    engine.reconfigure(num_slots=proposal.value)
                elif proposal.knob == "prefill_chunk":
                    engine.reconfigure(prefill_chunk=proposal.value)
                else:
                    raise ValueError(
                        f"serving tuner cannot actuate knob "
                        f"{proposal.knob!r}"
                    )
        except Exception as exc:
            # verify_tuning_knobs rejection (PlanError), infeasible live
            # requests (ValueError): the engine is untouched — block the
            # signature and close the arc
            self.blocked.add(proposal.signature)
            self._record(REJECTED, proposal=proposal.describe(),
                         error=str(exc))
            tracer.async_end("autotune", self._lane(tracer), self._arc_id,
                             {"outcome": REJECTED})
            self._logger.warning(
                f"ServingAutotuner: rejected {proposal.signature}: {exc}"
            )
            return
        self._pending = dict(proposal=proposal, base=base, revert=revert,
                             waited=0, arc_id=self._arc_id)
        self._record(APPLIED, proposal=proposal.describe(), base=base)
        self._logger.info(
            f"ServingAutotuner: applied {proposal.signature} "
            f"({proposal.reason}); verifying next window"
        )

    def _settle(self, tracer, report: Dict[str, Any]) -> None:
        pending = self._pending
        proposal: Proposal = pending["proposal"]
        new = self._metric(report, proposal.metric)
        if new is None:
            # the window carried no evidence (e.g. no prefill waves for
            # a padding metric): wait, bounded — then judge on what the
            # proposal was for, which without evidence means rollback
            pending["waited"] += 1
            if pending["waited"] < self.settle_windows:
                return
            new = float("inf")
        if improved(pending["base"], new, self.min_improvement):
            self.tunes += 1
            self._pending = None
            self._record(COMMITTED, proposal=proposal.describe(),
                         base=pending["base"], new=new)
            tracer.async_end("autotune", self._lane(tracer),
                             pending["arc_id"], {"outcome": COMMITTED})
            self._logger.info(
                f"ServingAutotuner: committed {proposal.signature} "
                f"({proposal.metric} {pending['base']:.4f} -> {new:.4f})"
            )
            return
        self.blocked.add(proposal.signature)
        self._pending = None
        try:
            with tracer.span("autotune.rollback", self._lane(tracer),
                             proposal.describe()):
                self.engine.reconfigure(**pending["revert"])
        except Exception as exc:
            # a request may have grown past the OLD operating point
            # (e.g. beyond a removed bucket) — the revert is infeasible,
            # so the new point stays; the signature is blocked either
            # way and the engine keeps serving
            self._record("rollback_infeasible",
                         proposal=proposal.describe(), error=str(exc))
            tracer.async_end("autotune", self._lane(tracer),
                             pending["arc_id"],
                             {"outcome": "rollback_infeasible"})
            self._logger.warning(
                f"ServingAutotuner: rollback of {proposal.signature} "
                f"infeasible ({exc}); keeping the new operating point"
            )
            return
        self._record(ROLLED_BACK, proposal=proposal.describe(),
                     base=pending["base"], new=new)
        tracer.async_end("autotune", self._lane(tracer),
                         pending["arc_id"], {"outcome": ROLLED_BACK})
        self._logger.warning(
            f"ServingAutotuner: rolled back {proposal.signature} "
            f"({proposal.metric} {pending['base']:.4f} -> {new:.4f}, "
            f"no improvement)"
        )


__all__ = [
    "APPLIED",
    "COMMITTED",
    "NO_OP",
    "REJECTED",
    "ROLLED_BACK",
    "ServingAutotuner",
    "improved",
    "restore_partition",
    "snapshot_partition",
    "window_events",
]
