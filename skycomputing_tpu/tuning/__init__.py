"""Closed-loop autotuning: telemetry in, faster plans out.

PR 5's tracer made bubbles, stragglers, dispatch stalls, and TTFT/TPOT
components *measurable*; this package makes them *actionable* — the
observe -> decide -> act cycle the paper's load-balanced allocation is
built around, with traces instead of startup benchmarks as the sensor
(PipeDream's profiler -> partitioner loop, extended to serving):

- :mod:`.advisor` — ``TuningAdvisor``, the pure decide step: analysis
  report in, at most one knob ``Proposal`` out;
- :mod:`.autotune` — the act step: verify-then-apply, measure-then-
  commit, guarded rollback; includes ``ServingAutotuner`` (attaches to
  a live ``ServingEngine``);
- the training-side actuator is
  :class:`~skycomputing_tpu.runner.AutotuneHook`
  (``runner/hooks_collection/autotune_hook.py``), which drives the same
  contract through the Runner's hook lifecycle and the self-heal
  in-process rebuild path.

See ``docs/autotuning.md`` for trace signatures, the knob space, and
the verify/rollback semantics.
"""

from .advisor import Proposal, TuningAdvisor
from .autotune import (
    ServingAutotuner,
    improved,
    restore_partition,
    snapshot_partition,
    window_events,
)

__all__ = [
    "Proposal",
    "ServingAutotuner",
    "TuningAdvisor",
    "improved",
    "restore_partition",
    "snapshot_partition",
    "window_events",
]
