"""TuningAdvisor: map trace signatures onto candidate knob changes.

The advisor is the *decide* third of the observe -> decide -> act loop:
it reads the analysis report (``telemetry/analysis.py`` — the same dict
``tools/trace_report.py`` renders) and returns at most one
:class:`Proposal` naming a knob the runtime already exposes:

==================  =======================================================
trace signature     proposed knob change
==================  =======================================================
straggler           one stage's busy time dominates the median stage ->
                    ``allocation``: re-solve with the measured per-stage
                    seconds folded into the DEVICE model
                    (``Allocator.refine_allocation(attribute="devices")``)
high bubble         bubble fraction above threshold on a gpipe schedule ->
                    ``schedule``: switch to 1f1b; already 1f1b (or M=1) ->
                    ``microbatches``: double the microbatch count
skewed buckets      prefill padding waste above threshold ->
                    ``buckets``: insert a bucket sized to the over-padded
                    bucket's observed mean prompt length
queue pressure      admission stalls on a large share of engine ticks ->
                    ``slots``: double the KV slot count
decode tail         per-request TPOT p95/p50 above threshold (decode
                    ticks stalling behind whole prefill waves) ->
                    ``prefill_chunk``: enable chunked prefill at the
                    largest sub-max bucket, or shrink one bucket if on
clean trace         ``None`` — a healthy run is left alone
==================  =======================================================

The advisor is PURE: report in, proposal out, no side effects and no
jax — so it unit-tests on synthetic traces in microseconds and
``tools/bench_autotune.py`` can exercise it on a bare CI runner by
file-path load (the ``tools/skylint.py`` idiom).  Applying, verifying,
and rolling back proposals is the hook's job (``tuning/autotune.py``,
``runner/hooks_collection/autotune_hook.py``).

``blocked`` threading: the acting layer passes the signatures of
proposals that were rejected by the pre-flight verifier or rolled back
after failing to improve; the advisor never re-proposes those, which is
what makes the closed loop converge instead of thrash.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Iterable, Optional, Sequence

# signature ids (stable: recorded in hook events and blocked-sets)
STRAGGLER = "straggler"
PIPELINE_SCHEDULE = "pipeline_schedule"
MICROBATCH_COUNT = "microbatch_count"
SKEWED_BUCKETS = "skewed_buckets"
QUEUE_PRESSURE = "queue_pressure"
DECODE_TAIL = "decode_tail"


@dataclass(frozen=True)
class Proposal:
    """One candidate knob change, with its provenance.

    ``knob`` is the actuator (``allocation`` | ``schedule`` |
    ``microbatches`` | ``buckets`` | ``slots``), ``value`` its target
    setting, ``signature`` the stable trace-signature id that produced
    it (the unit of blocking/rollback), ``metric`` the report quantity
    the proposal promises to improve, and ``reason`` the human-readable
    diagnosis for logs and trace args.
    """

    knob: str
    value: Any
    signature: str
    metric: str
    reason: str

    def describe(self) -> Dict[str, Any]:
        """JSON-able form for trace args and event records."""
        value = self.value
        if isinstance(value, (list, tuple)):
            value = [round(v, 6) if isinstance(v, float) else v
                     for v in value]
        return dict(knob=self.knob, value=value, signature=self.signature,
                    metric=self.metric, reason=self.reason)


def _median(values: Sequence[float]) -> float:
    ordered = sorted(values)
    mid = len(ordered) // 2
    if len(ordered) % 2:
        return ordered[mid]
    return 0.5 * (ordered[mid - 1] + ordered[mid])


class TuningAdvisor:
    """Thresholded signature detection over analysis reports.

    Thresholds are deliberately conservative: a proposal triggers a
    solver run, a pipeline rebuild, or a serving reconfiguration, so
    borderline traces should read as clean.  ``straggler_ratio`` is the
    max/median stage-busy ratio that reads as a straggler (the
    self-heal confirm threshold's trace-side analog);
    ``bubble_threshold`` the bubble fraction that reads as a schedule
    problem; ``padding_threshold`` the prefill padding waste that reads
    as a mis-sized bucket set; ``stall_threshold`` the queue-stall
    share of engine ticks that reads as slot starvation.
    """

    def __init__(
        self,
        straggler_ratio: float = 1.6,
        bubble_threshold: float = 0.35,
        padding_threshold: float = 0.30,
        stall_threshold: float = 0.25,
        max_microbatches: int = 32,
        bucket_quantum: int = 8,
        tail_ratio_threshold: float = 3.0,
    ):
        if straggler_ratio <= 1.0:
            raise ValueError(
                f"straggler_ratio must be > 1, got {straggler_ratio}"
            )
        if tail_ratio_threshold <= 1.0:
            raise ValueError(
                f"tail_ratio_threshold must be > 1, got "
                f"{tail_ratio_threshold}"
            )
        self.straggler_ratio = float(straggler_ratio)
        self.bubble_threshold = float(bubble_threshold)
        self.padding_threshold = float(padding_threshold)
        self.stall_threshold = float(stall_threshold)
        self.max_microbatches = int(max_microbatches)
        self.bucket_quantum = int(bucket_quantum)
        self.tail_ratio_threshold = float(tail_ratio_threshold)

    # --- training ----------------------------------------------------------
    def propose_training(
        self,
        report: Dict[str, Any],
        *,
        schedule: str,
        num_microbatches: int,
        batch_size: Optional[int] = None,
        steps: Optional[int] = None,
        blocked: Iterable[str] = (),
    ) -> Optional[Proposal]:
        """One proposal for a training-pipeline trace, or None.

        ``schedule``/``num_microbatches``/``batch_size`` describe the
        CURRENT operating point (the advisor proposes deltas, not
        absolutes, so it must know where the run stands).  ``steps``
        overrides the report's iteration count when the caller measured
        it out-of-band (a hook window without TraceHook iter spans).
        """
        blocked = set(blocked)
        busy = report.get("stage_busy_ms") or {}
        n_steps = steps or (report.get("steps") or {}).get("count") or 1

        # 1. straggler: the most specific signature — one stage burning
        #    far more wall time than the median stage is a device
        #    problem, and no schedule change can fix a device problem
        if len(busy) >= 2 and STRAGGLER not in blocked:
            per_stage = [busy[k] for k in sorted(busy, key=int)]
            med = _median(per_stage)
            worst = max(per_stage)
            if med > 0 and worst / med >= self.straggler_ratio:
                stage = per_stage.index(worst)
                measured = [b / 1e3 / n_steps for b in per_stage]
                return Proposal(
                    knob="allocation",
                    value=measured,
                    signature=STRAGGLER,
                    metric="step_p50_ms",
                    reason=(
                        f"stage {stage} busy {worst / med:.2f}x the "
                        f"median stage over {n_steps} step(s)"
                    ),
                )

        # 2. schedule shape: lots of idle stage-seconds with no single
        #    straggler is a scheduling problem
        bubble = report.get("bubble_fraction", 0.0)
        if bubble >= self.bubble_threshold and len(busy) >= 2:
            if (schedule == "gpipe" and num_microbatches > 1
                    and PIPELINE_SCHEDULE not in blocked):
                return Proposal(
                    knob="schedule",
                    value="1f1b",
                    signature=PIPELINE_SCHEDULE,
                    metric="bubble_fraction",
                    reason=(
                        f"bubble fraction {bubble:.2f} >= "
                        f"{self.bubble_threshold:.2f} on gpipe with "
                        f"{num_microbatches} microbatches"
                    ),
                )
            doubled = num_microbatches * 2
            if (MICROBATCH_COUNT not in blocked
                    and doubled <= self.max_microbatches
                    and (batch_size is None or (
                        batch_size % doubled == 0))):
                return Proposal(
                    knob="microbatches",
                    value=doubled,
                    signature=MICROBATCH_COUNT,
                    metric="bubble_fraction",
                    reason=(
                        f"bubble fraction {bubble:.2f} >= "
                        f"{self.bubble_threshold:.2f}; deepening the "
                        f"pipeline fill ({num_microbatches} -> {doubled} "
                        f"microbatches)"
                    ),
                )
        return None

    # --- serving -----------------------------------------------------------
    def propose_serving(
        self,
        report: Dict[str, Any],
        *,
        buckets: Sequence[int],
        num_slots: int,
        max_len: int,
        prefill_chunk: Optional[int] = None,
        blocked: Iterable[str] = (),
    ) -> Optional[Proposal]:
        """One proposal for a serving-engine trace, or None.

        ``prefill_chunk`` describes the CURRENT chunked-prefill knob
        (None = off): the decode-tail signature proposes enabling or
        shrinking it, so the advisor must know where it stands.
        """
        blocked = set(blocked)
        serving = report.get("serving")
        if not serving:
            return None

        # 1. decode tail blowup: per-request TPOT p95 far above p50
        #    means decode ticks are stalling behind whole prefill waves
        #    (interference — the tick itself is fixed-shape and
        #    uniform).  Chunked prefill bounds that stall: enable it,
        #    or shrink the chunk if it is already on.  The TPOT
        #    percentiles ride in the serving section when the acting
        #    layer merges them from the engine's SLO stats
        #    (ServingAutotuner does); traces without them skip the
        #    signature.
        tail = self._tail_ratio(serving)
        if (DECODE_TAIL not in blocked and tail is not None
                and tail >= self.tail_ratio_threshold):
            new_chunk = self._chunk_proposal(buckets, prefill_chunk)
            if new_chunk is not None:
                action = (
                    f"enable chunked prefill at {new_chunk}"
                    if prefill_chunk is None
                    else f"shrink prefill_chunk {prefill_chunk} -> "
                         f"{new_chunk}"
                )
                return Proposal(
                    knob="prefill_chunk",
                    value=new_chunk,
                    signature=DECODE_TAIL,
                    metric="tpot_tail_ratio",
                    reason=(
                        f"tpot_p95/p50 ratio {tail:.1f} >= "
                        f"{self.tail_ratio_threshold:.1f}: decode "
                        f"ticks stall behind prefill waves -> {action}"
                    ),
                )

        # 2. skewed buckets: prefill FLOPs burned on pad positions.
        #    Target the bucket wasting the most padded tokens and insert
        #    a new bucket sized to its observed mean prompt length
        #    (rounded up to the compile quantum) — one extra warmup
        #    compile buys every future admission a tighter pad target.
        hist = serving.get("buckets") or {}
        if SKEWED_BUCKETS not in blocked and hist:
            worst_bucket, worst_padded = None, 0
            for bucket_str, row in hist.items():
                if not row.get("requests") or not row.get("tokens"):
                    continue
                padded = int(bucket_str) * row["requests"] - row["tokens"]
                if padded > worst_padded:
                    worst_bucket, worst_padded = int(bucket_str), padded
            # analyze() computes this once (serving_padding_fraction);
            # reading the field keeps decide and judge on one number
            padding = serving.get("padding_fraction")
            if (worst_bucket is not None and padding is not None
                    and padding >= self.padding_threshold):
                row = hist[str(worst_bucket)]
                mean_len = row["tokens"] / row["requests"]
                q = self.bucket_quantum
                new_bucket = max(q, int(-(-mean_len // q)) * q)
                if new_bucket < worst_bucket and new_bucket <= max_len:
                    proposed = tuple(sorted(set(buckets) | {new_bucket}))
                    if proposed != tuple(sorted(set(buckets))):
                        return Proposal(
                            knob="buckets",
                            value=proposed,
                            signature=SKEWED_BUCKETS,
                            metric="padding_fraction",
                            reason=(
                                f"prefill padding waste {padding:.0%} "
                                f">= {self.padding_threshold:.0%}; bucket "
                                f"{worst_bucket} holds prompts averaging "
                                f"{mean_len:.1f} tokens -> add bucket "
                                f"{new_bucket}"
                            ),
                        )

        # 3. queue pressure: admission repeatedly found no free slot —
        #    concurrency is capped by the slab, not by compute
        ticks = serving.get("prefill_waves", 0) + serving.get(
            "decode_ticks", 0
        )
        stalls = serving.get("queue_stalls", 0)
        if (QUEUE_PRESSURE not in blocked and ticks > 0
                and stalls / ticks >= self.stall_threshold):
            return Proposal(
                knob="slots",
                value=num_slots * 2,
                signature=QUEUE_PRESSURE,
                metric="stall_fraction",
                reason=(
                    f"{stalls} queue stalls over {ticks} engine ticks "
                    f"({stalls / ticks:.0%} >= "
                    f"{self.stall_threshold:.0%}); doubling slots "
                    f"{num_slots} -> {num_slots * 2}"
                ),
            )
        return None

    @staticmethod
    def _tail_ratio(serving: Dict[str, Any]) -> Optional[float]:
        """Per-request TPOT p95/p50 from the serving section, or None
        when the section carries no SLO percentiles (trace-only
        reports) or the p50 is degenerate."""
        p50 = serving.get("tpot_p50_s")
        p95 = serving.get("tpot_p95_s")
        if not p50 or not p95 or p50 <= 0:
            return None
        return float(p95) / float(p50)

    @staticmethod
    def _chunk_proposal(
        buckets: Sequence[int], prefill_chunk: Optional[int],
    ) -> Optional[int]:
        """The next chunked-prefill operating point: enable at the
        largest bucket below the max (chunking at the max bucket is a
        no-op), else shrink to the next smaller bucket; None when
        already at the smallest bucket (or the bucket set offers no
        smaller shape) — the signature has nothing left to actuate."""
        ordered = sorted(set(int(b) for b in buckets))
        if len(ordered) < 2:
            return None
        if prefill_chunk is None:
            return ordered[-2]
        smaller = [b for b in ordered if b < int(prefill_chunk)]
        return smaller[-1] if smaller else None


__all__ = [
    "DECODE_TAIL",
    "MICROBATCH_COUNT",
    "PIPELINE_SCHEDULE",
    "Proposal",
    "QUEUE_PRESSURE",
    "SKEWED_BUCKETS",
    "STRAGGLER",
    "TuningAdvisor",
]
