"""Whole-run invariant auditor: what a chaos campaign must NOT break.

The injector (:mod:`.injector`) proves faults happened; this module
proves the fleet's promises survived them.  One call —
:func:`audit_run` — over a finished replay's artifacts (the fleet, the
:class:`~..workload.player.PlayerReport`, optionally the fault-free
reference replay and the injector) returns an :class:`AuditReport` of
named checks:

- **tokens_conserved** — every admitted-and-finished request produced
  EXACTLY its requested token count (zero lost, zero duplicated), and
  no admitted request is left non-terminal;
- **terminal_reasoned** — every arrival is terminal with a reason:
  finished, FAILED with ``fail_reason``, or rejected with an admission
  reason — nothing vanished silently;
- **token_identity** — on a digest-equal trace, every stream that
  finished in BOTH the faulted and fault-free runs is token-identical:
  faults may delay or fail work, never corrupt it;
- **page_consistency** — every live engine passes the page pool's
  refcount/free-list audit (``check_consistency``) and replica slot
  accounting;
- **counters_monotonic** — every counter in the probe timeline is
  non-decreasing, and per-reason rejection counts sum to the total;
- **recovery_within_budget** — the fleet returned to a settled state
  within ``recovery_budget_ticks`` of the last injected fault
  (time-to-healthy, gated);
- **ledger_conserved** — on a disaggregated fleet (duck-typed off
  ``fleet.ledger``), the KV-handoff ledger's conservation invariant
  holds (every enqueued record in exactly one of pending / delivered /
  failed-with-reason) and no handoff is left stranded PENDING after
  the run drained; a plain fleet passes trivially.

:func:`make_probe` builds the per-tick ``sample_fn`` the player feeds
the timeline with; :func:`fleet_settled` is the shared 'healthy again'
predicate.  The report's :meth:`~AuditReport.digest` excludes request
ids and wall times, so two same-seed replays in one process digest
identically — the double-run determinism gate.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from ..fleet.replica import HEALTHY, RETIRED
from ..serving.batcher import FAILED, FINISHED

#: FleetStats counters the probe samples every tick (scalars only;
#: rejected_by_reason rides alongside as its own dict)
_PROBE_COUNTERS = (
    "submitted", "admitted", "dispatched", "rejected", "migrations",
    "failed", "reforms", "reform_failures", "missed_beats", "ticks",
    "scale_ups", "scale_downs", "scale_rejected", "faults_injected",
    "recoveries_completed",
)


def fleet_settled(fleet) -> bool:
    """The recovery predicate: every replica serving or honestly
    retired, nothing crashed-but-undetected, no migration limbo, at
    least one healthy replica, no live admission blip."""
    states_ok = all(r.state in (HEALTHY, RETIRED)
                    for r in fleet.replicas)
    crashed = any(r.crashed and r.state != RETIRED
                  for r in fleet.replicas)
    return (states_ok and not crashed
            and len(fleet.healthy_replicas) >= 1
            and not fleet._limbo
            and not getattr(fleet.admission, "blip_active", False))


def make_probe(fleet) -> Callable[[], Dict[str, Any]]:
    """A ``sample_fn`` for :class:`~..workload.player.ScenarioPlayer`:
    one dict per tick with fleet shape, the settled predicate, and the
    scalar counters — everything the auditor's timeline checks read."""

    def probe() -> Dict[str, Any]:
        snap = fleet.stats.snapshot()
        return dict(
            tick=int(fleet.tick),
            healthy=len(fleet.healthy_replicas),
            live=sum(1 for r in fleet.replicas
                     if r.state != RETIRED),
            quarantined=sum(1 for r in fleet.replicas
                            if r.state == RETIRED),
            limbo=len(fleet._limbo),
            settled=fleet_settled(fleet),
            counters={k: snap[k] for k in _PROBE_COUNTERS},
            rejected_by_reason=dict(snap["rejected_by_reason"]),
        )

    return probe


@dataclass
class AuditCheck:
    """One named invariant's verdict."""

    name: str
    ok: bool
    detail: str = ""

    def to_dict(self) -> Dict[str, Any]:
        return dict(name=self.name, ok=self.ok, detail=self.detail)


@dataclass
class AuditReport:
    """Every check from one :func:`audit_run` (artifact-ready)."""

    plan: str
    scenario: str
    checks: List[AuditCheck] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return all(c.ok for c in self.checks)

    def failures(self) -> List[AuditCheck]:
        return [c for c in self.checks if not c.ok]

    def to_dict(self) -> Dict[str, Any]:
        return dict(
            plan=self.plan, scenario=self.scenario, ok=self.ok,
            checks=[c.to_dict() for c in self.checks],
        )

    def digest(self) -> str:
        """sha256 over the report content — request-id- and wall-time-
        free by construction, so same-seed replays digest equal."""
        return hashlib.sha256(
            repr(self.to_dict()).encode()
        ).hexdigest()


def _check_tokens_conserved(report) -> AuditCheck:
    lost: List[str] = []
    for v in report.admitted:
        r = v.request
        if r.status == FINISHED:
            if len(r.tokens) != v.arrival.new_tokens:
                lost.append(
                    f"arrival@{v.arrival.tick} generated "
                    f"{len(r.tokens)}/{v.arrival.new_tokens}"
                )
        elif r.status != FAILED:
            lost.append(
                f"arrival@{v.arrival.tick} left non-terminal "
                f"({r.status})"
            )
    return AuditCheck(
        "tokens_conserved", not lost,
        "; ".join(lost[:5]) if lost
        else f"{len(report.finished)} finished streams exact",
    )


def _check_terminal_reasoned(report) -> AuditCheck:
    bad: List[str] = []
    for v in report.verdicts:
        r = v.request
        if v.admitted:
            if r.status == FAILED and not r.fail_reason:
                bad.append(
                    f"arrival@{v.arrival.tick} FAILED without a reason"
                )
        elif not v.reason:
            bad.append(
                f"arrival@{v.arrival.tick} rejected without a reason"
            )
    return AuditCheck(
        "terminal_reasoned", not bad,
        "; ".join(bad[:5]) if bad
        else "every terminal state carries its reason",
    )


def _check_token_identity(report, reference) -> AuditCheck:
    if report.digest != reference.digest:
        return AuditCheck(
            "token_identity", False,
            "trace digests differ: the runs replayed different "
            "arrivals and cannot be compared",
        )
    if len(report.verdicts) != len(reference.verdicts):
        return AuditCheck(
            "token_identity", False,
            f"verdict counts differ ({len(report.verdicts)} vs "
            f"{len(reference.verdicts)})",
        )
    compared, divergent = 0, []
    for v, ref in zip(report.verdicts, reference.verdicts):
        if v.request.status == FINISHED \
                and ref.request.status == FINISHED:
            compared += 1
            if list(v.request.tokens) != list(ref.request.tokens):
                divergent.append(f"arrival@{v.arrival.tick}")
    return AuditCheck(
        "token_identity", not divergent,
        "; ".join(divergent[:5]) if divergent
        else f"{compared} streams token-identical to the fault-free "
             f"reference",
    )


def _check_page_consistency(fleet) -> AuditCheck:
    bad: List[str] = []
    for r in fleet.replicas:
        if r.state == RETIRED or r.engine is None:
            continue
        pool = getattr(r.engine, "_pool", None)
        if pool is not None:
            try:
                pool.check_consistency()
            except Exception as exc:
                bad.append(f"{r.name}: {exc}")
        if not r.slot_accounting_ok:
            bad.append(f"{r.name}: leaked slots")
    return AuditCheck(
        "page_consistency", not bad,
        "; ".join(bad[:5]) if bad
        else "every live pool and slot ledger consistent",
    )


def _check_counters_monotonic(fleet, report) -> AuditCheck:
    bad: List[str] = []
    prev: Dict[str, Any] = {}
    for sample in report.timeline:
        counters = sample.get("counters", {})
        for key, value in counters.items():
            before = prev.get(key)
            if before is not None and value < before:
                bad.append(
                    f"{key} regressed {before} -> {value} at tick "
                    f"{sample.get('tick')}"
                )
        prev.update(counters)
    by_reason = fleet.stats.rejected_by_reason
    if fleet.stats.rejected != sum(by_reason.values()):
        bad.append(
            f"rejected={fleet.stats.rejected} != "
            f"sum(by_reason)={sum(by_reason.values())}"
        )
    return AuditCheck(
        "counters_monotonic", not bad,
        "; ".join(bad[:5]) if bad
        else f"{len(_PROBE_COUNTERS)} counters monotonic across "
             f"{len(report.timeline)} samples",
    )


def _check_ledger_conserved(fleet) -> AuditCheck:
    ledger = getattr(fleet, "ledger", None)
    if ledger is None:
        return AuditCheck(
            "ledger_conserved", True, "fleet has no handoff ledger"
        )
    bad: List[str] = []
    summary = ledger.audit()
    if not summary["conservation_ok"]:
        bad.append(
            f"conservation broken: enqueued={summary['enqueued_total']}"
            f" pending={summary['pending']}"
            f" delivered={summary['delivered']}"
            f" failed={summary['failed']}"
        )
    if summary["pending"]:
        # the replay ran its idle epilogue: anything still PENDING was
        # stranded in flight, exactly what the ledger exists to forbid
        bad.append(
            f"{summary['pending']} handoff(s) stranded PENDING after "
            f"the run drained"
        )
    reasons = ", ".join(
        f"{r} x{n}"
        for r, n in sorted(summary["failed_reasons"].items())
    )
    return AuditCheck(
        "ledger_conserved", not bad,
        "; ".join(bad) if bad
        else (f"{summary['total']} handoffs conserved "
              f"({summary['delivered']} delivered, "
              f"{summary['failed']} failed"
              + (f": {reasons}" if reasons else "") + ")"),
    )


def _check_recovery(fleet, report, injector,
                    budget: Optional[int]) -> AuditCheck:
    if injector is None or injector.last_fault_tick is None:
        return AuditCheck(
            "recovery_within_budget", True, "no faults applied"
        )
    if budget is None:
        budget = injector.plan.recovery_budget_ticks
    worst, detail = 0, []
    for rec in injector.recoveries:
        took = rec["settled_tick"] - rec["fault_tick"]
        worst = max(worst, took)
        detail.append(f"{took}t")
    if injector._recovery_open:
        # the run drained before the injector's NEXT on_tick could
        # close the arc: find the first settled probe sample after the
        # last fault (or judge the fleet's final state directly)
        settled_at = next(
            (s["tick"] for s in report.timeline
             if s.get("settled") and s["tick"] > injector.last_fault_tick),
            None,
        )
        if settled_at is None and fleet_settled(fleet):
            settled_at = int(fleet.tick)
        if settled_at is None:
            return AuditCheck(
                "recovery_within_budget", False,
                f"fleet never settled after the fault at tick "
                f"{injector.last_fault_tick}",
            )
        took = settled_at - injector.last_fault_tick
        worst = max(worst, took)
        detail.append(f"{took}t")
    ok = worst <= budget
    return AuditCheck(
        "recovery_within_budget", ok,
        f"time-to-healthy {', '.join(detail)} (budget {budget}t)",
    )


def audit_run(
    fleet,
    report,
    *,
    reference=None,
    injector=None,
    recovery_budget_ticks: Optional[int] = None,
) -> AuditReport:
    """Audit one finished replay.  ``reference`` (the fault-free
    replay of the same digest-equal trace) enables the token-identity
    check; ``injector`` enables the recovery-budget check (budget
    defaults to the plan's own ``recovery_budget_ticks``)."""
    audit = AuditReport(
        plan=injector.plan.name if injector is not None else "",
        scenario=report.scenario,
    )
    audit.checks.append(_check_tokens_conserved(report))
    audit.checks.append(_check_terminal_reasoned(report))
    if reference is not None:
        audit.checks.append(_check_token_identity(report, reference))
    audit.checks.append(_check_page_consistency(fleet))
    audit.checks.append(_check_counters_monotonic(fleet, report))
    audit.checks.append(_check_ledger_conserved(fleet))
    audit.checks.append(
        _check_recovery(fleet, report, injector,
                        recovery_budget_ticks)
    )
    return audit


__all__ = [
    "AuditCheck",
    "AuditReport",
    "audit_run",
    "fleet_settled",
    "make_probe",
]
