"""Seeded, replayable fault plans: named chaos scenarios for the fleet.

The workload plane made *traffic* a value (:mod:`..workload.scenario`);
this module does the same for *faults* — the missing half of the
fleet's survival story.  A fault that only exists inside one test's
``replica.crash()`` call cannot be replayed by the next bench, pinned
by CI, or named in a bug report.  Here a fault campaign is a VALUE:

- :class:`FaultEvent` — one scheduled disruption: the fleet tick it
  fires at, a target selector (which replica, or the fleet itself), a
  kind from the sanctioned-hook vocabulary, kind-specific params, a
  duration for timed kinds, and optional seeded tick jitter;
- :class:`FaultPlan` — an ordered event list plus the workload pairing
  (which catalog scenario the plan is meant to be replayed under, with
  its sizing knobs), a fleet-shape hint, and the plan's gated
  ``recovery_budget_ticks`` — the ticks the fleet is allowed between
  its LAST injected fault and returning to a settled state.

**Kinds are sanctioned hooks, by contract.**  Every kind names one
public fault surface the fleet/serving layers expose on purpose —
:meth:`~..fleet.replica.EngineReplica.crash`, ``inject_stall``,
``fail_next_builds``, :meth:`~..serving.engine.ServingEngine.
corrupt_swap_record`, the admission controller's blip flag.  The
injector (:mod:`.injector`) refuses to apply anything else, so a chaos
plan can never monkeypatch internals into states the real system
cannot reach.

**Seeding contract** (what replayability means here): one
``random.Random(seed)``, consumed in declaration order — each event
with ``jitter_ticks > 0`` draws exactly one ``randint(-j, +j)`` tick
offset; events without jitter draw nothing.  :meth:`FaultPlan.
resolved_events` is therefore a pure function of the plan's fields,
and :meth:`FaultPlan.digest` hashes the plan identity (name + seed +
pairing) together with the resolved events, so "same seed, same fault
campaign" is one string comparison.

**Target selectors** (resolved against the live fleet at fire time by
the injector, validated syntactically here):

- ``index:N`` — the Nth entry of ``fleet.replicas`` (skipped, and
  logged as skipped, when the index is out of range);
- ``name:X`` — the replica named ``X`` (skipped when absent);
- ``pending_removal`` — the first replica the autoscaler is currently
  draining OUT of the fleet; when none is mid-removal at the event's
  tick, the event ARMS and fires at the next drain instead (the
  mid-drain-kill selector: the plan cannot know the drain's exact
  tick, so it says "kill the next one");
- ``fleet`` — no replica: the event targets fleet-level machinery
  (the only selector ``admission_blip`` accepts).

PURE STDLIB BY CONTRACT (the ``workload/scenario.py`` idiom): loadable
by file path on a bare CI runner with no jax/numpy —
``tools/chaos_smoke.py`` gates exactly that.  The actuator that applies
events to a real fleet lives one module over, in :mod:`.injector`.
"""

from __future__ import annotations

import dataclasses
import hashlib
import random
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

# the sanctioned-hook vocabulary (stable ids in plans, event logs,
# trace args and the plan_check schema — analysis/plan_check.py
# mirrors this tuple by value, tests pin the two in sync)
REPLICA_CRASH = "replica_crash"
STAGE_SLOWDOWN = "stage_slowdown"
SWAP_CORRUPTION = "swap_corruption"
REFORM_FAILURE = "reform_failure"
ADMISSION_BLIP = "admission_blip"
HANDOFF_CORRUPTION = "handoff_corruption"

FAULT_KINDS: Tuple[str, ...] = (
    REPLICA_CRASH,
    STAGE_SLOWDOWN,
    SWAP_CORRUPTION,
    REFORM_FAILURE,
    ADMISSION_BLIP,
    HANDOFF_CORRUPTION,
)

#: kinds whose selector is the FLEET itself, not any replica:
#: admission_blip flips the front door, handoff_corruption rots a
#: fleet-held prefill→decode payload (``DisaggFleet.corrupt_handoff``)
_FLEET_TARGET_KINDS = (ADMISSION_BLIP, HANDOFF_CORRUPTION)

#: selectors that name a replica (everything except ``fleet``)
_REPLICA_SELECTOR_PREFIXES = ("index:", "name:")
_BARE_SELECTORS = ("pending_removal", "fleet")


def _validate_target(kind: str, target: str) -> None:
    if kind in _FLEET_TARGET_KINDS:
        if target != "fleet":
            raise ValueError(
                f"{kind} targets fleet-level machinery; its selector "
                f"must be 'fleet', got {target!r}"
            )
        return
    if target in _BARE_SELECTORS:
        if target == "fleet":
            raise ValueError(
                f"{kind} needs a replica selector "
                f"(index:N / name:X / pending_removal), got 'fleet'"
            )
        return
    if target.startswith("index:"):
        tail = target[len("index:"):]
        if not tail.isdigit():
            raise ValueError(
                f"selector {target!r} needs a non-negative integer "
                f"after 'index:'"
            )
        return
    if target.startswith("name:"):
        if not target[len("name:"):]:
            raise ValueError(
                f"selector {target!r} needs a replica name after "
                f"'name:'"
            )
        return
    raise ValueError(
        f"unknown target selector {target!r}; known forms: index:N, "
        f"name:X, pending_removal, fleet"
    )


def _validate_params(kind: str, params: Dict[str, Any],
                     duration: int) -> None:
    """Kind-specific parameter schema — malformed plans die at build
    time, not mid-replay (the Dist-factory idiom)."""
    def _reject_extra(allowed):
        extra = sorted(set(params) - set(allowed))
        if extra:
            raise ValueError(
                f"{kind} does not take params {extra}; allowed: "
                f"{sorted(allowed)}"
            )

    if kind == REPLICA_CRASH:
        _reject_extra(())
    elif kind == STAGE_SLOWDOWN:
        _reject_extra(("seconds",))
        seconds = params.get("seconds")
        if not isinstance(seconds, (int, float)) \
                or isinstance(seconds, bool) or seconds <= 0:
            raise ValueError(
                f"{kind} needs params={{'seconds': > 0}} (the per-tick "
                f"stall the slowdown lowers to), got {params!r}"
            )
        if duration < 1:
            raise ValueError(
                f"{kind} needs duration >= 1 tick, got {duration}"
            )
    elif kind == SWAP_CORRUPTION:
        _reject_extra(("force",))
        force = params.get("force", True)
        if not isinstance(force, bool):
            raise ValueError(
                f"{kind} param 'force' must be a bool, got {force!r}"
            )
    elif kind == REFORM_FAILURE:
        _reject_extra(("builds",))
        builds = params.get("builds")
        if isinstance(builds, bool) or not isinstance(builds, int) \
                or builds < 1:
            raise ValueError(
                f"{kind} needs params={{'builds': >= 1}} (how many "
                f"consecutive rebuilds must fail), got {params!r}"
            )
    elif kind == ADMISSION_BLIP:
        _reject_extra(())
        if duration < 1:
            raise ValueError(
                f"{kind} needs duration >= 1 tick, got {duration}"
            )
    elif kind == HANDOFF_CORRUPTION:
        # mirrors swap_corruption: one optional bool — with force and
        # nothing in flight, the hook exports a handoff to poison
        _reject_extra(("force",))
        force = params.get("force", True)
        if not isinstance(force, bool):
            raise ValueError(
                f"{kind} param 'force' must be a bool, got {force!r}"
            )
    else:
        raise ValueError(
            f"unknown fault kind {kind!r}; known: {list(FAULT_KINDS)}"
        )


@dataclass(frozen=True)
class FaultEvent:
    """One scheduled disruption inside a fault plan.

    ``params`` is a tuple of ``(key, value)`` pairs (hashable — the
    frozen-dataclass twin of a dict); :meth:`params_dict` is the
    ergonomic view.  ``duration`` only matters to timed kinds
    (``stage_slowdown`` clears its stall, ``admission_blip`` lifts its
    gate, ``duration`` ticks after firing).  ``jitter_ticks`` is the
    seeded wiggle :meth:`FaultPlan.resolved_events` lowers."""

    tick: int
    kind: str
    target: str = "index:0"
    params: Tuple[Tuple[str, Any], ...] = ()
    duration: int = 1
    jitter_ticks: int = 0

    def __post_init__(self):
        if int(self.tick) < 0:
            raise ValueError(
                f"a fault event needs tick >= 0, got {self.tick}"
            )
        if int(self.duration) < 1:
            raise ValueError(
                f"a fault event needs duration >= 1, got "
                f"{self.duration}"
            )
        if int(self.jitter_ticks) < 0:
            raise ValueError(
                f"jitter_ticks must be >= 0, got {self.jitter_ticks}"
            )
        _validate_target(self.kind, self.target)
        _validate_params(self.kind, self.params_dict(), self.duration)

    def params_dict(self) -> Dict[str, Any]:
        return dict(self.params)

    def key(self) -> Tuple:
        """The byte-identity view (what :meth:`FaultPlan.digest`
        hashes and the determinism smoke compares)."""
        return (self.tick, self.kind, self.target,
                tuple(sorted(self.params)), self.duration)

    def to_dict(self) -> Dict[str, Any]:
        return dict(
            tick=self.tick, kind=self.kind, target=self.target,
            params=self.params_dict(), duration=self.duration,
            jitter_ticks=self.jitter_ticks,
        )


@dataclass(frozen=True)
class FaultPlan:
    """A named, seeded fault campaign plus its workload pairing.

    ``scenario`` names the workload-catalog entry the plan is designed
    to be replayed under (``scenario_seed`` / ``rate_scale`` /
    ``ticks_scale`` are passed straight to ``get_scenario``), so
    "``reform_flap`` under its paired trace" is fully reproducible from
    the plan object alone.  ``replicas`` / ``autoscale`` are the fleet
    shape the plan assumes; ``recovery_budget_ticks`` is the gated
    time-to-healthy bound the invariant auditor enforces after the
    LAST injected fault."""

    name: str
    seed: int
    events: Tuple[FaultEvent, ...]
    scenario: str
    recovery_budget_ticks: int
    scenario_seed: int = 0
    rate_scale: float = 1.0
    ticks_scale: float = 1.0
    replicas: int = 2
    autoscale: bool = False
    #: replay against a disaggregated fleet (prefill/decode pools with
    #: the KV-handoff plane): the harness builds ``DisaggFleet`` with
    #: one prefill replica and ``replicas - 1`` decode replicas, so
    #: ``index:0`` deterministically names the prefill specialist
    disagg: bool = False
    description: str = ""

    def __post_init__(self):
        if not self.name:
            raise ValueError("a fault plan needs a name")
        if not self.events:
            raise ValueError(f"plan {self.name!r} has no events")
        if not self.scenario:
            raise ValueError(
                f"plan {self.name!r} needs a paired workload scenario"
            )
        if int(self.recovery_budget_ticks) < 1:
            raise ValueError(
                f"plan {self.name!r} needs recovery_budget_ticks >= 1, "
                f"got {self.recovery_budget_ticks}"
            )
        if int(self.replicas) < 1:
            raise ValueError(
                f"plan {self.name!r} needs replicas >= 1, got "
                f"{self.replicas}"
            )
        if self.disagg and int(self.replicas) < 2:
            raise ValueError(
                f"plan {self.name!r} replays disaggregated: it needs "
                f"replicas >= 2 (one prefill + at least one decode)"
            )
        for scale, value in (("rate_scale", self.rate_scale),
                             ("ticks_scale", self.ticks_scale)):
            if float(value) <= 0:
                raise ValueError(
                    f"plan {self.name!r} {scale} must be > 0, got "
                    f"{value}"
                )

    def resolved_events(self) -> List[FaultEvent]:
        """Lower seeded jitter to concrete ticks — the deterministic
        event schedule the injector fires.  Pure: one
        ``random.Random(seed)`` consumed in declaration order, one
        draw per jittered event, so two calls (or two processes) with
        the same plan return identical schedules."""
        rng = random.Random(self.seed)
        out: List[FaultEvent] = []
        for event in self.events:
            tick = event.tick
            if event.jitter_ticks > 0:
                tick = max(0, tick + rng.randint(-event.jitter_ticks,
                                                 event.jitter_ticks))
            out.append(dataclasses.replace(event, tick=tick,
                                           jitter_ticks=0))
        return out

    @property
    def last_declared_tick(self) -> int:
        """Upper bound (pre-jitter) on when the plan stops injecting —
        sizing aid for benches pairing plans with finite traces."""
        return max(e.tick + e.jitter_ticks for e in self.events)

    def digest(self) -> str:
        """sha256 of the plan identity + its RESOLVED schedule — fault
        campaign identity as one comparable string (committed into
        bench artifacts so generator drift is visible as a hash
        change).  The seed participates directly: a different seed is
        a different campaign even when no event carries jitter."""
        h = hashlib.sha256()
        h.update(repr((self.name, self.seed, self.scenario,
                       self.scenario_seed, self.rate_scale,
                       self.ticks_scale)).encode())
        for event in self.resolved_events():
            h.update(repr(event.key()).encode())
        return h.hexdigest()

    def to_dict(self) -> Dict[str, Any]:
        """The artifact/docs/plan_check form: everything needed to
        re-declare the plan (the schedule is regenerable from this)."""
        return dict(
            name=self.name, seed=self.seed,
            scenario=self.scenario,
            scenario_seed=self.scenario_seed,
            rate_scale=self.rate_scale,
            ticks_scale=self.ticks_scale,
            replicas=self.replicas,
            autoscale=self.autoscale,
            disagg=self.disagg,
            recovery_budget_ticks=self.recovery_budget_ticks,
            description=self.description,
            events=[e.to_dict() for e in self.events],
        )

    def with_seed(self, seed: int) -> "FaultPlan":
        """The same named campaign shape under a different seed (the
        catalog's ``seed=`` plumbing)."""
        return dataclasses.replace(self, seed=int(seed))


# --------------------------------------------------------------------------
# the named-fault-plan catalog
# --------------------------------------------------------------------------
#
# One ``--plan`` flag per chaos campaign: every entry is a zero-ceremony
# builder ``(seed=0) -> FaultPlan`` registered under a stable name, so a
# bench, a test, or a postmortem can say ``reform_flap @ seed 3`` and
# mean exactly one byte-identical fault schedule.  Each plan pairs
# itself with the workload-catalog scenario whose traffic shape makes
# its faults bite (sizing follows the scenario catalog's CPU-harness
# contract: tiny GPT, 2-3 replicas, ~0.1 req/tick of service per
# replica).  The registry lives HERE (not a sibling module) so the
# whole fault plane stays ONE self-contained stdlib file the CI smoke
# loads by path.

#: name -> builder; insertion order is the documented catalog order
FAULT_PLANS: Dict[str, Callable[..., FaultPlan]] = {}


def register_fault_plan(name: str):
    """Decorator: register a fault-plan builder under ``name``
    (benches and tools resolve ``--plan`` flags against this
    registry)."""

    def deco(fn: Callable[..., FaultPlan]):
        if name in FAULT_PLANS:
            raise ValueError(
                f"fault plan {name!r} is already registered"
            )
        FAULT_PLANS[name] = fn
        return fn

    return deco


def fault_plan_names() -> List[str]:
    return list(FAULT_PLANS)


def get_fault_plan(name: str, seed: int = 0) -> FaultPlan:
    """Build a named fault plan; unknown names fail with the catalog
    in the message (the ``--plan`` flag's error surface)."""
    builder = FAULT_PLANS.get(name)
    if builder is None:
        raise ValueError(
            f"unknown fault plan {name!r}; catalog: "
            f"{fault_plan_names()}"
        )
    return builder(seed=seed)


def _crash(tick: int, target: str, jitter: int = 0) -> FaultEvent:
    return FaultEvent(tick=tick, kind=REPLICA_CRASH, target=target,
                      jitter_ticks=jitter)


@register_fault_plan("replica_crash_storm")
def replica_crash_storm(seed: int = 0) -> FaultPlan:
    return FaultPlan(
        name="replica_crash_storm", seed=seed,
        scenario="flash_crowd", rate_scale=0.8, ticks_scale=0.45,
        replicas=3, recovery_budget_ticks=45,
        events=(
            _crash(12, "index:0", jitter=2),
            _crash(26, "index:1", jitter=2),
            _crash(40, "index:2", jitter=2),
        ),
        description="three replicas crash in succession under a flash "
                    "crowd; every crash heals through drain/migrate/"
                    "re-form with zero token loss",
    )


@register_fault_plan("rolling_stragglers")
def rolling_stragglers(seed: int = 0) -> FaultPlan:
    def slow(tick, target, jitter=1):
        return FaultEvent(tick=tick, kind=STAGE_SLOWDOWN,
                          target=target,
                          params=(("seconds", 0.03),),
                          duration=10, jitter_ticks=jitter)

    return FaultPlan(
        name="rolling_stragglers", seed=seed,
        scenario="tenant_mix", rate_scale=0.8, ticks_scale=0.4,
        replicas=3, recovery_budget_ticks=60,
        events=(
            slow(8, "index:0"),
            slow(24, "index:1"),
            slow(40, "index:2"),
        ),
        description="a stage slowdown rolls across the fleet one "
                    "replica at a time; the EWMA health score may heal "
                    "stragglers away, and streams stay identical "
                    "either way",
    )


@register_fault_plan("mid_drain_kill")
def mid_drain_kill(seed: int = 0) -> FaultPlan:
    # full-size diurnal_ramp (the autoscaler acceptance scenario):
    # night 0-39, ramp 40-79, peak 80-149, evening 150-189, late
    # night 190-249.  The fleet starts at min (1 replica), burns up
    # during the peak, sheds in the tail — the pending_removal kills
    # arm just before the tail and strike whichever drain comes next.
    return FaultPlan(
        name="mid_drain_kill", seed=seed,
        scenario="diurnal_ramp", rate_scale=1.6,
        replicas=1, autoscale=True, recovery_budget_ticks=60,
        events=(
            _crash(120, "index:1", jitter=2),
            # armed BEFORE the evening slack: each kill strikes the
            # next drain the autoscaler opens, one per window
            _crash(150, "pending_removal", jitter=2),
            _crash(152, "pending_removal", jitter=2),
        ),
        description="a crash mid-peak while scaled up, then kills "
                    "aimed at whichever replica the autoscaler drains "
                    "out during the quiet tail — the mid-drain-death "
                    "removal path",
    )


@register_fault_plan("swap_corruption")
def swap_corruption(seed: int = 0) -> FaultPlan:
    def corrupt(tick, target, jitter=0):
        return FaultEvent(tick=tick, kind=SWAP_CORRUPTION,
                          target=target, params=(("force", True),),
                          jitter_ticks=jitter)

    return FaultPlan(
        name="swap_corruption", seed=seed,
        scenario="rag_shared_prefix", ticks_scale=0.4,
        replicas=2, recovery_budget_ticks=30,
        events=(
            corrupt(10, "index:0"),
            corrupt(22, "index:1", jitter=2),
            corrupt(30, "index:0"),
        ),
        description="host swap records are bit-flipped under RAG "
                    "traffic; the checksum catches every corruption "
                    "and the victim resumes by recompute, token-"
                    "identical",
    )


@register_fault_plan("reform_flap")
def reform_flap(seed: int = 0) -> FaultPlan:
    return FaultPlan(
        name="reform_flap", seed=seed,
        scenario="tenant_mix", rate_scale=0.8, ticks_scale=0.35,
        replicas=3, recovery_budget_ticks=60,
        events=(
            FaultEvent(tick=4, kind=REFORM_FAILURE, target="index:1",
                       params=(("builds", 1),)),
            _crash(6, "index:1"),
            FaultEvent(tick=20, kind=REFORM_FAILURE, target="index:2",
                       params=(("builds", 2),)),
            _crash(22, "index:2"),
        ),
        description="crashes whose re-forms fail: one replica flaps "
                    "(fail once, back off, heal), one exhausts "
                    "max_reforms and lands in quarantine — the fleet "
                    "keeps serving on survivors",
    )


@register_fault_plan("overload_then_crash")
def overload_then_crash(seed: int = 0) -> FaultPlan:
    return FaultPlan(
        name="overload_then_crash", seed=seed,
        scenario="flash_crowd", ticks_scale=0.5,
        replicas=2, recovery_budget_ticks=50,
        events=(
            FaultEvent(tick=26, kind=ADMISSION_BLIP, target="fleet",
                       duration=6),
            _crash(36, "index:0", jitter=2),
        ),
        description="an admission blip lands mid-spike (every submit "
                    "sheds, visibly), then a replica dies in the "
                    "aftermath — overload and failure composed",
    )


@register_fault_plan("prefill_kill_mid_handoff")
def prefill_kill_mid_handoff(seed: int = 0) -> FaultPlan:
    def corrupt(tick, jitter=0):
        return FaultEvent(tick=tick, kind=HANDOFF_CORRUPTION,
                          target="fleet", params=(("force", True),),
                          jitter_ticks=jitter)

    return FaultPlan(
        name="prefill_kill_mid_handoff", seed=seed,
        scenario="disagg_mix", ticks_scale=0.5,
        replicas=3, disagg=True, recovery_budget_ticks=60,
        events=(
            corrupt(10),
            # the prefill specialist dies with handoffs in flight:
            # exported records are fleet-held, so the pump re-delivers
            # them while the supervisor re-forms the pool
            _crash(18, "index:0", jitter=2),
            corrupt(34, jitter=2),
        ),
        description="a handoff payload is bit-flipped, then the "
                    "prefill specialist is killed with handoffs in "
                    "flight; the ledger conserves every record — "
                    "corrupted ones recompute with a reason, in-"
                    "flight ones re-deliver — and streams stay token-"
                    "identical",
    )


__all__ = [
    "ADMISSION_BLIP",
    "FAULT_KINDS",
    "FAULT_PLANS",
    "FaultEvent",
    "FaultPlan",
    "HANDOFF_CORRUPTION",
    "REFORM_FAILURE",
    "REPLICA_CRASH",
    "STAGE_SLOWDOWN",
    "SWAP_CORRUPTION",
    "fault_plan_names",
    "get_fault_plan",
    "register_fault_plan",
]
