"""FaultInjector: the actuator that fires a FaultPlan at a live fleet.

The plan (:mod:`.plan`) is the value; this is the arm.  The fleet loop
already polls ``fleet.fault_injector.on_tick(fleet)`` FIRST in
``step()`` — before limbo redispatch, replica ticks, and the
supervisor — so an event scheduled at tick N lands before ANY of tick
N's work, exactly like the dynamics-plane ``FleetFaultInjector`` it
generalizes.  Composition with the workload plane is one assignment:

    fleet.fault_injector = FaultInjector(get_fault_plan("reform_flap"))
    ScenarioPlayer(scenario, fleet, sample_fn=make_probe(fleet)).play()

**Sanctioned hooks only.**  Every kind lowers to one public fault
surface — ``replica.crash()`` / ``inject_stall`` / ``fail_next_builds``,
``engine.corrupt_swap_record``, ``admission.blip_active`` — never a
monkeypatch, so an injected run can only reach states the real system
can.  Before the first event fires, the plan is re-verified through
``analysis.plan_check.verify_fault_plan`` (verify-then-apply: a
malformed plan dies before any mutation, and the checker is imported
lazily at decision time, the autoscaler idiom).

**Honest bookkeeping.**  Every application appends one entry to the
event log — including SKIPS (a selector that resolves to nothing, a
corruption with no record to poison even under force) with
``ok=False`` and a note, because a fault that silently didn't happen
poisons every downstream invariant.  The one deliberate exception to
exact-tick firing: a ``pending_removal`` event whose tick passes with
no drain in flight ARMS (logged) and fires at the first later tick
the autoscaler is mid-removal — "kill the next drain" is the only
honest way to hit a window whose exact tick the plan cannot know.  A
``handoff_corruption`` that finds nothing in flight arms the same way
and fires at the first tick a handoff record IS mid-flight (the
KV-handoff window is one tick wide by construction — "corrupt the
next handoff" is the only honest way to hit it).  The log carries NO request ids or
wall times (ids mint from a process-global counter), and its
``deterministic_log()`` projection — everything except which live
replica a load-based selector resolved to — is byte-identical across
same-seed runs.  Applied faults count ``FleetStats.faults_injected`` and emit
``fault_inject`` trace instants; each fault burst opens an async
``recovery`` arc on the chaos lane that closes — counting
``recoveries_completed`` — when the fleet next reaches a settled state
(every replica HEALTHY or RETIRED, nobody crashed-but-undetected, no
migration limbo, at least one healthy replica, no live blip).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from ..fleet.replica import RETIRED
from ..telemetry import get_tracer
from ..utils import Logger
from .invariants import fleet_settled
from .plan import (
    ADMISSION_BLIP,
    HANDOFF_CORRUPTION,
    REFORM_FAILURE,
    REPLICA_CRASH,
    STAGE_SLOWDOWN,
    SWAP_CORRUPTION,
    FaultEvent,
    FaultPlan,
)


class FaultInjector:
    """Apply a :class:`~.plan.FaultPlan`'s events at exact fleet ticks
    through sanctioned hooks, with a replayable event log."""

    def __init__(self, plan: FaultPlan,
                 logger: Optional[Logger] = None):
        self.plan = plan
        self._logger = logger or Logger()
        self._by_tick: Dict[int, List[FaultEvent]] = {}
        for event in plan.resolved_events():
            self._by_tick.setdefault(event.tick, []).append(event)
        self._verified = False
        self._blip_clear_tick: Optional[int] = None
        #: ``pending_removal`` events whose tick passed with no drain
        #: in flight: they stay armed and fire at the FIRST later tick
        #: where the autoscaler is mid-removal (both the arming and the
        #: eventual firing are logged — honest bookkeeping)
        self._armed: List[FaultEvent] = []
        #: the replayable record: one dict per event APPLICATION
        #: attempt, in firing order — no request ids, no wall times
        self.applied: List[Dict[str, Any]] = []
        #: tick of the most recent successfully applied fault (the
        #: auditor's recovery-budget anchor); None before any fired
        self.last_fault_tick: Optional[int] = None
        #: closed recovery arcs: (fault burst's last tick, settled
        #: tick) pairs — time-to-healthy as data
        self.recoveries: List[Dict[str, int]] = []
        self._recovery_open = False
        self._arc_id = 0

    # --- plan surface -------------------------------------------------------
    def event_log(self) -> List[Dict[str, Any]]:
        """The applications so far (copy), including which live
        replica each selector resolved to."""
        return [dict(e) for e in self.applied]

    def deterministic_log(self) -> List[Dict[str, Any]]:
        """The event log minus ``resolved`` — the determinism artifact
        two same-seed runs compare byte for byte.  ``resolved`` is
        excluded deliberately: load-based selection (the autoscaler's
        scale-down victim a ``pending_removal`` kill lands on) reads
        wall-clock-sensitive routing state the chaos plane does not
        control; everything the PLAN controls — which events fired at
        which ticks, with which outcome — is replayable."""
        return [{k: e[k] for k in ("tick", "kind", "target", "params",
                                   "duration", "ok", "note")}
                for e in self.applied]

    def _verify(self) -> None:
        # verify-then-apply, lazily: the schema checker is an analysis
        # import pulled in at decision time only (keeping chaos's
        # import graph to serving/fleet/telemetry at module load)
        from ..analysis.plan_check import verify_fault_plan
        problems = verify_fault_plan(self.plan.to_dict())
        if problems:
            raise ValueError(
                f"fault plan {self.plan.name!r} failed verification: "
                f"{problems}"
            )
        self._verified = True

    # --- the tick hook ------------------------------------------------------
    def on_tick(self, fleet) -> None:
        """Called FIRST in ``ServingFleet.step()``: settle any open
        recovery arc, lift expired blips, then fire this tick's
        events."""
        if not self._verified:
            self._verify()
        if (self._blip_clear_tick is not None
                and fleet.tick >= self._blip_clear_tick):
            fleet.admission.blip_active = False
            self._blip_clear_tick = None
        # recovery settles BEFORE this tick's events fire, so a burst
        # landing on an already-settled fleet opens a fresh arc
        # (fleet_settled is the auditor's own predicate — the arc and
        # the gate agree by construction)
        if self._recovery_open and fleet_settled(fleet):
            self._close_recovery(fleet)
        if self._armed:
            # at most ONE armed event fires per tick: a drain window is
            # one removal, and killing the same draining replica twice
            # proves nothing — the rest stay armed for the next drain
            for i, event in enumerate(self._armed):
                if not self._armed_ready(fleet, event):
                    continue
                self._armed.pop(i)
                self._apply(fleet, event)
                break
        for event in self._by_tick.get(fleet.tick, ()):
            self._apply(fleet, event)

    def _close_recovery(self, fleet) -> None:
        self._recovery_open = False
        fleet.stats.recoveries_completed += 1
        self.recoveries.append(dict(
            fault_tick=int(self.last_fault_tick),
            settled_tick=int(fleet.tick),
        ))
        tracer = get_tracer()
        if tracer is not None:
            tracer.async_end(
                "recovery", tracer.lane("fleet", "chaos"),
                self._arc_id,
                {"fault_tick": self.last_fault_tick,
                 "settled_tick": fleet.tick},
            )

    def _open_recovery(self, fleet) -> None:
        if self._recovery_open:
            return
        self._recovery_open = True
        self._arc_id += 1
        tracer = get_tracer()
        if tracer is not None:
            tracer.async_begin(
                "recovery", tracer.lane("fleet", "chaos"),
                self._arc_id, {"tick": fleet.tick},
            )

    # --- event application --------------------------------------------------
    def _armed_ready(self, fleet, event: FaultEvent) -> bool:
        """Can this ARMED event fire now?  ``pending_removal`` needs a
        drain in flight; ``handoff_corruption`` needs a handoff record
        mid-flight (the one-tick PENDING window)."""
        if event.kind == HANDOFF_CORRUPTION:
            ledger = getattr(fleet, "ledger", None)
            return ledger is not None and bool(ledger.pending())
        _, note = self._resolve(fleet, event)
        return note is None

    def _resolve(self, fleet, event: FaultEvent):
        """(replica-or-None, note): the live target, or why there is
        none.  ``fleet``-targeted events resolve to (None, None)."""
        target = event.target
        if target == "fleet":
            return None, None
        if target == "pending_removal":
            for r in fleet.replicas:
                if r.pending_removal and r.state != RETIRED:
                    return r, None
            return None, "no replica is mid-removal"
        if target.startswith("index:"):
            idx = int(target[len("index:"):])
            if idx >= len(fleet.replicas):
                return None, f"index {idx} out of range"
            replica = fleet.replicas[idx]
        else:  # name:X (plan validation allows nothing else)
            name = target[len("name:"):]
            replica = next(
                (r for r in fleet.replicas if r.name == name), None
            )
            if replica is None:
                return None, f"no replica named {name!r}"
        if replica.state == RETIRED:
            return None, "target is retired"
        return replica, None

    def _apply(self, fleet, event: FaultEvent) -> None:
        params = event.params_dict()
        replica, note = self._resolve(fleet, event)
        ok = note is None
        if (not ok and event.target == "pending_removal"
                and event not in self._armed):
            # a mid-drain kill with no drain in flight ARMS instead of
            # dying: it fires at the next tick a removal is draining
            # (two-phase scale-down guarantees every removal has one)
            self._armed.append(event)
            note = f"{note}; armed"
        if ok:
            if event.kind == REPLICA_CRASH:
                replica.crash()
            elif event.kind == STAGE_SLOWDOWN:
                replica.inject_stall(
                    params["seconds"],
                    clear_at_tick=fleet.tick + event.duration,
                )
            elif event.kind == REFORM_FAILURE:
                replica.fail_next_builds(params["builds"])
            elif event.kind == SWAP_CORRUPTION:
                if replica.engine is None:
                    ok, note = False, "target has no engine"
                else:
                    try:
                        rid = replica.engine.corrupt_swap_record(
                            force=params.get("force", True)
                        )
                    except ValueError as exc:
                        ok, note = False, str(exc)
                    else:
                        if rid is None:
                            ok = False
                            note = "no swap record to corrupt"
            elif event.kind == HANDOFF_CORRUPTION:
                # duck-typed: only a disagg fleet exposes the hook, and
                # an honest skip beats a monkeypatch on a plain fleet
                hook = getattr(fleet, "corrupt_handoff", None)
                if hook is None:
                    ok, note = False, "fleet has no handoff plane"
                else:
                    try:
                        rid = hook(force=params.get("force", True))
                    except (KeyError, ValueError) as exc:
                        ok, note = False, str(exc)
                    else:
                        if rid is None:
                            ok = False
                            note = "no handoff record to corrupt"
            elif event.kind == ADMISSION_BLIP:
                fleet.admission.blip_active = True
                clear = fleet.tick + event.duration
                self._blip_clear_tick = (
                    clear if self._blip_clear_tick is None
                    else max(self._blip_clear_tick, clear)
                )
            else:  # pragma: no cover - plan validation forbids this
                raise ValueError(
                    f"unsanctioned fault kind {event.kind!r}"
                )
        if (not ok and event.kind == HANDOFF_CORRUPTION
                and note == "no handoff record to corrupt"
                and event not in self._armed):
            # the in-flight window is one tick wide: arm and poison the
            # NEXT handoff instead of dying (a fleet with no handoff
            # plane at all stays an honest skip — it will never fire)
            self._armed.append(event)
            note = f"{note}; armed"
        entry = dict(
            tick=int(fleet.tick), kind=event.kind,
            target=event.target,
            resolved="fleet" if replica is None and ok
            else (replica.name if replica is not None else None),
            params=params, duration=int(event.duration),
            ok=bool(ok), note=note,
        )
        self.applied.append(entry)
        if not ok:
            self._logger.warning(
                f"FaultInjector: {event.kind} at tick {fleet.tick} "
                f"skipped ({note})"
            )
            return
        self.last_fault_tick = int(fleet.tick)
        fleet.stats.faults_injected += 1
        self._open_recovery(fleet)
        tracer = get_tracer()
        if tracer is not None:
            tracer.instant(
                "fault_inject", tracer.lane("fleet", "chaos"),
                {"kind": event.kind, "target": event.target,
                 "resolved": entry["resolved"],
                 "duration": event.duration},
            )
        self._logger.info(
            f"FaultInjector: {event.kind} -> {entry['resolved']} "
            f"at tick {fleet.tick}"
        )


__all__ = ["FaultInjector"]
