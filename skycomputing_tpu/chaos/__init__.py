"""The chaos plane: seeded, replayable fault campaigns + auditing.

- :mod:`.plan` — the PURE-STDLIB core: :class:`FaultEvent` /
  :class:`FaultPlan` declare a fault campaign; one seeded
  ``random.Random`` lowers jitter to a byte-reproducible event
  schedule, and the named catalog (``replica_crash_storm``,
  ``rolling_stragglers``, ``mid_drain_kill``, ``swap_corruption``,
  ``reform_flap``, ``overload_then_crash``) gives every campaign a
  stable ``--plan`` name (``tools/chaos_smoke.py`` file-path-loads
  this on a bare runner);
- :mod:`.injector` — :class:`FaultInjector` fires a plan's events at
  exact fleet ticks through sanctioned hooks only, with an honest,
  replayable event log;
- :mod:`.invariants` — the whole-run auditor: token conservation,
  reasoned terminal states, token identity against a fault-free
  reference, page/refcount consistency, counter monotonicity, and the
  gated recovery budget.

The heavy halves (injector/invariants import the fleet stack) load
lazily so the stdlib core stays importable anywhere.
"""

from __future__ import annotations

from .plan import (
    ADMISSION_BLIP,
    FAULT_KINDS,
    FAULT_PLANS,
    FaultEvent,
    FaultPlan,
    REFORM_FAILURE,
    REPLICA_CRASH,
    STAGE_SLOWDOWN,
    SWAP_CORRUPTION,
    fault_plan_names,
    get_fault_plan,
    register_fault_plan,
)

try:  # fleet-backed halves; absent on bare stdlib-only runners
    from .injector import FaultInjector
    from .invariants import (
        AuditCheck,
        AuditReport,
        audit_run,
        fleet_settled,
        make_probe,
    )
except ImportError:  # pragma: no cover - exercised on bare runners
    FaultInjector = None  # type: ignore[assignment]
    AuditCheck = AuditReport = None  # type: ignore[assignment]
    audit_run = fleet_settled = make_probe = None  # type: ignore

__all__ = [
    "ADMISSION_BLIP",
    "AuditCheck",
    "AuditReport",
    "FAULT_KINDS",
    "FAULT_PLANS",
    "FaultEvent",
    "FaultInjector",
    "FaultPlan",
    "REFORM_FAILURE",
    "REPLICA_CRASH",
    "STAGE_SLOWDOWN",
    "SWAP_CORRUPTION",
    "audit_run",
    "fault_plan_names",
    "fleet_settled",
    "get_fault_plan",
    "make_probe",
    "register_fault_plan",
]
