"""One fleet member: a ServingEngine plus health state and a rebuild path.

The replica is the unit of failure AND of recovery: it owns the
zero-arg ``build_engine`` callable that produced its engine (the
worker-manager-path constructor, with its serving pre-flight), so
re-forming after a crash is *the same verified construction* the fleet
booted with — verify-then-apply by reuse, not by re-implementation.
Fault injection lands here too (:meth:`crash` / :meth:`inject_stall` /
:meth:`leak_slots`, driven by
:class:`~..dynamics.faults.FleetFaultInjector`), so a chaos plan and the
supervisor see one consistent surface.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional

from ..serving.engine import ServingEngine, ServingStats

# replica lifecycle states
HEALTHY = "healthy"    # serving traffic
DRAINING = "draining"  # sick, out of rotation, finishing requests that
#                        cannot migrate (resume prefix outgrew every
#                        bucket) before re-forming — alive is alive
DEAD = "dead"          # crashed/declared dead, awaiting re-form
EVICTED = "evicted"    # drained (sick), awaiting re-form
RETIRED = "retired"    # re-form budget exhausted; permanently out


class ReplicaCrashed(RuntimeError):
    """A tick reached a crashed replica's engine (the in-process stand-in
    for an RPC timeout against a dead server)."""


class EngineReplica:
    """A named :class:`ServingEngine` with health and fault surface."""

    #: metric classification for :meth:`stats_snapshot`, this class's
    #: registered fleet source: the engine's own surface plus the
    #: replica-level ``generation`` (a version stamp, not a rate-able
    #: counter — it only moves at re-forms and resets with the replica
    #: object, so deriving a per-second rate from it is meaningless).
    #: skyaudit cross-checks every key the snapshot produces against
    #: this dict (MANIFEST snapshot_contracts).
    FIELD_TYPES = {**ServingStats.FIELD_TYPES, "generation": "gauge"}

    def __init__(self, name: str,
                 build_engine: Callable[[], ServingEngine],
                 *, defer_build: bool = False, role: str = ""):
        self.name = str(name)
        self._build = build_engine
        # pool role for disaggregated serving ("prefill" / "decode";
        # empty = the monolithic default, routable for any work).  A
        # label, not behavior: the engine spec behind build_engine is
        # what actually specializes the replica — the role just makes
        # that specialization visible to the router and autoscaler.
        self.role = str(role)
        if defer_build:
            # a PROVISIONAL replica (fleet scale-up): no engine yet,
            # parked DEAD so the only way it can ever serve is through
            # the supervisor's budgeted verify-then-apply re-form path
            # (_attempt_reform -> rebuild) — an autoscaler ADD is the
            # same verified construction as a post-crash re-form, by
            # reuse.  generation -1 so the first successful build lands
            # at 0, exactly like an eagerly-built replica.
            self.engine: Optional[ServingEngine] = None
            self.state = DEAD
            self.generation = -1
        else:
            self.engine = build_engine()
            # request-scoped trace spans attribute their segments to
            # the replica, not the anonymous "engine"
            self.engine.trace_name = self.name
            self.state = HEALTHY
            self.generation = 0
        # set by the autoscaler's drain-then-remove: a DRAINING replica
        # flagged here is finishing its last requests on the way OUT of
        # the fleet — the supervisor must finalize the removal when the
        # drain empties, never re-form it
        self.pending_removal = False
        # monotonic counter discipline across re-forms: a rebuilt
        # engine starts a fresh ServingStats, but the REPLICA's
        # counters must never go backwards mid-run or every
        # time-series rate over the fleet registry turns undefined at
        # each heal.  Prior generations' cumulative counters accumulate
        # here; stats_snapshot() adds them back.
        self._carried: Dict[str, float] = {}
        # fault surface (written by FleetFaultInjector and the chaos
        # plane's FaultInjector)
        self.crashed = False
        self._stall_s = 0.0
        self._stall_clear_tick: Optional[int] = None
        self.leaked_slots: List[int] = []
        self._pending_leaks = 0
        self._build_failures = 0
        # heartbeat ledger: beats are successful ticks; the supervisor
        # reads (and resets) consecutive misses
        self.beats = 0
        self.missed_beats = 0

    # --- serving ------------------------------------------------------------
    def tick(self, fleet_tick: int) -> None:
        """One engine iteration, or :class:`ReplicaCrashed`.

        Named ``tick`` (not ``step``) deliberately: the engine's
        ``step()`` blocks on its own device work internally, so the
        fleet timing a ``tick()`` call measures real compute, and the
        name keeps that distinction visible at the call site."""
        if self.crashed:
            raise ReplicaCrashed(f"replica {self.name} is crashed")
        if (self._stall_clear_tick is not None
                and fleet_tick >= self._stall_clear_tick):
            self._stall_s = 0.0
            self._stall_clear_tick = None
        if self._pending_leaks > 0:
            # a leak is sticky: it seizes capacity as it frees, the way
            # a real free-list bug eats a pool one release at a time
            self._pending_leaks -= self._leak_now(self._pending_leaks)
        self.engine.step()
        if self._stall_s > 0.0:
            # the injected degradation: a slow host/NIC stretches every
            # iteration, which is exactly what the EWMA must catch
            time.sleep(self._stall_s)
        self.beats += 1
        self.missed_beats = 0

    @property
    def serving(self) -> bool:
        return self.state == HEALTHY

    # --- health surface -----------------------------------------------------
    @property
    def slot_accounting_ok(self) -> bool:
        """Every occupied KV slot is owned by a running request.

        A leak (occupied > running) is capacity silently gone — the
        deterministic detection signal for the ``slot_leak`` fault and
        for real free-list bugs alike."""
        pool = self.engine.stages[0].pool
        return pool.used_slots <= len(self.engine.running_requests)

    #: SLO samples a snapshot reads: the engine's lifetime lists are
    #: unbounded, and this snapshot sits on the router's per-dispatch
    #: hot path — recent samples are both cheaper (bounded sort) and
    #: the truer routing signal (a replica's pace NOW, not its history)
    SNAPSHOT_WINDOW = 256

    def stats_snapshot(self) -> dict:
        """``ServingStats.snapshot()`` with counters made monotonic for
        the REPLICA's lifetime: cumulative fields carry across re-forms
        (``_carried``), so the fleet registry's per-replica source
        never shows a counter reset mid-run.  Gauges and percentile
        summaries stay the live engine's own.  This is the fleet's
        registered metric source for the replica."""
        snap = self.engine.stats.snapshot()
        for field, base in self._carried.items():
            value = snap.get(field)
            if isinstance(value, (int, float)):
                snap[field] = value + base
        snap["generation"] = self.generation
        return snap

    def snapshot(self) -> dict:
        """The router/admission view of this replica (plain scalars,
        feeds the fleet ``MetricsRegistry`` too)."""
        if self.engine is None:
            # provisional replica mid-scale-up: visible, never routable
            return dict(name=self.name, healthy=False,
                        state=self.state, generation=self.generation,
                        role=self.role,
                        slots=0, free_slots=0, queue_depth=0,
                        running=0, ttft_p95_s=None, tpot_p50_s=None,
                        tpot_p95_s=None)
        pool = self.engine.stages[0].pool
        stats = self.engine.stats
        w = self.SNAPSHOT_WINDOW
        ttft, tpot = stats.ttft_s[-w:], stats.tpot_s[-w:]
        return dict(
            name=self.name,
            healthy=self.serving and not self.crashed,
            state=self.state,
            generation=self.generation,
            role=self.role,
            slots=self.engine.num_slots,
            free_slots=pool.free_slots,
            queue_depth=self.engine.stats.queue_depth,
            running=len(self.engine.running_requests),
            ttft_p95_s=_pct(ttft, 95),
            tpot_p50_s=_pct(tpot, 50),
            tpot_p95_s=_pct(tpot, 95),
        )

    # --- fault surface (FleetFaultInjector) ---------------------------------
    def crash(self) -> None:
        self.crashed = True

    def inject_stall(self, seconds: float,
                     clear_at_tick: Optional[int] = None) -> None:
        """Stall every tick by ``seconds``; with ``clear_at_tick`` the
        stall clears when ``tick()`` first runs at/after that fleet
        tick, else it persists until re-form."""
        self._stall_s = float(seconds)
        self._stall_clear_tick = (
            None if clear_at_tick is None else int(clear_at_tick)
        )

    def leak_slots(self, count: int) -> int:
        """Leak ``count`` slots (allocated with no owning request).

        Whatever the pool cannot give up right now stays pending and is
        seized tick by tick as slots free — a leak against a saturated
        pool is deferred, not defeated.  Returns how many leaked
        immediately."""
        leaked = self._leak_now(max(count, 0))
        self._pending_leaks += max(count, 0) - leaked
        return leaked

    def fail_next_builds(self, count: int) -> None:
        """Force the next ``count`` :meth:`rebuild` calls to fail (the
        ``reform_failure`` chaos kind: an infeasible re-allocation, an
        OOMing builder — any rebuild the pre-flight would reject).

        The failure fires BEFORE the builder runs, so the rollback
        contract holds exactly as for a real builder failure: nothing
        is mutated, the supervisor's ``max_reforms`` budget is spent,
        and the backoff clock starts."""
        self._build_failures = max(int(count), 0)

    def _leak_now(self, count: int) -> int:
        leaked = 0
        for _ in range(count):
            slot = self.engine._allocate_slot()
            if slot is None:
                break
            self.leaked_slots.append(slot)
            leaked += 1
        return leaked

    # --- recovery -----------------------------------------------------------
    def rebuild(self) -> None:
        """Re-form: construct a FRESH engine through the same builder
        that made the original (worker-manager pre-flight included) and
        only then swap it in — a failed build leaves the old state
        untouched for the supervisor's rollback accounting."""
        if self._build_failures > 0:
            # the injected reform_failure: spend one charge and die
            # exactly where a real builder rejection would, before any
            # state is touched
            self._build_failures -= 1
            raise RuntimeError(
                f"injected build failure on replica {self.name} "
                f"({self._build_failures} more pending)"
            )
        engine = self._build()
        # bank the dying generation's cumulative counters BEFORE the
        # swap (the stats object is still readable even for a crashed
        # replica — the crash is simulated at the RPC surface), so
        # stats_snapshot() stays monotonic across the re-form.  A
        # provisional (defer_build) replica has no prior generation to
        # bank.
        if self.engine is not None:
            old = self.engine.stats
            for field in ServingStats.COUNTER_FIELDS:
                self._carried[field] = (
                    self._carried.get(field, 0) + getattr(old, field)
                )
        engine.trace_name = self.name
        self.engine = engine
        self.state = HEALTHY
        self.generation += 1
        self.crashed = False
        self._stall_s = 0.0
        self._stall_clear_tick = None
        self.leaked_slots = []
        self._pending_leaks = 0
        self.missed_beats = 0


def _pct(samples, q) -> Optional[float]:
    """Percentile by nearest-rank over a small sample list (stdlib-only
    twin of the ServingStats computation; None with no samples)."""
    if not samples:
        return None
    ordered = sorted(samples)
    rank = max(0, min(len(ordered) - 1,
                      round(q / 100.0 * (len(ordered) - 1))))
    return float(ordered[int(rank)])


__all__ = [
    "DEAD",
    "DRAINING",
    "EVICTED",
    "EngineReplica",
    "HEALTHY",
    "RETIRED",
    "ReplicaCrashed",
]
