"""ServingFleet: N replicated engines behind SLO-aware admission.

One :class:`~..serving.ServingEngine` is one pipeline; the fleet is the
layer the ROADMAP's "millions of users" north star actually needs:

- **replication** — N engine replicas, each built through the same
  allocator/worker-manager path (and the same serving pre-flight) a
  single engine uses; Orca's iteration-level scheduling stays strictly
  per-replica, so this layer never reaches into an engine's tick.
- **routing** — :class:`~.router.Router` least-loaded + prefix-affinity
  dispatch over live replica snapshots (queue depth, free slots,
  TTFT/TPOT percentiles — the ``MetricsRegistry`` surface).
- **admission control** — :class:`~.admission.AdmissionController`
  bounded intake with priority classes, deadline-aware rejects, and
  ``Retry-After``-style hints; rejects are counted per reason, never
  silent.
- **self-heal** — :class:`~.supervisor.FleetSupervisor` detects sick or
  dead replicas (heartbeat + EWMA health score), drains them through
  the engine ``preempt`` contract, re-queues the work
  recomputation-style onto survivors (token streams provably intact —
  the ``Request`` object carries its committed tokens, so a migrated
  request resumes exactly), and re-forms the lost replica through its
  original verified builder.

The fleet loop is synchronous and single-threaded (the single-
controller design this repo runs everywhere): ``step()`` ticks every
healthy replica once, then lets the supervisor look.  Determinism is
the point — a seeded :class:`~..dynamics.faults.FleetFaultInjector`
plan replays a replica crash byte-for-byte, which is what makes the
chaos suite a real gate instead of a flake generator.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from ..serving.batcher import FAILED, FINISHED, QueueFullError, REJECTED, Request
from ..serving.engine import ServingEngine, ServingStats
from ..telemetry import (
    FlightRecorder,
    IncidentEngine,
    LiveMetricsMixin,
    MetricsRegistry,
    SEV_CRITICAL,
    build_bundle,
    get_tracer,
)
from ..utils import Logger
from ..utils.retry import retry_call
from .admission import (
    AdmissionController,
    AdmitDecision,
    BATCH,
    REPLICAS_SATURATED,
)
from .replica import (
    DRAINING,
    EngineReplica,
    HEALTHY,
    RETIRED,
    ReplicaCrashed,
)
from .router import Router
from .supervisor import FleetSupervisor, REFORMED


@dataclass
class FleetStats:
    """Fleet-level accounting (the ``ServingStats`` of the fleet layer).

    Per-replica serving counters stay on each replica's own
    ``ServingStats``; this records what only the fleet can see —
    admission outcomes, migrations, re-forms, failures.  Every request
    turned away or lost increments a counter here: degradation is only
    acceptable when it is visible.
    """

    submitted: int = 0
    admitted: int = 0
    dispatched: int = 0
    rejected: int = 0
    rejected_by_reason: Dict[str, int] = field(default_factory=dict)
    migrations: int = 0
    failed: int = 0
    reforms: int = 0
    reform_failures: int = 0
    missed_beats: int = 0
    ticks: int = 0
    # autoscaler outcomes: replicas added / drained-and-removed /
    # decisions the pre-flight (or the verified build) rejected with
    # the fleet untouched — scale events must be as countable as
    # rejections, or "it scaled down overnight" is unexplainable
    scale_ups: int = 0
    scale_downs: int = 0
    scale_rejected: int = 0
    # chaos-plane accounting: faults the injector actually applied
    # (skipped targets never count) and fault bursts the fleet fully
    # recovered from — the recovery arc's terminal counter
    faults_injected: int = 0
    recoveries_completed: int = 0
    #: incident plane: anomalies the detector rules opened over the
    #: fleet's own flight recorder (0 until ``attach_flight``)
    incidents_opened: int = 0
    # gauges (last step)
    replicas_healthy: int = 0
    replicas_total: int = 0
    #: replicas RETIRED out of rotation (re-form budget exhausted):
    #: permanently lost capacity an operator must see as a number, not
    #: infer from replicas_total minus replicas_healthy
    replicas_quarantined: int = 0
    pending: int = 0
    #: queued-but-unserved backlog (replica queues + limbo, running
    #: excluded) — the overload gauge SLO targets should burn on:
    #: ``pending`` includes running work, so a full-but-keeping-up
    #: fleet reads high on it by design
    queue_depth: int = 0
    limbo_depth: int = 0
    #: incidents currently open (gauge twin of ``incidents_opened``)
    incidents_open: int = 0

    def count_rejection(self, reason: str) -> None:
        self.rejected += 1
        self.rejected_by_reason[reason] = (
            self.rejected_by_reason.get(reason, 0) + 1
        )

    #: metric classification (telemetry.MetricsRegistry contract):
    #: counters are cumulative for the FLEET's lifetime — re-forms and
    #: reconfigurations never reset them — so time-series rates over
    #: the fleet source are always well-defined.  The percentile keys
    #: the fleet snapshot adds are gauges over rolling windows.
    FIELD_TYPES = {
        "submitted": "counter", "admitted": "counter",
        "dispatched": "counter", "rejected": "counter",
        "rejected_by_reason": "counter", "migrations": "counter",
        "failed": "counter", "reforms": "counter",
        "reform_failures": "counter", "missed_beats": "counter",
        "ticks": "counter",
        "scale_ups": "counter", "scale_downs": "counter",
        "scale_rejected": "counter",
        "faults_injected": "counter",
        "recoveries_completed": "counter",
        "incidents_opened": "counter",
        "replicas_healthy": "gauge", "replicas_total": "gauge",
        "replicas_quarantined": "gauge",
        "pending": "gauge", "queue_depth": "gauge",
        "limbo_depth": "gauge", "incidents_open": "gauge",
        "ttft_p50_s": "gauge", "ttft_p95_s": "gauge",
        "tpot_p50_s": "gauge", "tpot_p95_s": "gauge",
    }

    def snapshot(self) -> Dict[str, Any]:
        return dict(
            submitted=self.submitted,
            admitted=self.admitted,
            dispatched=self.dispatched,
            rejected=self.rejected,
            rejected_by_reason=dict(self.rejected_by_reason),
            migrations=self.migrations,
            failed=self.failed,
            reforms=self.reforms,
            reform_failures=self.reform_failures,
            missed_beats=self.missed_beats,
            ticks=self.ticks,
            scale_ups=self.scale_ups,
            scale_downs=self.scale_downs,
            scale_rejected=self.scale_rejected,
            faults_injected=self.faults_injected,
            recoveries_completed=self.recoveries_completed,
            incidents_opened=self.incidents_opened,
            replicas_healthy=self.replicas_healthy,
            replicas_total=self.replicas_total,
            replicas_quarantined=self.replicas_quarantined,
            pending=self.pending,
            queue_depth=self.queue_depth,
            limbo_depth=self.limbo_depth,
            incidents_open=self.incidents_open,
        )


class ServingFleet(LiveMetricsMixin):
    """N serving-engine replicas behind routing, admission, self-heal.

    ``model_cfg``/``params_list`` are the standard layer-config list and
    per-layer param trees every engine shares (replicas serve the same
    model; params are committed per replica device by each engine's own
    constructor).  ``replica_specs`` gives each replica its placement —
    any ``ServingEngine`` kwargs (``partition``/``devices``/
    ``worker_manager``...) — while ``engine_kwargs`` carries the shared
    operating point (slots, buckets, ``max_queue``...).  Default: one
    single-stage replica per fake/real device, round-robin.
    """

    def __init__(
        self,
        model_cfg: Sequence[Dict],
        params_list: Sequence[Any],
        *,
        replicas: int = 2,
        replica_specs: Optional[Sequence[Dict[str, Any]]] = None,
        engine_kwargs: Optional[Dict[str, Any]] = None,
        router: Optional[Router] = None,
        admission: Optional[AdmissionController] = None,
        supervisor: Optional[FleetSupervisor] = None,
        fault_injector=None,
        autoscaler=None,
        devices: Optional[Sequence[Any]] = None,
        finished_history: int = 4096,
        slo_window: int = 2048,
        slo=None,
        logger: Optional[Logger] = None,
    ):
        self._logger = logger or Logger()
        self.router = router or Router()
        self.admission = admission or AdmissionController()
        self.supervisor = supervisor or FleetSupervisor(
            logger=self._logger
        )
        self.fault_injector = fault_injector
        self.stats = FleetStats()
        # kept for replica ADDs: a scaled-up replica is built through
        # the SAME shared-operating-point builder path the fleet booted
        # with (and the same serving pre-flight), not a parallel one
        self._model_cfg = model_cfg
        self._params_list = params_list
        self._shared_kwargs = dict(engine_kwargs or {})
        self._devices = (list(devices) if devices is not None
                         else list(jax.devices()))
        if replica_specs is None:
            replica_specs = [
                dict(devices=[self._devices[i % len(self._devices)]])
                for i in range(int(replicas))
            ]
        if not replica_specs:
            raise ValueError("a fleet needs at least one replica")
        self.replicas: List[EngineReplica] = [
            EngineReplica(f"replica{i}", self._make_builder(spec),
                          role=str(spec.get("role", "")))
            for i, spec in enumerate(replica_specs)
        ]
        self._by_name = {r.name: r for r in self.replicas}
        # per-replica placement specs (chip accounting for the scale
        # pre-flight) + a monotonic name sequence: replica names are
        # never reused, so supervisor telemetry and metric sources
        # can't alias across scale events
        self._specs: Dict[str, Dict[str, Any]] = {
            r.name: dict(spec)
            for r, spec in zip(self.replicas, replica_specs)
        }
        self._replica_seq = len(self.replicas)
        self.tick = 0
        # fleet ledger: every admitted, unfinished request — the source
        # of truth a dead replica's recovery reads (Request objects
        # carry their committed tokens, so nothing dies with an engine)
        self._pending: Dict[int, Request] = {}
        self._assignment: Dict[int, str] = {}
        # bounded: a fleet sized for "millions of users" must not grow
        # its ledgers with lifetime traffic.  _finished is a recency
        # history (insertion-ordered, oldest evicted past the cap); the
        # SLO windows are rolling samples the percentiles read in O(w)
        # instead of walking every request ever served.
        self._finished: Dict[int, Request] = {}
        self._finished_limit = max(int(finished_history), 1)
        self._ttft_window: deque = deque(maxlen=max(int(slo_window), 1))
        self._tpot_window: deque = deque(maxlen=max(int(slo_window), 1))
        # run()'s output collector: filled incrementally at finish time,
        # so history eviction can never lose a return value mid-call
        self._collector: Optional[Dict[int, Request]] = None
        # migration limbo: drained requests no survivor could hold yet;
        # re-dispatched at the start of every step
        self._limbo: List[Request] = []
        # one registry over the whole fleet: the "fleet" source plus one
        # serving source per replica (same poller reads everything).
        # Replica sources go through stats_snapshot so counters stay
        # monotonic across re-forms (see EngineReplica).
        self.metrics = MetricsRegistry()
        self.metrics.register("fleet", self._fleet_snapshot,
                              types=FleetStats.FIELD_TYPES)
        for rep in self.replicas:
            # the replica's OWN classification: engine fields plus the
            # replica-level `generation` stamp — registering the bare
            # ServingStats types left `generation` untyped on the
            # exporter (caught by skyaudit's snapshot-contract check)
            self.metrics.register(rep.name, rep.stats_snapshot,
                                  types=type(rep).FIELD_TYPES)
        # live observability (LiveMetricsMixin: enable_timeseries /
        # start_exporter; opt-in, zero-cost until enabled) plus the
        # fleet-only leg: an online SLO monitor evaluated every tick
        self.timeseries = None
        self.slo = None
        self._exporter = None
        # flight recorder + incident plane (opt-in via attach_flight;
        # zero-cost until attached — one `is not None` test per step)
        self.flight = None
        self.incidents = None
        self._flight_cursors: Dict[str, int] = {}
        self._flight_engine_marks: Dict[str, Tuple[int, int]] = {}
        self._slo_firing_prev: Tuple[str, ...] = ()
        self._bundle_events = 256
        self._bundles: deque = deque(maxlen=8)
        if slo is not None:
            self.attach_slo(slo)
        # the explicit admission bound was sized for THIS capacity;
        # stamping the baseline lets pending_bound() track live
        # healthy-replica capacity as the fleet scales (an explicit
        # baseline set by the caller wins)
        if getattr(self.admission, "baseline_capacity", None) is None:
            self.admission.baseline_capacity = self._capacity_slots()
        self.autoscaler = None
        if autoscaler is not None:
            self.attach_autoscaler(autoscaler)

    def _make_builder(self, spec: Dict[str, Any]):
        """Zero-arg engine builder for one replica spec merged over the
        fleet's shared operating point — the verified-construction
        callable both boot and every later re-form/scale-up run."""
        merged = dict(self._shared_kwargs)
        merged.update(spec)
        # "role" is replica metadata (disaggregated pool membership),
        # not an engine knob — it rides the spec so scale-ups re-form
        # into the right pool, but never reaches the engine ctor
        merged.pop("role", None)

        def build() -> ServingEngine:
            return ServingEngine(self._model_cfg, self._params_list,
                                 **merged)

        return build

    # --- live observability (LiveMetricsMixin + the SLO leg) ----------------
    #: fleet ticks are the finest sampling grain in the repo; keep a
    #: longer window than the single-engine default
    _timeseries_window = 1024

    def attach_slo(self, monitor):
        """Wire an online SLO monitor into the fleet loop.

        The monitor binds the fleet's time-series (created on demand),
        registers as the ``"slo"`` metric source, and becomes the
        optional tightening/priority signal for the admission
        controller and supervisor — unless they already carry their
        own.  ``step()`` then evaluates it every tick, emitting
        ``slo_alert`` trace instants while any target burns.
        """
        if self.slo is not None:
            raise ValueError("an SLO monitor is already attached")
        if monitor.timeseries is None:
            monitor.timeseries = self.enable_timeseries()
        self.slo = monitor
        self.metrics.register("slo", monitor.snapshot,
                              types=type(monitor).FIELD_TYPES)
        if getattr(self.admission, "slo_monitor", None) is None:
            self.admission.slo_monitor = monitor
        if getattr(self.supervisor, "slo_monitor", None) is None:
            self.supervisor.slo_monitor = monitor
        return monitor

    def attach_autoscaler(self, autoscaler):
        """Wire a :class:`~.autoscaler.FleetAutoscaler` into the fleet
        loop: ``step()`` polls it after the SLO monitor has judged the
        tick, so every decision reads this tick's freshest burn/slack
        evidence."""
        if self.autoscaler is not None:
            raise ValueError("an autoscaler is already attached")
        self.autoscaler = autoscaler
        return autoscaler

    def attach_flight(self, recorder: Optional[FlightRecorder] = None,
                      *, rules=None, quiet_ticks: int = 8,
                      bundle_events: int = 256, max_bundles: int = 8):
        """Wire the always-on flight recorder + incident plane into the
        fleet loop.

        ``step()`` then drains every subsystem's event surface into the
        recorder once per tick (the sanctioned taps: supervisor,
        autoscaler, fault injector, disagg ledger, SLO firing edges,
        engine recompile/swap-corruption counters) and runs the
        detector rules over it; a triggered rule opens an incident and
        snapshots a postmortem bundle (:meth:`bundles`).  The incident
        engine reads the fleet time-series, so one is enabled on
        attach.
        """
        if self.flight is not None:
            raise ValueError("a flight recorder is already attached")
        recorder = recorder if recorder is not None else FlightRecorder()
        self.flight = recorder
        self._bundle_events = int(bundle_events)
        self._bundles = deque(maxlen=max(int(max_bundles), 1))
        self.metrics.register("flight", recorder.snapshot,
                              types=type(recorder).FIELD_TYPES)
        self.incidents = IncidentEngine(
            recorder, self.enable_timeseries(), rules,
            quiet_ticks=quiet_ticks,
        )
        self.metrics.register("incidents", self.incidents.snapshot,
                              types=type(self.incidents).FIELD_TYPES)
        return recorder

    @property
    def bundles(self) -> List[Dict[str, Any]]:
        """The retained postmortem bundles, oldest first (bounded by
        ``attach_flight``'s ``max_bundles``)."""
        return list(self._bundles)

    def _health_snapshot(self) -> Dict[str, Any]:
        """The ``/healthz`` body: per-replica lifecycle states plus an
        overall verdict (``ok`` all healthy / ``degraded`` some /
        ``down`` none)."""
        states = {r.name: r.state for r in self.replicas}
        healthy = len(self.healthy_replicas)
        status = ("ok" if healthy == len(self.replicas)
                  else "degraded" if healthy else "down")
        incidents_open: List[Dict[str, Any]] = []
        if self.incidents is not None:
            incidents_open = [i.to_dict()
                              for i in self.incidents.open_incidents]
            # an open critical incident caps the verdict: "every
            # replica is up" is not "ok" while a detector says the
            # fleet is corrupting counters or quarantining capacity
            if status == "ok" and any(
                i["severity"] == SEV_CRITICAL for i in incidents_open
            ):
                status = "degraded"
        return dict(
            status=status,
            tick=self.tick,
            healthy=healthy,
            replicas=states,
            # the supervisor's quarantine ledger: WHO is permanently
            # out, when, and why — not just a shrinking healthy count
            quarantined={
                name: dict(entry)
                for name, entry in self.supervisor.quarantined.items()
            },
            pending=len(self._pending),
            limbo=len(self._limbo),
            slo_firing=list(self.slo.firing) if self.slo else [],
            incidents_open=incidents_open,
        )

    # --- views --------------------------------------------------------------
    def replica_by_index(self, index: int) -> EngineReplica:
        return self.replicas[index]

    def replica_snapshots(self) -> List[Dict[str, Any]]:
        return [r.snapshot() for r in self.replicas]

    @property
    def healthy_replicas(self) -> List[EngineReplica]:
        return [r for r in self.replicas
                if r.state == HEALTHY and not r.crashed]

    def _capacity_slots(self) -> int:
        return sum(r.engine.num_slots for r in self.healthy_replicas)

    def _pending_depth(self) -> int:
        depth = sum(
            r.engine.stats.queue_depth for r in self.healthy_replicas
        )
        return depth + len(self._limbo)

    # --- replica scale-up / scale-down (driven by the autoscaler) -----------
    def chip_capacity(self) -> int:
        """Total chips this fleet may place replicas on (the device
        pool it was constructed over)."""
        return len(self._devices)

    def _replica_chips(self, name: str) -> int:
        devs = self._specs.get(name, {}).get("devices")
        return len(devs) if devs else 1

    def chips_in_use(self) -> int:
        """Chips held by every live (non-retired) replica — what the
        scale pre-flight subtracts from :meth:`chip_capacity`."""
        return sum(self._replica_chips(r.name) for r in self.replicas
                   if r.state != RETIRED)

    def add_replica(
        self, spec: Optional[Dict[str, Any]] = None
    ) -> EngineReplica:
        """Verified scale-up: one new replica through the supervisor's
        budgeted re-form machinery.

        The replica is created PROVISIONAL (no engine, parked DEAD) and
        only becomes HEALTHY through ``FleetSupervisor``'s
        ``_attempt_reform`` path — the same verified builder + serving
        pre-flight a post-crash re-form runs, with the same trace arcs.
        A rejected build unwinds structurally: the provisional replica
        is dropped, no metric source was registered, no request was
        ever routable to it — the fleet is exactly as before, and the
        caller (the autoscaler) counts the rejection."""
        if spec is None:
            spec = dict(devices=[
                self._devices[self._replica_seq % len(self._devices)]
            ])
        name = f"replica{self._replica_seq}"
        replica = EngineReplica(name, self._make_builder(spec),
                                defer_build=True,
                                role=str(spec.get("role", "")))
        self.replicas.append(replica)
        self._by_name[name] = replica
        self._specs[name] = dict(spec)
        self._replica_seq += 1
        outcome = self.supervisor.retry_reform(self, replica)
        if outcome != REFORMED:
            # structural rollback: the provisional replica never held
            # an engine, a request, or a metric source
            self.replicas = [r for r in self.replicas
                             if r is not replica]
            self._by_name.pop(name, None)
            self._specs.pop(name, None)
            self.supervisor.forget_replica(name)
            raise RuntimeError(
                f"scale-up replica {name} was rejected by the verified "
                f"build ({outcome})"
            )
        self.metrics.register(name, replica.stats_snapshot,
                              types=type(replica).FIELD_TYPES)
        self.stats.replicas_total = len(self.replicas)
        self._logger.info(
            f"ServingFleet: replica {name} added "
            f"(devices={spec.get('devices')})"
        )
        return replica

    def remove_replica(self, name: str) -> str:
        """Drain-then-remove scale-down; always returns ``"draining"``:
        the replica parks DRAINING (out of rotation, requests migrated)
        and the supervisor finalizes the removal on its next poll —
        once any requests that could not migrate finish.  Two-phase by
        design: every removal has a real DRAINING window, so a replica
        dying mid-removal always exercises the same hardened
        ``finish_removal(dead=True)`` path instead of racing an inline
        finalize.  Token streams survive exactly as they do a
        sick-replica heal: graceful preempt, forced redispatch onto
        survivors."""
        replica = self._by_name.get(name)
        if replica is None:
            raise ValueError(f"unknown replica {name!r}")
        if replica.pending_removal:
            return "draining"
        survivors = [r for r in self.replicas
                     if r.state == HEALTHY and r is not replica]
        if not survivors:
            raise ValueError(
                f"cannot remove {name}: it is the last healthy replica"
            )
        replica.pending_removal = True
        migrated = self.drain_replica(replica, dead=False)
        # out of rotation BEFORE redispatch, so the migrated requests
        # can only land on survivors
        replica.state = DRAINING
        self.router.forget_replica(name)
        self.redispatch(migrated)
        return "draining"

    def finalize_removal(self, replica: EngineReplica) -> None:
        """Drop a fully-drained replica from the fleet (chips
        released, metric source unregistered, name never reused)."""
        replica.state = RETIRED
        replica.pending_removal = False
        self.replicas = [r for r in self.replicas if r is not replica]
        self._by_name.pop(replica.name, None)
        self._specs.pop(replica.name, None)
        self.router.forget_replica(replica.name)
        self.supervisor.forget_replica(replica.name)
        self.metrics.unregister(replica.name)
        self.stats.replicas_total = len(self.replicas)
        self._logger.info(
            f"ServingFleet: replica {replica.name} removed"
        )

    def reset_slo_windows(self) -> None:
        """Forget the rolling TTFT/TPOT samples (benches call this
        after compile warmup: a warm request's TTFT is dominated by
        bucket compiles and would sit in the percentile window —
        and therefore in every SLO verdict — for the whole run)."""
        self._ttft_window.clear()
        self._tpot_window.clear()

    @staticmethod
    def _window_percentile(window: deque, q: float) -> Optional[float]:
        if not window:
            return None
        return float(np.percentile(list(window), q))

    def _fleet_snapshot(self) -> Dict[str, Any]:
        snap = self.stats.snapshot()
        snap.update(
            ttft_p50_s=self._window_percentile(self._ttft_window, 50),
            ttft_p95_s=self._window_percentile(self._ttft_window, 95),
            tpot_p50_s=self._window_percentile(self._tpot_window, 50),
            tpot_p95_s=self._window_percentile(self._tpot_window, 95),
        )
        return snap

    # --- admission + dispatch ----------------------------------------------
    def submit(self, request: Request, *, priority: str = BATCH,
               deadline_s: Optional[float] = None) -> AdmitDecision:
        """Admit-or-shed, then route.  Returns the decision either way
        — a reject carries the reason and a ``Retry-After``-style hint
        and marks the request ``REJECTED``; an accept carries the
        replica it landed on."""
        self.stats.submitted += 1
        tracer = get_tracer()
        if tracer is not None:
            # the request's trace starts HERE: one stable id (the
            # request_id) threads submit -> admission -> routing ->
            # engine spans -> any migration, on one recycled lane
            lane = tracer.request_lane(request.request_id)
            if lane is not None:
                tracer.instant(
                    "submitted", lane,
                    {"request": request.request_id,
                     "priority": priority},
                )
        decision = self._admit_decision(priority, deadline_s)
        if not decision.admitted:
            self._reject(request, decision, tracer)
            return decision
        # snapshots only after the admission gate: a rejected request
        # must not pay the per-replica snapshot walk for nothing
        snaps = self.replica_snapshots()
        try:
            name = self._dispatch(request, snaps, deadline_s,
                                  role=self._dispatch_role(request))
        except QueueFullError as exc:
            decision = AdmitDecision(
                False, reason=REPLICAS_SATURATED,
                retry_after_s=self.admission.estimate_wait_s(
                    exc.queue_depth + 1, max(self._capacity_slots(), 1),
                    self._window_percentile(self._tpot_window, 50),
                ),
                detail=dict(queue_depth=exc.queue_depth),
            )
            self._reject(request, decision, tracer)
            return decision
        self.stats.admitted += 1
        self.stats.dispatched += 1
        self._pending[request.request_id] = request
        self._assignment[request.request_id] = name
        if tracer is not None:
            tracer.instant(
                "dispatch", tracer.lane("fleet", "router"),
                {"request": request.request_id, "replica": name,
                 "priority": priority},
            )
        return AdmitDecision(True, replica=name,
                             detail=decision.detail)

    def _reject(self, request: Request, decision: AdmitDecision,
                tracer) -> None:
        request.status = REJECTED
        self.stats.count_rejection(decision.reason)
        if tracer is not None:
            tracer.instant(
                "reject", tracer.lane("fleet", "admission"),
                {"request": request.request_id,
                 "reason": decision.reason,
                 "retry_after_s": decision.retry_after_s},
            )
            lane = tracer.request_lane(request.request_id,
                                       lease=False)
            if lane is not None:
                tracer.instant(
                    "rejected", lane,
                    {"request": request.request_id,
                     "reason": decision.reason,
                     "retry_after_s": decision.retry_after_s},
                )
            tracer.release_request_lane(request.request_id)

    def _admit_decision(self, priority: str,
                        deadline_s: Optional[float]) -> AdmitDecision:
        """The front-door admission verdict for one submit.  A hook so
        disaggregated fleets can gate each pool's controller separately
        (per-pool pending/capacity) while :meth:`submit` stays the one
        tracing/accounting path."""
        return self.admission.decide(
            pending=self._pending_depth(),
            capacity_slots=self._capacity_slots(),
            priority=priority,
            deadline_s=deadline_s,
            tpot_p50_s=self._window_percentile(self._tpot_window, 50),
        )

    def _dispatch_role(self, request: Request) -> Optional[str]:
        """The pool a request should route to — None on monolithic
        fleets (every replica competes).  Disaggregated fleets override
        this: fresh work goes to the prefill pool, work with committed
        tokens (a handoff fallback, a migrated decode) to the decode
        pool."""
        return None

    def _dispatch(self, request: Request,
                  snaps: Sequence[Dict[str, Any]],
                  deadline_s: Optional[float],
                  role: Optional[str] = None) -> str:
        """Walk the router's ranking until a replica's bounded queue
        accepts, under the caller's total deadline (the ``retry_call``
        budget): a saturated-or-dying fleet must give up within the
        request's patience, not after an unbounded crawl."""
        ranked = self.router.rank(snaps, prompt=request.prompt,
                                  role=role)
        if not ranked:  # admission already gates on capacity; belt+braces
            raise QueueFullError("no healthy replica", 0)
        tracer = get_tracer()
        if tracer is not None:
            # the router's decision, attributable per request: the
            # ranking it produced (truncated — the winner is what
            # matters) before the dispatch walk consumed it
            tracer.instant(
                "route", tracer.lane("fleet", "router"),
                {"request": request.request_id,
                 "ranked": ranked[:4]},
            )
        candidates = list(ranked)

        def attempt() -> str:
            name = candidates.pop(0)
            self._by_name[name].engine.submit(request)
            return name

        name = retry_call(
            attempt,
            attempts=len(candidates),
            retry_on=(QueueFullError,),
            base_delay_s=0.0, jitter=0.0, seed=0,
            deadline_s=deadline_s,
        )
        self.router.record_dispatch(name, request.prompt)
        return name

    # --- drain / migrate (called by the supervisor) -------------------------
    def drain_replica(self, replica: EngineReplica,
                      dead: bool) -> List[Request]:
        """Everything in flight on ``replica``, token streams intact.

        Sick (alive) replicas drain gracefully through the engine's
        ``preempt`` contract; a still-running request the engine could
        not preempt (resume prefix outgrew every bucket) stays on the
        engine, and the supervisor parks the replica DRAINING until it
        finishes — alive is alive.  A dead replica's engine state is
        treated as unreachable; its requests come from the fleet ledger
        — reset to queued with their committed tokens in place, the
        recomputation-resume invariant — and a non-resumable request
        there is FAILED by redispatch, visibly.
        """
        if not dead:
            return replica.engine.drain()
        tracer = get_tracer()
        migrated: List[Request] = []
        for rid, name in list(self._assignment.items()):
            if name != replica.name:
                continue
            # un-assign NOW: a collected request that later parks in
            # limbo must not keep pointing at this replica, or a second
            # death of the (re-formed) replica would collect it again
            # and double-queue the same token stream
            self._assignment.pop(rid)
            r = self._pending.get(rid)
            if r is None or r.status == FINISHED or r.done:
                continue
            r.slot = None
            # an involuntary eviction IS a preemption — honest per-
            # request accounting, and the marker that shields a not-yet-
            # started migrant from ever being a shed victim downstream
            r.preemptions += 1
            migrated.append(r)
            if tracer is not None:
                # the dead engine can't close its own segments (its
                # state is unreachable by contract), so the fleet —
                # holding the ledger AND the request's open trace mark
                # — ends whatever was in flight and stamps the
                # migration: no orphaned spans, and the waterfall shows
                # exactly where replica A's story stops
                self._trace_interrupt(r, tracer, replica.name)
        return migrated

    def _trace_interrupt(self, request: Request, tracer,
                         replica_name: str) -> None:
        """Close a collected request's open segment at its (dead)
        replica's name and stamp the ``migrate`` marker."""
        lane = tracer.request_lane(request.request_id, lease=False)
        mark_decode = request.trace_marks.pop("decode", None)
        mark_prefill = request.trace_marks.pop("prefill", None)
        mark_queued = request.trace_marks.pop("queued", None)
        if lane is not None:
            base = {"request": request.request_id,
                    "replica": replica_name, "interrupted": True}
            if mark_decode is not None:
                tracer.complete(
                    "decode", lane, mark_decode,
                    dict(base, tokens=len(request.tokens)),
                )
            elif mark_prefill is not None:
                # a chunked prefill cut short by its replica's death
                tracer.complete("prefill", lane, mark_prefill, base)
            elif mark_queued is not None:
                tracer.complete("queue_wait", lane, mark_queued, base)
            tracer.instant(
                "migrate", lane,
                {"request": request.request_id, "from": replica_name},
            )

    def redispatch(self, requests: Sequence[Request]) -> Tuple[int, int]:
        """Place migrated requests on survivors; (placed, parked).

        Placement is FORCED (already-admitted requests are never
        re-judged by a survivor's bound); a request parks in limbo only
        while NO healthy replica exists, retrying every step, and one
        no replica can EVER hold (bucket infeasibility everywhere)
        fails visibly."""
        placed = parked = 0
        for r in requests:
            if r.status == FINISHED or r.done:
                continue
            outcome = self._redispatch_one(r)
            if outcome == "placed":
                placed += 1
            elif outcome == "parked":
                parked += 1
        self.stats.limbo_depth = len(self._limbo)
        return placed, parked

    def _redispatch_one(self, request: Request) -> str:
        snaps = self.replica_snapshots()
        ranked = self.router.rank(snaps, prompt=request.prompt,
                                  role=self._dispatch_role(request))
        infeasible = 0
        for name in ranked:
            rep = self._by_name[name]
            try:
                # force: this request was already admitted — the fleet's
                # promise survives the replica it was first placed on,
                # so the survivor's bound/shed policy does not re-judge
                # it (transient overshoot is bounded by the dead
                # replica's former load)
                rep.engine.submit(request, force=True)
            except ValueError:
                infeasible += 1
                continue
            self._assignment[request.request_id] = name
            self.stats.migrations += 1
            self.router.record_dispatch(name, request.prompt)
            return "placed"
        if ranked and infeasible == len(ranked):
            self._fail(request,
                       "no replica's bucket set fits the resume prefix")
            return "failed"
        # parked requests are owned by the fleet, not any replica: a
        # stale assignment here would let a dead-drain collect the same
        # request a second time
        self._assignment.pop(request.request_id, None)
        self._limbo.append(request)
        tracer = get_tracer()
        if tracer is not None:
            lane = tracer.request_lane(request.request_id,
                                       lease=False)
            if lane is not None:
                tracer.instant(
                    "limbo", lane,
                    {"request": request.request_id},
                )
        return "parked"

    def _fail(self, request: Request, why: str) -> None:
        request.status = FAILED
        request.fail_reason = why
        self._pending.pop(request.request_id, None)
        self._assignment.pop(request.request_id, None)
        self.stats.failed += 1
        self._logger.warning(
            f"ServingFleet: request {request.request_id} failed: {why}"
        )
        tracer = get_tracer()
        if tracer is not None:
            tracer.instant(
                "request_failed", tracer.lane("fleet", "supervisor"),
                {"request": request.request_id, "why": why},
            )
            lane = tracer.request_lane(request.request_id,
                                       lease=False)
            if lane is not None:
                tracer.instant(
                    "failed", lane,
                    {"request": request.request_id, "why": why},
                )
            tracer.release_request_lane(request.request_id)

    # --- the fleet loop -----------------------------------------------------
    def has_work(self) -> bool:
        return bool(self._pending) or bool(self._limbo)

    def step(self) -> None:
        """One fleet iteration: inject scheduled faults, retry limbo,
        tick every healthy replica, then let the supervisor look."""
        if self.fault_injector is not None:
            self.fault_injector.on_tick(self)
        if self._limbo:
            limbo, self._limbo = self._limbo, []
            self.redispatch(limbo)
        for replica in self.replicas:
            # DRAINING replicas still tick: they are finishing requests
            # that cannot migrate — out of rotation, not out of work
            if replica.state not in (HEALTHY, DRAINING):
                continue
            stats0 = replica.engine.stats
            compiles0 = stats0.compiles
            waves0 = stats0.prefill_waves
            decoded0 = stats0.decode_tokens
            t0 = time.perf_counter()
            try:
                replica.tick(self.tick)
            except ReplicaCrashed:
                replica.missed_beats += 1
                self.stats.missed_beats += 1
                continue
            # honest compute timing: tick() blocks on the engine's own
            # device sync before returning
            tick_s = time.perf_counter() - t0
            stats = replica.engine.stats
            if (replica.state == HEALTHY
                    and stats.compiles == compiles0
                    and stats.prefill_waves == waves0
                    and stats.decode_tokens > decoded0):
                # the health probe is the PURE-DECODE tick: decode is
                # fixed-shape ([slots, 1] against the slab), so its wall
                # time is workload-independent and comparable across the
                # replica's whole life.  Ticks that compiled (bucket
                # warmup — e.g. right after a migration re-buckets),
                # ran a prefill wave (cost scales with the wave, not the
                # host's health), or did nothing would all poison the
                # EWMA baseline and let the fleet's own admission
                # rhythm read as a straggler.
                self.supervisor.observe_tick(replica, tick_s)
        self.supervisor.poll(self)
        self._sweep_terminal()
        self.stats.ticks += 1
        self.stats.replicas_healthy = len(self.healthy_replicas)
        self.stats.replicas_total = len(self.replicas)
        self.stats.replicas_quarantined = sum(
            1 for r in self.replicas if r.state == RETIRED
        )
        self.stats.pending = len(self._pending)
        self.stats.queue_depth = self._pending_depth()
        self.stats.limbo_depth = len(self._limbo)
        # observability tail: sample the tick's final state, then judge
        # it — the SLO monitor must see the sample it alerts on, and
        # the autoscaler polls LAST so its sustained-burn/slack
        # evidence includes this very tick's verdict
        if self.timeseries is not None:
            self.timeseries.sample()
        if self.slo is not None:
            self.slo.evaluate(get_tracer())
        if self.autoscaler is not None:
            self.autoscaler.poll(self)
        if self.flight is not None:
            # the black box drains every subsystem's event surface
            # AFTER the autoscaler, so this tick's whole story — fault,
            # heal, scale, SLO verdict — is in the ring before the
            # detector rules judge it
            self._flight_tap()
            self._incident_tick()
        self.tick += 1

    # --- flight recorder taps (the sanctioned black-box feeds) --------------
    #: supervisor event kind -> flight vocabulary
    _SUPERVISOR_KINDS = {
        "detect": "replica_detect",
        "drain": "replica_drain",
        "migrate": "replica_migrate",
        "removed": "replica_removed",
        "retired": "replica_retired",
        "reform_failed": "reform_failed",
        "reformed": "replica_reformed",
    }
    _AUTOSCALER_KINDS = ("scale_up", "scale_down", "scale_rejected")
    _LEDGER_KINDS = {
        "enqueue": "handoff_enqueued",
        "deliver": "handoff_delivered",
        "fail": "handoff_failed",
    }
    #: wall-microsecond width of the trace slice a bundle embeds
    _bundle_trace_window_us = 2_000_000.0

    def _drain_list(self, cursor_key: str, source: list) -> list:
        """Cursor-drain a component's append-only event list: the tap
        reads each entry exactly once, and components never know the
        recorder exists."""
        start = self._flight_cursors.get(cursor_key, 0)
        fresh = source[start:]
        self._flight_cursors[cursor_key] = len(source)
        return fresh

    def _flight_tap(self) -> None:
        """Drain every subsystem's event surface into the flight
        recorder (once per tick, end of ``step()``).  Components keep
        their own append-only logs; this tap is the single sanctioned
        feed, so the recorder stays pure stdlib and no subsystem grows
        a recorder dependency."""
        rec = self.flight
        tick = self.tick
        inj = self.fault_injector
        if inj is not None:
            applied = getattr(inj, "applied", None)
            if applied is not None:
                for e in self._drain_list("chaos.applied", applied):
                    rec.record(
                        int(e.get("tick", tick)), "chaos",
                        "fault_applied" if e.get("ok")
                        else "fault_skipped",
                        subject=str(e.get("target") or ""), detail=e,
                    )
            recoveries = getattr(inj, "recoveries", None)
            if recoveries is not None:
                for e in self._drain_list("chaos.recoveries",
                                          recoveries):
                    rec.record(int(e.get("settled_tick", tick)),
                               "chaos", "recovery_settled", detail=e)
        for e in self._drain_list("supervisor", self.supervisor.events):
            kind = self._SUPERVISOR_KINDS.get(e.get("kind"))
            if kind is None:
                continue
            rec.record(int(e.get("tick", tick)), "supervisor", kind,
                       subject=str(e.get("replica") or ""), detail=e)
        if self.autoscaler is not None:
            events = getattr(self.autoscaler, "events", None)
            if events is not None:
                for e in self._drain_list("autoscaler", events):
                    kind = e.get("kind")
                    if kind not in self._AUTOSCALER_KINDS:
                        continue
                    rec.record(
                        int(e.get("tick", tick)), "autoscaler", kind,
                        subject=str(e.get("replica")
                                    or e.get("pool") or ""),
                        detail=e,
                    )
        # serving lane: per-replica recompile / swap-corruption COUNTER
        # DELTAS — the engine is never modified to push; the fleet (the
        # only layer allowed to import both) reads the stats it already
        # walks each tick
        for replica in self.replicas:
            engine = getattr(replica, "engine", None)
            stats = getattr(engine, "stats", None)
            if stats is None:
                continue
            compiles = int(getattr(stats, "compiles", 0))
            corrupt = int(getattr(stats, "swap_corruptions", 0))
            mark = self._flight_engine_marks.get(replica.name)
            if mark is None or compiles < mark[0] or corrupt < mark[1]:
                # first sight, or a re-formed engine reset its stats:
                # re-baseline silently (re-form warmup compiles are the
                # supervisor's story, not steady-state anomalies)
                self._flight_engine_marks[replica.name] = (compiles,
                                                           corrupt)
                continue
            if compiles > mark[0]:
                rec.record(tick, "serving", "recompile",
                           subject=replica.name,
                           detail={"count": compiles - mark[0],
                                   "total": compiles})
            if corrupt > mark[1]:
                rec.record(tick, "serving", "swap_corrupt",
                           subject=replica.name,
                           detail={"count": corrupt - mark[1],
                                   "total": corrupt})
            self._flight_engine_marks[replica.name] = (compiles,
                                                       corrupt)
        # slo lane: firing-set EDGES (alert raised / cleared), not the
        # level — the recorder logs transitions, the timeseries holds
        # the level
        if self.slo is not None:
            firing = tuple(self.slo.firing)
            prev, now = set(self._slo_firing_prev), set(firing)
            for target in sorted(now - prev):
                rec.record(tick, "slo", "slo_alert", subject=target)
            for target in sorted(prev - now):
                rec.record(tick, "slo", "slo_clear", subject=target)
            self._slo_firing_prev = firing
        self._flight_drain_ledger(tick)

    def _flight_drain_ledger(self, tick: int) -> None:
        """Drain the disagg handoff ledger's event list (no-op on
        monolithic fleets).  Split out of :meth:`_flight_tap` because
        ``DisaggFleet`` pumps handoffs AFTER the base step — its pump
        calls this again so same-tick ledger transitions land in the
        ring under the tick they happened on."""
        if self.flight is None:
            return
        events = getattr(getattr(self, "ledger", None), "events", None)
        if events is None:
            return
        for e in self._drain_list("disagg", events):
            kind = self._LEDGER_KINDS.get(e.get("kind"))
            if kind is None:
                continue
            # which decode replica a handoff lands on is routing
            # resolution (least-loaded / latency-scored — wall-state
            # dependent by design), so it rides under the det-excluded
            # "resolved" key: live views keep it, deterministic logs
            # and bundle digests never see it
            detail = dict(e)
            resolved = {key: detail.pop(key)
                        for key in ("source", "target")
                        if key in detail}
            if resolved:
                detail["resolved"] = resolved
            self.flight.record(
                int(e.get("tick", tick)), "disagg", kind,
                subject="", detail=detail,
            )

    def _incident_tick(self) -> None:
        """Run the detector rules over this tick's recorded events;
        every newly opened incident snapshots its postmortem bundle
        HERE — at detection time, while the evidence is still in the
        ring — not when someone asks for it later."""
        engine = self.incidents
        if engine is None:
            return
        opened, closed = engine.evaluate(self.tick)
        tracer = get_tracer()
        for inc in closed:
            self.flight.record(
                self.tick, "fleet", "incident_closed",
                subject=inc.rule,
                detail={"incident_id": inc.incident_id,
                        "opened_tick": inc.opened_tick},
            )
            if tracer is not None:
                tracer.instant(
                    "incident_closed",
                    tracer.lane("fleet", "incidents"),
                    {"rule": inc.rule, "incident": inc.incident_id},
                )
        for inc in opened:
            bundle = self._snapshot_incident_bundle(inc)
            self._bundles.append(bundle)
            self.flight.record(
                self.tick, "fleet", "incident_opened",
                subject=inc.rule,
                detail={"incident_id": inc.incident_id,
                        "severity": inc.severity,
                        "bundle_digest": inc.bundle_digest},
            )
            if tracer is not None:
                tracer.instant(
                    "incident_opened",
                    tracer.lane("fleet", "incidents"),
                    {"rule": inc.rule, "incident": inc.incident_id,
                     "severity": inc.severity},
                )
            self._logger.warning(
                f"ServingFleet: incident {inc.incident_id} opened "
                f"({inc.severity}): {inc.reason}"
            )
        # this is the engine's only evaluator, so the opened delta is
        # exact and the stats counter stays monotone (AUD006)
        self.stats.incidents_opened += len(opened)
        self.stats.incidents_open = engine.open_count

    def _topology_snapshot(self) -> Dict[str, Any]:
        """Deterministic fleet shape: per-replica lifecycle + per-pool
        (role) rollup — the 'what did the fleet look like' a bundle
        stamps, and part of the bundle's digest-covered identity."""
        replicas: Dict[str, Any] = {}
        pools: Dict[str, Dict[str, int]] = {}
        for r in self.replicas:
            replicas[r.name] = dict(
                state=r.state, role=r.role,
                generation=int(getattr(r, "generation", 0)),
                pending_removal=bool(getattr(r, "pending_removal",
                                             False)),
            )
            pool = pools.setdefault(r.role or "default",
                                    {"replicas": 0, "healthy": 0})
            pool["replicas"] += 1
            if r.state == HEALTHY and not r.crashed:
                pool["healthy"] += 1
        return dict(tick=self.tick, replicas=replicas, pools=pools)

    def _snapshot_incident_bundle(self,
                                  incident) -> Dict[str, Any]:
        tracer = get_tracer()
        trace_slice: List[Dict[str, Any]] = []
        if tracer is not None:
            since = max(0.0,
                        tracer.now() - self._bundle_trace_window_us)
            trace_slice = tracer.to_chrome(
                since_us=since)["traceEvents"]
        summary: Dict[str, Any] = {}
        if self.timeseries is not None:
            summary = self.timeseries.summary(points=16)
        audit = getattr(getattr(self, "ledger", None), "audit", None)
        return build_bundle(
            incident, self.flight,
            flight_events=self._bundle_events,
            metrics_summary=summary,
            trace_slice=trace_slice,
            healthz=self._health_snapshot(),
            topology=self._topology_snapshot(),
            ledger_audit=audit() if callable(audit) else {},
        )

    def _sweep_terminal(self) -> None:
        """Move finished requests to the fleet ledger's done side, and
        account engine-level sheds (a replica's bounded queue displaced
        a fleet-dispatched request) as fleet rejections."""
        for rid, r in list(self._pending.items()):
            if r.status == FINISHED:
                self._finished[rid] = self._pending.pop(rid)
                self._assignment.pop(rid, None)
                if self._collector is not None:
                    self._collector[rid] = r
                ttft, tpot = r.ttft_s(), r.tpot_s()
                if ttft is not None:
                    self._ttft_window.append(ttft)
                if tpot is not None:
                    self._tpot_window.append(tpot)
                while len(self._finished) > self._finished_limit:
                    oldest = next(iter(self._finished))
                    del self._finished[oldest]
            elif r.status == REJECTED:
                self._pending.pop(rid)
                self._assignment.pop(rid, None)
                self.stats.count_rejection("engine_shed")
            elif r.status == FAILED:
                self._pending.pop(rid, None)
                self._assignment.pop(rid, None)
        # nobody left to serve and nobody coming back: fail limbo
        # loudly instead of spinning forever
        if self._limbo and all(r.state == RETIRED
                               for r in self.replicas):
            for r in self._limbo:
                self._fail(r, "every replica is retired")
            self._limbo = []

    def run(
        self,
        requests: Optional[Sequence[Request]] = None,
        *,
        priority: str = BATCH,
        max_ticks: int = 100_000,
    ) -> Dict[int, np.ndarray]:
        """Submit ``requests`` and drive ``step`` until the fleet
        drains; returns ``{request_id: prompt + generated tokens}`` for
        everything that finished during the call (rejected/failed
        requests are visible on their ``status`` and in ``stats``).
        Outputs are collected incrementally at finish time, so the
        bounded finished-history eviction can never lose one mid-call."""
        collector: Dict[int, Request] = {}
        self._collector = collector
        try:
            for r in requests or ():
                self.submit(r, priority=priority)
            for _ in range(max_ticks):
                if not self.has_work():
                    break
                self.step()
            else:  # pragma: no cover - scheduler liveness guard
                raise RuntimeError(
                    f"serving fleet did not drain in {max_ticks} ticks "
                    f"(pending={len(self._pending)}, "
                    f"limbo={len(self._limbo)})"
                )
        finally:
            self._collector = None
        return {rid: r.output() for rid, r in collector.items()}

    @property
    def finished_requests(self) -> List[Request]:
        """The most recent finished requests (bounded recency history —
        ``finished_history`` — not lifetime traffic)."""
        return list(self._finished.values())


__all__ = ["FleetStats", "ServingFleet"]
