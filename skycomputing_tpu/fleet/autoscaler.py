"""FleetAutoscaler: close the SLO loop at replica granularity.

Before this module the control loop had a hole: the SLO monitor emits
multi-window burn rates, admission tightens under them, the supervisor
heals what breaks — but the replica count was fixed at construction.
Sustained burn could only shed traffic; sustained slack never released
a chip.  The autoscaler converts both signals into VERIFIED replica
mutations:

- **sustained burn -> ADD**: when the monitor's ``firing_streak`` (both
  burn windows >= 1.0, for N consecutive ticks — one blip is not a
  trend) clears ``up_streak``, the autoscaler proposes one replica.
  The proposal passes a ``plan_check.verify_scale_payload`` pre-flight
  (chip budget, ``max_replicas``) BEFORE any mutation; a feasible add
  then builds through the supervisor's budgeted verify-then-apply
  re-form machinery (``ServingFleet.add_replica`` parks a provisional
  replica and ``_attempt_reform`` runs the same verified builder a
  post-crash re-form runs).  A rejected add leaves the fleet exactly
  as it was, counted in ``scale_rejected``.
- **sustained slack -> drain-then-REMOVE**: when no target fires and
  fleet utilization stays under ``slack_utilization`` for
  ``down_streak`` consecutive ticks, the least-loaded healthy replica
  drains gracefully (the same preempt contract a sick-replica heal
  uses, token streams intact) and leaves the fleet; requests that
  cannot migrate finish on the replica first (DRAINING +
  ``pending_removal`` — the supervisor finalizes, never re-forms).

**Hysteresis + cooldown**: ``up_streak`` < ``down_streak`` by default
(adding capacity under burn is urgent, releasing it is not), and every
decision — including a rejection — starts a ``cooldown_ticks`` window
in which no further decision fires, so one noisy window can never flap
the fleet.  Every decision lands in :attr:`events`, in trace instants
on the ``("fleet", "autoscaler")`` lane, and in the counter-disciplined
``FleetStats`` fields (``scale_ups`` / ``scale_downs`` /
``scale_rejected``).

The autoscaler never touches an engine: it reads fleet-level evidence
and calls the two fleet verbs.  ``plan_check`` is imported lazily at
decision time (the repo-wide idiom for analysis-layer verifiers).

**Per-pool mode** (disaggregated fleets): construct with ``pools``
mapping each replica role to its own ``min_replicas`` /
``max_replicas`` bounds and the SLO ``signals`` that attribute burn to
it.  Sustained burn then scales the pool whose signals match the firing
targets — TTFT burn grows the prefill pool, TPOT/queue-depth burn grows
the decode pool — and sustained slack drains the pool furthest above
its floor.  Every decision payload carries the ``pool`` it targets, so
``verify_scale_payload`` pre-flights the per-pool bounds and the chip
budget before any mutation, exactly as in the monolithic mode.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from ..telemetry import get_tracer
from ..utils import Logger
from .replica import HEALTHY, RETIRED

# decision kinds (stable ids in events and trace args)
SCALE_UP = "scale_up"
SCALE_DOWN = "scale_down"
SCALE_REJECTED = "scale_rejected"

#: default burn-attribution signals per well-known pool role: a firing
#: SLO target whose name or metric contains one of these substrings
#: charges its burn to that pool.  TTFT is prefill work by definition;
#: TPOT and queue depth are decode-side pressure (slots and pace).
POOL_SIGNALS = {
    "prefill": ("ttft",),
    "decode": ("tpot", "queue"),
}


class FleetAutoscaler:
    """Burn/slack -> verified replica add/remove, with hysteresis."""

    def __init__(
        self,
        *,
        min_replicas: int = 1,
        max_replicas: Optional[int] = None,
        chip_budget: Optional[int] = None,
        replica_chips: int = 1,
        up_streak: int = 3,
        down_streak: int = 24,
        cooldown_ticks: int = 32,
        slack_utilization: float = 0.3,
        pools: Optional[Dict[str, Dict[str, Any]]] = None,
        logger: Optional[Logger] = None,
    ):
        if min_replicas < 1:
            raise ValueError(
                f"min_replicas must be >= 1, got {min_replicas}"
            )
        if max_replicas is not None and max_replicas < min_replicas:
            raise ValueError(
                f"max_replicas ({max_replicas}) must be >= "
                f"min_replicas ({min_replicas})"
            )
        if up_streak < 1 or down_streak < 1:
            raise ValueError(
                "up_streak and down_streak must be >= 1"
            )
        if cooldown_ticks < 0:
            raise ValueError(
                f"cooldown_ticks must be >= 0, got {cooldown_ticks}"
            )
        if replica_chips < 1:
            raise ValueError(
                f"replica_chips must be >= 1, got {replica_chips}"
            )
        if not 0.0 <= float(slack_utilization) < 1.0:
            raise ValueError(
                f"slack_utilization must be in [0, 1), got "
                f"{slack_utilization}"
            )
        self.min_replicas = int(min_replicas)
        self.max_replicas = (None if max_replicas is None
                             else int(max_replicas))
        #: chips the fleet may hold in total; None = the fleet's own
        #: device pool (``ServingFleet.chip_capacity``)
        self.chip_budget = (None if chip_budget is None
                            else int(chip_budget))
        self.replica_chips = int(replica_chips)
        self.up_streak = int(up_streak)
        self.down_streak = int(down_streak)
        self.cooldown_ticks = int(cooldown_ticks)
        self.slack_utilization = float(slack_utilization)
        #: per-role pool config (disaggregated fleets): role ->
        #: dict(min_replicas, max_replicas, signals).  Empty dict =
        #: monolithic mode, every decision fleet-wide.
        self.pools: Dict[str, Dict[str, Any]] = {}
        for pool, cfg in (pools or {}).items():
            if not isinstance(pool, str) or not pool:
                raise ValueError(
                    f"pool role must be a non-empty string, got {pool!r}"
                )
            if not isinstance(cfg, dict):
                raise ValueError(
                    f"pool {pool!r} config must be a dict, got "
                    f"{type(cfg).__name__}"
                )
            lo = int(cfg.get("min_replicas", 1))
            hi = cfg.get("max_replicas")
            hi = None if hi is None else int(hi)
            if lo < 1:
                raise ValueError(
                    f"pool {pool!r} min_replicas must be >= 1, got {lo}"
                )
            if hi is not None and hi < lo:
                raise ValueError(
                    f"pool {pool!r} max_replicas ({hi}) must be >= "
                    f"min_replicas ({lo})"
                )
            signals = tuple(
                str(s).lower()
                for s in (cfg.get("signals")
                          or POOL_SIGNALS.get(pool)
                          or (pool,))
            )
            self.pools[pool] = dict(min_replicas=lo, max_replicas=hi,
                                    signals=signals)
        self._logger = logger or Logger()
        self._slack_streak = 0
        self._cooldown_until = 0
        self._arc_id = 0
        #: every decision, in order: kind, tick, detail — the
        #: supervisor-events idiom for the scale plane
        self.events: List[Dict[str, Any]] = []

    # --- evidence -----------------------------------------------------------
    @staticmethod
    def _live_replicas(fleet) -> List[Any]:
        """Replicas that hold (or will hold) chips: everything not
        retired and not already on its way out."""
        return [r for r in fleet.replicas
                if r.state != RETIRED and not r.pending_removal]

    @staticmethod
    def utilization(fleet) -> float:
        """Busy work (running + queued + limbo) over live slot
        capacity; >= 1.0 means the fleet cannot even hold its backlog
        concurrently."""
        capacity = fleet._capacity_slots()
        if capacity <= 0:
            return 1.0
        busy = len(fleet._limbo)
        for r in fleet.healthy_replicas:
            busy += len(r.engine.running_requests)
            busy += r.engine.stats.queue_depth
        return busy / capacity

    def burn_streak(self, fleet) -> int:
        """Consecutive fleet ticks with >= 1 SLO target firing on BOTH
        burn windows (the monitor's ``firing_streak`` surface); 0 with
        no monitor attached — an autoscaler cannot read burn that is
        not being measured."""
        return int(getattr(fleet.slo, "firing_streak", 0) or 0)

    def _pool_live(self, fleet, pool: str) -> List[Any]:
        """Live replicas carrying ``pool``'s role."""
        return [r for r in self._live_replicas(fleet)
                if getattr(r, "role", "") == pool]

    def _burn_pool(self, fleet) -> Optional[str]:
        """The pool the current SLO burn charges to (per-pool mode).

        Matches every firing target's name AND metric against each
        pool's signal substrings, in pool declaration order.  Burn no
        signal claims falls to the LAST declared pool — unattributed
        pressure still grows capacity somewhere, and decode (declared
        last by :class:`~..disagg.pools.DisaggFleet`) is the
        general-purpose sink."""
        if not self.pools:
            return None
        firing = tuple(getattr(fleet.slo, "firing", ()) or ())
        metrics = {
            str(t.name): str(getattr(t, "metric", ""))
            for t in (getattr(fleet.slo, "targets", ()) or ())
        }
        for pool, cfg in self.pools.items():
            for name in firing:
                hay = f"{name} {metrics.get(str(name), '')}".lower()
                if any(sig in hay for sig in cfg["signals"]):
                    return pool
        return next(reversed(self.pools))

    def _slack_pool(self, fleet) -> Optional[str]:
        """The pool with the most removable slack: live count above its
        own floor, >= 2 healthy members (never drain a pool to an
        unserved role mid-heal).  None when no pool can shrink."""
        best, best_slack = None, 0
        for pool, cfg in self.pools.items():
            live = self._pool_live(fleet, pool)
            healthy = [r for r in live if r.state == HEALTHY]
            slack = len(live) - cfg["min_replicas"]
            if slack > best_slack and len(healthy) >= 2:
                best, best_slack = pool, slack
        return best

    def _payload(self, fleet, action: str, live: int,
                 pool: Optional[str] = None) -> Dict[str, Any]:
        budget = (self.chip_budget if self.chip_budget is not None
                  else fleet.chip_capacity())
        cfg = self.pools.get(pool) if pool is not None else None
        payload = dict(
            action=action,
            replicas=live,
            delta=1,
            min_replicas=(cfg["min_replicas"] if cfg
                          else self.min_replicas),
            max_replicas=(cfg["max_replicas"] if cfg
                          else self.max_replicas),
            chips_required=self.replica_chips,
            chips_free=max(budget - fleet.chips_in_use(), 0),
        )
        if pool is not None:
            payload["pool"] = pool
        return payload

    # --- the decision loop --------------------------------------------------
    def _record(self, kind: str, tick: int, **extra) -> None:
        self.events.append(dict(kind=kind, tick=tick, **extra))

    def _reject(self, fleet, payload: Dict[str, Any],
                problems: List[str], tracer) -> None:
        fleet.stats.scale_rejected += 1
        self._record(SCALE_REJECTED, fleet.tick, payload=payload,
                     problems=problems)
        self._cooldown_until = fleet.tick + self.cooldown_ticks
        self._logger.warning(
            f"FleetAutoscaler: {payload['action']} rejected at tick "
            f"{fleet.tick}: {'; '.join(problems)}"
        )
        if tracer is not None:
            tracer.instant(
                SCALE_REJECTED, tracer.lane("fleet", "autoscaler"),
                {"action": payload["action"], "problems": problems},
            )

    def poll(self, fleet) -> Optional[str]:
        """One decision pass; called by ``ServingFleet.step`` after the
        SLO monitor evaluated this tick.  Returns the decision kind it
        acted on (or None)."""
        live = self._live_replicas(fleet)
        burn = self.burn_streak(fleet)
        firing = bool(getattr(fleet.slo, "firing", ()) or ())
        if not firing and self.utilization(fleet) \
                <= self.slack_utilization:
            self._slack_streak += 1
        else:
            self._slack_streak = 0
        if fleet.tick < self._cooldown_until:
            return None
        if any(r.pending_removal for r in fleet.replicas):
            # a drain is still in flight; one mutation at a time
            return None
        if burn >= self.up_streak:
            pool = self._burn_pool(fleet)
            count = (len(self._pool_live(fleet, pool))
                     if pool is not None else len(live))
            return self._try_scale_up(fleet, count, pool=pool)
        if self._slack_streak >= self.down_streak:
            if self.pools:
                pool = self._slack_pool(fleet)
                if pool is None:
                    return None
                return self._try_scale_down(
                    fleet, self._pool_live(fleet, pool), pool=pool)
            healthy = [r for r in live if r.state == HEALTHY]
            if (len(live) > self.min_replicas
                    # a sick/dead replica mid-heal is not removable
                    # slack: with < 2 healthy replicas the victim
                    # would be the last one serving
                    and len(healthy) >= 2):
                return self._try_scale_down(fleet, live)
        return None

    # --- execution ----------------------------------------------------------
    def _role_spec(self, fleet, pool: Optional[str]
                   ) -> Optional[Dict[str, Any]]:
        """The replica spec a per-pool add builds with: the fleet's
        own ``role_spec`` (pool kwargs + device placement) when it has
        one, a bare role tag otherwise.  None in monolithic mode —
        ``add_replica`` then picks its own default spec."""
        if pool is None:
            return None
        role_spec = getattr(fleet, "role_spec", None)
        if callable(role_spec):
            return role_spec(pool)
        return dict(role=pool)

    def _try_scale_up(self, fleet, live: int,
                      pool: Optional[str] = None) -> Optional[str]:
        from ..analysis.plan_check import verify_scale_payload

        tracer = get_tracer()
        payload = self._payload(fleet, "add", live, pool=pool)
        problems = verify_scale_payload(payload)
        if problems:
            self._reject(fleet, payload, problems, tracer)
            return SCALE_REJECTED
        spec = self._role_spec(fleet, pool)
        self._arc_id += 1
        lane = None
        if tracer is not None:
            lane = tracer.lane("fleet", "autoscaler")
            tracer.async_begin(
                "fleet_scale", lane, self._arc_id,
                {"action": "add", "tick": fleet.tick,
                 "replicas": live, "pool": pool or "",
                 "burn_streak": self.burn_streak(fleet)},
            )
        try:
            if tracer is not None:
                with tracer.span("fleet.scale_up", lane,
                                 {"replicas": live}):
                    replica = fleet.add_replica(spec)
            else:
                replica = fleet.add_replica(spec)
        except Exception as exc:
            # the verified build said no (slab allocation, serving
            # pre-flight): structural rollback already happened inside
            # add_replica — count it and back off
            self._reject(fleet, payload, [str(exc)], tracer)
            if tracer is not None:
                tracer.async_end("fleet_scale", lane, self._arc_id,
                                 {"outcome": SCALE_REJECTED,
                                  "error": str(exc)})
            return SCALE_REJECTED
        fleet.stats.scale_ups += 1
        self._record(SCALE_UP, fleet.tick, replica=replica.name,
                     replicas=live + 1, pool=pool or "")
        self._cooldown_until = fleet.tick + self.cooldown_ticks
        self._slack_streak = 0
        self._logger.info(
            f"FleetAutoscaler: scaled up to {live + 1} replicas "
            f"(+{replica.name}) at tick {fleet.tick}"
        )
        if tracer is not None:
            tracer.async_end("fleet_scale", lane, self._arc_id,
                             {"outcome": SCALE_UP,
                              "replica": replica.name})
        return SCALE_UP

    def _pick_victim(self, live: List[Any],
                     pool: Optional[str] = None) -> Optional[Any]:
        """Least-loaded HEALTHY replica (cheapest drain); newest wins
        ties so long-lived replicas keep their warmed caches.  With a
        pool, only that role's members are candidates."""
        healthy = [r for r in live if r.state == HEALTHY
                   and (pool is None
                        or getattr(r, "role", "") == pool)]
        if not healthy:
            return None
        return min(
            reversed(healthy),
            key=lambda r: (len(r.engine.running_requests)
                           + r.engine.stats.queue_depth),
        )

    def _try_scale_down(self, fleet, live: List[Any],
                        pool: Optional[str] = None) -> Optional[str]:
        from ..analysis.plan_check import verify_scale_payload

        tracer = get_tracer()
        payload = self._payload(fleet, "remove", len(live), pool=pool)
        problems = verify_scale_payload(payload)
        if problems:
            self._reject(fleet, payload, problems, tracer)
            return SCALE_REJECTED
        victim = self._pick_victim(live, pool=pool)
        if victim is None:
            return None
        self._arc_id += 1
        lane = None
        if tracer is not None:
            lane = tracer.lane("fleet", "autoscaler")
            tracer.async_begin(
                "fleet_scale", lane, self._arc_id,
                {"action": "remove", "tick": fleet.tick,
                 "replica": victim.name,
                 "slack_streak": self._slack_streak},
            )
        try:
            if tracer is not None:
                with tracer.span("fleet.scale_down", lane,
                                 {"replica": victim.name}):
                    outcome = fleet.remove_replica(victim.name)
            else:
                outcome = fleet.remove_replica(victim.name)
        except ValueError as exc:
            # the fleet's own guard said no (e.g. the victim became the
            # last healthy replica between the pick and the drain): a
            # rejected decision, never a crashed serving loop
            self._reject(fleet, payload, [str(exc)], tracer)
            if tracer is not None:
                tracer.async_end("fleet_scale", lane, self._arc_id,
                                 {"outcome": SCALE_REJECTED,
                                  "error": str(exc)})
            return SCALE_REJECTED
        fleet.stats.scale_downs += 1
        self._record(SCALE_DOWN, fleet.tick, replica=victim.name,
                     replicas=len(live) - 1, drain=outcome,
                     pool=pool or "")
        self._cooldown_until = fleet.tick + self.cooldown_ticks
        self._slack_streak = 0
        self._logger.info(
            f"FleetAutoscaler: scaling down to {len(live) - 1} "
            f"replicas (-{victim.name}, {outcome}) at tick {fleet.tick}"
        )
        if tracer is not None:
            tracer.async_end("fleet_scale", lane, self._arc_id,
                             {"outcome": SCALE_DOWN,
                              "replica": victim.name,
                              "drain": outcome})
        return SCALE_DOWN


__all__ = [
    "FleetAutoscaler",
    "POOL_SIGNALS",
    "SCALE_DOWN",
    "SCALE_REJECTED",
    "SCALE_UP",
]
