"""Fleet admission control: bounded intake with explicit load-shedding.

PURE STDLIB BY CONTRACT, like :mod:`.router` — decision logic over
scalar fleet state, loadable by file path for the CI smoke on a bare
runner.

The philosophy is vLLM's exhaustion-as-queueing extended one level up:
a single engine turns slot exhaustion into queueing; the fleet turns
queue exhaustion into *visible rejection*.  Under a spike the failure
mode to prevent is the unbounded queue — every accepted request makes
every other request slower, TPOT for *everyone* collapses, and the host
eventually OOMs on queued prompts.  Shedding keeps the accepted
population's SLOs intact and tells the rejected population exactly when
to come back (a ``Retry-After``-style hint), which is strictly more
information than timing out.

Three gates, in order:

1. **pending bound** — total queued work across the fleet above
   ``max_pending`` (default: ``queue_factor ×`` live slot capacity)
   rejects with ``queue_full``.
2. **priority shed band** — above ``shed_fraction × max_pending``,
   ``batch``-class requests shed (``shed_low_priority``) while
   ``interactive`` requests still admit; a spike degrades background
   work first.
3. **deadline feasibility** — a request whose caller gave it
   ``deadline_s`` is rejected up front (``deadline_unmeetable``) when
   the estimated queue wait already exceeds it: admitting work that
   cannot possibly meet its deadline only steals capacity from work
   that can.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional

# priority classes, lowest number = most important
INTERACTIVE = "interactive"
BATCH = "batch"
_PRIORITY_RANK = {INTERACTIVE: 0, BATCH: 1}

# rejection reasons (stable ids, counted per-reason in FleetStats)
QUEUE_FULL = "queue_full"
SHED_LOW_PRIORITY = "shed_low_priority"
DEADLINE_UNMEETABLE = "deadline_unmeetable"
NO_HEALTHY_REPLICA = "no_healthy_replica"
REPLICAS_SATURATED = "replicas_saturated"
ADMISSION_BLIP = "admission_blip"


@dataclass
class AdmitDecision:
    """The outcome of one admission decision.

    ``admitted`` False carries a ``reason`` and a ``retry_after_s``
    backpressure hint (the Retry-After header of this stack); True
    carries the ``replica`` name once the fleet has dispatched."""

    admitted: bool
    reason: Optional[str] = None
    retry_after_s: Optional[float] = None
    replica: Optional[str] = None
    detail: Dict[str, Any] = field(default_factory=dict)


class AdmissionController:
    """Bounded, priority- and deadline-aware fleet admission."""

    def __init__(
        self,
        max_pending: Optional[int] = None,
        queue_factor: float = 4.0,
        shed_fraction: float = 0.75,
        service_s_estimate: float = 0.05,
        slo_monitor=None,
        slo_tighten: float = 0.5,
    ):
        if max_pending is not None and max_pending < 1:
            raise ValueError(
                f"max_pending must be >= 1, got {max_pending}"
            )
        if not 0.0 < shed_fraction <= 1.0:
            raise ValueError(
                f"shed_fraction must be in (0, 1], got {shed_fraction}"
            )
        if not 0.0 < slo_tighten <= 1.0:
            raise ValueError(
                f"slo_tighten must be in (0, 1], got {slo_tighten}"
            )
        self.max_pending = max_pending
        self.queue_factor = float(queue_factor)
        self.shed_fraction = float(shed_fraction)
        self.service_s_estimate = float(service_s_estimate)
        # the slot capacity an explicit max_pending was SIZED FOR.  The
        # fleet stamps its construction-time capacity here, and
        # pending_bound() re-scales the explicit bound by live/baseline
        # — so when the autoscaler adds replicas the bound loosens with
        # them (capacity the admission gate never uses is capacity
        # wasted), and when replicas die it tightens, which is exactly
        # when admission must tighten.  None (the default, and every
        # directly-constructed controller) keeps the explicit bound
        # fixed, the historical behavior.
        self.baseline_capacity: Optional[int] = None
        # optional online-SLO signal (telemetry.slo.SloMonitor, but
        # DUCK-TYPED — this module stays pure stdlib / file-path
        # loadable): while any declared SLO burns, the pending bound
        # tightens by slo_tighten, shedding load before the burn
        # exhausts the error budget.  The decision stays pure: the
        # monitor only moves the bound, visibly (detail carries it).
        self.slo_monitor = slo_monitor
        self.slo_tighten = float(slo_tighten)
        # the sanctioned chaos hook (the chaos plane's admission_blip
        # kind): while set, every decision rejects with the
        # ADMISSION_BLIP reason — a transient front-door outage that
        # stays VISIBLE (reasoned verdict + per-reason counter), never
        # a silent drop.  The injector owns setting/clearing it at
        # exact ticks; the decision itself stays pure.
        self.blip_active = False

    # --- sizing -------------------------------------------------------------
    def _slo_burning(self) -> bool:
        return bool(self.slo_monitor is not None
                    and getattr(self.slo_monitor, "firing", ()))

    def pending_bound(self, capacity_slots: int) -> int:
        """The effective pending bound for the current LIVE capacity.

        An explicit ``max_pending`` wins — re-scaled by
        ``capacity_slots / baseline_capacity`` when the fleet stamped
        the baseline it was sized for, so the bound tracks healthy-
        replica capacity as the fleet scales (or loses replicas)
        instead of freezing at its construction-time value.  Otherwise
        ``queue_factor ×`` the healthy fleet's slot capacity — which
        shrinks when replicas die, exactly when admission must tighten.
        A firing SLO monitor tightens either form by ``slo_tighten``."""
        if self.max_pending is not None:
            bound = self.max_pending
            base = self.baseline_capacity
            if base and base > 0 and capacity_slots >= 0 \
                    and capacity_slots != base:
                bound = max(1, int(round(
                    bound * capacity_slots / base
                )))
        else:
            bound = max(1, int(self.queue_factor * max(capacity_slots, 0)))
        if self._slo_burning():
            bound = max(1, int(bound * self.slo_tighten))
        return bound

    def _service_s(self, tpot_p50_s: Optional[float]) -> float:
        """Per-queue-position wait estimate: observed decode pace when
        the fleet has one, the configured prior until then."""
        if tpot_p50_s is not None and tpot_p50_s > 0:
            return float(tpot_p50_s)
        return self.service_s_estimate

    def estimate_wait_s(self, pending: int, capacity_slots: int,
                        tpot_p50_s: Optional[float] = None) -> float:
        """Rough queue-wait estimate: pending requests drain
        ``capacity_slots`` at a time, one service quantum each."""
        lanes = max(capacity_slots, 1)
        quantum = self._service_s(tpot_p50_s)
        return (pending / lanes) * quantum

    # --- the decision -------------------------------------------------------
    def decide(
        self,
        *,
        pending: int,
        capacity_slots: int,
        priority: str = BATCH,
        deadline_s: Optional[float] = None,
        tpot_p50_s: Optional[float] = None,
    ) -> AdmitDecision:
        """One admission decision from live fleet state.

        ``pending`` is total queued-but-unserved work across the fleet
        (replica queues + migration limbo); ``capacity_slots`` the
        healthy replicas' total KV slots.  Pure and side-effect-free:
        the fleet owns counting the outcome.
        """
        if priority not in _PRIORITY_RANK:
            raise ValueError(
                f"unknown priority {priority!r}; known: "
                f"{sorted(_PRIORITY_RANK)}"
            )
        if self.blip_active:
            # the injected front-door outage gates FIRST: a blip means
            # the intake itself is down, so no other evidence matters —
            # callers get the standard Retry-After-style hint
            return AdmitDecision(
                False, reason=ADMISSION_BLIP,
                retry_after_s=self._service_s(tpot_p50_s) * 5.0,
                detail=dict(pending=pending),
            )
        if capacity_slots <= 0:
            return AdmitDecision(
                False, reason=NO_HEALTHY_REPLICA,
                retry_after_s=self._service_s(tpot_p50_s) * 10.0,
                detail=dict(pending=pending),
            )
        bound = self.pending_bound(capacity_slots)
        wait_s = self.estimate_wait_s(pending, capacity_slots, tpot_p50_s)
        # the hint callers get on any reject: how long until the
        # overflow ahead of them should have drained
        over = max(pending - bound + 1, 1)
        retry_after_s = self.estimate_wait_s(
            over, capacity_slots, tpot_p50_s
        )
        slo_tightened = self._slo_burning()
        if pending >= bound:
            return AdmitDecision(
                False, reason=QUEUE_FULL, retry_after_s=retry_after_s,
                detail=dict(pending=pending, bound=bound,
                            slo_tightened=slo_tightened),
            )
        if (priority != INTERACTIVE
                and pending >= self.shed_fraction * bound):
            return AdmitDecision(
                False, reason=SHED_LOW_PRIORITY,
                retry_after_s=retry_after_s,
                detail=dict(pending=pending, bound=bound,
                            priority=priority),
            )
        if deadline_s is not None and wait_s > deadline_s:
            return AdmitDecision(
                False, reason=DEADLINE_UNMEETABLE,
                retry_after_s=max(retry_after_s, wait_s - deadline_s),
                detail=dict(estimated_wait_s=wait_s,
                            deadline_s=deadline_s),
            )
        return AdmitDecision(True, detail=dict(pending=pending,
                                               bound=bound))


__all__ = [
    "ADMISSION_BLIP",
    "AdmissionController",
    "AdmitDecision",
    "BATCH",
    "DEADLINE_UNMEETABLE",
    "INTERACTIVE",
    "NO_HEALTHY_REPLICA",
    "QUEUE_FULL",
    "REPLICAS_SATURATED",
    "SHED_LOW_PRIORITY",
]
