"""FleetSupervisor: detect sick/dead replicas, migrate, re-form.

Detection reuses PR 1's straggler machinery at fleet-tick granularity:

- **heartbeat** — every successful replica tick is a beat; a tick that
  raises :class:`~.replica.ReplicaCrashed` is a miss, and
  ``heartbeat_misses`` consecutive misses declare the replica DEAD
  (the in-process analog of ``PeerHeartbeat``'s timed collective).
- **EWMA health score** — per-replica EWMA of tick wall time against a
  per-era baseline (minimum over the first ``baseline_ticks``
  post-grace observations, the ``SelfHealHook`` idiom: one hiccup must
  not inflate "normal").  ``k_checks`` consecutive checks above
  ``sick_threshold ×`` baseline declare the replica SICK.
- **slot accounting** — occupied KV slots not owned by any running
  request (the ``slot_leak`` fault, or a real free-list bug) declare it
  SICK immediately: leaked capacity never heals by waiting.

Recovery follows the PR 6 verify-then-apply contract:

1. **drain** — a sick replica is drained gracefully through the
   engine's ``preempt`` contract (token streams provably intact); a
   dead replica's requests are recovered from the fleet ledger (the
   ``Request`` objects carry their committed tokens, so recomputation
   resume is exact).
2. **migrate** — drained requests re-dispatch through the router onto
   survivors; requests no survivor can hold yet park in the fleet's
   migration limbo and re-try every tick.  A request whose resume
   prefix no longer fits any bucket is marked FAILED and counted —
   never silently dropped.
3. **re-form** — the replica rebuilds through the same builder that
   constructed it (worker-manager serving pre-flight included), so an
   infeasible re-allocation is REJECTED by the verifier before any
   state is touched; the rollback is structural — the old fleet state
   was never mutated — and the failure spends the replica's
   ``max_reforms`` budget until it is RETIRED.

Every attempt is an async ``fleet_heal`` trace arc (opened at
detection, ``fleet.drain`` / ``fleet.migrate`` / ``fleet.reform`` spans
inside, closed with the outcome), the self-heal arc convention applied
to the fleet lane.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

from ..telemetry import get_tracer
from ..utils import Logger
from .replica import (
    DEAD,
    DRAINING,
    EVICTED,
    HEALTHY,
    RETIRED,
    EngineReplica,
)

# detection reasons (stable ids in events and trace args)
REASON_DEAD = "dead"
REASON_LATENCY = "latency"
REASON_SLOT_LEAK = "slot_leak"

# heal outcomes
REFORMED = "reformed"
REFORM_FAILED = "reform_failed"
RETIRED_OUT = "retired"


class _Health:
    """Per-replica, per-era health telemetry (reset on re-form)."""

    __slots__ = ("seen", "ewma", "baseline", "baseline_obs", "streak")

    def __init__(self):
        self.seen = 0
        self.ewma: Optional[float] = None
        self.baseline: Optional[float] = None
        self.baseline_obs: List[float] = []
        self.streak = 0


class FleetSupervisor:
    """Health scoring + the drain/migrate/re-form executor."""

    def __init__(
        self,
        *,
        ewma_alpha: float = 0.4,
        sick_threshold: float = 3.0,
        k_checks: int = 2,
        grace_ticks: int = 2,
        baseline_ticks: int = 4,
        heartbeat_misses: int = 2,
        check_every: int = 2,
        max_reforms: int = 2,
        reform_backoff_base: int = 2,
        reform_backoff_cap: int = 32,
        clock: Optional[Callable[[], float]] = None,
        logger: Optional[Logger] = None,
        slo_monitor=None,
    ):
        if check_every < 1 or heartbeat_misses < 1 or k_checks < 1:
            raise ValueError(
                "check_every, heartbeat_misses and k_checks must be >= 1"
            )
        if baseline_ticks < 1:
            raise ValueError("baseline_ticks must be >= 1")
        if reform_backoff_base < 0 or reform_backoff_cap < 0:
            raise ValueError(
                "reform_backoff_base and reform_backoff_cap must be "
                ">= 0"
            )
        self._alpha = float(ewma_alpha)
        self._sick_threshold = float(sick_threshold)
        self._k_checks = int(k_checks)
        self._grace_ticks = int(grace_ticks)
        self._baseline_ticks = int(baseline_ticks)
        self.heartbeat_misses = int(heartbeat_misses)
        self.check_every = int(check_every)
        self.max_reforms = int(max_reforms)
        # exponential backoff between STANDALONE re-form retries (the
        # poll()-driven path for replicas stranded DEAD/EVICTED or
        # finishing a drain): a failed attempt schedules the next one
        # base * 2^(failures-1) ticks out, capped — without this the
        # supervisor hammers a rejecting builder every single poll.
        # heal()'s inline attempt is deliberately NOT gated: fresh
        # detection evidence earns an immediate try.  The clock is
        # injectable (tests drive it deterministically); default is the
        # fleet's own tick counter.
        self.reform_backoff_base = int(reform_backoff_base)
        self.reform_backoff_cap = int(reform_backoff_cap)
        self._clock = clock
        self._next_retry_at: Dict[str, float] = {}
        # quarantine ledger: replicas RETIRED out of the fleet (re-form
        # budget exhausted), kept visible — /healthz and FleetStats
        # surface them so an operator sees WHAT is permanently out and
        # WHY, instead of inferring it from a shrinking replica count
        self.quarantined: Dict[str, Dict[str, Any]] = {}
        self._logger = logger or Logger()
        # optional online-SLO signal (duck-typed like the admission
        # controller's): while any declared SLO burns, the sick-check
        # runs EVERY tick instead of every check_every — an alerting
        # fleet earns a closer look, not a scheduled one
        self.slo_monitor = slo_monitor
        self._health: Dict[str, _Health] = {}
        self._reform_attempts: Dict[str, int] = {}
        self._arc_id = 0
        self.events: List[Dict[str, Any]] = []

    # --- telemetry ----------------------------------------------------------
    def _h(self, replica: EngineReplica) -> _Health:
        got = self._health.get(replica.name)
        if got is None:
            got = self._health[replica.name] = _Health()
        return got

    def observe_tick(self, replica: EngineReplica,
                     tick_s: float) -> None:
        """Fold one successful tick's wall time into the replica's
        health score.  The first ``grace_ticks`` of an era are compile
        warmup and are skipped, exactly like ``SelfHealHook``."""
        h = self._h(replica)
        h.seen += 1
        if h.seen <= self._grace_ticks:
            return
        h.ewma = (
            tick_s if h.ewma is None
            else self._alpha * tick_s + (1.0 - self._alpha) * h.ewma
        )
        if h.baseline is None:
            h.baseline_obs.append(tick_s)
            if len(h.baseline_obs) >= self._baseline_ticks:
                h.baseline = min(h.baseline_obs)
                h.baseline_obs = []

    def health_score(self, replica: EngineReplica) -> Optional[float]:
        """EWMA / baseline, or None before the baseline is learned."""
        h = self._h(replica)
        if h.ewma is None or h.baseline is None or h.baseline <= 0:
            return None
        return h.ewma / h.baseline

    def reset_era(self, replica: EngineReplica) -> None:
        """Forget a replica's telemetry (after re-form: new engine, new
        compile warmup, new normal)."""
        self._health[replica.name] = _Health()

    def forget_replica(self, name: str) -> None:
        """Drop ALL per-replica state for a name that left the fleet
        for good (autoscaler removal / rolled-back add).  Names are
        never reused, so without this an always-on autoscaled fleet
        minting fresh names every diurnal cycle grows these dicts
        without bound."""
        self._health.pop(name, None)
        self._reform_attempts.pop(name, None)
        self._next_retry_at.pop(name, None)
        self.quarantined.pop(name, None)

    def _now(self, fleet) -> float:
        """The backoff clock: injected when the caller wants control
        (tests), the fleet's tick counter otherwise — both monotonic,
        both in 'ticks' units for the default config."""
        if self._clock is not None:
            return float(self._clock())
        return float(fleet.tick)

    def _retry_gated(self, fleet, replica: EngineReplica) -> bool:
        """True while the replica's backoff window is still open."""
        return self._now(fleet) < self._next_retry_at.get(
            replica.name, 0.0
        )

    # --- detection ----------------------------------------------------------
    def _diagnose(self, replica: EngineReplica) -> Optional[str]:
        if replica.crashed or (
                replica.missed_beats >= self.heartbeat_misses):
            return REASON_DEAD
        if not replica.slot_accounting_ok:
            return REASON_SLOT_LEAK
        h = self._h(replica)
        score = self.health_score(replica)
        if score is not None and score >= self._sick_threshold:
            h.streak += 1
            if h.streak >= self._k_checks:
                h.streak = 0
                return REASON_LATENCY
        else:
            h.streak = 0
        return None

    def poll(self, fleet) -> None:
        """One detection pass (every ``check_every`` fleet ticks),
        healing whatever it finds.  Called by ``ServingFleet.step``
        after the replicas have ticked, so this tick's evidence is in.
        Replicas left DEAD/EVICTED by an earlier failed re-form get a
        fresh attempt here while their budget lasts — a transient
        allocation failure must not strand a replica forever."""
        slo_burning = bool(self.slo_monitor is not None
                           and getattr(self.slo_monitor, "firing", ()))
        if fleet.tick % self.check_every != 0 and not slo_burning:
            return
        # snapshot the list: finishing a pending removal mutates
        # fleet.replicas mid-walk
        for replica in list(fleet.replicas):
            if replica.state == HEALTHY:
                reason = self._diagnose(replica)
                if reason is not None:
                    self.heal(fleet, replica, reason)
            elif replica.state == DRAINING:
                # finishing the requests that could not migrate; a crash
                # mid-drain escalates to the dead path, an empty engine
                # graduates to re-form — or, for a replica the
                # autoscaler is removing, to leaving the fleet
                if (replica.crashed or replica.missed_beats
                        >= self.heartbeat_misses):
                    if replica.pending_removal:
                        self.finish_removal(fleet, replica, dead=True)
                    else:
                        self.heal(fleet, replica, REASON_DEAD)
                elif not replica.engine.running_requests:
                    if replica.pending_removal:
                        self.finish_removal(fleet, replica, dead=False)
                    elif not self._retry_gated(fleet, replica):
                        self.retry_reform(fleet, replica)
            elif replica.state in (DEAD, EVICTED):
                if replica.pending_removal:
                    self.finish_removal(fleet, replica,
                                        dead=replica.state == DEAD)
                elif not self._retry_gated(fleet, replica):
                    self.retry_reform(fleet, replica)

    # --- recovery -----------------------------------------------------------
    def _record(self, kind: str, replica: EngineReplica, tick: int,
                **extra) -> None:
        self.events.append(
            dict(kind=kind, replica=replica.name, tick=tick, **extra)
        )

    def heal(self, fleet, replica: EngineReplica, reason: str) -> str:
        """Drain -> migrate -> re-form one replica; returns the outcome.

        Structural rollback guarantee: the survivors' state is only
        ever *added to* (migrated requests), and the replica's rebuild
        swaps its engine only after the builder (and its pre-flight)
        succeeded — so a failed re-form leaves the fleet exactly as the
        drain left it: serving on survivors, replica out of rotation.
        """
        tracer = get_tracer()
        self._arc_id += 1
        lane = None
        if tracer is not None:
            lane = tracer.lane("fleet", "supervisor")
            tracer.async_begin(
                "fleet_heal", lane, self._arc_id,
                {"replica": replica.name, "reason": reason,
                 "tick": fleet.tick},
            )
        self._record("detect", replica, fleet.tick, reason=reason,
                     score=self.health_score(replica))
        self._logger.info(
            f"FleetSupervisor: replica {replica.name} unhealthy "
            f"({reason}) at tick {fleet.tick}; draining"
        )

        dead = reason == REASON_DEAD
        if tracer is not None:
            with tracer.span("fleet.drain", lane,
                             {"replica": replica.name, "dead": dead}):
                migrated = fleet.drain_replica(replica, dead=dead)
        else:
            migrated = fleet.drain_replica(replica, dead=dead)
        stuck = 0 if dead else len(replica.engine.running_requests)
        if dead:
            replica.state = DEAD
        elif stuck:
            # alive is alive: requests whose resume prefix outgrew every
            # bucket cannot migrate, so the sick replica finishes them
            # out of rotation instead of the fleet failing them
            replica.state = DRAINING
        else:
            replica.state = EVICTED
        fleet.router.forget_replica(replica.name)
        self._record("drain", replica, fleet.tick, dead=dead,
                     migrated=len(migrated), stuck=stuck)

        if tracer is not None:
            with tracer.span("fleet.migrate", lane,
                             {"replica": replica.name,
                              "requests": len(migrated)}):
                placed, parked = fleet.redispatch(migrated)
        else:
            placed, parked = fleet.redispatch(migrated)
        self._record("migrate", replica, fleet.tick, placed=placed,
                     parked=parked)

        if replica.state == DRAINING:
            # re-forming now would discard the engine the stuck
            # requests are still decoding on; poll() re-forms once the
            # drain completes (its own fleet_heal arc)
            if tracer is not None:
                tracer.async_end("fleet_heal", lane, self._arc_id,
                                 {"outcome": "draining", "stuck": stuck})
            return "draining"
        outcome, detail = self._attempt_reform(fleet, replica, tracer,
                                               lane)
        if tracer is not None:
            tracer.async_end("fleet_heal", lane, self._arc_id,
                             dict({"outcome": outcome}, **detail))
        return outcome

    def finish_removal(self, fleet, replica: EngineReplica,
                       *, dead: bool) -> None:
        """Complete an autoscaler scale-down whose drain has finished
        (or whose replica died mid-drain: its ledger requests are
        recovered first — a removal must lose exactly as many tokens
        as a heal, zero).  The replica leaves the fleet for good."""
        tracer = get_tracer()
        lane = (tracer.lane("fleet", "autoscaler")
                if tracer is not None else None)
        if dead:
            if tracer is not None:
                with tracer.span("fleet.drain", lane,
                                 {"replica": replica.name,
                                  "dead": True, "removal": True}):
                    migrated = fleet.drain_replica(replica, dead=True)
            else:
                migrated = fleet.drain_replica(replica, dead=True)
            fleet.redispatch(migrated)
        fleet.finalize_removal(replica)
        self._record("removed", replica, fleet.tick, dead=dead)
        self._logger.info(
            f"FleetSupervisor: replica {replica.name} removed "
            f"(scale-down{' after mid-drain death' if dead else ''})"
        )
        if tracer is not None:
            tracer.instant(
                "scale_down_complete", lane,
                {"replica": replica.name, "dead": dead},
            )

    def retry_reform(self, fleet, replica: EngineReplica) -> str:
        """A fresh re-form attempt for a replica stranded by an earlier
        failure — its own ``fleet_heal`` arc (reason ``reform_retry``),
        same budget."""
        tracer = get_tracer()
        self._arc_id += 1
        lane = None
        if tracer is not None:
            lane = tracer.lane("fleet", "supervisor")
            tracer.async_begin(
                "fleet_heal", lane, self._arc_id,
                {"replica": replica.name, "reason": "reform_retry",
                 "tick": fleet.tick},
            )
        outcome, detail = self._attempt_reform(fleet, replica, tracer,
                                               lane)
        if tracer is not None:
            tracer.async_end("fleet_heal", lane, self._arc_id,
                             dict({"outcome": outcome}, **detail))
        return outcome

    def _quarantine(self, fleet, replica: EngineReplica,
                    attempts: int) -> None:
        """Retire a replica whose re-form budget is exhausted and
        ledger it: quarantined replicas stay in ``fleet.replicas``
        (visible capacity loss) but are permanently out of rotation,
        and the ledger entry says when and why."""
        replica.state = RETIRED
        self._next_retry_at.pop(replica.name, None)
        self.quarantined[replica.name] = dict(
            tick=fleet.tick, attempts=int(attempts),
            reason="reform_budget_exhausted",
        )
        self._record("retired", replica, fleet.tick,
                     attempts=int(attempts))

    def _attempt_reform(self, fleet, replica: EngineReplica, tracer,
                        lane) -> tuple:
        """One budgeted rebuild; (outcome, trace-arg detail)."""
        attempts = self._reform_attempts.get(replica.name, 0)
        if attempts >= self.max_reforms:
            self._quarantine(fleet, replica, attempts)
            return RETIRED_OUT, {}
        self._reform_attempts[replica.name] = attempts + 1
        try:
            if tracer is not None:
                with tracer.span("fleet.reform", lane,
                                 {"replica": replica.name,
                                  "attempt": attempts + 1}):
                    replica.rebuild()
            else:
                replica.rebuild()
        except Exception as exc:
            # the verifier (or the slab allocation) rejected the
            # re-form: the rollback is structural — nothing was mutated
            # — and the budget decides whether the replica retires now
            fleet.stats.reform_failures += 1
            failures = self._reform_attempts[replica.name]
            retired = failures >= self.max_reforms
            backoff = 0.0
            if retired:
                self._quarantine(fleet, replica, failures)
            elif self.reform_backoff_base > 0:
                # exponential: base, 2*base, 4*base ... capped — the
                # NEXT standalone retry waits this long (heal()'s
                # inline attempt on fresh detection is never gated)
                backoff = float(min(
                    self.reform_backoff_cap,
                    self.reform_backoff_base * 2 ** (failures - 1),
                ))
                self._next_retry_at[replica.name] = (
                    self._now(fleet) + backoff
                )
            self._record(REFORM_FAILED, replica, fleet.tick,
                         error=str(exc), retired=retired,
                         backoff=backoff)
            self._logger.warning(
                f"FleetSupervisor: re-form of {replica.name} rejected "
                f"({exc}); serving on survivors"
                + (" — replica retired" if retired else "")
            )
            return REFORM_FAILED, {"error": str(exc)}
        # a SUCCESSFUL re-form refunds the budget: max_reforms bounds
        # consecutive failures, not lifetime faults — a long-lived fleet
        # must not monotonically retire replicas it keeps proving it
        # can heal
        self._reform_attempts[replica.name] = 0
        self._next_retry_at.pop(replica.name, None)
        self.reset_era(replica)
        fleet.stats.reforms += 1
        self._record(REFORMED, replica, fleet.tick,
                     generation=replica.generation)
        self._logger.info(
            f"FleetSupervisor: replica {replica.name} re-formed "
            f"(generation {replica.generation})"
        )
        return REFORMED, {"generation": replica.generation}


__all__ = [
    "FleetSupervisor",
    "REASON_DEAD",
    "REASON_LATENCY",
    "REASON_SLOT_LEAK",
    "REFORMED",
    "REFORM_FAILED",
]
