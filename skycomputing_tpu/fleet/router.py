"""SLO-aware request routing over engine-replica snapshots.

PURE STDLIB BY CONTRACT (the skylint/trace_report idiom): the router is
decision logic over plain snapshot dicts — no jax, no numpy — so
``tools/bench_fleet.py --smoke`` can load it by file path on a bare CI
runner and exercise every dispatch decision on synthetic snapshots.

Policy, in priority order:

- **least-loaded**: each healthy replica's load is its outstanding work
  — queued requests plus occupied slots — scaled by its observed decode
  pace (``tpot_p95_s``) when available, so a replica that is *slower*
  per token counts as more loaded at equal depth.  This is the
  drain-time estimate, driven by the live ``MetricsRegistry`` snapshot
  (queue depth, free slots, TPOT percentiles), not a guess.
- **prefix affinity**: requests sharing a prompt prefix prefer the
  replica that last served that prefix, but only while its outstanding
  work stays within ``affinity_slack`` REQUESTS of the least-loaded
  choice — affinity is a locality hint (warm compiled buckets today,
  prefix-cache reuse when the paged-KV work lands), never a license to
  pile onto a hot replica.

Ties break on replica name, so dispatch is deterministic for tests and
replayable chaos runs.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

#: prompt tokens hashed into the affinity key: long enough to separate
#: real system prompts, short enough that near-identical prompts collide
#: into the same warm replica.  When the fleet serves PAGED engines,
#: construct the router with ``page_size=<engine page_size>`` instead:
#: the key then becomes the first full KV page of the prompt — the
#: minimal unit the radix prefix cache can share — so affinity routing
#: sends same-system-prompt requests to the replica that already holds
#: their prefix pages, and the hint pays off as REAL ``prefix_hits``
#: instead of just warm compiled buckets.
DEFAULT_PREFIX_TOKENS = 8


def prefix_key(prompt: Sequence[int],
               n: int = DEFAULT_PREFIX_TOKENS) -> Tuple[int, ...]:
    """The affinity key for a prompt: its first ``n`` token ids."""
    return tuple(int(t) for t in list(prompt)[:n])


def radix_prefix_key(prompt: Sequence[int],
                     page_size: int) -> Tuple[int, ...]:
    """The paged affinity key: the prompt's first full KV page (or the
    whole prompt when it is shorter than one page — too short to share
    pages, but still a stable identity for bucket warmth)."""
    return tuple(int(t) for t in list(prompt)[:max(int(page_size), 1)])


def replica_load(snapshot: Dict[str, Any],
                 default_pace: float = 1.0) -> float:
    """Estimated drain cost of a replica from its snapshot.

    ``(queue_depth + occupied slots)`` requests ahead, each paced at
    the replica's observed ``tpot_p95_s`` when it has one and at
    ``default_pace`` otherwise.  Callers comparing replicas should pass
    the fleet's typical pace as the default (see :meth:`Router.rank`):
    a just-re-formed replica has no samples yet, and scoring it with an
    arbitrary large constant would make the idle rebuilt replica look
    busier than saturated survivors — starving exactly the capacity the
    heal just restored."""
    depth = int(snapshot.get("queue_depth", 0))
    occupied = int(snapshot.get("slots", 0)) - int(
        snapshot.get("free_slots", 0)
    )
    pace = snapshot.get("tpot_p95_s") or default_pace
    return (depth + max(occupied, 0)) * float(pace)


def _outstanding(snapshot: Dict[str, Any]) -> int:
    """Outstanding work in requests: queued plus occupied slots."""
    occupied = int(snapshot.get("slots", 0)) - int(
        snapshot.get("free_slots", 0)
    )
    return int(snapshot.get("queue_depth", 0)) + max(occupied, 0)


def _typical_pace(snapshots: Sequence[Dict[str, Any]]) -> float:
    """Median observed ``tpot_p95_s`` across snapshots that have one;
    1.0 when nobody has samples yet (all-cold fleets compare by raw
    depth, which is the right cold-start behavior)."""
    paces = sorted(
        float(s["tpot_p95_s"]) for s in snapshots
        if s.get("tpot_p95_s")
    )
    if not paces:
        return 1.0
    return paces[len(paces) // 2]


class Router:
    """Least-loaded + prefix-affinity dispatch over replica snapshots."""

    def __init__(self, affinity_slack: float = 2.0,
                 prefix_tokens: int = DEFAULT_PREFIX_TOKENS,
                 max_affinity: int = 4096,
                 page_size: Optional[int] = None):
        if affinity_slack < 0:
            raise ValueError(
                f"affinity_slack must be >= 0, got {affinity_slack}"
            )
        self.affinity_slack = float(affinity_slack)
        # page_size aligns the affinity key with the radix prefix
        # cache's sharing unit (one full page): requests that CAN share
        # pages get the same key, so sticking them to one replica turns
        # the locality hint into real prefix_hits there
        self.page_size = None if page_size is None else int(page_size)
        self.prefix_tokens = (
            int(prefix_tokens) if self.page_size is None
            else self.page_size
        )
        self.max_affinity = int(max_affinity)
        # prefix key -> replica name; plain dict, insertion-ordered, so
        # the cap evicts the oldest learned affinity first
        self._affinity: Dict[Tuple[int, ...], str] = {}

    def _key(self, prompt: Sequence[int]) -> Tuple[int, ...]:
        """The affinity key: radix-aligned (first full KV page) on
        paged fleets, first-``prefix_tokens`` otherwise."""
        if self.page_size is not None:
            return radix_prefix_key(prompt, self.page_size)
        return prefix_key(prompt, self.prefix_tokens)

    # --- ranking -----------------------------------------------------------
    def rank(self, snapshots: Sequence[Dict[str, Any]],
             prompt: Optional[Sequence[int]] = None,
             role: Optional[str] = None) -> List[str]:
        """Replica names, best dispatch target first.

        Only snapshots marked ``healthy`` participate.  With ``role``
        set (disaggregated fleets), only replicas carrying that role
        compete — prefill work never lands on a decode specialist and
        vice versa; role-less fleets pass None and rank everyone.  With
        a prompt, the learned affinity replica is promoted to the front
        while its outstanding request count stays within
        ``affinity_slack`` requests of the least-loaded candidate — on
        a paged fleet the key is the prompt's first full KV page, so
        role-aware prefill placement follows page-aligned prefix
        affinity.  The full ranking (not just the winner) lets the
        fleet walk the list when the best target's bounded queue
        rejects."""
        healthy = [s for s in snapshots if s.get("healthy")]
        if role is not None:
            healthy = [s for s in healthy
                       if str(s.get("role", "")) == str(role)]
        if not healthy:
            return []
        pace = _typical_pace(healthy)
        ordered = sorted(
            healthy,
            key=lambda s: (replica_load(s, pace), str(s["name"])),
        )
        names = [str(s["name"]) for s in ordered]
        if prompt is not None:
            key = self._key(prompt)
            sticky = self._affinity.get(key)
            if sticky is not None and sticky in names:
                by_name = {str(s["name"]): s for s in healthy}
                # the slack is in REQUESTS (outstanding-work counts),
                # not pace-scaled load: scaled, a realistic ~20ms TPOT
                # would let the sticky replica carry ~slack/0.02 ≈ 100
                # extra requests before yielding — an unbounded pile-on
                # wearing a bounded constant's name
                best_count = _outstanding(ordered[0])
                if (_outstanding(by_name[sticky])
                        <= best_count + self.affinity_slack):
                    names.remove(sticky)
                    names.insert(0, sticky)
        return names

    def choose(self, snapshots: Sequence[Dict[str, Any]],
               prompt: Optional[Sequence[int]] = None,
               role: Optional[str] = None) -> Optional[str]:
        """The single best dispatch target, or None with no healthy
        replica (in the requested role, when one is given)."""
        ranked = self.rank(snapshots, prompt, role=role)
        return ranked[0] if ranked else None

    # --- affinity bookkeeping ----------------------------------------------
    def record_dispatch(self, replica_name: str,
                        prompt: Sequence[int]) -> None:
        """Learn (or refresh) the prefix -> replica affinity after an
        actual dispatch — the router only trusts placements that
        happened, not ones it merely suggested."""
        key = self._key(prompt)
        # re-insert so the cap below evicts least-recently-dispatched
        self._affinity.pop(key, None)
        self._affinity[key] = str(replica_name)
        while len(self._affinity) > self.max_affinity:
            self._affinity.pop(next(iter(self._affinity)))

    def forget_replica(self, replica_name: str) -> int:
        """Drop every affinity pointing at ``replica_name`` (it died or
        was evicted); returns how many entries were dropped."""
        stale = [k for k, v in self._affinity.items()
                 if v == str(replica_name)]
        for k in stale:
            del self._affinity[k]
        return len(stale)

    @property
    def affinity_size(self) -> int:
        return len(self._affinity)


__all__ = [
    "DEFAULT_PREFIX_TOKENS",
    "Router",
    "prefix_key",
    "radix_prefix_key",
    "replica_load",
]
