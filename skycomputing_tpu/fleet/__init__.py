"""Fault-tolerant serving fleet: replicated engines behind SLO-aware
routing, admission control with explicit load-shedding, and replica
self-heal.

- :mod:`.router` — least-loaded + prefix-affinity dispatch over live
  replica metric snapshots (PURE stdlib: loadable by file path for the
  CI smoke, the skylint idiom);
- :mod:`.admission` — bounded intake, priority classes, deadline-aware
  rejects with ``Retry-After``-style hints (pure stdlib too);
- :mod:`.replica` — :class:`EngineReplica`, one named
  :class:`~..serving.ServingEngine` with health state, the chaos fault
  surface, and its verified rebuild path;
- :mod:`.supervisor` — :class:`FleetSupervisor`, heartbeat + EWMA
  detection and the drain -> migrate -> re-form executor (PR 6's
  verify-then-apply / guarded-rollback contract, visible as async
  ``fleet_heal`` trace arcs);
- :mod:`.fleet` — :class:`ServingFleet`, the orchestrator, with
  :class:`FleetStats` and a fleet-wide :class:`~..telemetry.
  MetricsRegistry`;
- :mod:`.autoscaler` — :class:`FleetAutoscaler`, sustained SLO burn ->
  verified replica ADD, sustained slack -> drain-then-REMOVE, with
  hysteresis + cooldown and a ``plan_check`` scale pre-flight.
"""

from __future__ import annotations

from .admission import (
    AdmissionController,
    AdmitDecision,
    BATCH,
    INTERACTIVE,
)
from .autoscaler import FleetAutoscaler
from .fleet import FleetStats, ServingFleet
from .replica import EngineReplica, ReplicaCrashed
from .router import Router, prefix_key, replica_load
from .supervisor import FleetSupervisor

__all__ = [
    "AdmissionController",
    "AdmitDecision",
    "BATCH",
    "EngineReplica",
    "FleetAutoscaler",
    "FleetStats",
    "FleetSupervisor",
    "INTERACTIVE",
    "ReplicaCrashed",
    "Router",
    "ServingFleet",
    "prefix_key",
    "replica_load",
]
