"""jax version-compatibility shims for the parallel engines.

The engines target the modern ``jax.shard_map`` spelling (with its
``check_vma`` knob).  Older jax ships the same primitive as
``jax.experimental.shard_map.shard_map`` with the knob named
``check_rep`` — semantically the same replication/varying-manual-axes
check, renamed upstream.  Dispatching here keeps every call site on one
spelling and the pinned-jaxlib image green.
"""

from __future__ import annotations

import jax


def shard_map(body, mesh, in_specs, out_specs, check_vma: bool = False):
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            body,
            mesh=mesh,
            in_specs=in_specs,
            out_specs=out_specs,
            check_vma=check_vma,
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(
        body,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        check_rep=check_vma,
    )


__all__ = ["shard_map"]
