"""Compiled SPMD pipeline for the GPT family.

Reuses :class:`~.spmd.CompiledBertPipeline`'s ring-schedule machinery (the
GPipe and interleaved shard_map bodies operate on an opaque ``(hidden,
side)`` pair) with GPT-specific ends: token embeddings in, LM head out,
causal-LM loss.  The pipelined stage flows ``(hidden, side)`` — the causal
mask is rebuilt inside each block from shapes, so the side tensor is a
zero placeholder for dense stages, and the Switch load-balance aux-loss
accumulator for MoE stages (``GptMoeEncoderStage`` + ``side_outputs``).

This makes the one-jit engine a two-family surface (the reference's engine
was BERT-only end to end — ``scaelum/experiment/config.py:26-49``).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import optax
import flax.linen as nn

from ..models.gpt import (
    ACT2FN,
    GptBlock_Attn,
    GptBlock_Mlp,
    GptBlock_MoeMlp,
    GptConfig,
    GptEmbeddings,
    GptLmHead,
)
from ..ops.losses import causal_lm_loss
from .spmd import CompiledBertPipeline, _TpDense, split_stage_params_for_tp

# GPT Dense submodules by Megatron role: q/k/v and the FFN up-projection are
# column-parallel; both attention-out and FFN-down share the name ``c_proj``
# and are row-parallel (psum)
GPT_TP_COL = ("q_proj", "k_proj", "v_proj", "c_fc")
GPT_TP_ROW = ("c_proj",)


class GptEncoderUnit(nn.Module):
    """One transformer block (attention + MLP), tuple signature."""

    config: Any

    @nn.compact
    def __call__(self, hidden, dummy):
        hidden = GptBlock_Attn(self.config, deterministic=True,
                               name="attn")(hidden)
        hidden = GptBlock_Mlp(self.config, deterministic=True,
                              name="mlp")(hidden)
        return hidden, dummy


class GptEncoderStage(nn.Module):
    """``units`` rematerialized blocks = one uniform pipeline stage."""

    config: Any
    units: int

    @nn.compact
    def __call__(self, hidden, dummy):
        for u in range(self.units):
            hidden, dummy = nn.remat(GptEncoderUnit)(
                self.config, name=f"unit_{u}"
            )(hidden, dummy)
        return hidden, dummy


class GptMoeEncoderStage(nn.Module):
    """``units`` blocks where every ``moe_every``-th MLP is a Switch MoE.

    The MoE load-balance aux loss cannot be sown through ``lax.scan`` +
    ``shard_map``, so each MoE block ADDS its aux scalar onto the ring's
    side tensor (shape [mb]); the engine reads it back from the final
    stage's side output.  Param tree mirrors the monolithic
    :class:`~..models.gpt.GptBlock_MoeMlp` (``router``/``w1``..``b2``
    under ``unit_u/mlp``) so checkpoints port between the two paths.
    """

    config: Any
    units: int
    moe_every: int
    num_experts: int = 8
    top_k: int = 1
    capacity_factor: float = 1.25

    @nn.compact
    def __call__(self, hidden, side):
        # every stage runs the SAME module (stage params stack on one
        # leading axis), so the MoE pattern must repeat per stage; with
        # moe_every | units the stage-local placement (u+1) % moe_every
        # coincides exactly with the monolithic model's global placement
        # (b+1) % moe_every of models/gpt.py::gpt_layer_configs
        if self.moe_every <= 0 or self.units % self.moe_every:
            raise ValueError(
                f"moe_every ({self.moe_every}) must divide units_per_stage "
                f"({self.units}) so the per-stage MoE pattern matches the "
                f"monolithic block placement"
            )
        outer = self

        class Unit(nn.Module):
            is_moe: bool

            @nn.compact
            def __call__(sf, h, s):
                h = GptBlock_Attn(outer.config, deterministic=True,
                                  name="attn")(h)
                if sf.is_moe:
                    h, aux = GptBlock_MoeMlp(
                        outer.config, num_experts=outer.num_experts,
                        top_k=outer.top_k,
                        capacity_factor=outer.capacity_factor,
                        deterministic=True, return_aux=True, name="mlp",
                    )(h)
                    s = s + aux.astype(s.dtype)
                else:
                    h = GptBlock_Mlp(outer.config, deterministic=True,
                                     name="mlp")(h)
                return h, s

        for u in range(self.units):
            is_moe = (u + 1) % self.moe_every == 0
            hidden, side = nn.remat(Unit)(is_moe, name=f"unit_{u}")(
                hidden, side
            )
        return hidden, side


class TpGptUnit(nn.Module):
    """Megatron-style tensor-parallel GPT block for the pipeline body.

    q/k/v are column-parallel (heads split across tp), the attention output
    projection and the FFN down-projection are row-parallel with a ``psum``;
    LayerNorms and residuals are replicated.  The param tree mirrors
    :class:`GptEncoderUnit` (``attn/q_proj`` etc.) with tp-local leaf
    shapes, so full weights split by pure reshape
    (:func:`split_stage_params_for_tp` with the GPT role sets).
    Deterministic only (the compiled pipeline body never applies dropout).
    """

    config: Any
    tp: int
    axis_name: str = "tp"

    @nn.compact
    def __call__(self, hidden, dummy):
        cfg = GptConfig.from_dict(self.config)
        dtype = jnp.dtype(cfg.dtype)
        if (
            cfg.hidden_size % self.tp
            or cfg.num_attention_heads % self.tp
            or cfg.intermediate_size % self.tp
        ):
            raise ValueError(
                f"hidden/heads/intermediate "
                f"({cfg.hidden_size}/{cfg.num_attention_heads}/"
                f"{cfg.intermediate_size}) must all be divisible by "
                f"tp={self.tp}"
            )
        n_heads = cfg.num_attention_heads // self.tp
        head_dim = cfg.hidden_size // cfg.num_attention_heads
        h_local = cfg.hidden_size // self.tp
        i_local = cfg.intermediate_size // self.tp
        tp_axis = self.axis_name

        class Attn(nn.Module):
            @nn.compact
            def __call__(sf, hidden):
                x = nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32,
                                 name="ln_1")(hidden).astype(dtype)
                mk = lambda nm: _TpDense(h_local, dtype, "col", tp_axis,
                                         name=nm)
                split = lambda t: t.reshape(
                    t.shape[0], t.shape[1], n_heads, head_dim
                )
                q = split(mk("q_proj")(x))
                k = split(mk("k_proj")(x))
                v = split(mk("v_proj")(x))
                scores = jnp.einsum("blhd,bmhd->bhlm", q, k) / jnp.sqrt(
                    jnp.asarray(head_dim, dtype)
                )
                L = q.shape[1]
                causal = jnp.tril(jnp.ones((L, L), bool))
                scores = jnp.where(causal[None, None], scores, -jnp.inf)
                probs = jax.nn.softmax(
                    scores.astype(jnp.float32), axis=-1
                ).astype(dtype)
                ctx = jnp.einsum("bhlm,bmhd->blhd", probs, v)
                ctx = ctx.reshape(ctx.shape[0], ctx.shape[1], h_local)
                out = _TpDense(cfg.hidden_size, dtype, "row", tp_axis,
                               name="c_proj")(ctx)
                return hidden + out

        class Mlp(nn.Module):
            @nn.compact
            def __call__(sf, hidden):
                act = ACT2FN[cfg.hidden_act]
                x = nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32,
                                 name="ln_2")(hidden).astype(dtype)
                x = act(_TpDense(i_local, dtype, "col", tp_axis,
                                 name="c_fc")(x))
                x = _TpDense(cfg.hidden_size, dtype, "row", tp_axis,
                             name="c_proj")(x)
                return hidden + x

        hidden = Attn(name="attn")(hidden)
        hidden = Mlp(name="mlp")(hidden)
        return hidden, dummy


class TpGptStage(nn.Module):
    """``units`` tensor-parallel GPT blocks; remat like GptEncoderStage."""

    config: Any
    units: int
    tp: int
    axis_name: str = "tp"

    @nn.compact
    def __call__(self, hidden, dummy):
        for u in range(self.units):
            hidden, dummy = nn.remat(TpGptUnit)(
                self.config, self.tp, self.axis_name, name=f"unit_{u}"
            )(hidden, dummy)
        return hidden, dummy


class CompiledGptPipeline(CompiledBertPipeline):
    """GPT causal LM with blocks pipelined across a ('pp',) / ('dp','pp')
    / ('dp','pp','tp') mesh; inherits the GPipe + interleaved schedules,
    tensor parallelism, ZeRO-1, and the jitted train step from the BERT
    engine."""

    tp_col_modules = GPT_TP_COL
    tp_row_modules = GPT_TP_ROW

    def __init__(self, config, mesh, units_per_stage, *args,
                 moe_every: int = 0, num_experts: int = 8,
                 moe_top_k: int = 1, moe_capacity_factor: float = 1.25,
                 moe_aux_coef: float = 0.01, **kwargs):
        # consumed by _build_modules, which the base ctor calls
        self.moe_every = int(moe_every)
        self.num_experts = int(num_experts)
        self.moe_top_k = int(moe_top_k)
        self.moe_capacity_factor = float(moe_capacity_factor)
        self.moe_aux_coef = float(moe_aux_coef)
        super().__init__(config, mesh, units_per_stage, *args, **kwargs)

    @staticmethod
    def _parse_config(config):
        return GptConfig.from_dict(config)

    def _build_modules(self, units_per_stage: int, num_classes: int) -> None:
        cfg_dict = self.cfg.to_dict()
        self.embeddings = GptEmbeddings(cfg_dict, deterministic=True)
        if self.moe_every:
            if self.tp > 1:
                raise NotImplementedError(
                    "MoE stages do not compose with in-pipeline tensor "
                    "parallelism yet"
                )
            self.stage = GptMoeEncoderStage(
                cfg_dict, units_per_stage, self.moe_every,
                self.num_experts, self.moe_top_k, self.moe_capacity_factor,
            )
            self.side_outputs = True
        else:
            self.stage = GptEncoderStage(cfg_dict, units_per_stage)
        self.tp_stage = (
            TpGptStage(cfg_dict, units_per_stage, self.tp)
            if self.tp > 1 else None
        )
        self.lm_head = GptLmHead(cfg_dict, deterministic=True)

    # --- init ----------------------------------------------------------------
    def init(self, rng: jax.Array, input_ids):
        from jax.sharding import NamedSharding

        k_embed, k_stage, k_head = jax.random.split(rng, 3)
        embed_vars = self.embeddings.init({"params": k_embed}, input_ids)
        hidden = self.embeddings.apply(embed_vars, input_ids)
        dummy = jnp.zeros((), hidden.dtype)

        def init_one_stage(key):
            return self.stage.init({"params": key}, hidden, dummy)["params"]

        S, V = self.num_stages, self.virtual_stages
        chunk_keys = jax.random.split(k_stage, S * V)
        order = [(p % V) * S + p // V for p in range(S * V)]
        stages = jax.vmap(init_one_stage)(chunk_keys[jnp.asarray(order)])
        if self.tp > 1:
            stages = split_stage_params_for_tp(
                stages, self.tp, self.tp_col_modules, self.tp_row_modules
            )

        head_vars = self.lm_head.init({"params": k_head}, hidden)
        params = {
            "embeddings": embed_vars["params"],
            "stages": stages,
            "lm_head": head_vars["params"],
        }
        self.param_shardings = {
            "embeddings": NamedSharding(self.mesh, self._repl_spec),
            "stages": self._stage_shardings(stages),
            "lm_head": NamedSharding(self.mesh, self._repl_spec),
        }
        return jax.device_put(params, self.param_shardings)

    # --- full model ----------------------------------------------------------
    def _logits(self, params, input_ids):
        M = self.num_microbatches
        hidden = self.embeddings.apply(
            {"params": params["embeddings"]}, input_ids
        )
        B = hidden.shape[0]
        if B % M != 0:
            raise ValueError(f"batch {B} not divisible by microbatches {M}")
        if (B // M) % self.dp != 0:
            raise ValueError(
                f"microbatch size {B // M} not divisible by dp={self.dp}"
            )
        hidden_mb = hidden.reshape(M, B // M, *hidden.shape[1:])
        # the ring schedule threads a per-microbatch side tensor; GPT needs
        # none, so ride a batch-shaped zero (batch-like so the dp sharding
        # spec applies to it uniformly).  MoE accumulates its Switch aux
        # scalar into this tensor across every MoE layer — keep that
        # accumulator float32 even under bf16 configs (it is tiny, [M, mb])
        # so the load-balance loss does not lose precision to repeated
        # bf16 rounding; dense stages keep hidden.dtype (pure placeholder).
        side_dtype = jnp.float32 if self.side_outputs else hidden.dtype
        dummy_mb = jnp.zeros((M, B // M), side_dtype)

        aux = None
        encoder = (self._interleaved_encoder if self.virtual_stages > 1
                   else self._pipelined_encoder)
        encoded = encoder(params["stages"], hidden_mb, dummy_mb)
        if self.side_outputs:
            # the side rides the ring as a per-microbatch aux accumulator
            encoded, side_out = encoded
            aux = side_out.mean()  # avg over microbatches of summed aux
        encoded = encoded.reshape(B, *encoded.shape[2:])
        logits = self.lm_head.apply({"params": params["lm_head"]}, encoded)
        return (logits, aux) if self.side_outputs else logits

    def loss(self, params, batch, labels):
        (input_ids,) = batch if isinstance(batch, tuple) else (batch,)
        out = self._logits(params, input_ids)
        if self.side_outputs:
            logits, aux = out
            return causal_lm_loss(logits, labels) + (
                self.moe_aux_coef * aux.astype(jnp.float32)
            )
        return causal_lm_loss(out, labels)


__all__ = [
    "CompiledGptPipeline",
    "GptEncoderStage",
    "GptEncoderUnit",
    "GptMoeEncoderStage",
    "TpGptStage",
    "TpGptUnit",
    "GPT_TP_COL",
    "GPT_TP_ROW",
]
