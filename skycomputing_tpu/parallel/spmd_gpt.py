"""Compiled SPMD pipeline for the GPT family.

Reuses :class:`~.spmd.CompiledBertPipeline`'s ring-schedule machinery (the
GPipe and interleaved shard_map bodies operate on an opaque ``(hidden,
side)`` pair) with GPT-specific ends: token embeddings in, LM head out,
causal-LM loss.  The pipelined stage flows ``(hidden, side)`` — the causal
mask is rebuilt inside each block from shapes, so the side tensor is a
zero placeholder for dense stages, and the Switch load-balance aux-loss
accumulator for MoE stages (``GptMoeEncoderStage`` + ``side_outputs``).

This makes the one-jit engine a two-family surface (the reference's engine
was BERT-only end to end — ``scaelum/experiment/config.py:26-49``).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
import optax
import flax.linen as nn
from jax import lax

from ..models.gpt import (
    ACT2FN,
    GptBlock_Attn,
    GptBlock_Mlp,
    GptBlock_MoeMlp,
    GptConfig,
    GptEmbeddings,
    GptLmHead,
)
from ..ops.losses import causal_lm_loss
from .spmd import CompiledBertPipeline, _TpDense, split_stage_params_for_tp

# GPT Dense submodules by Megatron role: q/k/v and the FFN up-projection are
# column-parallel; both attention-out and FFN-down share the name ``c_proj``
# and are row-parallel (psum)
GPT_TP_COL = ("q_proj", "k_proj", "v_proj", "c_fc")
GPT_TP_ROW = ("c_proj",)

# MoE expert tensors fit the same col/row role tables as direct
# ``(module, param)`` pairs: w1/b1 [E, H, I]/[E, I] split the expert
# intermediate (last axis, column role); w2 [E, I, H] splits its input
# features (second-to-last, row role, psum after the expert down-proj);
# router and b2 replicate (b2 is added after the psum)
GPT_MOE_TP_COL = GPT_TP_COL + (("mlp", "w1"), ("mlp", "b1"))
GPT_MOE_TP_ROW = GPT_TP_ROW + (("mlp", "w2"),)


class GptEncoderUnit(nn.Module):
    """One transformer block (attention + MLP), tuple signature."""

    config: Any
    deterministic: bool = True

    @nn.compact
    def __call__(self, hidden, dummy):
        hidden = GptBlock_Attn(self.config,
                               deterministic=self.deterministic,
                               name="attn")(hidden)
        hidden = GptBlock_Mlp(self.config,
                              deterministic=self.deterministic,
                              name="mlp")(hidden)
        return hidden, dummy


class GptEncoderStage(nn.Module):
    """``units`` rematerialized blocks = one uniform pipeline stage."""

    config: Any
    units: int
    deterministic: bool = True

    @nn.compact
    def __call__(self, hidden, dummy):
        for u in range(self.units):
            hidden, dummy = nn.remat(GptEncoderUnit)(
                self.config, self.deterministic, name=f"unit_{u}"
            )(hidden, dummy)
        return hidden, dummy


class GptMoeEncoderStage(nn.Module):
    """``units`` blocks where every ``moe_every``-th MLP is a Switch MoE.

    The MoE load-balance aux loss cannot be sown through ``lax.scan`` +
    ``shard_map``, so each MoE block ADDS its aux scalar onto the ring's
    side tensor (shape [mb]); the engine reads it back from the final
    stage's side output.  Param tree mirrors the monolithic
    :class:`~..models.gpt.GptBlock_MoeMlp` (``router``/``w1``..``b2``
    under ``unit_u/mlp``) so checkpoints port between the two paths.
    """

    config: Any
    units: int
    moe_every: int
    num_experts: int = 8
    top_k: int = 1
    capacity_factor: float = 1.25
    deterministic: bool = True

    @nn.compact
    def __call__(self, hidden, side):
        # every stage runs the SAME module (stage params stack on one
        # leading axis), so the MoE pattern must repeat per stage; with
        # moe_every | units the stage-local placement (u+1) % moe_every
        # coincides exactly with the monolithic model's global placement
        # (b+1) % moe_every of models/gpt.py::gpt_layer_configs
        if self.moe_every <= 0 or self.units % self.moe_every:
            raise ValueError(
                f"moe_every ({self.moe_every}) must divide units_per_stage "
                f"({self.units}) so the per-stage MoE pattern matches the "
                f"monolithic block placement"
            )
        outer = self

        class Unit(nn.Module):
            is_moe: bool

            @nn.compact
            def __call__(sf, h, s):
                det = outer.deterministic
                h = GptBlock_Attn(outer.config, deterministic=det,
                                  name="attn")(h)
                if sf.is_moe:
                    h, aux = GptBlock_MoeMlp(
                        outer.config, num_experts=outer.num_experts,
                        top_k=outer.top_k,
                        capacity_factor=outer.capacity_factor,
                        deterministic=det, return_aux=True, name="mlp",
                    )(h)
                    s = s + aux.astype(s.dtype)
                else:
                    h = GptBlock_Mlp(outer.config, deterministic=det,
                                     name="mlp")(h)
                return h, s

        for u in range(self.units):
            is_moe = (u + 1) % self.moe_every == 0
            hidden, side = nn.remat(Unit)(is_moe, name=f"unit_{u}")(
                hidden, side
            )
        return hidden, side


def _check_tp_divisibility(cfg, tp: int) -> None:
    if (
        cfg.hidden_size % tp
        or cfg.num_attention_heads % tp
        or cfg.intermediate_size % tp
    ):
        raise ValueError(
            f"hidden/heads/intermediate "
            f"({cfg.hidden_size}/{cfg.num_attention_heads}/"
            f"{cfg.intermediate_size}) must all be divisible by tp={tp}"
        )


class _TpGptAttn(nn.Module):
    """Megatron attention half: col-parallel q/k/v, row-parallel c_proj.

    GPT's block dropouts all act on REPLICATED activations (after the
    row-parallel psum), so under ``deterministic=False`` they draw from
    the shared per-tick key — identical masks on every tp rank keep the
    replicas equal; no per-rank desync is needed anywhere in this family.
    """

    config: Any
    tp: int
    axis_name: str = "tp"
    deterministic: bool = True

    @nn.compact
    def __call__(self, hidden):
        cfg = GptConfig.from_dict(self.config)
        dtype = jnp.dtype(cfg.dtype)
        n_heads = cfg.num_attention_heads // self.tp
        head_dim = cfg.hidden_size // cfg.num_attention_heads
        h_local = cfg.hidden_size // self.tp
        x = nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32,
                         name="ln_1")(hidden).astype(dtype)
        mk = lambda nm: _TpDense(h_local, dtype, "col", self.axis_name,
                                 name=nm)
        split = lambda t: t.reshape(
            t.shape[0], t.shape[1], n_heads, head_dim
        )
        q = split(mk("q_proj")(x))
        k = split(mk("k_proj")(x))
        v = split(mk("v_proj")(x))
        scores = jnp.einsum("blhd,bmhd->bhlm", q, k) / jnp.sqrt(
            jnp.asarray(head_dim, dtype)
        )
        L = q.shape[1]
        causal = jnp.tril(jnp.ones((L, L), bool))
        scores = jnp.where(causal[None, None], scores, -jnp.inf)
        probs = jax.nn.softmax(
            scores.astype(jnp.float32), axis=-1
        ).astype(dtype)
        ctx = jnp.einsum("bhlm,bmhd->blhd", probs, v)
        ctx = ctx.reshape(ctx.shape[0], ctx.shape[1], h_local)
        out = _TpDense(cfg.hidden_size, dtype, "row", self.axis_name,
                       name="c_proj")(ctx)
        out = nn.Dropout(cfg.dropout_prob)(
            out, deterministic=self.deterministic
        )
        return hidden + out


class _TpGptMlp(nn.Module):
    """Megatron dense MLP half: col-parallel c_fc, row-parallel c_proj."""

    config: Any
    tp: int
    axis_name: str = "tp"
    deterministic: bool = True

    @nn.compact
    def __call__(self, hidden):
        cfg = GptConfig.from_dict(self.config)
        dtype = jnp.dtype(cfg.dtype)
        i_local = cfg.intermediate_size // self.tp
        act = ACT2FN[cfg.hidden_act]
        x = nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32,
                         name="ln_2")(hidden).astype(dtype)
        x = act(_TpDense(i_local, dtype, "col", self.axis_name,
                         name="c_fc")(x))
        x = _TpDense(cfg.hidden_size, dtype, "row", self.axis_name,
                     name="c_proj")(x)
        x = nn.Dropout(cfg.dropout_prob)(
            x, deterministic=self.deterministic
        )
        return hidden + x


class _TpGptMoeMlp(nn.Module):
    """Megatron-sharded Switch MoE MLP half for the pipeline body.

    Expert intermediates split across tp: w1/b1 hold the ``I/tp`` column
    shard, w2 the matching row shard whose partial expert outputs are
    ``psum``-reduced before the replicated b2 — the same col/row algebra as
    the dense blocks, lifted onto the leading expert axis (see
    ``GPT_MOE_TP_COL``/``GPT_MOE_TP_ROW``).  Router, dispatch, and the aux
    loss are computed identically on every tp rank from the replicated
    activations, so no collective is needed for routing.  Param tree
    mirrors the monolithic :class:`~..models.gpt.GptBlock_MoeMlp`
    (``router``/``w1``..``b2`` under ``mlp``) with tp-local leaf shapes.
    """

    config: Any
    tp: int
    num_experts: int = 8
    top_k: int = 1
    capacity_factor: float = 1.25
    axis_name: str = "tp"
    deterministic: bool = True

    @nn.compact
    def __call__(self, hidden):
        from ..ops.moe import (
            moe_dispatch_combine,
            router_probs,
            top_k_dispatch,
        )

        cfg = GptConfig.from_dict(self.config)
        dtype = jnp.dtype(cfg.dtype)
        act = ACT2FN[cfg.hidden_act]
        E, H = self.num_experts, cfg.hidden_size
        i_local = cfg.intermediate_size // self.tp

        x = nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32, name="ln_2")(
            hidden
        ).astype(dtype)
        B, L, _ = x.shape
        tokens = x.reshape(B * L, H)
        T = B * L
        capacity = max(1, int(np.ceil(T / E * self.capacity_factor)))

        router = self.param(
            "router", nn.initializers.normal(cfg.initializer_range), (H, E),
            jnp.float32,
        )
        init = nn.initializers.normal(cfg.initializer_range)
        w1 = self.param("w1", init, (E, H, i_local), jnp.float32)
        b1 = self.param("b1", nn.initializers.zeros, (E, i_local),
                        jnp.float32)
        w2 = self.param("w2", init, (E, i_local, H), jnp.float32)
        b2 = self.param("b2", nn.initializers.zeros, (E, H), jnp.float32)

        probs = router_probs(tokens, router)
        dispatch, combine, aux = top_k_dispatch(probs, capacity, self.top_k)

        def experts(buf):  # [E, C, H] -> [E, C, H]
            h = act(
                jnp.einsum("ech,ehi->eci", buf, w1.astype(dtype))
                + b1[:, None, :].astype(dtype)
            )
            partial = jnp.einsum("eci,eih->ech", h, w2.astype(dtype))
            full = lax.psum(partial, self.axis_name)
            return full + b2[:, None, :].astype(dtype)

        out = moe_dispatch_combine(tokens, dispatch, combine, experts)
        out = out.reshape(B, L, H).astype(dtype)
        out = nn.Dropout(cfg.dropout_prob)(
            out, deterministic=self.deterministic
        )
        return hidden + out, aux


class TpGptUnit(nn.Module):
    """Megatron-style tensor-parallel GPT block for the pipeline body.

    q/k/v are column-parallel (heads split across tp), the attention output
    projection and the FFN down-projection are row-parallel with a ``psum``;
    LayerNorms and residuals are replicated.  The param tree mirrors
    :class:`GptEncoderUnit` (``attn/q_proj`` etc.) with tp-local leaf
    shapes, so full weights split by pure reshape
    (:func:`split_stage_params_for_tp` with the GPT role sets).
    Deterministic only (the compiled pipeline body never applies dropout).
    """

    config: Any
    tp: int
    axis_name: str = "tp"
    deterministic: bool = True

    @nn.compact
    def __call__(self, hidden, dummy):
        cfg = GptConfig.from_dict(self.config)
        _check_tp_divisibility(cfg, self.tp)
        hidden = _TpGptAttn(self.config, self.tp, self.axis_name,
                            self.deterministic, name="attn")(hidden)
        hidden = _TpGptMlp(self.config, self.tp, self.axis_name,
                           self.deterministic, name="mlp")(hidden)
        return hidden, dummy


class TpGptStage(nn.Module):
    """``units`` tensor-parallel GPT blocks; remat like GptEncoderStage."""

    config: Any
    units: int
    tp: int
    axis_name: str = "tp"
    deterministic: bool = True

    @nn.compact
    def __call__(self, hidden, dummy):
        for u in range(self.units):
            hidden, dummy = nn.remat(TpGptUnit)(
                self.config, self.tp, self.axis_name, self.deterministic,
                name=f"unit_{u}",
            )(hidden, dummy)
        return hidden, dummy


class TpGptMoeStage(nn.Module):
    """``units`` tensor-parallel blocks, every ``moe_every``-th MLP a
    tp-sharded Switch MoE; same stage-local placement rule and side-tensor
    aux accumulation as :class:`GptMoeEncoderStage`, same remat policy as
    :class:`TpGptStage`."""

    config: Any
    units: int
    moe_every: int
    tp: int
    num_experts: int = 8
    top_k: int = 1
    capacity_factor: float = 1.25
    axis_name: str = "tp"
    deterministic: bool = True

    @nn.compact
    def __call__(self, hidden, side):
        if self.moe_every <= 0 or self.units % self.moe_every:
            raise ValueError(
                f"moe_every ({self.moe_every}) must divide units_per_stage "
                f"({self.units}) so the per-stage MoE pattern matches the "
                f"monolithic block placement"
            )
        cfg = GptConfig.from_dict(self.config)
        _check_tp_divisibility(cfg, self.tp)
        outer = self

        class Unit(nn.Module):
            is_moe: bool

            @nn.compact
            def __call__(sf, h, s):
                det = outer.deterministic
                h = _TpGptAttn(outer.config, outer.tp, outer.axis_name,
                               det, name="attn")(h)
                if sf.is_moe:
                    h, aux = _TpGptMoeMlp(
                        outer.config, outer.tp,
                        num_experts=outer.num_experts, top_k=outer.top_k,
                        capacity_factor=outer.capacity_factor,
                        axis_name=outer.axis_name, deterministic=det,
                        name="mlp",
                    )(h)
                    s = s + aux.astype(s.dtype)
                else:
                    h = _TpGptMlp(outer.config, outer.tp, outer.axis_name,
                                  det, name="mlp")(h)
                return h, s

        for u in range(self.units):
            is_moe = (u + 1) % self.moe_every == 0
            hidden, side = nn.remat(Unit)(is_moe, name=f"unit_{u}")(
                hidden, side
            )
        return hidden, side


class CompiledGptPipeline(CompiledBertPipeline):
    """GPT causal LM with blocks pipelined across a ('pp',) / ('dp','pp')
    / ('dp','pp','tp') mesh; inherits the GPipe + interleaved schedules,
    tensor parallelism, ZeRO-1, and the jitted train step from the BERT
    engine."""

    tp_col_modules = GPT_TP_COL
    tp_row_modules = GPT_TP_ROW

    def __init__(self, config, mesh, units_per_stage, *args,
                 moe_every: int = 0, num_experts: int = 8,
                 moe_top_k: int = 1, moe_capacity_factor: float = 1.25,
                 moe_aux_coef: float = 0.01, **kwargs):
        # consumed by _build_modules, which the base ctor calls
        self.moe_every = int(moe_every)
        self.num_experts = int(num_experts)
        self.moe_top_k = int(moe_top_k)
        self.moe_capacity_factor = float(moe_capacity_factor)
        self.moe_aux_coef = float(moe_aux_coef)
        super().__init__(config, mesh, units_per_stage, *args, **kwargs)

    @staticmethod
    def _parse_config(config):
        return GptConfig.from_dict(config)

    def _build_modules(self, units_per_stage: int, num_classes: int) -> None:
        cfg_dict = self.cfg.to_dict()
        det = self.deterministic
        self.embeddings = GptEmbeddings(cfg_dict, deterministic=det)
        if self.moe_every:
            self.stage = GptMoeEncoderStage(
                cfg_dict, units_per_stage, self.moe_every,
                self.num_experts, self.moe_top_k, self.moe_capacity_factor,
                deterministic=det,
            )
            self.side_outputs = True
            # expert tensors join the Megatron role tables (w1/b1 column,
            # w2 row, router/b2 replicated) for both weight splitting and
            # the replicated-gradient guard
            self.tp_col_modules = GPT_MOE_TP_COL
            self.tp_row_modules = GPT_MOE_TP_ROW
            self.tp_stage = (
                TpGptMoeStage(
                    cfg_dict, units_per_stage, self.moe_every, self.tp,
                    self.num_experts, self.moe_top_k,
                    self.moe_capacity_factor, deterministic=det,
                )
                if self.tp > 1 else None
            )
        else:
            self.stage = GptEncoderStage(cfg_dict, units_per_stage,
                                         deterministic=det)
            self.tp_stage = (
                TpGptStage(cfg_dict, units_per_stage, self.tp,
                           deterministic=det)
                if self.tp > 1 else None
            )
        self.lm_head = GptLmHead(cfg_dict, deterministic=det)

    # --- init ----------------------------------------------------------------
    def init(self, rng: jax.Array, input_ids):
        from jax.sharding import NamedSharding

        k_embed, k_stage, k_head = jax.random.split(rng, 3)
        drop = (
            {} if self.deterministic
            else {"dropout": jax.random.fold_in(rng, 99)}
        )
        embed_vars = self.embeddings.init(
            {"params": k_embed, **drop}, input_ids
        )
        hidden = self.embeddings.apply(embed_vars, input_ids,
                                       rngs=drop or None)
        dummy = jnp.zeros((), hidden.dtype)

        def init_one_stage(key):
            return self.stage.init(
                {"params": key, **drop}, hidden, dummy
            )["params"]

        S, V = self.num_stages, self.virtual_stages
        chunk_keys = jax.random.split(k_stage, S * V)
        order = [(p % V) * S + p // V for p in range(S * V)]
        stages = jax.vmap(init_one_stage)(chunk_keys[jnp.asarray(order)])
        if self.tp > 1:
            stages = split_stage_params_for_tp(
                stages, self.tp, self.tp_col_modules, self.tp_row_modules
            )

        head_vars = self.lm_head.init({"params": k_head, **drop}, hidden)
        params = {
            "embeddings": embed_vars["params"],
            "stages": stages,
            "lm_head": head_vars["params"],
        }
        self.param_shardings = {
            "embeddings": NamedSharding(self.mesh, self._repl_spec),
            "stages": self._stage_shardings(stages),
            "lm_head": NamedSharding(self.mesh, self._repl_spec),
        }
        return jax.device_put(params, self.param_shardings)

    # --- full model ----------------------------------------------------------
    def _logits(self, params, input_ids, rng=None):
        rng = self._check_rng(rng)
        sub = (
            (lambda i: None) if rng is None
            else (lambda i: {"dropout": jax.random.fold_in(rng, i)})
        )
        M = self.num_microbatches
        hidden = self.embeddings.apply(
            {"params": params["embeddings"]}, input_ids, rngs=sub(0)
        )
        B = hidden.shape[0]
        if B % M != 0:
            raise ValueError(f"batch {B} not divisible by microbatches {M}")
        if (B // M) % self.dp != 0:
            raise ValueError(
                f"microbatch size {B // M} not divisible by dp={self.dp}"
            )
        hidden_mb = hidden.reshape(M, B // M, *hidden.shape[1:])
        # the ring schedule threads a per-microbatch side tensor; GPT needs
        # none, so ride a batch-shaped zero (batch-like so the dp sharding
        # spec applies to it uniformly).  MoE accumulates its Switch aux
        # scalar into this tensor across every MoE layer — keep that
        # accumulator float32 even under bf16 configs (it is tiny, [M, mb])
        # so the load-balance loss does not lose precision to repeated
        # bf16 rounding; dense stages keep hidden.dtype (pure placeholder).
        side_dtype = jnp.float32 if self.side_outputs else hidden.dtype
        dummy_mb = jnp.zeros((M, B // M), side_dtype)

        aux = None
        encoder = (self._interleaved_encoder if self.virtual_stages > 1
                   else self._pipelined_encoder)
        ring_rng = None if rng is None else jax.random.fold_in(rng, 1)
        encoded = encoder(params["stages"], hidden_mb, dummy_mb,
                          rng=ring_rng)
        if self.side_outputs:
            # the side rides the ring as a per-microbatch aux accumulator
            encoded, side_out = encoded
            aux = side_out.mean()  # avg over microbatches of summed aux
        encoded = encoded.reshape(B, *encoded.shape[2:])
        logits = self.lm_head.apply({"params": params["lm_head"]}, encoded,
                                    rngs=sub(2))
        return (logits, aux) if self.side_outputs else logits

    def loss(self, params, batch, labels, rng=None):
        (input_ids,) = batch if isinstance(batch, tuple) else (batch,)
        out = self._logits(params, input_ids, rng=rng)
        if self.side_outputs:
            logits, aux = out
            return causal_lm_loss(logits, labels) + (
                self.moe_aux_coef * aux.astype(jnp.float32)
            )
        return causal_lm_loss(out, labels)


__all__ = [
    "CompiledGptPipeline",
    "GptEncoderStage",
    "GptEncoderUnit",
    "GptMoeEncoderStage",
    "TpGptMoeStage",
    "TpGptStage",
    "TpGptUnit",
    "GPT_TP_COL",
    "GPT_TP_ROW",
    "GPT_MOE_TP_COL",
    "GPT_MOE_TP_ROW",
]
