"""Compiled SPMD pipeline for the GPT family.

Reuses :class:`~.spmd.CompiledBertPipeline`'s ring-schedule machinery (the
GPipe and interleaved shard_map bodies operate on an opaque ``(hidden,
side)`` pair) with GPT-specific ends: token embeddings in, LM head out,
causal-LM loss.  The pipelined stage flows ``(hidden, dummy)`` — the causal
mask is rebuilt inside each block from shapes, so no side tensor rides the
ring.

This makes the one-jit engine a two-family surface (the reference's engine
was BERT-only end to end — ``scaelum/experiment/config.py:26-49``).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import optax
import flax.linen as nn

from ..models.gpt import (
    GptBlock_Attn,
    GptBlock_Mlp,
    GptConfig,
    GptEmbeddings,
    GptLmHead,
)
from ..ops.losses import causal_lm_loss
from .spmd import CompiledBertPipeline


class GptEncoderUnit(nn.Module):
    """One transformer block (attention + MLP), tuple signature."""

    config: Any

    @nn.compact
    def __call__(self, hidden, dummy):
        hidden = GptBlock_Attn(self.config, deterministic=True,
                               name="attn")(hidden)
        hidden = GptBlock_Mlp(self.config, deterministic=True,
                              name="mlp")(hidden)
        return hidden, dummy


class GptEncoderStage(nn.Module):
    """``units`` rematerialized blocks = one uniform pipeline stage."""

    config: Any
    units: int

    @nn.compact
    def __call__(self, hidden, dummy):
        for u in range(self.units):
            hidden, dummy = nn.remat(GptEncoderUnit)(
                self.config, name=f"unit_{u}"
            )(hidden, dummy)
        return hidden, dummy


class CompiledGptPipeline(CompiledBertPipeline):
    """GPT causal LM with blocks pipelined across a ('pp',) / ('dp','pp')
    mesh; inherits the GPipe + interleaved schedules, ZeRO-1, and the
    jitted train step from the BERT engine."""

    @staticmethod
    def _parse_config(config):
        return GptConfig.from_dict(config)

    def _build_modules(self, units_per_stage: int, num_classes: int) -> None:
        if self.tp > 1:
            raise NotImplementedError(
                "tensor parallelism inside the compiled GPT pipeline is "
                "not wired yet; use the BERT engine or a ('dp','pp') mesh"
            )
        cfg_dict = self.cfg.to_dict()
        self.embeddings = GptEmbeddings(cfg_dict, deterministic=True)
        self.stage = GptEncoderStage(cfg_dict, units_per_stage)
        self.tp_stage = None
        self.lm_head = GptLmHead(cfg_dict, deterministic=True)

    # --- init ----------------------------------------------------------------
    def init(self, rng: jax.Array, input_ids):
        from jax.sharding import NamedSharding

        k_embed, k_stage, k_head = jax.random.split(rng, 3)
        embed_vars = self.embeddings.init({"params": k_embed}, input_ids)
        hidden = self.embeddings.apply(embed_vars, input_ids)
        dummy = jnp.zeros((), hidden.dtype)

        def init_one_stage(key):
            return self.stage.init({"params": key}, hidden, dummy)["params"]

        S, V = self.num_stages, self.virtual_stages
        chunk_keys = jax.random.split(k_stage, S * V)
        order = [(p % V) * S + p // V for p in range(S * V)]
        stages = jax.vmap(init_one_stage)(chunk_keys[jnp.asarray(order)])

        head_vars = self.lm_head.init({"params": k_head}, hidden)
        params = {
            "embeddings": embed_vars["params"],
            "stages": stages,
            "lm_head": head_vars["params"],
        }
        self.param_shardings = {
            "embeddings": NamedSharding(self.mesh, self._repl_spec),
            "stages": jax.tree_util.tree_map(
                lambda _: NamedSharding(self.mesh, self._stage_spec), stages
            ),
            "lm_head": NamedSharding(self.mesh, self._repl_spec),
        }
        return jax.device_put(params, self.param_shardings)

    # --- full model ----------------------------------------------------------
    def _logits(self, params, input_ids):
        M = self.num_microbatches
        hidden = self.embeddings.apply(
            {"params": params["embeddings"]}, input_ids
        )
        B = hidden.shape[0]
        if B % M != 0:
            raise ValueError(f"batch {B} not divisible by microbatches {M}")
        if (B // M) % self.dp != 0:
            raise ValueError(
                f"microbatch size {B // M} not divisible by dp={self.dp}"
            )
        hidden_mb = hidden.reshape(M, B // M, *hidden.shape[1:])
        # the ring schedule threads a per-microbatch side tensor; GPT needs
        # none, so ride a batch-shaped zero (batch-like so the dp sharding
        # spec applies to it uniformly)
        dummy_mb = jnp.zeros((M, B // M), hidden.dtype)

        if self.virtual_stages > 1:
            encoded = self._interleaved_encoder(
                params["stages"], hidden_mb, dummy_mb
            )
        else:
            encoded = self._pipelined_encoder(
                params["stages"], hidden_mb, dummy_mb
            )
        encoded = encoded.reshape(B, *encoded.shape[2:])
        return self.lm_head.apply({"params": params["lm_head"]}, encoded)

    def loss(self, params, batch, labels):
        (input_ids,) = batch if isinstance(batch, tuple) else (batch,)
        logits = self._logits(params, input_ids)
        return causal_lm_loss(logits, labels)


__all__ = ["CompiledGptPipeline", "GptEncoderStage", "GptEncoderUnit"]
