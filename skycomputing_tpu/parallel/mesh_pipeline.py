"""Mesh-native stage execution: per-stage NamedSharding programs on one
global device order.

The MPMD engine (:mod:`.pipeline`) drives one program per DEVICE per
microbatch from its Python issue loop — on small steps 75-88% of the
step is host dispatch (BENCH_pr2_hotpath.json), and a stage can never
span more than one chip.  This engine keeps the paper's unequal
layer->stage allocation but runs every stage as ONE ``jax.jit`` program
placed on a contiguous sub-mesh slice of the global device order
(:func:`.mesh.stage_submeshes`): 1..K chips per stage with named
``('dp', 'tp')`` axes inside the stage, parameters replicated over the
sub-mesh via ``NamedSharding(mesh, P())`` and microbatch rows sharded
over ``'dp'``.  What changes relative to the per-device loop:

- **dispatch collapses from O(devices) to O(stages) per microbatch
  tick** — chips-per-stage becomes an allocator output
  (``dynamics.solver.solve_mesh_shapes``) instead of a hardcoded 1, the
  per-(microbatch, stage) rng table is built by ONE jitted fold per step
  and committed per stage (M x S host folds become 1 program + S puts,
  identical threefry bits), and backward + gradient accumulation fuse
  into one program per (microbatch, stage);
- **activation handoff is device_put-to-sharding**: the schedules'
  ``device_put_elided`` calls target the next stage's input
  ``NamedSharding`` — XLA owns placement and layout, one batched put per
  boundary, elision when producer and consumer share a sharding.  The
  hand-rolled transfer-elision/donation counters stay as observability
  over the new path;
- **the schedules are unchanged**: this class subclasses
  :class:`~.pipeline.PipelineModel` and reuses its gpipe/1f1b issue
  loops verbatim — on the same allocation at one chip per stage the two
  engines produce bitwise-identical gradients and parameters (gated in
  ``BENCH_mesh_pipeline.json`` and ``tests/test_mesh_pipeline.py``).

Chips-per-stage comes from the workers' ``extra_config['mesh_chips']``
(written by ``Allocator.mesh_allocate``) or an explicit
``chips_per_stage`` argument; stages take contiguous device blocks in
pipeline order.  Sub-mesh programs run their chips in lockstep, so the
mesh engine targets homogeneous pods — per-device heterogeneity remains
the MPMD engine's domain (see docs/design.md's decision table).
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from functools import partial

from ..builder import as_tuple
from .mesh import stage_submeshes
from .pipeline import (
    _DISPATCH_STATS,
    PipelineModel,
    StageRuntime,
    _donation_enabled,
    _StagePrograms,
    cached_programs,
    device_put_elided,
)


@partial(jax.jit, static_argnums=(1, 2))
def _fold_table(rng, M: int, S: int):
    """All M x S per-(microbatch, stage) keys in one program — the same
    pair-fold threefry math as ``pipeline._fold2``, M x S fewer
    dispatches."""
    return [
        [
            jax.random.fold_in(jax.random.fold_in(rng, m), k)
            for k in range(S)
        ]
        for m in range(M)
    ]


class _MeshStagePrograms(_StagePrograms):
    """``_StagePrograms`` plus the fused backward+accumulate programs.

    The fwd/bwd/update math is the PARENT's raw closures verbatim (one
    definition — the bitwise-equivalence contract between the engines
    cannot drift), with two mesh-specific notes: the rng operand is a
    plain key PRE-COMMITTED to the stage's sub-mesh by the engine's
    per-step rng table (an uncommitted key pays a per-call resharding
    transfer ~7x the program's own dispatch cost on the multi-device
    path), and ``bwd_acc`` fuses gradient accumulation so one program
    per (microbatch, stage) covers what the MPMD engine issues as two.
    Placement comes from the COMMITTED operands (params/inputs carry
    their stage's NamedSharding), so one program object serves every
    stage with this structure — jit caches one executable per distinct
    sub-mesh.
    """

    def __init__(self, layer_cfgs, optimizer):
        super().__init__(layer_cfgs, optimizer)
        bwd, bwd_params_only = self._raw_bwd, self._raw_bwd_params_only

        # fused backward + accumulate: `total is None` is static per
        # pytree structure, so the first microbatch traces the no-add
        # variant and later microbatches the adding one — two traces of
        # ONE function, still one invocation per (microbatch, stage).
        # The adds are the same elementwise jnp.add the MPMD grad_add
        # program runs, so accumulation order (and bits) are identical.
        def bwd_acc(params, inputs, rng, dy, total):
            dparams, dx = bwd(params, inputs, rng, dy)
            if total is not None:
                dparams = jax.tree_util.tree_map(jnp.add, total, dparams)
            return dparams, dx

        def bwd_acc_params_only(params, inputs, rng, dy, total):
            dparams = bwd_params_only(params, inputs, rng, dy)
            if total is not None:
                dparams = jax.tree_util.tree_map(jnp.add, total, dparams)
            return dparams, None

        # donation invariants as in pipeline.py: the stored input tuple
        # dies when its backward issues, the running total is rebound to
        # the fused program's output; dy is never donated (shared cached
        # zero tail).  The parent's undonated bwd/bwd_params_only twins
        # remain the profiling programs (measure_stage_times re-executes
        # with the same buffers).
        if _donation_enabled():
            self.bwd_acc = jax.jit(bwd_acc, donate_argnums=(1, 4))
            self.bwd_acc_params_only = jax.jit(
                bwd_acc_params_only, donate_argnums=(1, 4)
            )
        else:
            self.bwd_acc = jax.jit(bwd_acc)
            self.bwd_acc_params_only = jax.jit(bwd_acc_params_only)


def get_mesh_stage_programs(layer_cfgs, optimizer) -> _MeshStagePrograms:
    """Mesh-native twin of ``get_stage_programs`` — shares the bounded
    process-global LRU (and its hit/miss counters) under a ``"mesh"``
    key prefix, so the two engines' program structures compete for the
    same capped executable budget."""
    key = (
        "mesh",
        json.dumps(list(layer_cfgs), sort_keys=True, default=str),
        id(optimizer),
        _donation_enabled(),
    )
    return cached_programs(
        key, lambda: _MeshStagePrograms(layer_cfgs, optimizer)
    )


class MeshStageRuntime(StageRuntime):
    """One mesh-native stage: layer slice + contiguous sub-mesh + one
    compiled program per phase, placed by ``NamedSharding``.

    ``device`` IS the stage's input sharding (microbatch rows over
    ``'dp'``): the schedule loops hand activations off with
    ``device_put_elided(acts, stage.device)``, so the same loops drive
    device-committed (MPMD) and sharding-committed (mesh) stages.
    """

    def __init__(
        self,
        stage_index: int,
        layer_cfgs: Sequence[Dict],
        params: Sequence[Any],
        submesh,
        optimizer: optax.GradientTransformation,
        slowdown: float = 1.0,
        differentiable_inputs: bool = True,
    ):
        self.stage_index = stage_index
        self.mesh = submesh
        self.num_layers = len(layer_cfgs)
        self.dp = int(submesh.shape["dp"])
        self.tp = int(submesh.shape["tp"])
        self.param_sharding = NamedSharding(submesh, P())
        self.batch_sharding = NamedSharding(submesh, P("dp"))
        self.device = self.batch_sharding
        devs = list(submesh.devices.flatten())
        # keep the "stage N" prefix: tools/trace_report.py keys stage
        # utilization on it
        self.lane_name = (
            f"stage {stage_index} [{devs[0]}x{len(devs)} dp={self.dp}"
            f" tp={self.tp}]"
        )
        self.slowdown = float(slowdown)
        self._differentiable_inputs = differentiable_inputs
        self.config_key = json.dumps(list(layer_cfgs), sort_keys=True,
                                     default=str)

        programs = get_mesh_stage_programs(layer_cfgs, optimizer)
        self.stack = programs.stack
        self._fwd = programs.fwd
        self._bwd = programs.bwd
        self._bwd_params_only = programs.bwd_params_only
        self._bwd_acc = programs.bwd_acc
        self._bwd_acc_params_only = programs.bwd_acc_params_only
        self._update = programs.update
        self._optimizer = optimizer

        self.params: List[Any] = jax.device_put(
            list(params), self.param_sharding
        )
        self.opt_state = jax.device_put(
            optimizer.init(self.params), self.param_sharding
        )

    # --- execution ----------------------------------------------------------
    def forward_placed(self, inputs, rng):
        _DISPATCH_STATS["programs"] += 1
        out = self._fwd(self.params, inputs, rng)
        self._emulate_slowdown(out)
        return out

    def backward_accumulate(self, total, inputs, rng, dy):
        """ONE fused program: backward for this microbatch plus
        accumulation into the running grad total (vs the MPMD engine's
        bwd + grad_add pair) — same values, same bits, half the issue
        calls."""
        dy = device_put_elided(dy, self.device)
        _DISPATCH_STATS["programs"] += 1
        if self._differentiable_inputs:
            new_total, dx = self._bwd_acc(
                self.params, inputs, rng, dy, total
            )
        else:
            new_total, dx = self._bwd_acc_params_only(
                self.params, inputs, rng, dy, total
            )
        self._emulate_slowdown(new_total)
        return new_total, dx

    def backward(self, inputs, rng, dy):  # pragma: no cover - guard
        raise NotImplementedError(
            "mesh stages fuse backward+accumulate; drive them through "
            "backward_accumulate (the schedule loops do)"
        )

    def accumulate(self, total, grads):  # pragma: no cover - guard
        raise NotImplementedError(
            "mesh stages fuse backward+accumulate; drive them through "
            "backward_accumulate (the schedule loops do)"
        )

    # --- weights exchange ---------------------------------------------------
    def load_weights(self, state_dict_list: Sequence[Any]) -> None:
        if len(state_dict_list) != self.num_layers:
            raise ValueError(
                f"stage {self.stage_index} holds {self.num_layers} layers, "
                f"got {len(state_dict_list)} state dicts"
            )
        self.params = jax.device_put(
            list(state_dict_list), self.param_sharding
        )
        self.opt_state = jax.device_put(
            self._optimizer.init(self.params), self.param_sharding
        )


class MeshPipelineModel(PipelineModel):
    """The mesh-native pipeline: stage runtimes on sub-mesh slices.

    Same constructor contract as :class:`~.pipeline.PipelineModel`
    (stage slices come from the worker manager's allocation; parameters
    from the layer-indexed parameter server), plus chips-per-stage:
    read from each staged worker's ``extra_config['mesh_chips']`` when
    present (the ``Allocator.mesh_allocate`` /
    ``refine_mesh_allocation`` output — ``rebuild()`` re-reads it, so a
    mesh reshape applies through the same verify-then-apply rebuild path
    as an MPMD re-allocation), else from the ``chips_per_stage``
    argument, else one chip per stage.  Devices are consumed as
    contiguous blocks of ``devices`` in pipeline order.
    """

    def __init__(
        self,
        worker_manager,
        parameter_server,
        optimizer: optax.GradientTransformation,
        loss_fn,
        devices: Optional[Sequence[Any]] = None,
        num_microbatches: int = 1,
        schedule: str = "gpipe",
        chips_per_stage: Optional[Sequence[int]] = None,
        tp: int = 1,
    ):
        self._chips_override = (
            [int(k) for k in chips_per_stage]
            if chips_per_stage is not None else None
        )
        self._tp = int(tp)
        super().__init__(
            worker_manager, parameter_server, optimizer, loss_fn,
            devices=devices, num_microbatches=num_microbatches,
            schedule=schedule,
        )

    # --- construction -------------------------------------------------------
    def _build_stages(self) -> None:
        self.stages = []
        workers = sorted(
            self._worker_manager.worker_pool, key=lambda w: w.rank
        )
        staged = [w for w in workers if w.model_config]
        if any("mesh_chips" in w.extra_config for w in staged):
            # the allocator owns the mesh shape: a reshape rewrites
            # extra_config and rebuild() picks it up here
            chips = [
                int(w.extra_config.get("mesh_chips", 1)) for w in staged
            ]
        elif self._chips_override is not None:
            chips = list(self._chips_override)
            if len(chips) != len(staged):
                raise ValueError(
                    f"chips_per_stage has {len(chips)} entries for "
                    f"{len(staged)} staged workers"
                )
        else:
            chips = [1] * len(staged)
        meshes = stage_submeshes(chips, self._devices, tp=self._tp)
        layer_cursor = 0
        for i, (worker, submesh) in enumerate(zip(staged, meshes)):
            layer_cfgs = worker.model_config
            params = self._parameter_server.get_layer_slice(
                layer_cursor, layer_cursor + len(layer_cfgs)
            )
            self.stages.append(
                MeshStageRuntime(
                    stage_index=i,
                    layer_cfgs=layer_cfgs,
                    params=params,
                    submesh=submesh,
                    optimizer=self._optimizer,
                    slowdown=float(worker.extra_config.get("slowdown", 1.0)),
                    differentiable_inputs=i > 0,
                )
            )
            layer_cursor += len(layer_cfgs)
        if layer_cursor != self._parameter_server.num_layers:
            raise ValueError(
                f"workers cover {layer_cursor} layers but the model has "
                f"{self._parameter_server.num_layers} — run an allocator "
                f"first"
            )

    @property
    def chips_per_stage(self) -> List[int]:
        """Chips owned by each stage, pipeline order (dp x tp)."""
        return [s.dp * s.tp for s in self.stages]

    # --- execution ----------------------------------------------------------
    def _step_rngs(self, rng, M: int, S: int):
        """The whole (microbatch, stage) key table in ONE jitted fold,
        then one batched put per stage committing its column replicated
        onto the stage's sub-mesh.

        Two costs die here: the MPMD path's M x S per-cell fold
        dispatches become 1 + S, and — the expensive one — stage
        programs never see an UNCOMMITTED key operand (each call would
        pay a resharding transfer onto the sub-mesh ~7x the program's
        own dispatch cost).  The fold math is the same
        ``fold_in(fold_in(rng, m), k)`` pair-fold, so seeded runs replay
        the MPMD engine's masks bit-for-bit.
        """
        _DISPATCH_STATS["programs"] += 1
        table = _fold_table(rng, M, S)
        columns = []
        for k, stage in enumerate(self.stages):
            _DISPATCH_STATS["puts"] += 1
            columns.append(jax.device_put(
                [table[m][k] for m in range(M)], stage.param_sharding
            ))
        return [[columns[k][m] for k in range(S)] for m in range(M)]

    def compute_gradients(self, data, labels, rng=None, block: bool = True):
        leaves = jax.tree_util.tree_leaves(as_tuple(data))
        # np.shape reads host metadata only — no device sync
        rows = np.shape(leaves[0])[0] // max(self.num_microbatches, 1)
        bad = [s for s in self.stages if rows % s.dp]
        if bad:
            raise ValueError(
                f"microbatch rows {rows} not divisible by stage "
                f"{bad[0].stage_index}'s dp={bad[0].dp} — pick "
                f"num_microbatches/chips so every stage's dp divides "
                f"the microbatch"
            )
        return super().compute_gradients(data, labels, rng, block)


__all__ = [
    "MeshPipelineModel",
    "MeshStageRuntime",
    "get_mesh_stage_programs",
]
