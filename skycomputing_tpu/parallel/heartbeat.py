"""Peer liveness detection for the multi-host world.

The reference detects trainer failure with RPC timeouts around
``dist.rpc`` calls; under a JAX multi-process world there is no RPC — the
failure mode is a *collective that never completes* because a peer died or
wedged.  The detector is therefore a tiny global all-reduce ("beat")
issued at a safe synchronization point (every process beats at the same
iteration), watched by a timer thread that never touches the device: if
the beat neither completes nor raises within ``timeout_s``, the peer
world is declared failed.

Design notes (TPU/XLA):
- the beat is a jitted replicated-sum over every device in the world —
  one scalar per device, so it costs one DCN/ICI latency, not bandwidth;
- the watchdog only OBSERVES (logs + optional abort): a wedged XLA
  collective cannot be cancelled from Python, so recovery is process
  restart, exactly like the reference's torch RPC world after a peer
  loss;
- a raised exception from the runtime (the coordination service notices
  dead clients) counts as detection too, not a crash.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Optional

import numpy as np
import jax
import jax.numpy as jnp

from ..utils.logger import Logger


class PeerHeartbeat:
    """Detect dead/stalled peers with timed global all-reduces.

    ``beat()`` is collective: EVERY process in the world must call it at
    the same logical point (e.g. the same training iteration), or the
    beat itself becomes the stall it is trying to detect.
    """

    def __init__(
        self,
        timeout_s: float = 60.0,
        on_failure: Optional[Callable[[str], None]] = None,
        abort_on_failure: bool = False,
        abort_exit_code: int = 17,
        logger: Optional[Logger] = None,
    ):
        self.timeout_s = float(timeout_s)
        self.failed = False
        self.last_beat_s: Optional[float] = None
        self.beats = 0
        self._logger = logger or Logger()
        self._on_failure = on_failure
        self._abort = bool(abort_on_failure)
        self._abort_exit_code = int(abort_exit_code)
        self._beat_fn = None

    def _build(self):
        from jax.sharding import NamedSharding, PartitionSpec as P

        from .multihost import global_mesh

        world = len(jax.devices())
        mesh = global_mesh(("all",), (world,))
        ones = jax.make_array_from_callback(
            (world,), NamedSharding(mesh, P("all")),
            lambda idx: np.ones((1,), np.float32),
        )
        fn = jax.jit(
            lambda x: x.sum(), out_shardings=NamedSharding(mesh, P())
        )
        self._expected = float(world)
        self._ones = ones
        self._beat_fn = fn
        # warm the executable so the first timed beat measures the
        # collective, not compilation
        jax.block_until_ready(fn(ones))

    def _fail(self, reason: str) -> None:
        self.failed = True
        self._logger.info(f"peer heartbeat FAILED: {reason}")
        if self._on_failure is not None:
            self._on_failure(reason)
        if self._abort:
            # a wedged collective cannot be cancelled; die so the
            # scheduler can restart the world
            os._exit(self._abort_exit_code)

    def beat(self) -> bool:
        """One timed global all-reduce; returns True when peers are live.

        The lazy first-call ``_build()`` (compile + warm-up collective)
        runs INSIDE the watchdog window too — a peer that died before the
        first beat wedges the warm-up exactly like a regular beat.
        """
        fired_this_beat = threading.Event()
        was_failed = self.failed

        def on_timeout():
            fired_this_beat.set()
            self._fail(
                f"collective did not complete within {self.timeout_s}s "
                f"(a peer process is dead or wedged)"
            )

        timer = threading.Timer(self.timeout_s, on_timeout)
        timer.daemon = True
        start = time.perf_counter()
        timer.start()
        try:
            if self._beat_fn is None:
                self._build()
            total = float(jax.block_until_ready(self._beat_fn(self._ones)))
        except Exception as exc:  # runtime noticed a dead peer
            timer.cancel()
            self._fail(f"collective raised: {exc!r}")
            return False
        timer.cancel()
        self.last_beat_s = time.perf_counter() - start
        self.beats += 1
        if not was_failed and fired_this_beat.is_set() and total == self._expected:
            # THIS beat's watchdog fired but the collective then completed
            # with the right sum — transient slowness (a one-off compile,
            # a DCN hiccup), not a dead peer.  Clear the latch so one blip
            # cannot permanently poison ``beat()``; ``on_failure`` has
            # already fired once for the blip (and with
            # ``abort_on_failure`` the process never reaches this line).
            # A failure latched by a PREVIOUS beat (wrong sum, exception)
            # is deliberately NOT cleared: ``was_failed`` is snapshotted
            # before the timer starts, so only the per-beat watchdog blip
            # is recoverable.
            self._logger.info(
                "peer heartbeat recovered: collective completed after the "
                f"watchdog fired ({self.last_beat_s:.1f}s > "
                f"{self.timeout_s}s timeout)"
            )
            self.failed = False
            return True
        if self.failed:
            return False  # a previous beat detected a real failure
        if total != self._expected:
            self._fail(
                f"beat sum {total} != world size {self._expected} "
                f"(device dropped mid-collective?)"
            )
            return False
        return True


__all__ = ["PeerHeartbeat"]
