"""Tensor parallelism for the monolithic model path (GSPMD).

Megatron-style intra-layer sharding expressed the XLA-native way: instead of
hand-writing column/row-parallel matmuls with explicit all-reduces, the
parameter pytree is annotated with shardings — attention QKV/output and FFN
up/down projections split over a ``('tp',)`` mesh axis — and GSPMD derives
the computation partitioning and inserts the collectives.  The classic
Megatron pairing falls out of the annotations: the FFN up-projection is
column-sharded and the down-projection row-sharded, so the only
communication per block is the all-reduce after the row-parallel matmuls.

This path covers the monolithic (non-pipelined) model.  TP *inside* the
shard_map pipeline — manual psums in the stage body — lives in
:mod:`.spmd` (``TpEncoderStage`` / ``_TpDense``) and composes with the
compiled GPipe/interleaved schedules there; this module remains the
GSPMD-annotated alternative for monolithic models.
"""

from __future__ import annotations

import re
from typing import Any, Optional, Sequence

import jax
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..builder import LayerStack
from .mesh import make_1d_mesh

# module-name patterns -> PartitionSpec for 2-D kernels [in, out].
# column-parallel (split output features): QKV projections, FFN up.
# row-parallel (split input features): attention output proj, FFN down.
_COLUMN = re.compile(r"(query|key|value|dense_act|c_fc|q_proj|k_proj|v_proj)$")
_ROW = re.compile(r"(dense|c_proj)$")


def make_tp_mesh(tp: int, devices: Optional[Sequence] = None) -> Mesh:
    return make_1d_mesh(tp, "tp", devices)


def _spec_for(path: str, leaf) -> P:
    if getattr(leaf, "ndim", 0) != 2 or not path.endswith("/kernel"):
        return P()  # biases, norms, embeddings stay replicated
    module_path = path[: -len("/kernel")]
    if _COLUMN.search(module_path):
        return P(None, "tp")  # split output features
    if _ROW.search(module_path):
        return P("tp", None)  # split input features
    return P()


def tp_shardings(params_list, mesh: Mesh):
    """Per-leaf NamedShardings for a LayerStack params list."""

    def one_layer(params):
        flat, treedef = jax.tree_util.tree_flatten_with_path(params)
        specs = []
        for path, leaf in flat:
            path_str = "/".join(
                getattr(k, "key", str(k)) for k in path
            )
            specs.append(NamedSharding(mesh, _spec_for(path_str, leaf)))
        return jax.tree_util.tree_unflatten(treedef, specs)

    return [one_layer(p) for p in params_list]


def shard_params(params_list, mesh: Mesh):
    """Place a LayerStack params list under tensor-parallel shardings."""
    return jax.device_put(params_list, tp_shardings(params_list, mesh))


def tp_train_step_fn(stack: LayerStack, loss_fn, optimizer):
    """A jittable (params, opt_state, batch, labels) -> updated step for a
    monolithic model whose params carry TP shardings.

    GSPMD propagates the parameter shardings through forward, backward, and
    the optimizer update; gradients inherit the param shardings, so the
    optimizer state stays sharded too.
    """

    def step(params_list, opt_state, batch, labels):
        def loss(params_list):
            logits = stack.apply(params_list, *batch)
            return loss_fn(logits, labels)

        loss_val, grads = jax.value_and_grad(loss)(params_list)
        updates, opt_state = optimizer.update(grads, opt_state, params_list)
        new_params = optax.apply_updates(params_list, updates)
        return new_params, opt_state, loss_val

    return jax.jit(step, donate_argnums=(0, 1))


__all__ = [
    "make_tp_mesh",
    "tp_shardings",
    "shard_params",
    "tp_train_step_fn",
]
