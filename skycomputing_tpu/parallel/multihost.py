"""Multi-host scale-out: the DCN-facing side of the communication backend.

The reference's cluster bring-up is Slurm ranks + a HOST rendezvous file +
``rpc.init_rpc`` (``experiment/launch.py:20-46``, ``experiment/ip.py``).  The
JAX-native equivalent is ``jax.distributed.initialize``: each host process
joins a coordination service, after which ``jax.devices()`` spans the whole
pod and every mesh built in this package — pp stages, dp replicas, sp rings —
extends across hosts with XLA routing collectives over ICI within a slice
and DCN between slices.  No other code in the framework changes: meshes are
built from ``jax.devices()`` either way.

This module cannot be exercised on single-host CI; it is deliberately thin
glue over public JAX APIs, with environment-driven configuration matching
the launchers of common schedulers (Slurm/GKE set these variables).
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import numpy as np
import jax
from jax.sharding import Mesh


def initialize_from_env(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Join (or skip) the multi-host world based on env/args.

    Reads ``SKYTPU_COORDINATOR`` (host:port), ``SKYTPU_NUM_PROCESSES``,
    ``SKYTPU_PROCESS_ID`` — falling back to the Slurm variables the
    reference used (``SLURM_NPROCS`` / ``SLURM_PROCID``).  Returns True when
    a multi-process world was initialized, False for the single-process
    case (no coordinator configured).
    """
    global _initialized
    coordinator_address = coordinator_address or os.getenv(
        "SKYTPU_COORDINATOR"
    )
    if coordinator_address is None:
        return False
    if _initialized:
        # jax.distributed.initialize may be called exactly once; this glue
        # is env-driven call-anywhere, so repeat calls are no-ops
        return True

    num_processes = num_processes if num_processes is not None else int(
        os.getenv("SKYTPU_NUM_PROCESSES", os.getenv("SLURM_NPROCS", "1"))
    )
    process_id = process_id if process_id is not None else int(
        os.getenv("SKYTPU_PROCESS_ID", os.getenv("SLURM_PROCID", "0"))
    )

    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    _initialized = True
    return True


_initialized = False


def global_mesh(axis_names: Sequence[str], axis_sizes: Sequence[int]) -> Mesh:
    """A mesh over ALL devices in the (possibly multi-host) world.

    Axis order is (outer..inner); put the communication-heavy axis last so
    its collectives ride ICI neighbors within a host's slice and only the
    outer axes cross DCN.
    """
    devices = np.asarray(jax.devices())
    want = int(np.prod(axis_sizes))
    if devices.size < want:
        raise ValueError(
            f"mesh {dict(zip(axis_names, axis_sizes))} needs {want} devices, "
            f"world has {devices.size}"
        )
    grid = devices[:want].reshape(tuple(axis_sizes))
    return Mesh(grid, axis_names=tuple(axis_names))


def is_coordinator() -> bool:
    """True on the process that should write checkpoints/logs (rank 0)."""
    return jax.process_index() == 0


__all__ = ["initialize_from_env", "global_mesh", "is_coordinator"]
