"""Ring attention: exact attention over sequence-sharded inputs.

Long-context capability the reference lacks entirely (its attention is
vanilla O(L^2) full softmax, ``scaelum/model/bert_layers.py:249-275``, with
seq fixed at 128).  Here the sequence axis is sharded across a ``('sp',)``
mesh axis; each device keeps its query block resident while key/value blocks
rotate around the ring via ``lax.ppermute`` over ICI neighbor links, and
softmax is accumulated online (flash-attention style running max / running
sum in float32), so attention over a sequence of length L costs O(L/S) HBM
per chip and never materializes the full score matrix.

The rotation count equals the ring size, communication is neighbor-only
(bandwidth-optimal on a TPU torus), and the whole thing is differentiable —
``jax.grad`` through the scan + ppermute yields the reverse ring
automatically.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from .compat import shard_map as _shard_map


def _online_block_update(o, m, l, scores, v_blk):
    """Fold one block of scores/values into the running softmax state."""
    blk_max = jnp.max(scores, axis=-1)                       # [B, H, Lq]
    new_m = jnp.maximum(m, blk_max)
    correction = jnp.exp(m - new_m)
    p = jnp.exp(scores - new_m[..., None])                   # [B, H, Lq, Lk]
    l = l * correction + jnp.sum(p, axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p, v_blk)
    o = o * correction.transpose(0, 2, 1)[..., None] + pv
    return o, new_m, l


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    axis_name: str = "sp",
    causal: bool = False,
    scale: Optional[float] = None,
    bias: Optional[jax.Array] = None,
) -> jax.Array:
    """Exact attention with q/k/v sharded on the sequence axis.

    Args:
        q, k, v: [batch, seq, heads, head_dim], sharded on ``seq`` over
            ``axis_name`` (global views; shard_map slices them).
        causal: apply a causal mask using *global* positions.
        bias: optional additive per-key bias [batch, seq] (padding masks,
            BERT's ``(1-mask)*-1e4``), sharded on ``seq`` like k; rotated
            around the ring alongside the key/value blocks.

    Returns [batch, seq, heads, head_dim], sequence-sharded like q.
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    S = int(mesh.shape[axis_name])
    ring = [(i, (i + 1) % S) for i in range(S)]
    has_bias = bias is not None

    def local_fn(q_blk, k_blk, v_blk, bias_blk):
        # local shapes: [B, Lb, H, D]; bias [B, Lb]
        idx = lax.axis_index(axis_name)
        B, Lb, H, D = q_blk.shape
        q_f32 = q_blk.astype(jnp.float32) * scale

        o = jnp.zeros((B, Lb, H, D), jnp.float32)
        m = jnp.full((B, H, Lb), -jnp.inf, jnp.float32)
        l = jnp.zeros((B, H, Lb), jnp.float32)

        q_pos = idx * Lb + jnp.arange(Lb)

        def step(carry, i):
            o, m, l, k_cur, v_cur, b_cur = carry
            scores = jnp.einsum(
                "bqhd,bkhd->bhqk", q_f32, k_cur.astype(jnp.float32)
            )
            if has_bias:
                scores = scores + b_cur.astype(jnp.float32)[:, None, None, :]
            if causal:
                # after i rotations this device holds the block that
                # originated on device (idx - i) mod S
                src = jnp.mod(idx - i, S)
                k_pos = src * Lb + jnp.arange(Lb)
                allowed = q_pos[:, None] >= k_pos[None, :]
                scores = jnp.where(allowed[None, None], scores, -jnp.inf)
            o2, m2, l2 = _online_block_update(o, m, l, scores, v_cur)
            k_nxt = lax.ppermute(k_cur, axis_name, ring)
            v_nxt = lax.ppermute(v_cur, axis_name, ring)
            # rotate the bias with its key block only when one exists — a
            # dummy bias would cost a real collective per ring step
            b_nxt = (
                lax.ppermute(b_cur, axis_name, ring) if has_bias else b_cur
            )
            return (o2, m2, l2, k_nxt, v_nxt, b_nxt), None

        (o, m, l, _, _, _), _ = lax.scan(
            step, (o, m, l, k_blk, v_blk, bias_blk), jnp.arange(S)
        )
        # fully-masked rows (causal, early global positions) have l == 0
        denom = jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
        return (o / denom).astype(q_blk.dtype)

    seq_spec = P(None, axis_name, None, None)
    bias_spec = P(None, axis_name)
    if not has_bias:
        # zero-size placeholder keeps one code path; it is never read or
        # permuted (has_bias is trace-time static)
        bias = jnp.zeros((q.shape[0], q.shape[1]), jnp.float32)
    return _shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(seq_spec, seq_spec, seq_spec, bias_spec),
        out_specs=seq_spec,
        check_vma=False,
    )(q, k, v, bias)


def full_attention_reference(q, k, v, causal=False, scale=None, bias=None):
    """Single-device O(L^2) reference for testing."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    scores = jnp.einsum(
        "bqhd,bkhd->bhqk", q.astype(jnp.float32) * scale,
        k.astype(jnp.float32),
    )
    if bias is not None:
        scores = scores + bias.astype(jnp.float32)[:, None, None, :]
    if causal:
        L = q.shape[1]
        allowed = jnp.tril(jnp.ones((L, L), bool))
        scores = jnp.where(allowed[None, None], scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v).astype(q.dtype)


__all__ = ["ring_attention", "full_attention_reference"]
