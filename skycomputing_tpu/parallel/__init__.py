from .data_parallel import DataParallelPipeline
from .expert_parallel import ep_shardings, make_ep_mesh, shard_moe_params
from .mesh import (
    make_dp_pp_mesh,
    make_dp_pp_tp_mesh,
    make_pipeline_mesh,
    stage_submeshes,
)
from .mesh_pipeline import MeshPipelineModel, MeshStageRuntime
from .heartbeat import PeerHeartbeat
from .multihost import global_mesh, initialize_from_env, is_coordinator
from .ring_attention import full_attention_reference, ring_attention
from .tensor_parallel import (
    make_tp_mesh,
    shard_params,
    tp_shardings,
    tp_train_step_fn,
)
from .spmd_gpt import CompiledGptPipeline
from .ulysses import ulysses_attention
from .pipeline import (
    PipelineModel,
    PipelineStats,
    StageRuntime,
    clear_program_cache,
    device_put_elided,
    hotpath_counters,
    xla_compile_count,
)

__all__ = [
    "DataParallelPipeline",
    "CompiledGptPipeline",
    "ep_shardings",
    "make_ep_mesh",
    "shard_moe_params",
    "make_dp_pp_mesh",
    "make_dp_pp_tp_mesh",
    "make_pipeline_mesh",
    "stage_submeshes",
    "MeshPipelineModel",
    "MeshStageRuntime",
    "PipelineModel",
    "PipelineStats",
    "StageRuntime",
    "clear_program_cache",
    "device_put_elided",
    "hotpath_counters",
    "xla_compile_count",
    "global_mesh",
    "PeerHeartbeat",
    "initialize_from_env",
    "is_coordinator",
    "ring_attention",
    "full_attention_reference",
    "ulysses_attention",
    "make_tp_mesh",
    "shard_params",
    "tp_shardings",
    "tp_train_step_fn",
]
