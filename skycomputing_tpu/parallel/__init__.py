from .mesh import make_dp_pp_mesh, make_pipeline_mesh
from .pipeline import (
    PipelineModel,
    PipelineStats,
    StageRuntime,
    clear_program_cache,
)

__all__ = [
    "make_dp_pp_mesh",
    "make_pipeline_mesh",
    "PipelineModel",
    "PipelineStats",
    "StageRuntime",
    "clear_program_cache",
]
