"""Compiled SPMD pipeline: GPipe over a ('pp',) mesh in ONE jitted program.

This is the homogeneous-cluster fast path, complementary to the host-driven
MPMD engine in :mod:`.pipeline`:

- the MPMD engine supports *unequal* stages (the allocator's whole point)
  and re-slices without recompiling unmoved stages;
- this SPMD engine requires uniform stages but compiles the ENTIRE training
  step — forward, pipelined microbatch schedule, backward, optimizer — into
  a single XLA program over a ``jax.sharding.Mesh``, with stage-to-stage
  activation handoff as ``lax.ppermute`` over ICI neighbor links and
  per-stage parameters sharded on the ``pp`` mesh axis (leading-axis stack).

The schedule is classic GPipe fill-drain: with S stages and M microbatches
the shard_map body scans T = M + S - 1 ticks; at tick t, stage s computes
microbatch ``t - s`` (bubble ticks compute-and-discard).  Backward is just
``jax.grad`` through the scan — ppermute transposes to the reverse
permutation, so XLA derives the reverse schedule automatically; no
distributed autograd machinery exists anywhere (the reference needed
torch.distributed.autograd + DistributedOptimizer for this,
``scaelum/runner/runner.py:127-139``).

Non-repeated ends (embeddings / pooler / classifier) run replicated outside
the pipelined block.  Dropout is disabled in this path (deterministic
pipeline body); the MPMD engine handles stochastic training.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import flax.linen as nn

from ..models.bert import (
    BertEmbeddings,
    BertLayer_Body,
    BertLayer_Head,
    BertLayer_Tail,
    BertPooler,
    BertTailForClassification,
)
from ..models.bert_config import BertConfig


class EncoderUnit(nn.Module):
    """One full encoder trio (attention + FFN)."""

    config: Any

    @nn.compact
    def __call__(self, hidden, mask):
        hidden, mask = BertLayer_Head(self.config, True, name="head")(
            hidden, mask
        )
        inter, attn, mask = BertLayer_Body(self.config, True, name="body")(
            hidden, mask
        )
        hidden, mask = BertLayer_Tail(self.config, True, name="tail")(
            inter, attn, mask
        )
        return hidden, mask


class EncoderStage(nn.Module):
    """``units`` encoder trios = one uniform pipeline stage.

    Each unit is rematerialized: through the GPipe scan the backward pass
    otherwise stores every tick's intermediate activations (attention
    scores context, FFN up-projection); with remat only each unit's input
    survives to the backward, bounding per-tick residency at one hidden
    block per unit.
    """

    config: Any
    units: int

    @nn.compact
    def __call__(self, hidden, mask):
        for u in range(self.units):
            hidden, mask = nn.remat(EncoderUnit)(
                self.config, name=f"unit_{u}"
            )(hidden, mask)
        return hidden, mask


class CompiledBertPipeline:
    """BERT classifier with the encoder pipelined across a ('pp',) mesh."""

    def __init__(
        self,
        config: Any,
        mesh: Mesh,
        units_per_stage: int,
        num_classes: int = 3,
        num_microbatches: Optional[int] = None,
        learning_rate: float = 1e-3,
        virtual_stages: int = 1,
    ):
        self.cfg = BertConfig.from_dict(config)
        self.mesh = mesh
        self.num_stages = int(mesh.shape["pp"])
        # interleaved scheduling (Megatron-style): each device owns
        # ``virtual_stages`` model chunks placed round-robin.  At M == S the
        # per-device bubble shrinks from (S-1)/(M+S-1) to (S-1)/(M+V*S-1)
        # in chunk-time units; for M < S idle ticks are V*(S-M)+M-1.  The
        # collision-free wavefront needs M <= S.
        self.virtual_stages = int(virtual_stages)
        if self.virtual_stages < 1:
            raise ValueError(
                f"virtual_stages must be >= 1, got {virtual_stages}"
            )
        # optional data-parallel axis: batch sharded over 'dp', stage params
        # replicated across it.  Inside the shard_map the stage-grad
        # reduction over 'dp' comes from the spec-driven transpose (params'
        # in_spec P('pp') omits 'dp', so the cotangent is psummed over it);
        # GSPMD handles only the code outside the shard_map.
        self.dp = int(mesh.shape["dp"]) if "dp" in mesh.shape else 1
        self.units_per_stage = units_per_stage
        self.num_classes = num_classes
        self.num_microbatches = num_microbatches or self.num_stages
        if self.virtual_stages > 1 and self.num_microbatches > self.num_stages:
            raise ValueError(
                f"interleaved scheduling needs num_microbatches "
                f"({self.num_microbatches}) <= num_stages ({self.num_stages})"
            )
        self.optimizer = optax.sgd(learning_rate)

        cfg_dict = self.cfg.to_dict()
        self.embeddings = BertEmbeddings(cfg_dict, deterministic=True)
        self.stage = EncoderStage(cfg_dict, units_per_stage)
        self.pooler = BertPooler(cfg_dict, deterministic=True)
        self.classifier = BertTailForClassification(
            hidden_dropout_prob=self.cfg.hidden_dropout_prob,
            hidden_size=self.cfg.hidden_size,
            num_classes=num_classes,
            deterministic=True,
            dtype=self.cfg.dtype,
        )

        self._stage_spec = P("pp")
        self._repl_spec = P()
        self.param_shardings: Optional[Dict] = None
        self._train_step = None

    # --- init ----------------------------------------------------------------
    def init(self, rng: jax.Array, input_ids, token_type_ids, attention_mask):
        """Initialize params: stage params stacked on a leading 'pp' axis."""
        k_embed, k_stage, k_pool, k_cls = jax.random.split(rng, 4)
        embed_vars = self.embeddings.init(
            {"params": k_embed}, input_ids, token_type_ids, attention_mask
        )
        hidden, mask4 = self.embeddings.apply(
            embed_vars, input_ids, token_type_ids, attention_mask
        )

        def init_one_stage(key):
            return self.stage.init({"params": key}, hidden, mask4)["params"]

        S, V = self.num_stages, self.virtual_stages
        chunk_keys = jax.random.split(k_stage, S * V)
        # stacked position p on device p//V, local slot p%V, holds model
        # chunk c = (p%V)*S + p//V — round-robin placement so sharding the
        # leading axis over 'pp' gives each device chunks {d, S+d, 2S+d,...}
        order = [(p % V) * S + p // V for p in range(S * V)]
        stages = jax.vmap(init_one_stage)(chunk_keys[jnp.asarray(order)])

        pooler_vars = self.pooler.init({"params": k_pool}, hidden, mask4)
        pooled = self.pooler.apply(pooler_vars, hidden, mask4)
        cls_vars = self.classifier.init({"params": k_cls}, pooled)

        params = {
            "embeddings": embed_vars["params"],
            "stages": stages,
            "pooler": pooler_vars["params"],
            "classifier": cls_vars["params"],
        }
        self.param_shardings = {
            "embeddings": NamedSharding(self.mesh, self._repl_spec),
            "stages": jax.tree_util.tree_map(
                lambda _: NamedSharding(self.mesh, self._stage_spec),
                stages,
            ),
            "pooler": NamedSharding(self.mesh, self._repl_spec),
            "classifier": NamedSharding(self.mesh, self._repl_spec),
        }
        params = jax.device_put(params, self.param_shardings)
        return params

    def init_opt_state(self, params):
        # any momentum/trace buffers are shaped like params and inherit
        # their shardings (params are already placed by init())
        return self.optimizer.init(params)

    # --- the pipelined encoder ----------------------------------------------
    def _run_ring_schedule(self, body, stage_params, hidden_mb, mask_mb):
        """Shared shard_map scaffolding for both pipeline schedules.

        ``body(local_stage_params, hidden_mb, mask_mb) -> [M, ...]`` runs
        per device; activations keep their optional dp sharding, outputs
        stack per-stage buffers along axis 0 and only the last device's
        block (the final stage/chunk) is meaningful.
        """
        M = self.num_microbatches
        act_spec = P(None, "dp") if self.dp > 1 else P()
        out_spec = P("pp", "dp") if self.dp > 1 else P("pp")
        out = jax.shard_map(
            body,
            mesh=self.mesh,
            in_specs=(self._stage_spec, act_spec, act_spec),
            out_specs=out_spec,
            check_vma=False,
        )(stage_params, hidden_mb, mask_mb)
        return out[-M:]

    def _pipelined_encoder(self, stage_params, hidden_mb, mask_mb):
        """shard_map GPipe: [M, mb, L, H] -> [M, mb, L, H]."""
        S = self.num_stages
        M = self.num_microbatches
        stage_mod = self.stage

        def body(local_stage_params, hidden_mb, mask_mb):
            # local leaves have leading dim 1 (this device's stage)
            params = jax.tree_util.tree_map(
                lambda x: x[0], local_stage_params
            )
            idx = lax.axis_index("pp")
            fwd_perm = [(i, (i + 1) % S) for i in range(S)]

            state = jnp.zeros_like(hidden_mb[0])
            outputs = jnp.zeros_like(hidden_mb)

            def tick(carry, t):
                state, outputs = carry
                recv = lax.ppermute(state, "pp", fwd_perm)
                feed = hidden_mb[jnp.clip(t, 0, M - 1)]
                inp = jnp.where(idx == 0, feed, recv)
                mb_idx = jnp.clip(t - idx, 0, M - 1)
                out, _ = stage_mod.apply(
                    {"params": params}, inp, mask_mb[mb_idx]
                )
                # last stage records its finished microbatch; earlier
                # (bubble) writes land on index 0 and are overwritten at
                # t == S-1 by the real microbatch 0
                w = jnp.clip(t - (S - 1), 0, M - 1)
                outputs = lax.dynamic_update_index_in_dim(
                    outputs, out, w, axis=0
                )
                return (out, outputs), None

            (_, outputs), _ = lax.scan(
                tick, (state, outputs), jnp.arange(M + S - 1)
            )
            return outputs

        return self._run_ring_schedule(body, stage_params, hidden_mb, mask_mb)

    def _interleaved_encoder(self, stage_params, hidden_mb, mask_mb):
        """V>1 chunk-wavefront schedule: [M, mb, L, H] -> [M, mb, L, H].

        Chunk c (device c mod S, local slot c // S) processes microbatch m
        at tick t = m + c; with M <= S each device runs at most one chunk
        per tick, and the uniform neighbor ring delivers every chunk
        transition — including slot boundaries (chunk vS-1 on device S-1
        feeds chunk vS on device 0).
        """
        S, V, M = self.num_stages, self.virtual_stages, self.num_microbatches
        C = S * V
        T = M + C - 1
        stage_mod = self.stage

        def body(local_stage_params, hidden_mb, mask_mb):
            d = lax.axis_index("pp")
            fwd_perm = [(i, (i + 1) % S) for i in range(S)]

            state = jnp.zeros_like(hidden_mb[0])
            outputs = jnp.zeros_like(hidden_mb)

            def tick(carry, t):
                state, outputs = carry
                recv = lax.ppermute(state, "pp", fwd_perm)
                k = (t - d) // S  # jnp floor-division: negative -> k < 0
                m = t - d - S * k
                k_c = jnp.clip(k, 0, V - 1)
                m_c = jnp.clip(m, 0, M - 1)

                params_k = jax.tree_util.tree_map(
                    lambda x: lax.dynamic_index_in_dim(
                        x, k_c, 0, keepdims=False
                    ),
                    local_stage_params,
                )
                is_first_chunk = (d == 0) & (k_c == 0)
                inp = jnp.where(is_first_chunk, hidden_mb[m_c], recv)
                out, _ = stage_mod.apply(
                    {"params": params_k}, inp, mask_mb[m_c]
                )
                # idle ticks (bubble) compute on clamped inputs; their
                # outputs are never consumed by an active receiver
                w = jnp.clip(t - (C - 1), 0, M - 1)
                outputs = lax.dynamic_update_index_in_dim(
                    outputs, out, w, axis=0
                )
                return (out, outputs), None

            (_, outputs), _ = lax.scan(
                tick, (state, outputs), jnp.arange(T)
            )
            return outputs

        return self._run_ring_schedule(body, stage_params, hidden_mb, mask_mb)

    # --- full model ----------------------------------------------------------
    def _logits(self, params, input_ids, token_type_ids, attention_mask):
        M = self.num_microbatches
        hidden, mask4 = self.embeddings.apply(
            {"params": params["embeddings"]},
            input_ids, token_type_ids, attention_mask,
        )
        B = hidden.shape[0]
        if B % M != 0:
            raise ValueError(f"batch {B} not divisible by microbatches {M}")
        if (B // M) % self.dp != 0:
            raise ValueError(
                f"microbatch size {B // M} not divisible by dp={self.dp}"
            )
        hidden_mb = hidden.reshape(M, B // M, *hidden.shape[1:])
        mask_mb = mask4.reshape(M, B // M, *mask4.shape[1:])

        if self.virtual_stages > 1:
            encoded = self._interleaved_encoder(
                params["stages"], hidden_mb, mask_mb
            )
        else:
            encoded = self._pipelined_encoder(
                params["stages"], hidden_mb, mask_mb
            )
        encoded = encoded.reshape(B, *encoded.shape[2:])

        pooled = self.pooler.apply(
            {"params": params["pooler"]}, encoded, mask4
        )
        return self.classifier.apply(
            {"params": params["classifier"]}, pooled
        )

    def loss(self, params, batch, labels):
        input_ids, token_type_ids, attention_mask = batch
        logits = self._logits(
            params, input_ids, token_type_ids, attention_mask
        )
        return optax.softmax_cross_entropy_with_integer_labels(
            logits.astype(jnp.float32), labels
        ).mean()

    # --- training ------------------------------------------------------------
    def make_train_step(self):
        """The FULL train step — grad + update — as one jitted program."""

        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def train_step(params, opt_state, batch, labels):
            loss, grads = jax.value_and_grad(self.loss)(params, batch, labels)
            updates, opt_state = self.optimizer.update(
                grads, opt_state, params
            )
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss

        self._train_step = train_step
        return train_step

    def train_step(self, params, opt_state, batch, labels):
        if self._train_step is None:
            self.make_train_step()
        return self._train_step(params, opt_state, batch, labels)


__all__ = ["CompiledBertPipeline", "EncoderStage"]
