"""Compiled SPMD pipeline: GPipe over a ('pp',) mesh in ONE jitted program.

This is the homogeneous-cluster fast path, complementary to the host-driven
MPMD engine in :mod:`.pipeline`:

- the MPMD engine supports *unequal* stages (the allocator's whole point)
  and re-slices without recompiling unmoved stages;
- this SPMD engine requires uniform stages but compiles the ENTIRE training
  step — forward, pipelined microbatch schedule, backward, optimizer — into
  a single XLA program over a ``jax.sharding.Mesh``, with stage-to-stage
  activation handoff as ``lax.ppermute`` over ICI neighbor links and
  per-stage parameters sharded on the ``pp`` mesh axis (leading-axis stack).

The schedule is classic GPipe fill-drain: with S stages and M microbatches
the shard_map body scans T = M + S - 1 ticks; at tick t, stage s computes
microbatch ``t - s`` (bubble ticks compute-and-discard).  Backward is just
``jax.grad`` through the scan — ppermute transposes to the reverse
permutation, so XLA derives the reverse schedule automatically; no
distributed autograd machinery exists anywhere (the reference needed
torch.distributed.autograd + DistributedOptimizer for this,
``scaelum/runner/runner.py:127-139``).

Non-repeated ends (embeddings / pooler / classifier) run replicated outside
the pipelined block.  Dropout is disabled in this path (deterministic
pipeline body); the MPMD engine handles stochastic training.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from .compat import shard_map as _shard_map

import flax.linen as nn

from ..models.bert import (
    ACT2FN,
    BertEmbeddings,
    BertLayer_Body,
    BertLayer_Head,
    BertLayer_Tail,
    BertPooler,
    BertTailForClassification,
)
from ..models.bert_config import BertConfig


class EncoderUnit(nn.Module):
    """One full encoder trio (attention + FFN)."""

    config: Any
    deterministic: bool = True

    @nn.compact
    def __call__(self, hidden, mask):
        hidden, mask = BertLayer_Head(
            self.config, self.deterministic, name="head"
        )(hidden, mask)
        inter, attn, mask = BertLayer_Body(
            self.config, self.deterministic, name="body"
        )(hidden, mask)
        hidden, mask = BertLayer_Tail(
            self.config, self.deterministic, name="tail"
        )(inter, attn, mask)
        return hidden, mask


class EncoderStage(nn.Module):
    """``units`` encoder trios = one uniform pipeline stage.

    Each unit is rematerialized: through the GPipe scan the backward pass
    otherwise stores every tick's intermediate activations (attention
    scores context, FFN up-projection); with remat only each unit's input
    survives to the backward, bounding per-tick residency at one hidden
    block per unit.
    """

    config: Any
    units: int
    deterministic: bool = True

    @nn.compact
    def __call__(self, hidden, mask):
        for u in range(self.units):
            hidden, mask = nn.remat(EncoderUnit)(
                self.config, self.deterministic, name=f"unit_{u}"
            )(hidden, mask)
        return hidden, mask


class _TpDense(nn.Module):
    """Tensor-parallel dense holding this device's weight shard.

    ``col``: output features sharded over the tp axis (no collective);
    ``row``: input features sharded, partial products ``psum``-reduced over
    the tp axis before the (replicated) bias is added.  The param tree keeps
    the plain Dense layout (``kernel``/``bias``) so full weights split into
    tp shards by pure reshape/transpose (see ``split_stage_params_for_tp``).
    """

    out_features: int
    dtype: Any
    mode: str  # 'col' | 'row'
    axis_name: str = "tp"

    @nn.compact
    def __call__(self, x):
        kernel = self.param(
            "kernel", nn.initializers.zeros,
            (x.shape[-1], self.out_features), jnp.float32,
        )
        bias = self.param(
            "bias", nn.initializers.zeros, (self.out_features,), jnp.float32
        )
        y = x @ kernel.astype(self.dtype)
        if self.mode == "row":
            y = lax.psum(y, self.axis_name)
        return y + bias.astype(self.dtype)


class TpEncoderUnit(nn.Module):
    """Megatron-style tensor-parallel encoder trio for the pipeline body.

    Attention q/k/v are column-parallel (heads split across tp), the
    attention output projection and the FFN down-projection are
    row-parallel with a ``psum``; LayerNorms and residuals are replicated.
    Param tree mirrors :class:`EncoderUnit` (``head/self/query`` etc.) with
    tp-local leaf shapes.

    Dropout (``deterministic=False``) follows Megatron RNG discipline: the
    dropouts on REPLICATED activations (attention output, FFN output —
    both after the row-parallel psum) draw from the shared per-tick key,
    so every tp rank applies the identical mask and replicas stay equal;
    the attention-probs dropout acts on head-SHARDED activations and is
    desynchronized across tp by folding ``lax.axis_index('tp')`` into its
    key (independent masks per head shard).
    """

    config: Any
    tp: int
    axis_name: str = "tp"
    deterministic: bool = True

    @nn.compact
    def __call__(self, hidden, mask):
        cfg = BertConfig.from_dict(self.config)
        dtype = jnp.dtype(cfg.dtype)
        if (
            cfg.hidden_size % self.tp
            or cfg.num_attention_heads % self.tp
            or cfg.intermediate_size % self.tp
        ):
            raise ValueError(
                f"hidden/heads/intermediate "
                f"({cfg.hidden_size}/{cfg.num_attention_heads}/"
                f"{cfg.intermediate_size}) must all be divisible by "
                f"tp={self.tp}"
            )
        n_heads = cfg.num_attention_heads // self.tp
        head_dim = cfg.hidden_size // cfg.num_attention_heads
        h_local = cfg.hidden_size // self.tp
        i_local = cfg.intermediate_size // self.tp
        deterministic = self.deterministic
        tp_axis = self.axis_name

        class Head(nn.Module):
            @nn.compact
            def __call__(sf, hidden, mask):
                class Self(nn.Module):
                    @nn.compact
                    def __call__(sf2, x, mask):
                        mk = lambda nm: _TpDense(
                            h_local, dtype, "col", tp_axis, name=nm
                        )
                        split = lambda t: t.reshape(
                            t.shape[0], t.shape[1], n_heads, head_dim
                        )
                        q = split(mk("query")(x))
                        k = split(mk("key")(x))
                        v = split(mk("value")(x))
                        scores = jnp.einsum("blhd,bmhd->bhlm", q, k) / (
                            jnp.sqrt(jnp.asarray(head_dim, dtype))
                        )
                        scores = scores + mask
                        probs = jax.nn.softmax(
                            scores.astype(jnp.float32), axis=-1
                        ).astype(dtype)
                        if (
                            not deterministic
                            and cfg.attention_probs_dropout_prob > 0.0
                        ):
                            # head-sharded region: desync masks across tp
                            rng = jax.random.fold_in(
                                sf2.make_rng("dropout"),
                                lax.axis_index(tp_axis),
                            )
                            probs = nn.Dropout(
                                cfg.attention_probs_dropout_prob
                            )(probs, deterministic=False, rng=rng)
                        ctx = jnp.einsum("bhlm,bmhd->blhd", probs, v)
                        return ctx.reshape(ctx.shape[0], ctx.shape[1],
                                           h_local)

                class Out(nn.Module):
                    @nn.compact
                    def __call__(sf2, ctx, residual):
                        y = _TpDense(cfg.hidden_size, dtype, "row",
                                     tp_axis, name="dense")(ctx)
                        # replicated region (post-psum): shared key ->
                        # identical mask on every tp rank
                        y = nn.Dropout(cfg.hidden_dropout_prob)(
                            y, deterministic=deterministic
                        )
                        out = nn.LayerNorm(
                            epsilon=1e-12, dtype=jnp.float32,
                            name="LayerNorm",
                        )(y + residual)
                        return out.astype(dtype)

                ctx = Self(name="self")(hidden, mask)
                return Out(name="output")(ctx, hidden), mask

        class Body(nn.Module):
            @nn.compact
            def __call__(sf, attn_out, mask):
                act = ACT2FN[cfg.hidden_act]
                inter = act(_TpDense(i_local, dtype, "col", tp_axis,
                                     name="dense_act")(attn_out))
                return inter, attn_out, mask

        class Tail(nn.Module):
            @nn.compact
            def __call__(sf, inter, attn_out, mask):
                y = _TpDense(cfg.hidden_size, dtype, "row", tp_axis,
                             name="dense")(inter)
                y = nn.Dropout(cfg.hidden_dropout_prob)(
                    y, deterministic=deterministic
                )
                out = nn.LayerNorm(
                    epsilon=1e-12, dtype=jnp.float32, name="LayerNorm"
                )(y + attn_out)
                return out.astype(dtype), mask

        hidden, mask = Head(name="head")(hidden, mask)
        inter, attn, mask = Body(name="body")(hidden, mask)
        return Tail(name="tail")(inter, attn, mask)


class TpEncoderStage(nn.Module):
    """``units`` tensor-parallel encoder trios; remat like EncoderStage."""

    config: Any
    units: int
    tp: int
    axis_name: str = "tp"
    deterministic: bool = True

    @nn.compact
    def __call__(self, hidden, mask):
        for u in range(self.units):
            hidden, mask = nn.remat(TpEncoderUnit)(
                self.config, self.tp, self.axis_name, self.deterministic,
                name=f"unit_{u}",
            )(hidden, mask)
        return hidden, mask


def _leaf_role(path) -> Tuple[str, str]:
    keys = [getattr(p, "key", str(p)) for p in path]
    return keys[-2], keys[-1]  # (module, param) e.g. ('query', 'kernel')


# module names whose Dense is column- vs row-parallel, per model family
BERT_TP_COL = ("query", "key", "value", "dense_act")
BERT_TP_ROW = ("dense",)


def _tp_split_axis(module, param, col_modules, row_modules):
    """Which axis of a full leaf splits across tp; None = replicate.

    Role sets may name a whole submodule (every param of a Dense) or a
    specific ``(module, param)`` pair (direct params, e.g. MoE expert
    tensors).  Column-parallel leaves split their LAST axis (output
    features / expert up-projection); row-parallel ones split the
    second-to-last (input features), with module-matched row biases
    replicated (they are added after the psum).
    """
    if module in col_modules or (module, param) in col_modules:
        return -1
    if (module, param) in row_modules:
        return -2
    if module in row_modules and param == "kernel":
        return -2
    return None


def split_stage_params_for_tp(stages, tp: int,
                              col_modules=BERT_TP_COL,
                              row_modules=BERT_TP_ROW):
    """[P, ...full...] stacked stage params -> [P, tp, ...local...].

    Column-parallel leaves (q/k/v, FFN up) slice output features; row-
    parallel kernels (attention out, FFN down) slice input features; biases
    of row-parallel layers and LayerNorms replicate across tp.
    ``col_modules``/``row_modules`` name the submodules (or
    ``(module, param)`` pairs) playing each role — defaults match the BERT
    encoder; the GPT engines pass their own.
    """

    def split(path, leaf):
        module, param = _leaf_role(path)
        ax = _tp_split_axis(module, param, col_modules, row_modules)
        if ax is None:
            # row-parallel bias, LayerNorm scale/bias, routers: replicate
            return jnp.broadcast_to(
                leaf[:, None], (leaf.shape[0], tp) + leaf.shape[1:]
            )
        k = ax % leaf.ndim
        shape = leaf.shape
        parts = leaf.reshape(
            shape[:k] + (tp, shape[k] // tp) + shape[k + 1:]
        )
        return jnp.moveaxis(parts, k, 1)

    return jax.tree_util.tree_map_with_path(split, stages)


@jax.custom_vjp
def _psum_grad_tp(x):
    """Identity whose cotangent is ``psum``-med over the 'tp' axis.

    Replicated param leaves (LayerNorms, row-parallel biases) get their
    copies stacked on a tp axis of the global array, so the spec-driven
    shard_map transpose hands each device only its *partial* cotangent
    (the partials sum to the true one; sharded kernels are exact because
    their reverse path crosses the forward ``psum``, whose transpose is a
    ``psum`` under ``check_vma=False``).  Wrapping the forward use of a
    replicated leaf in this identity makes each copy's gradient the full
    cross-tp sum, keeping copies equal and equal to the unsharded model's
    gradient.
    """
    return x


def _psum_grad_tp_fwd(x):
    return x, None


def _psum_grad_tp_bwd(_, g):
    return (lax.psum(g, "tp"),)


_psum_grad_tp.defvjp(_psum_grad_tp_fwd, _psum_grad_tp_bwd)


def merge_stage_params_from_tp(stages_tp,
                               col_modules=BERT_TP_COL,
                               row_modules=BERT_TP_ROW):
    """Inverse of :func:`split_stage_params_for_tp`."""

    def merge(path, leaf):
        module, param = _leaf_role(path)
        ax = _tp_split_axis(module, param, col_modules, row_modules)
        if ax is None:
            return leaf[:, 0]
        # leaf: [P, tp, ...local...]; put tp back next to its split axis
        k = ax % (leaf.ndim - 1)  # axis index in the FULL (merged) leaf
        parts = jnp.moveaxis(leaf, 1, k)
        shape = parts.shape
        return parts.reshape(
            shape[:k] + (shape[k] * shape[k + 1],) + shape[k + 2:]
        )

    return jax.tree_util.tree_map_with_path(merge, stages_tp)


class CompiledBertPipeline:
    """BERT classifier with the encoder pipelined across a ('pp',) mesh."""

    # Dense submodule names by Megatron role (overridden per model family);
    # used both to split full weights into tp shards and to pick which
    # leaves need the replicated-gradient guard in the stage body
    tp_col_modules = BERT_TP_COL
    tp_row_modules = BERT_TP_ROW

    def __init__(
        self,
        config: Any,
        mesh: Mesh,
        units_per_stage: int,
        num_classes: int = 3,
        num_microbatches: Optional[int] = None,
        learning_rate: float = 1e-3,
        virtual_stages: int = 1,
        optimizer: Optional[optax.GradientTransformation] = None,
        zero1: bool = False,
        zero2: bool = False,
        zero3: bool = False,
        deterministic: bool = True,
    ):
        # deterministic=False enables dropout end to end (the reference
        # fine-tunes with dropout throughout,
        # scaelum/model/bert_layers.py): replicated ends use plain flax
        # rngs, the pipelined body threads a threefry key through the ring
        # scan folded by (device, tick) — every (stage, tick, microbatch)
        # cell draws an independent mask, reproducible per seed.
        self.deterministic = bool(deterministic)
        self.cfg = self._parse_config(config)
        self.mesh = mesh
        self.num_stages = int(mesh.shape["pp"])
        # interleaved scheduling (Megatron-style): each device owns
        # ``virtual_stages`` model chunks placed round-robin.  At M == S the
        # per-device bubble shrinks from (S-1)/(M+S-1) to (S-1)/(M+V*S-1)
        # in chunk-time units; for M < S idle ticks are V*(S-M)+M-1.  The
        # collision-free wavefront needs M <= S.
        self.virtual_stages = int(virtual_stages)
        if self.virtual_stages < 1:
            raise ValueError(
                f"virtual_stages must be >= 1, got {virtual_stages}"
            )
        # optional data-parallel axis: batch sharded over 'dp', stage params
        # replicated across it.  Inside the shard_map the stage-grad
        # reduction over 'dp' comes from the spec-driven transpose (params'
        # in_spec P('pp') omits 'dp', so the cotangent is psummed over it);
        # GSPMD handles only the code outside the shard_map.
        self.dp = int(mesh.shape["dp"]) if "dp" in mesh.shape else 1
        # optional tensor-parallel axis: each stage's weights sharded
        # Megatron-style over 'tp' with explicit psums in the stage body
        self.tp = int(mesh.shape["tp"]) if "tp" in mesh.shape else 1
        self.units_per_stage = units_per_stage
        self.num_classes = num_classes
        # interleaved scheduling accepts any M: the collision-free
        # wavefront covers M <= S, the grouped Megatron schedule covers
        # S | M, and other M pad up to the next multiple of S (pads are
        # sliced away; see _interleaved_encoder)
        self.num_microbatches = num_microbatches or self.num_stages
        self.optimizer = optimizer or optax.sgd(learning_rate)
        # ZeRO-1: shard optimizer-state tensors (momenta etc.) over the dp
        # axis instead of replicating them.  Under jit this is nothing but
        # sharding annotations — XLA derives the reduce-scatter of grads
        # into state shards and the all-gather of updates by itself.
        self.zero1 = bool(zero1)
        if self.zero1 and self.dp == 1:
            raise ValueError("zero1 requires a 'dp' mesh axis of size > 1")
        # ZeRO-2: additionally pin the GRADIENT tree to the same dp shards
        # (with_sharding_constraint right at the value_and_grad output), so
        # the full-size replicated gradient buffer never materializes —
        # XLA reduce-scatters the cross-dp gradient sum straight into
        # shards and every downstream optimizer op stays sharded.
        self.zero2 = bool(zero2)
        if self.zero2 and not self.zero1:
            raise ValueError("zero2 extends zero1; pass zero1=True as well")
        # ZeRO-3 / FSDP: stage params live dp-SHARDED at rest (one weight
        # axis split over 'dp' on top of the 'pp'/'tp' stacking) and are
        # all-gathered inside the stage body right before use; the
        # gather's transpose is a reduce-scatter, so gradients come out
        # dp-sharded too and the optimizer update runs entirely on
        # shards.  Param/state/grad memory all divide by dp.
        self.zero3 = bool(zero3)
        if self.zero3 and self.dp == 1:
            raise ValueError("zero3 requires a 'dp' mesh axis of size > 1")
        self._zero3_axes = None  # per-leaf gather axis, built by init()
        self._stage_in_specs = None  # per-leaf specs (zero3), ditto

        self._build_modules(units_per_stage, num_classes)

        self._stage_spec = P("pp", "tp") if self.tp > 1 else P("pp")
        self._repl_spec = P()
        self.opt_shardings = None
        self.param_shardings: Optional[Dict] = None
        self._train_step = None

    @staticmethod
    def _parse_config(config):
        return BertConfig.from_dict(config)

    def _build_modules(self, units_per_stage: int, num_classes: int) -> None:
        """Model-specific module construction (overridden per family)."""
        cfg_dict = self.cfg.to_dict()
        det = self.deterministic
        self.embeddings = BertEmbeddings(cfg_dict, deterministic=det)
        self.stage = EncoderStage(cfg_dict, units_per_stage,
                                  deterministic=det)
        self.tp_stage = (
            TpEncoderStage(cfg_dict, units_per_stage, self.tp,
                           deterministic=det)
            if self.tp > 1 else None
        )
        self.pooler = BertPooler(cfg_dict, deterministic=det)
        self.classifier = BertTailForClassification(
            hidden_dropout_prob=self.cfg.hidden_dropout_prob,
            hidden_size=self.cfg.hidden_size,
            num_classes=num_classes,
            deterministic=det,
            dtype=self.cfg.dtype,
        )

    def _pick_dp_axis(self, shape, first_axis: int) -> int:
        """Last dp-divisible axis of ``shape`` at/after ``first_axis``.

        The ONE rule shared by ZeRO state sharding (`_zero1_sharding`),
        ZeRO-2 gradient pinning, and ZeRO-3 param sharding — all three
        must agree or XLA reshards every stage gradient each step.
        Returns -1 when no axis qualifies.
        """
        for ax in range(len(shape) - 1, first_axis - 1, -1):
            if shape[ax] % self.dp == 0 and shape[ax] >= self.dp:
                return ax
        return -1

    def _stage_shardings(self, stages):
        """Per-leaf shardings for the stacked stage tree.

        Without zero3 every leaf gets the uniform ``self._stage_spec``;
        with zero3 one dp-divisible weight axis per leaf additionally
        carries 'dp', and the per-leaf gather axis (post-extraction
        coordinates, -1 = replicated) is recorded for the stage body.
        """
        stage_dims = 2 if self.tp > 1 else 1

        class _SpecAx:  # opaque pair so tree_map treats it as a leaf
            def __init__(self, spec, ax):
                self.spec, self.ax = spec, ax

        def spec_and_axis(leaf):
            shape = np.shape(leaf)
            spec = list(self._stage_spec) + [None] * (len(shape) - stage_dims)
            ax = self._pick_dp_axis(shape, stage_dims) if self.zero3 else -1
            if ax >= 0:
                spec[ax] = "dp"
            return _SpecAx(P(*spec), ax - stage_dims if ax >= 0 else -1)

        pairs = jax.tree_util.tree_map(spec_and_axis, stages)
        specs = jax.tree_util.tree_map(lambda p: p.spec, pairs)
        self._zero3_axes = jax.tree_util.tree_map(lambda p: p.ax, pairs)
        self._stage_in_specs = specs if self.zero3 else self._stage_spec
        return jax.tree_util.tree_map(
            lambda s: NamedSharding(self.mesh, s), specs
        )

    def _gather_zero3(self, params):
        """all-gather dp-sharded leaves inside the stage body (zero3)."""
        if not self.zero3:
            return params
        return jax.tree_util.tree_map(
            lambda x, ax: (
                lax.all_gather(x, "dp", axis=ax, tiled=True) if ax >= 0
                else x
            ),
            params, self._zero3_axes,
        )

    # --- init ----------------------------------------------------------------
    def init(self, rng: jax.Array, input_ids, token_type_ids, attention_mask):
        """Initialize params: stage params stacked on a leading 'pp' axis."""
        k_embed, k_stage, k_pool, k_cls = jax.random.split(rng, 4)
        # stochastic modules consume a 'dropout' stream during their init
        # forward; masks don't create params, so the tree is identical to
        # the deterministic engine's
        drop = (
            {} if self.deterministic
            else {"dropout": jax.random.fold_in(rng, 99)}
        )
        embed_vars = self.embeddings.init(
            {"params": k_embed, **drop},
            input_ids, token_type_ids, attention_mask,
        )
        hidden, mask4 = self.embeddings.apply(
            embed_vars, input_ids, token_type_ids, attention_mask,
            rngs=drop or None,
        )

        def init_one_stage(key):
            return self.stage.init(
                {"params": key, **drop}, hidden, mask4
            )["params"]

        S, V = self.num_stages, self.virtual_stages
        chunk_keys = jax.random.split(k_stage, S * V)
        # stacked position p on device p//V, local slot p%V, holds model
        # chunk c = (p%V)*S + p//V — round-robin placement so sharding the
        # leading axis over 'pp' gives each device chunks {d, S+d, 2S+d,...}
        order = [(p % V) * S + p // V for p in range(S * V)]
        stages = jax.vmap(init_one_stage)(chunk_keys[jnp.asarray(order)])
        if self.tp > 1:
            # full weights -> per-device Megatron shards on a new axis 1
            stages = split_stage_params_for_tp(
                stages, self.tp, self.tp_col_modules, self.tp_row_modules
            )

        pooler_vars = self.pooler.init(
            {"params": k_pool, **drop}, hidden, mask4
        )
        pooled = self.pooler.apply(pooler_vars, hidden, mask4,
                                   rngs=drop or None)
        cls_vars = self.classifier.init({"params": k_cls, **drop}, pooled)

        params = {
            "embeddings": embed_vars["params"],
            "stages": stages,
            "pooler": pooler_vars["params"],
            "classifier": cls_vars["params"],
        }
        self.param_shardings = {
            "embeddings": NamedSharding(self.mesh, self._repl_spec),
            "stages": self._stage_shardings(stages),
            "pooler": NamedSharding(self.mesh, self._repl_spec),
            "classifier": NamedSharding(self.mesh, self._repl_spec),
        }
        params = jax.device_put(params, self.param_shardings)
        return params

    def init_opt_state(self, params):
        # any momentum/trace buffers are shaped like params and inherit
        # their shardings (params are already placed by init())
        opt_state = self.optimizer.init(params)
        if not self.zero1:
            return opt_state
        self.opt_shardings = jax.tree_util.tree_map(
            self._zero1_sharding, opt_state
        )
        return jax.device_put(opt_state, self.opt_shardings)

    def _zero1_sharding(self, leaf):
        """dp-shard the largest dp-divisible axis of a state tensor.

        Param-shaped leaves keep their stage ('pp'/'tp') dims on the
        leading axes and additionally split one weight axis over 'dp';
        scalars/counters stay replicated.
        """
        shape = np.shape(leaf)
        if len(shape) == 0:
            return NamedSharding(self.mesh, P())
        # leading axes belong to the stacked-stage layout when they match
        stage_axes = 0
        if shape[0] == self.num_stages * self.virtual_stages:
            stage_axes = 2 if self.tp > 1 and len(shape) > 1 and (
                shape[1] == self.tp
            ) else 1
        spec = (["pp", "tp"][: stage_axes] + [None] * (len(shape) - stage_axes))
        best = self._pick_dp_axis(shape, stage_axes)
        if best >= 0:
            spec[best] = "dp"
        elif stage_axes == 0:
            return NamedSharding(self.mesh, P())  # replicated (embeddings
            # and heads are small next to the encoder stack)
        return NamedSharding(self.mesh, P(*spec))

    # side_outputs=True (set by engines whose stages accumulate a scalar
    # into the ring's side tensor, e.g. MoE aux loss): the schedule returns
    # (hidden_out, side_out) instead of hidden_out alone
    side_outputs = False

    # --- the pipelined encoder ----------------------------------------------
    def _run_ring_schedule(self, body, stage_params, hidden_mb, mask_mb,
                           rng=None):
        """Shared shard_map scaffolding for both pipeline schedules.

        ``body(local_stage_params, hidden_mb, mask_mb[, rng_data]) ->
        [M, ...]`` runs per device; activations keep their optional dp
        sharding, outputs stack per-stage buffers along axis 0 and only
        the last device's block (the final stage/chunk) is meaningful.
        With ``side_outputs`` the body returns a (hidden, side) buffer
        pair.  M comes from the input's leading axis (the padded count
        when the grouped schedule padded up to a multiple of S).

        ``rng`` (a jax PRNG key; stochastic engines only) enters the body
        as replicated raw key data — every device derives its own stream
        by folding in its mesh position, so no per-device key plumbing is
        needed at the call site.
        """
        M = hidden_mb.shape[0]
        act_spec = P(None, "dp") if self.dp > 1 else P()
        out_spec = P("pp", "dp") if self.dp > 1 else P("pp")
        out_specs = (out_spec, out_spec) if self.side_outputs else out_spec
        stage_specs = (
            self._stage_in_specs if self._stage_in_specs is not None
            else self._stage_spec
        )
        in_specs = [stage_specs, act_spec, act_spec]
        args = [stage_params, hidden_mb, mask_mb]
        if rng is not None:
            in_specs.append(P())
            args.append(jax.random.key_data(rng))
        out = _shard_map(
            body,
            mesh=self.mesh,
            in_specs=tuple(in_specs),
            out_specs=out_specs,
            check_vma=False,
        )(*args)
        if self.side_outputs:
            return out[0][-M:], out[1][-M:]
        return out[-M:]

    def _stage_rng_stream(self, maybe_rng):
        """Per-device dropout-key base + per-tick rngs-dict factory.

        ``maybe_rng`` is the body's trailing varargs: empty for the
        deterministic engine, else one raw-key-data array.  The base key
        folds in the device's 'pp' position; each tick t folds again, so
        every (device, tick) cell — hence every (stage/chunk, microbatch)
        pair — draws an independent, reproducible mask.
        """
        if not maybe_rng:
            return lambda t: {}
        base = jax.random.fold_in(
            jax.random.wrap_key_data(maybe_rng[0]), lax.axis_index("pp")
        )
        if self.dp > 1:
            # data-parallel shards hold different rows; desync their masks
            # (tp deliberately NOT folded — replicated-region masks must
            # match across tp, see TpEncoderUnit)
            base = jax.random.fold_in(base, lax.axis_index("dp"))
        return lambda t: {
            "rngs": {"dropout": jax.random.fold_in(base, t)}
        }

    def _guard_tp_replicated(self, local_stage_params):
        """Wrap tp-replicated leaves so their gradient sums across tp."""
        if self.tp == 1:
            return local_stage_params
        col, row = self.tp_col_modules, self.tp_row_modules

        def guard(path, leaf):
            module, param = _leaf_role(path)
            if _tp_split_axis(module, param, col, row) is not None:
                return leaf  # genuinely sharded: transpose is exact
            return _psum_grad_tp(leaf)

        return jax.tree_util.tree_map_with_path(guard, local_stage_params)

    def _select_chunk_params(self, local_stage_params, k_c):
        """This device's chunk ``k_c`` from its [V, (tp,) ...] local leaves.

        With zero3 the selected chunk is all-gathered over dp HERE, per
        tick — FSDP-style streaming: only the chunk in use is ever
        materialized full-size, the rest stay sharded at rest.
        """
        tp = self.tp

        def index_chunk(x):
            x = lax.dynamic_index_in_dim(x, k_c, 0, keepdims=False)
            return x[0] if tp > 1 else x

        return self._gather_zero3(
            jax.tree_util.tree_map(index_chunk, local_stage_params)
        )

    def _pipelined_encoder(self, stage_params, hidden_mb, mask_mb,
                           rng=None):
        """shard_map GPipe: [M, mb, L, H] -> [M, mb, L, H]."""
        S = self.num_stages
        M = hidden_mb.shape[0]
        tp = self.tp
        stage_mod = self.tp_stage if tp > 1 else self.stage

        def body(local_stage_params, hidden_mb, mask_mb, *maybe_rng):
            # local leaves have leading dim 1 (this device's stage); with
            # tensor parallelism a second singleton tp-shard dim follows
            params = jax.tree_util.tree_map(
                (lambda x: x[0, 0]) if tp > 1 else (lambda x: x[0]),
                local_stage_params,
            )
            params = self._gather_zero3(params)
            params = self._guard_tp_replicated(params)
            idx = lax.axis_index("pp")
            fwd_perm = [(i, (i + 1) % S) for i in range(S)]
            tick_rngs = self._stage_rng_stream(maybe_rng)

            if self.side_outputs:
                # the side is a per-microbatch accumulator (e.g. MoE aux
                # loss): it travels WITH the microbatch around the ring
                # instead of being re-fed per stage like the BERT mask
                state = (jnp.zeros_like(hidden_mb[0]),
                         jnp.zeros_like(mask_mb[0]))
                outputs = (jnp.zeros_like(hidden_mb),
                           jnp.zeros_like(mask_mb))

                def tick_side(carry, t):
                    state, (out_h, out_s) = carry
                    recv_h, recv_s = lax.ppermute(state, "pp", fwd_perm)
                    feed = jnp.clip(t, 0, M - 1)
                    inp_h = jnp.where(idx == 0, hidden_mb[feed], recv_h)
                    inp_s = jnp.where(idx == 0, mask_mb[feed], recv_s)
                    h, s = stage_mod.apply(
                        {"params": params}, inp_h, inp_s, **tick_rngs(t)
                    )
                    w = jnp.clip(t - (S - 1), 0, M - 1)
                    out_h = lax.dynamic_update_index_in_dim(out_h, h, w, 0)
                    out_s = lax.dynamic_update_index_in_dim(out_s, s, w, 0)
                    return ((h, s), (out_h, out_s)), None

                (_, outputs), _ = lax.scan(
                    tick_side, (state, outputs), jnp.arange(M + S - 1)
                )
                return outputs

            state = jnp.zeros_like(hidden_mb[0])
            outputs = jnp.zeros_like(hidden_mb)

            def tick(carry, t):
                state, outputs = carry
                recv = lax.ppermute(state, "pp", fwd_perm)
                feed = hidden_mb[jnp.clip(t, 0, M - 1)]
                inp = jnp.where(idx == 0, feed, recv)
                mb_idx = jnp.clip(t - idx, 0, M - 1)
                out, _ = stage_mod.apply(
                    {"params": params}, inp, mask_mb[mb_idx],
                    **tick_rngs(t),
                )
                # last stage records its finished microbatch; earlier
                # (bubble) writes land on index 0 and are overwritten at
                # t == S-1 by the real microbatch 0
                w = jnp.clip(t - (S - 1), 0, M - 1)
                outputs = lax.dynamic_update_index_in_dim(
                    outputs, out, w, axis=0
                )
                return (out, outputs), None

            (_, outputs), _ = lax.scan(
                tick, (state, outputs), jnp.arange(M + S - 1)
            )
            return outputs

        return self._run_ring_schedule(body, stage_params, hidden_mb,
                                       mask_mb, rng=rng)

    def _interleaved_encoder(self, stage_params, hidden_mb, mask_mb,
                             rng=None):
        """V>1 chunk-wavefront schedule: [M, mb, L, H] -> [M, mb, L, H].

        Chunk c (device c mod S, local slot c // S) processes microbatch m
        at tick t = m + c; with M <= S each device runs at most one chunk
        per tick, and the uniform neighbor ring delivers every chunk
        transition — including slot boundaries (chunk vS-1 on device S-1
        feeds chunk vS on device 0).  For M > S (M a multiple of S) the
        grouped variant below runs instead.
        """
        S = self.num_stages
        M = hidden_mb.shape[0]
        if M > S:
            if M % S:
                # pad with zero microbatches up to a multiple of S so the
                # grouped wavefront applies; the pads ride the ring as
                # extra bubble and their outputs are sliced away.  Cost:
                # pad/M extra chunk-compute — still ahead of falling back
                # to plain GPipe when V amortizes the bubble.
                pad = S - M % S
                zeros = lambda t: jnp.concatenate(
                    [t, jnp.zeros((pad,) + t.shape[1:], t.dtype)], axis=0
                )
                out = self._interleaved_grouped_encoder(
                    stage_params, zeros(hidden_mb), zeros(mask_mb), rng=rng
                )
                if self.side_outputs:
                    return out[0][:M], out[1][:M]
                return out[:M]
            return self._interleaved_grouped_encoder(
                stage_params, hidden_mb, mask_mb, rng=rng
            )
        V = self.virtual_stages
        C = S * V
        T = M + C - 1
        tp = self.tp
        stage_mod = self.tp_stage if tp > 1 else self.stage

        def body(local_stage_params, hidden_mb, mask_mb, *maybe_rng):
            local_stage_params = self._guard_tp_replicated(local_stage_params)
            d = lax.axis_index("pp")
            fwd_perm = [(i, (i + 1) % S) for i in range(S)]
            tick_rngs = self._stage_rng_stream(maybe_rng)

            def tick_coords(t):
                """t -> (chunk slot k_c, microbatch m_c, write index w)."""
                k = (t - d) // S  # jnp floor-division: negative -> k < 0
                m = t - d - S * k
                k_c = jnp.clip(k, 0, V - 1)
                m_c = jnp.clip(m, 0, M - 1)
                w = jnp.clip(t - (C - 1), 0, M - 1)
                return k_c, m_c, w

            if self.side_outputs:
                # the side travels WITH the microbatch between chunks
                # (aux accumulator), so it rides the ring alongside hidden
                state = (jnp.zeros_like(hidden_mb[0]),
                         jnp.zeros_like(mask_mb[0]))
                outputs = (jnp.zeros_like(hidden_mb),
                           jnp.zeros_like(mask_mb))

                def tick_side(carry, t):
                    state, (out_h, out_s) = carry
                    recv_h, recv_s = lax.ppermute(state, "pp", fwd_perm)
                    k_c, m_c, w = tick_coords(t)
                    params_k = self._select_chunk_params(
                        local_stage_params, k_c
                    )
                    first = (d == 0) & (k_c == 0)
                    inp_h = jnp.where(first, hidden_mb[m_c], recv_h)
                    inp_s = jnp.where(first, mask_mb[m_c], recv_s)
                    h, s = stage_mod.apply(
                        {"params": params_k}, inp_h, inp_s, **tick_rngs(t)
                    )
                    out_h = lax.dynamic_update_index_in_dim(out_h, h, w, 0)
                    out_s = lax.dynamic_update_index_in_dim(out_s, s, w, 0)
                    return ((h, s), (out_h, out_s)), None

                (_, outputs), _ = lax.scan(
                    tick_side, (state, outputs), jnp.arange(T)
                )
                return outputs

            state = jnp.zeros_like(hidden_mb[0])
            outputs = jnp.zeros_like(hidden_mb)

            def tick(carry, t):
                state, outputs = carry
                recv = lax.ppermute(state, "pp", fwd_perm)
                k_c, m_c, w = tick_coords(t)

                params_k = self._select_chunk_params(local_stage_params, k_c)
                is_first_chunk = (d == 0) & (k_c == 0)
                inp = jnp.where(is_first_chunk, hidden_mb[m_c], recv)
                out, _ = stage_mod.apply(
                    {"params": params_k}, inp, mask_mb[m_c], **tick_rngs(t)
                )
                # idle ticks (bubble) compute on clamped inputs; their
                # outputs are never consumed by an active receiver, and
                # their writes (w clipped) are overwritten at t == C-1
                outputs = lax.dynamic_update_index_in_dim(
                    outputs, out, w, axis=0
                )
                return (out, outputs), None

            (_, outputs), _ = lax.scan(
                tick, (state, outputs), jnp.arange(T)
            )
            return outputs

        return self._run_ring_schedule(body, stage_params, hidden_mb,
                                       mask_mb, rng=rng)

    def _interleaved_grouped_encoder(self, stage_params, hidden_mb, mask_mb,
                                     rng=None):
        """Megatron-style grouped interleaving for M > S, S | M.

        Microbatches run in G = M/S groups of S.  Device d at tick t maps
        tau = t - d to (group g, slot k, offset i) = (tau // (V*S),
        (tau mod V*S) // S, tau mod S) and computes chunk c = k*S + d on
        microbatch m = g*S + i.  Dependency check: chunk c-1 of the same
        microbatch finishes on device d-1 (same slot) or device S-1 (slot
        k-1, offset i) exactly one tick earlier, so the uniform neighbor
        ppermute still delivers every transition on time.  Per-device
        bubble is (S-1)/V chunk-units vs (S-1) for plain GPipe: total
        ticks T = M*V + S - 1 of 1/V-sized chunks.

        Completed microbatches surface only at (d = S-1, k = V-1); all
        other ticks write to a scratch slot M that is sliced away.
        """
        S, V = self.num_stages, self.virtual_stages
        M = hidden_mb.shape[0]  # caller pads to a multiple of S
        if M % S != 0:
            raise ValueError(
                f"grouped interleaving needs microbatches ({M}) to be a "
                f"multiple of num_stages ({S})"
            )
        T = M * V + S - 1
        tp = self.tp
        stage_mod = self.tp_stage if tp > 1 else self.stage

        def body(local_stage_params, hidden_mb, mask_mb, *maybe_rng):
            local_stage_params = self._guard_tp_replicated(local_stage_params)
            d = lax.axis_index("pp")
            fwd_perm = [(i, (i + 1) % S) for i in range(S)]
            tick_rngs = self._stage_rng_stream(maybe_rng)

            def tick_coords(t):
                """tau -> (active, chunk slot k_c, microbatch m_c, done)."""
                tau = t - d
                g = tau // (V * S)  # floor division: negative while filling
                r = tau - g * (V * S)
                k = r // S
                i = r - k * S
                m = g * S + i
                active = (tau >= 0) & (m >= 0) & (m < M)
                k_c = jnp.clip(k, 0, V - 1)
                m_c = jnp.clip(m, 0, M - 1)
                done = active & (k_c == V - 1)
                return active, k_c, m_c, done

            if self.side_outputs:
                state = (jnp.zeros_like(hidden_mb[0]),
                         jnp.zeros_like(mask_mb[0]))
                outputs = (
                    jnp.zeros((M + 1,) + hidden_mb.shape[1:],
                              hidden_mb.dtype),
                    jnp.zeros((M + 1,) + mask_mb.shape[1:], mask_mb.dtype),
                )

                def tick_side(carry, t):
                    state, (out_h, out_s) = carry
                    recv_h, recv_s = lax.ppermute(state, "pp", fwd_perm)
                    active, k_c, m_c, done = tick_coords(t)
                    params_k = self._select_chunk_params(
                        local_stage_params, k_c
                    )
                    first = (d == 0) & (k_c == 0) & active
                    inp_h = jnp.where(first, hidden_mb[m_c], recv_h)
                    inp_s = jnp.where(first, mask_mb[m_c], recv_s)
                    h, s = stage_mod.apply(
                        {"params": params_k}, inp_h, inp_s, **tick_rngs(t)
                    )
                    w = jnp.where(done, m_c, M)
                    out_h = lax.dynamic_update_index_in_dim(out_h, h, w, 0)
                    out_s = lax.dynamic_update_index_in_dim(out_s, s, w, 0)
                    return ((h, s), (out_h, out_s)), None

                (_, (out_h, out_s)), _ = lax.scan(
                    tick_side, (state, outputs), jnp.arange(T)
                )
                return out_h[:M], out_s[:M]

            state = jnp.zeros_like(hidden_mb[0])
            # slot M is the scratch target for bubble/non-final writes
            outputs = jnp.zeros(
                (M + 1,) + hidden_mb.shape[1:], hidden_mb.dtype
            )

            def tick(carry, t):
                state, outputs = carry
                recv = lax.ppermute(state, "pp", fwd_perm)
                active, k_c, m_c, done = tick_coords(t)

                params_k = self._select_chunk_params(local_stage_params, k_c)
                is_first_chunk = (d == 0) & (k_c == 0)
                inp = jnp.where(is_first_chunk & active, hidden_mb[m_c],
                                recv)
                out, _ = stage_mod.apply(
                    {"params": params_k}, inp, mask_mb[m_c], **tick_rngs(t)
                )
                # only the final chunk's completions are real outputs
                w = jnp.where(done, m_c, M)
                outputs = lax.dynamic_update_index_in_dim(
                    outputs, out, w, axis=0
                )
                return (out, outputs), None

            (_, outputs), _ = lax.scan(
                tick, (state, outputs), jnp.arange(T)
            )
            return outputs[:M]

        return self._run_ring_schedule(body, stage_params, hidden_mb,
                                       mask_mb, rng=rng)

    def _check_rng(self, rng):
        """Stochastic engines require a key; deterministic ones ignore it."""
        if self.deterministic:
            return None
        if rng is None:
            raise ValueError(
                "this engine was built with deterministic=False (dropout "
                "active); pass rng= to train_step/loss/_logits"
            )
        return rng

    # --- full model ----------------------------------------------------------
    def _logits(self, params, input_ids, token_type_ids, attention_mask,
                rng=None):
        rng = self._check_rng(rng)
        sub = (
            (lambda i: None) if rng is None
            else (lambda i: {"dropout": jax.random.fold_in(rng, i)})
        )
        M = self.num_microbatches
        hidden, mask4 = self.embeddings.apply(
            {"params": params["embeddings"]},
            input_ids, token_type_ids, attention_mask,
            rngs=sub(0),
        )
        B = hidden.shape[0]
        if B % M != 0:
            raise ValueError(f"batch {B} not divisible by microbatches {M}")
        if (B // M) % self.dp != 0:
            raise ValueError(
                f"microbatch size {B // M} not divisible by dp={self.dp}"
            )
        hidden_mb = hidden.reshape(M, B // M, *hidden.shape[1:])
        mask_mb = mask4.reshape(M, B // M, *mask4.shape[1:])

        ring_rng = None if rng is None else jax.random.fold_in(rng, 1)
        if self.virtual_stages > 1:
            encoded = self._interleaved_encoder(
                params["stages"], hidden_mb, mask_mb, rng=ring_rng
            )
        else:
            encoded = self._pipelined_encoder(
                params["stages"], hidden_mb, mask_mb, rng=ring_rng
            )
        encoded = encoded.reshape(B, *encoded.shape[2:])

        pooled = self.pooler.apply(
            {"params": params["pooler"]}, encoded, mask4, rngs=sub(2)
        )
        return self.classifier.apply(
            {"params": params["classifier"]}, pooled, rngs=sub(3)
        )

    def loss(self, params, batch, labels, rng=None):
        input_ids, token_type_ids, attention_mask = batch
        logits = self._logits(
            params, input_ids, token_type_ids, attention_mask, rng=rng
        )
        return optax.softmax_cross_entropy_with_integer_labels(
            logits.astype(jnp.float32), labels
        ).mean()

    # --- training ------------------------------------------------------------
    def make_train_step(self):
        """The FULL train step — grad + update — as one jitted program."""
        jit_kwargs = {}
        if self.zero1:
            # pin the updated state to its ZeRO shards (and params to
            # theirs) so XLA reduce-scatters grads into the state update
            # instead of re-replicating
            if self.param_shardings is None or self.opt_shardings is None:
                raise RuntimeError(
                    "zero1=True needs init() and init_opt_state() before "
                    "make_train_step() — the step pins outputs to the "
                    "shardings those calls compute"
                )
            jit_kwargs["out_shardings"] = (
                self.param_shardings, self.opt_shardings, None
            )
        elif self.zero3:
            if self.param_shardings is None:
                raise RuntimeError(
                    "zero3=True needs init() before make_train_step() — "
                    "the step pins updated params to their dp shards"
                )
            jit_kwargs["out_shardings"] = (self.param_shardings, None, None)

        @functools.partial(jax.jit, donate_argnums=(0, 1), **jit_kwargs)
        def train_step(params, opt_state, batch, labels, rng=None):
            loss, grads = jax.value_and_grad(self.loss)(
                params, batch, labels, rng
            )
            if self.zero2:
                # pin each gradient leaf to the same dp shards a
                # ZeRO-sharded state tensor of that shape gets (params
                # keep their own shardings; only their GRADIENTS live
                # dp-sharded, so the full replicated grad buffer never
                # materializes — the cross-dp sum reduce-scatters
                # straight into shards)
                grads = jax.tree_util.tree_map(
                    lambda g: jax.lax.with_sharding_constraint(
                        g, self._zero1_sharding(g)
                    ),
                    grads,
                )
            updates, opt_state = self.optimizer.update(
                grads, opt_state, params
            )
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss

        self._train_step = train_step
        return train_step

    def train_step(self, params, opt_state, batch, labels, rng=None):
        if self._train_step is None:
            self.make_train_step()
        if self.deterministic:
            if rng is not None:
                raise ValueError(
                    "rng= was passed but this engine is deterministic; "
                    "build it with deterministic=False to train with "
                    "dropout"
                )
            return self._train_step(params, opt_state, batch, labels)
        self._check_rng(rng)
        return self._train_step(params, opt_state, batch, labels, rng)


__all__ = [
    "CompiledBertPipeline",
    "EncoderStage",
    "TpEncoderStage",
    "TpEncoderUnit",
    "split_stage_params_for_tp",
    "merge_stage_params_from_tp",
]
