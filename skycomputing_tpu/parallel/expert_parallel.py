"""Expert parallelism: shard MoE expert weights over an 'ep' mesh axis.

With the static einsum dispatch in ``ops/moe.py``, expert parallelism is a
pure layout choice: stacked expert tensors ([E, ...] leaves of
``GptBlock_MoeMlp``) get ``P('ep', ...)``, everything else replicates, and
XLA lowers the dispatch/combine einsums into all-to-all exchanges over the
axis.  No bespoke communication code — same philosophy as the rest of the
SPMD surface (SURVEY.md §2.3: collectives come from shardings, not calls).
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

_EXPERT_LEAVES = {"w1", "b1", "w2", "b2"}


def make_ep_mesh(ep: int, devices: Optional[Sequence] = None) -> Mesh:
    """A 1-D ('ep',) mesh over the first ``ep`` devices."""
    devs = list(devices) if devices is not None else jax.devices()
    if len(devs) < ep:
        raise ValueError(f"need {ep} devices for ep mesh, have {len(devs)}")
    return Mesh(np.array(devs[:ep]), axis_names=("ep",))


def ep_shardings(params_list: List[Any], mesh: Mesh, axis: str = "ep"):
    """Same-structure tree of NamedShardings: expert-stacked leaves get
    ``P(axis)`` on their leading (expert) dim, the rest replicate."""

    def one_layer(layer_params):
        def assign(path, leaf):
            keys = [getattr(p, "key", str(p)) for p in path]
            if keys and keys[-1] in _EXPERT_LEAVES:
                if np.shape(leaf)[0] % mesh.shape[axis] != 0:
                    raise ValueError(
                        f"num_experts {np.shape(leaf)[0]} not divisible by "
                        f"{axis}={mesh.shape[axis]}"
                    )
                return NamedSharding(mesh, P(axis))
            return NamedSharding(mesh, P())

        return jax.tree_util.tree_map_with_path(assign, layer_params)

    return [one_layer(p) for p in params_list]


def shard_moe_params(params_list: List[Any], mesh: Mesh, axis: str = "ep"):
    """Place a layer-indexed param list on the mesh with expert sharding."""
    return jax.device_put(params_list, ep_shardings(params_list, mesh, axis))


__all__ = ["make_ep_mesh", "ep_shardings", "shard_moe_params"]
